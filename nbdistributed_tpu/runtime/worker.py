"""Per-rank worker runtime.

TPU-native rebuild of the reference worker process (reference:
worker.py:72-601).  One process per TPU chip (or per host on pods); the
data plane is ``jax.distributed`` + XLA collectives instead of
``torch.distributed``/NCCL (reference: worker.py:145-151), and the seeded
interactive namespace speaks JAX: ``jax``/``jnp``/``mesh``/``P``/``dist``
instead of ``torch``/``dist``/``device`` (reference: worker.py:160-177,
redesign per SURVEY §7).

Runs as ``python -m nbdistributed_tpu.runtime.worker --rank R ...``;
spawned and env-configured by :mod:`nbdistributed_tpu.manager`.

Startup order (deliberate, SURVEY §7 "hard parts"):
1. ``jax.distributed.initialize`` — the blocking rendezvous, while stdout
   still goes to the spawner's pipe so early failures are capturable
   (the reference relies on the same property: process_manager.py:136-150);
2. control-plane connect — the HELLO doubles as the readiness signal the
   reference lacked (it slept 2 s instead);
3. serial message loop; a heartbeat thread pings the coordinator so
   liveness is observable even during long cells or XLA compiles.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
import traceback

from ..messaging import Message, TransportError, WorkerChannel
from ..messaging import xfer as xfer_mod
from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..observability import spans as obs_spans
from ..observability import telemetry as obs_telemetry
from ..resilience import faults as faults_mod
from ..resilience.dedup import _READ_ONLY, ReplayCache, ResultMailbox
from ..resilience.faults import FaultPlan
from ..utils import knobs
from . import collective_guard, executor, introspect
from .interrupt import InterruptGate


def _load_hf_pretrained_lazy(name_or_path, **kw):
    """Seeded-namespace shim: defers the heavyweight torch/transformers
    import to the first call (workers must start fast)."""
    from ..models.hf import load_hf_pretrained
    return load_hf_pretrained(name_or_path, **kw)

HEARTBEAT_INTERVAL_S = 2.0

# Documented exemptions for the lifecycle self-lint
# (analysis/lifecycle.py): "Class:attr" → reason.
_LINT_LIFECYCLE_OK = {
    "DistributedWorker:_stack_file":
        "faulthandler holds this fd for SIGUSR1 stack dumps — the "
        "postmortem evidence channel must outlive shutdown() (a "
        "late SIGUSR1 against a closed fd would crash the handler); "
        "the OS reclaims it at process exit, which is the intended "
        "lifetime",
}


class _WorkerServe:
    """One serving tenant's worker-side decode state: the
    :class:`~..models.serving.DecodeServer` plus the request-id map and
    per-request emission cursors the ``serve_step`` protocol needs.

    ``sent[rid]`` is how many of the server's output tokens for that
    request have ALREADY been put in a reply — each step reply carries
    only the suffix beyond it, tagged with its offset, which is what
    lets the gateway dedup replayed/redelivered emissions exactly.
    """

    __slots__ = ("server", "rids", "sent", "tokens_total", "window",
                 "pf_seen", "dc_seen")

    def __init__(self, server):
        self.server = server
        self.rids: dict[str, int] = {}      # gateway rid -> local id
        self.sent: dict[str, int] = {}      # gateway rid -> reported
        self.tokens_total = 0
        self.window: list[tuple[float, int]] = []  # (t, tokens_total)
        # Cumulative prefill/decode token counts already reported —
        # each serve_step reply carries the per-tick DELTAS (the
        # observatory's prefill-vs-decode split, ISSUE 18).
        self.pf_seen = 0
        self.dc_seen = 0

    def note_rate(self) -> None:
        now = time.monotonic()
        self.window.append((now, self.tokens_total))
        while self.window and now - self.window[0][0] > 10.0:
            self.window.pop(0)

    def tokens_per_s(self) -> float:
        if len(self.window) < 2:
            return 0.0
        (t0, n0), (t1, n1) = self.window[0], self.window[-1]
        return (n1 - n0) / (t1 - t0) if t1 > t0 else 0.0

# Orphan grace (durable sessions, ISSUE 4): when the coordinator dies,
# the worker does NOT exit — it parks the in-flight cell's result,
# keeps its namespace and flight recorder, and waits up to
# NBD_ORPHAN_TTL_S for a fresh coordinator to reattach (dialing the
# control endpoint back, re-reading the session manifest between
# attempts in case the new coordinator had to bind a different port).
# TTL 0 disables the grace period (legacy exit-on-disconnect).
DEFAULT_ORPHAN_TTL_S = 600.0
ORPHAN_RECONNECT_POLL_S = 1.0


class DistributedWorker:
    def __init__(self, rank: int, world_size: int, coordinator_host: str,
                 control_port: int, dist_port: int | None = None,
                 backend: str | None = None,
                 dist_host: str | None = None,
                 gate: InterruptGate | None = None,
                 fault_plan: FaultPlan | None = None):
        self.rank = rank
        self.world_size = world_size
        self._shutdown = threading.Event()
        # (msg_type, started_monotonic, msg_id, deadline_s|None,
        # tenant|None) while a request is being handled, else None.
        # MONOTONIC clock on purpose: busy_s feeds the hang watchdog's
        # stall detection, and a wall-clock step (NTP slew,
        # suspend/resume) must not fake or mask a stall.  The tenant
        # element attributes the in-flight cell to the right tenant in
        # gateway pools (heartbeat busy_tenant piggyback, stream-output
        # routing).
        self._busy: tuple | None = None
        # Tenant namespace isolation (gateway pools, ISSUE 8): each
        # tenant executes in its own dict, seeded lazily as a copy of
        # the base interactive namespace, so one tenant's assignments
        # (or `del`s) can never leak into another's cells.  The ONE
        # deliberate crossing is `shared` — a dict injected into every
        # tenant namespace by the same object, the explicit opt-in
        # shared segment (`shared["params"] = ...` publishes;
        # everything else is isolated).  Untagged requests (the
        # single-kernel path) keep using self.namespace directly.
        self._tenant_ns: dict[str, dict] = {}
        self._shared_ns: dict = {}
        # Serving loops (ISSUE 11): tenant -> _WorkerServe.  Mutated
        # only on the serial request loop; the heartbeat thread reads
        # the atomically-rebound snapshot below (never the dict).
        self._serve: dict[str, _WorkerServe] = {}
        self._serve_snap: dict | None = None
        # Step-loop progress (ISSUE 14): {"i", "k", "last", "sps"}
        # while a --repeat cell is looping, else None.  Rebound
        # atomically by the progress callback on the serial loop; the
        # heartbeat thread piggybacks it (`rep` ping field) so the
        # coordinator sees per-step progress without a probe.
        self._rep_snap: dict | None = None
        self._ckpt_async = None          # in-flight background save
        # Resilience state: the reply-replay cache makes request
        # redelivery idempotent (a retried execute NEVER runs twice);
        # the fault plan (env knob / %dist_chaos) injects deterministic
        # control-plane failures.
        self._replay = ReplayCache()
        self._fault_plan = fault_plan
        self._install_plan: tuple | None = None  # armed by %dist_chaos
        self._msg_seen = 0  # control messages received (kill index)
        # Durable-session state: the session token proves a reattaching
        # coordinator resumes THIS session; the epoch fences stale
        # coordinators out (only a hello may raise it); the mailbox
        # parks results whose reply had no coordinator to land on.
        self._session_token = knobs.get_str("NBD_SESSION_TOKEN") or None
        self._epoch = knobs.get_int("NBD_SESSION_EPOCH", 0)
        # Host labels (multi-host worlds, ISSUE 6): which host this
        # worker runs on and which host the coordinator runs on — the
        # link-fault layer shapes frames by this pair, and the orphan
        # reconnect loop refuses to dial through a partitioned link.
        self._host_label = knobs.get_str("NBD_HOST") or "local"
        self._coord_label = knobs.get_str("NBD_COORD_HOST") or "local"
        # Manifest mirror (partition tolerance): multi-host worlds
        # share no run-dir filesystem, so the coordinator mirrors its
        # session manifest to every worker in the hello exchange — the
        # reconnect loop's endpoint discovery works from this copy when
        # no shared NBD_RUN_DIR manifest exists.
        self._manifest_mirror: dict | None = None
        self._orphan_ttl = knobs.get_float("NBD_ORPHAN_TTL_S",
                                           float(DEFAULT_ORPHAN_TTL_S))
        # Parked replies spill to the run dir past the in-memory bound
        # (ISSUE 20): a multi-hundred-MB cell result parked during
        # orphan grace lands on disk with an explicit verdict instead
        # of silently evicting the rest of the mailbox.
        self._mailbox = ResultMailbox(
            spill_dir=os.path.join(flightrec.run_dir(),
                                   f"spill-rank{rank}"))
        # Bulk-transfer endpoint (ISSUE 20): inbound/outbound chunked
        # transfer state machines; owned by the serial request loop.
        self._xfer = xfer_mod.XferEndpoint(rank, say=self._say)
        self._orphaned = False
        self._hb_fail_streak = 0
        # Message received while VALIDATING a reconnect (the hello a
        # new coordinator owes us) — consumed by the run loop before
        # its next channel.recv.
        self._resume_msg = None
        # (msg_type, msg_id, reply) of the last reply SENT: a send into
        # a dying coordinator's socket can succeed locally yet never be
        # read, so orphan entry re-parks it for redelivery (mutating
        # types only — see _park).
        self._last_reply: tuple | None = None
        # Observability: the process tracer (enabled by the 'trace'
        # control message), wire-frame accounting, and the directory
        # the ACTIVE jax.profiler trace was started with (None = not
        # profiling — the idempotence state for _handle_profile).
        self._tracer = obs_spans.tracer()
        obs_metrics.install_wire_hook()
        self._profile_dir: str | None = None
        # Flight recorder: opened FIRST (before the slow jax init) so
        # even a bring-up crash leaves a black box.  Always on; the
        # ring file lives under the run dir the coordinator exported
        # (NBD_RUN_DIR) and survives this process's death by SIGKILL.
        self._flight = flightrec.init(f"rank{rank}")
        self._flight.record("worker_start", rank=rank, pid=os.getpid(),
                            world_size=world_size)
        # Hang watchdog (ISSUE 5): when enabled (NBD_HANG, default on)
        # heartbeats also carry the in-flight request id, its optional
        # per-cell deadline, and the collective-progress snapshot from
        # the guard — the coordinator-side watchdog's raw material.
        # Disabled, the heartbeat pays exactly one flag check.
        self._hang_enabled = knobs.get_bool("NBD_HANG", True)
        # Stack dump on demand: SIGUSR1 makes faulthandler write every
        # thread's traceback to a per-rank file under the run dir —
        # the %dist_doctor's view INTO a wedged rank (works even while
        # the main thread is stuck in a loop or a native call; the C
        # handler needs no GIL).  The file object must stay referenced
        # for the lifetime of the process (faulthandler keeps the fd).
        # Per-pid name, like the flight rings: a healed/respawned rank
        # must never truncate its dead predecessor's dumped stacks —
        # they are postmortem evidence.
        self._stack_file = None
        try:
            import faulthandler
            import signal as _signal
            if threading.current_thread() is threading.main_thread():
                path = os.path.join(
                    flightrec.run_dir(),
                    f"stacks-rank{rank}.{os.getpid()}.txt")
                self._stack_file = open(path, "w")
                faulthandler.register(_signal.SIGUSR1,
                                      file=self._stack_file,
                                      all_threads=True)
        except Exception:
            self._stack_file = None  # never block bring-up on this
        # Spawn-time fault plans (NBD_FAULT_PLAN) bypass
        # _set_fault_plan — wire their collective-freeze fault here.
        self._install_freeze_hook(fault_plan)
        # Spawn-time plans (NBD_FAULT_PLAN / NBD_CORRUPT_SPEC) must be
        # visible to the training-integrity guard too (ISSUE 19).
        faults_mod.set_process_plan(fault_plan)
        # SIGINT discipline (see runtime/interrupt.py for the design
        # and the root-cause story).  main() installs the gate before
        # construction so interrupts during the slow init phase defer;
        # an uninstalled gate (direct construction, e.g. in-process
        # tests) degrades to plain default-handler semantics.
        self._gate = gate or InterruptGate()
        # Control plane dials the kernel; the jax.distributed rendezvous
        # dials rank 0's host (they differ on all-remote host plans).
        dist_host = dist_host or coordinator_host

        # --- data plane: JAX runtime init (reference: worker.py:145-151) --
        if backend == "cpu":
            import jax
            jax.config.update("jax_platforms", "cpu")
            if world_size > 1:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
        if world_size > 1 and dist_port is not None:
            import jax
            print(f"[worker {rank}] joining jax.distributed world "
                  f"({world_size} processes)...", flush=True)
            jax.distributed.initialize(
                coordinator_address=f"{dist_host}:{dist_port}",
                num_processes=world_size,
                process_id=rank)
        import jax  # noqa: F811 — backend resolves here
        self._jax = jax
        # Warm starts (ISSUE 16): the gateway ships a persistent
        # per-pool XLA compilation cache dir so a resized-in worker's
        # (or a migrated tenant's) first cell replays a compiled
        # executable instead of paying the cold compile.  Gated: old
        # jaxlibs without the option, or an unwritable dir, degrade
        # to the ordinary in-memory cache.
        cache_dir = knobs.get_str("NBD_COMPILE_CACHE_DIR") or ""
        if cache_dir and cache_dir.strip().lower() not in (
                "0", "off", "none"):
            try:
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir",
                                  cache_dir)
                # Cache every compile, however fast: the 1 B-param
                # first-cell compile is the target, but resize tests
                # ride tiny graphs.
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                print(f"[worker {rank}] compile cache: {cache_dir}",
                      flush=True)
            except Exception as e:
                print(f"[worker {rank}] compile cache disabled "
                      f"({type(e).__name__}: {e})", flush=True)
        n_local = jax.local_device_count()
        print(f"[worker {rank}] backend={jax.default_backend()} "
              f"local_devices={n_local} global_devices={jax.device_count()}",
              flush=True)

        # --- interactive namespace (reference: worker.py:160-177) --------
        self.namespace: dict = {}
        self._seed_namespace()

        # Telemetry sampler: snapshots HBM / live buffers / compile
        # activity off the hot path; the heartbeat thread piggybacks
        # the snapshots so the coordinator sees device state even while
        # the serial request loop is busy in a long cell.
        self._telemetry = obs_telemetry.TelemetrySampler(
            rank, extra_fn=self._telemetry_extra)

        # --- control plane (reference: worker.py:154-157) ----------------
        # NBD_AUTH_TOKEN: shared secret required by non-loopback
        # coordinators (multihost); shipped via the worker env.
        # Endpoint + auth kept for the orphan reconnect loop.
        self._coordinator_host = coordinator_host
        self._control_port = control_port
        self._auth_token = knobs.get_str("NBD_AUTH_TOKEN") or None
        self.channel = WorkerChannel(
            coordinator_host, control_port, rank=rank,
            auth_token=self._auth_token)
        self.channel.fault_plan = fault_plan
        self.channel.local_host = self._host_label
        self.channel.peer_host = self._coord_label
        self._flight.record("transport_connect", host=coordinator_host,
                            port=control_port)
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           name="nbd-heartbeat", daemon=True)
        self._hb_thread.start()

    # ------------------------------------------------------------------

    def _seed_namespace(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from .. import models
        from ..parallel import collectives, expert, mesh as mesh_mod, \
            pipeline
        from ..parallel.ring import (ring_attention, zigzag_shard,
                                     zigzag_unshard)
        from ..parallel.ulysses import ulysses_attention
        from ..utils import data as data_mod
        from ..utils.compat import shard_map as _compat_shard_map

        dist = collectives.DistNamespace()
        ns = {
            "jax": jax,
            "jnp": jnp,
            "np": np,
            "rank": self.rank,
            "world_size": self.world_size,
            "process_index": jax.process_index(),
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
            "device": jax.local_devices()[0],
            "Mesh": Mesh,
            "NamedSharding": NamedSharding,
            "P": PartitionSpec,
            "PartitionSpec": PartitionSpec,
            "shard_map": getattr(jax, "shard_map", _compat_shard_map),
            "dist": dist,
            "all_reduce": collectives.all_reduce,
            "all_gather": collectives.all_gather,
            "broadcast": collectives.broadcast,
            "barrier": collectives.barrier,
            "reduce_scatter": collectives.reduce_scatter,
            "all_reduce_quantized": collectives.all_reduce_quantized,
            "make_mesh": mesh_mod.make_mesh,
            "shard_batch": mesh_mod.shard_batch,
            "ring_attention": ring_attention,
            "zigzag_shard": zigzag_shard,
            "zigzag_unshard": zigzag_unshard,
            "ulysses_attention": ulysses_attention,
            "pipeline_forward": pipeline.pipeline_forward,
            "shard_stage_params": pipeline.shard_stage_params,
            "moe_ffn": expert.moe_ffn,
            "init_moe_params": expert.init_moe_params,
            "load_hf_pretrained": _load_hf_pretrained_lazy,
            "generate": models.generate,
            "speculative_generate": models.speculative_generate,
            "DecodeServer": models.DecodeServer,
            "batch_iterator": data_mod.batch_iterator,
            "shard_arrays": data_mod.shard_arrays,
            "pack_tokens": data_mod.pack_tokens,
            "__rank__": self.rank,
            "__world_size__": self.world_size,
            "__builtins__": __builtins__,
        }
        self.namespace.update(ns)

    # ------------------------------------------------------------------

    def _heartbeat(self) -> None:
        """Liveness pings; also the only traffic during long XLA compiles,
        so the coordinator can distinguish busy from dead (the reference
        cannot: SURVEY §7 'no-timeout mode hangs').

        Pings carry the main loop's busy state: the request loop is
        SERIAL, so a status probe stalls exactly when the user most
        wants it (mid-cell) — the heartbeat thread reports what the
        main thread is doing without going through the loop.  (A
        heartbeat alone proves only the *process* lives; ``busy_s``
        growing across pings is how the coordinator tells "crunching a
        long cell" from "idle".)

        Pings also piggyback a compact telemetry snapshot (HBM, live
        buffers, compile activity — every few pings, the sampler
        paces itself), making the coordinator's view push-based."""
        while not self._shutdown.wait(HEARTBEAT_INTERVAL_S):
            plan = self._fault_plan
            if plan is not None and plan.heartbeat_frozen():
                continue  # injected staleness: process alive, pings gone
            busy = self._busy  # one tuple, replaced atomically — the
            data = None        # read can never tear across fields
            if busy is not None:
                # Monotonic arithmetic: wall-clock jumps must neither
                # fake nor mask a stall (the watchdog consumes this).
                data = {"busy_type": busy[0],
                        "busy_s": round(time.monotonic() - busy[1], 3)}
                if len(busy) > 4 and busy[4] is not None:
                    # Gateway pools: whose cell the mesh is running —
                    # the %dist_top / pool-status tenant column.
                    data["busy_tenant"] = busy[4]
                if self._hang_enabled:
                    if busy[2] is not None:
                        data["busy_id"] = busy[2]
                    if busy[3] is not None:
                        data["busy_deadline"] = busy[3]
            if self._hang_enabled:
                col = collective_guard.progress()
                if col is not None:
                    data = dict(data or {})
                    data["col"] = col
            try:
                snap = self._telemetry.maybe_sample()
            except Exception:
                snap = None
            if snap is not None:
                data = dict(data or {})
                data["tel"] = snap
            srv = self._serve_snap  # atomic rebind; safe to read here
            if srv is not None:
                # Serving telemetry (ISSUE 11): tokens/s and KV-slot
                # occupancy ride every ping while a DecodeServer is
                # live — the %dist_top / pool-status serving columns.
                data = dict(data or {})
                data["srv"] = srv
            rep = self._rep_snap  # atomic rebind; safe to read here
            if rep is not None:
                # Step-loop telemetry (ISSUE 14): step index, last
                # scalar (loss), steps/s of an in-flight --repeat
                # cell — per-step progress with ONE dispatch, through
                # the same piggyback plane as tel/col.
                data = dict(data or {})
                data["rep"] = rep
            tg = self._tg_snapshot()
            if tg is not None:
                # Training-integrity guard (ISSUE 19): skips, last
                # audit step/verdict, rollback/repair counts, and any
                # quarantine suspects — the %dist_top guard column and
                # the Supervisor's quarantine scan feed off pings
                # alone, no status probe.
                data = dict(data or {})
                data["tg"] = tg
            try:
                self.channel.send(Message(msg_type="ping",
                                          rank=self.rank, data=data))
                self._hb_fail_streak = 0
            except Exception as e:
                # Say WHY the pings stopped: the coordinator sees only
                # silence, but the flight ring survives for the
                # postmortem.  With orphan grace enabled the thread
                # KEEPS RUNNING — the main loop owns reattach, and the
                # swapped-in channel makes these sends succeed again;
                # the streak counter is the orphan-entry signal.
                self._hb_fail_streak += 1
                obs_metrics.registry().counter(
                    "nbd_heartbeat_send_failures",
                    "heartbeat pings that failed to send").inc()
                self._flight.record("heartbeat_send_failed",
                                    error=f"{type(e).__name__}: {e}",
                                    streak=self._hb_fail_streak)
                self._flight.flush()
                if self._orphan_ttl <= 0:
                    return  # legacy: no grace period configured

    def _tg_snapshot(self):
        """Training-guard ping payload, or None when no guard is live.
        Lazy import + atomic-snapshot read: safe from the heartbeat
        thread, and a guard-free worker pays one dict lookup."""
        try:
            from ..resilience import trainguard
            return trainguard.snapshot()
        except Exception:
            return None

    def _telemetry_extra(self) -> dict:
        """Resilience counters riding each telemetry snapshot, so the
        coordinator's push-based view (and the postmortem's last
        snapshot) carries them without a status probe."""
        extra = {"dedup": self._replay.hits, "msgs": self._msg_seen}
        busy = self._busy
        if busy is not None:
            extra["busy"] = busy[0]
        return extra

    def _send_shielded(self, msg: Message) -> None:
        """Send with interrupts deferred (main thread only — that is
        where the gated handler runs): a %dist_interrupt landing
        mid-``sendall`` would otherwise abandon a half-written frame and
        corrupt the control-plane stream.  A deferred interrupt is
        raised at shield exit — after the frame is whole — so it still
        aborts the surrounding cell promptly.  Other threads (heartbeat,
        user threads that print) bypass the gate: CPython never runs
        signal handlers there."""
        if self._gate.main_thread():
            with self._gate.shielded():
                self.channel.send(msg)
        else:
            self.channel.send(msg)

    def _stream(self, text: str, stream: str) -> None:
        """Push stdout/result text to the coordinator immediately
        (reference: worker.py:45-63).  Tagged with the in-flight
        request's tenant (gateway pools) so the gateway can route the
        print to the one kernel whose cell produced it."""
        data = {"text": text, "stream": stream}
        busy = self._busy
        if busy is not None and len(busy) > 4 and busy[4] is not None:
            data["tenant"] = busy[4]
        try:
            self._send_shielded(Message(
                msg_type="stream_output", rank=self.rank, data=data))
        except Exception:
            pass  # printing must never kill execution

    # ------------------------------------------------------------------
    # tenant namespaces (gateway pools, ISSUE 8)

    def _ns_for(self, tenant: str | None) -> dict:
        """The namespace a request executes/reads/writes in: the base
        interactive namespace for untagged (single-kernel) requests, a
        per-tenant copy of the seeded base otherwise.  Every tenant
        namespace carries the SAME ``shared`` dict — the explicit
        opt-in shared segment — plus its own ``tenant`` name."""
        if tenant is None:
            return self.namespace
        ns = self._tenant_ns.get(tenant)
        if ns is None:
            ns = dict(self.namespace)
            ns["shared"] = self._shared_ns
            ns["tenant"] = tenant
            self._tenant_ns[tenant] = ns
            self._flight.record("tenant_ns_created", tenant=tenant)
        return ns

    # ------------------------------------------------------------------
    # message handlers (dispatch table analog of reference: worker.py:205-221)

    def _handle_execute(self, msg: Message) -> Message:
        code = (msg.data if isinstance(msg.data, str)
                else msg.data.get("code", ""))
        # Publish the cell's target ranks for the duration of the cell:
        # the eager world-collectives consult them at CALL time and
        # raise on a strict subset instead of deadlocking (see
        # runtime/collective_guard.py).  Raw-string requests (bench
        # cells, direct control-plane callers) carry no targets — the
        # subset check stays inactive for them.
        targets = (None if isinstance(msg.data, str)
                   else msg.data.get("target_ranks"))
        repeat = until = None
        if isinstance(msg.data, dict):
            repeat = msg.data.get("repeat")
            until = msg.data.get("until")
        collective_guard.begin_cell(targets, self.world_size)
        self._flight.record("cell_start", msg_id=msg.msg_id,
                            code=code.strip()[:120],
                            **({"tenant": msg.tenant}
                               if msg.tenant is not None else {}),
                            **({"repeat": int(repeat)}
                               if repeat else {}))
        try:
            if repeat:
                # Step loop (ISSUE 14): compile once, loop worker-side
                # — one dispatch, k steps; per-step progress rides the
                # heartbeat `rep` piggyback, and the replay cache
                # holds ONE entry for the whole loop (a redelivered
                # request never re-runs steps).
                def _note(i, k, last, sps):
                    self._rep_snap = {"i": i, "k": k,
                                      "last": last,
                                      "sps": round(sps, 2)}

                try:
                    result = executor.execute_repeat(
                        code, self._ns_for(msg.tenant), self._stream,
                        repeat=int(repeat), until=until,
                        rank=self.rank,
                        filename=f"<rank {self.rank}>",
                        progress=_note)
                finally:
                    self._rep_snap = None
            else:
                result = executor.execute_cell(
                    code, self._ns_for(msg.tenant), self._stream,
                    rank=self.rank, filename=f"<rank {self.rank}>")
        finally:
            ops = collective_guard.end_cell()
        self._flight.record(
            "cell_end", msg_id=msg.msg_id,
            status="error" if result.get("error") else "success",
            duration_s=round(result.get("duration_s", 0.0), 4))
        result["collective_ops"] = ops
        result["cell_sha1"] = collective_guard.cell_hash(code)
        reg = obs_metrics.registry()
        reg.counter("nbd_cells_total", "cells executed", {
            "status": "error" if result.get("error") else "success",
        }).inc()
        reg.histogram("nbd_cell_seconds",
                      "per-cell user-code duration").observe(
            result.get("duration_s", 0.0))
        return msg.reply(data=result, rank=self.rank)

    def _handle_get_var(self, msg: Message) -> Message:
        import jax
        import numpy as np

        name = msg.data if isinstance(msg.data, str) else msg.data["name"]
        ns = self._ns_for(msg.tenant)
        if name not in ns:
            return msg.reply(data={"error": f"name {name!r} not defined"},
                             rank=self.rank)
        value = ns[name]
        if isinstance(value, jax.Array):
            # Device arrays travel as raw buffers + metadata, the analog
            # of the reference's .cpu().detach() path (worker.py:412-418).
            arr = np.asarray(jax.device_get(value))
            return msg.reply(
                data={"array": True, "dtype": str(value.dtype),
                      "shape": list(value.shape),
                      "sharding": introspect._sharding_str(value)},
                rank=self.rank, bufs={"value": arr})
        if isinstance(value, np.ndarray):
            return msg.reply(data={"array": True, "dtype": str(value.dtype),
                                   "shape": list(value.shape),
                                   "sharding": None},
                             rank=self.rank, bufs={"value": value})
        if isinstance(value, (dict, list, tuple)):
            # Pytrees of arrays (params, optimizer state) travel on
            # the buffer path — treedef as JSON, leaves as raw bufs —
            # never the codec's pickle fallback, so they survive
            # allow_pickle=False channels (SURVEY §2.2's trust
            # boundary).  Non-conforming containers fall through.
            from ..messaging.codec import flatten_pytree_wire
            try:
                meta, bufs = flatten_pytree_wire(value)
            except TypeError:
                pass
            else:
                return msg.reply(
                    data={"pytree": meta, "n_leaves": len(bufs)},
                    rank=self.rank, bufs=bufs)
        return msg.reply(data={"array": False, "value": value},
                         rank=self.rank)

    def _handle_set_var(self, msg: Message) -> Message:
        import jax.numpy as jnp
        import numpy as np

        name = msg.data["name"]
        ns = self._ns_for(msg.tenant)
        if msg.data.get("pytree") is not None:
            from ..messaging.codec import unflatten_pytree_wire
            # jax leaves go back on device; numpy leaves are COPIED —
            # the decoded buffers are read-only frombuffer views.
            ns[name] = unflatten_pytree_wire(
                msg.data["pytree"], msg.bufs,
                leaf_fn=lambda a, is_jax: jnp.asarray(a) if is_jax
                else np.array(a))
        elif "value" in msg.bufs:
            ns[name] = jnp.asarray(msg.bufs["value"])
        else:
            ns[name] = msg.data.get("value")
        return msg.reply(data={"status": "set", "name": name},
                         rank=self.rank)

    # -- bulk-transfer plane (ISSUE 20, messaging/xfer.py) -------------
    #
    # The endpoint owns all chunk/bitmap/resume state; these shims
    # supply the two things only the worker knows — the namespace to
    # bind into and the flight recorder.  Chunk writes are bitmap-
    # idempotent and the commit bind runs exactly once (replay cache
    # for same-msg_id redeliveries, the endpoint's completed-xid memo
    # for commits from a post-SIGKILL successor coordinator).

    def _handle_xfer_begin(self, msg: Message) -> Message:
        return self._xfer.handle_begin(msg)

    def _handle_xfer_chunk(self, msg: Message) -> Message:
        return self._xfer.handle_chunk(msg)

    def _handle_xfer_commit(self, msg: Message) -> Message:
        def bind(st):
            if st.kind == "file":
                dest = os.path.abspath(os.path.expanduser(st.dest or ""))
                if not st.dest:
                    raise ValueError("file transfer without dest path")
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                st.sink.arrays["f0"].tofile(dest)
                probe = lambda: os.path.exists(dest)  # noqa: E731
            else:
                import jax.numpy as jnp
                from ..messaging.codec import unflatten_pytree_wire
                ns = self._ns_for(st.tenant if st.tenant is not None
                                  else msg.tenant)
                # jax leaves go back on device; numpy leaves bind the
                # preallocated destination arrays directly — the sink
                # already owns writable memory, so unlike set_var no
                # defensive copy is needed.
                value = unflatten_pytree_wire(
                    st.meta, st.sink.arrays,
                    leaf_fn=lambda a, is_jax: jnp.asarray(a) if is_jax
                    else a)
                ns[st.name] = value
                # id only — a strong ref here would pin the payload in
                # the memo after the user deletes the variable.
                vid, name = id(value), st.name
                probe = lambda: id(ns.get(name)) == vid  # noqa: E731
            self._flight.record("xfer_applied", xid=st.xid,
                                kind=st.kind, name=st.name,
                                bytes=st.sink.total)
            return probe
        return self._xfer.handle_commit(msg, bind)

    def _handle_xfer_pull_begin(self, msg: Message) -> Message:
        d = msg.data or {}
        ns = None if d.get("file") else self._ns_for(msg.tenant)
        return self._xfer.handle_pull_begin(msg, ns)

    def _handle_xfer_read(self, msg: Message) -> Message:
        return self._xfer.handle_read(msg)

    def _handle_xfer_pull_end(self, msg: Message) -> Message:
        return self._xfer.handle_pull_end(msg)

    def _handle_sync(self, msg: Message) -> Message:
        from ..parallel import collectives
        collectives.barrier()
        return msg.reply(data={"status": "synced"}, rank=self.rank)

    def _handle_get_status(self, msg: Message) -> Message:
        data = introspect.device_status(self.rank, self.world_size)
        # Resilience counters ride the status probe so chaos runs can
        # assert "zero double-executions" (every redelivery was
        # answered from the replay cache) from the coordinator side.
        data["dedup_hits"] = self._replay.hits
        plan = self._fault_plan
        if plan is not None:
            data["fault_counters"] = dict(plan.counters)
        # Observability state: until these fields, there was no way to
        # tell from the coordinator that a profiler trace or a span
        # trace was left running on a worker.
        data["profiling"] = self._profile_dir
        data["tracing"] = self._tracer.enabled
        if self._tracer.enabled:
            data["trace_spans"] = len(self._tracer)
        # Durable-session state: what a reattached coordinator rebuilds
        # its rank table from.
        data["session_epoch"] = self._epoch
        data["mailbox_parked"] = len(self._mailbox)
        # Bulk-transfer counters (ISSUE 20): the chaos pin asserts
        # applies == 1 per transfer (zero double-applies) and reads
        # dup/crc-reject counts from here.
        data["xfer"] = self._xfer.status()
        data["orphan_ttl_s"] = self._orphan_ttl
        # Gateway pools: which tenants have materialized a namespace on
        # this rank, and the shared segment's size.
        if self._tenant_ns:
            data["tenants"] = sorted(self._tenant_ns)
            data["shared_names"] = len(self._shared_ns)
        return msg.reply(data=data, rank=self.rank)

    def _handle_chaos(self, msg: Message) -> Message:
        """Install / clear / report the worker-side fault plan at
        runtime (``%dist_chaos``).  ``set`` ARMS the plan rather than
        installing it: it takes effect after this reply is sent, so
        the acknowledgement itself cannot be eaten by the plan it
        confirms."""
        data = msg.data or {}
        action = data.get("action", "status")
        if action == "set":
            try:
                plan = FaultPlan.from_spec(data.get("spec") or {})
            except (TypeError, ValueError) as e:
                return msg.reply(data={"error": f"bad fault spec: {e}"},
                                 rank=self.rank)
            self._install_plan = (plan,)
            self._flight.record("fault_plan_armed", spec=plan.spec())
            return msg.reply(data={"status": "armed",
                                   "spec": plan.spec()}, rank=self.rank)
        if action == "clear":
            old = self._fault_plan
            self._set_fault_plan(None)  # immediate: the ack must land
            return msg.reply(
                data={"status": "cleared",
                      "counters": dict(old.counters) if old else None},
                rank=self.rank)
        plan = self._fault_plan
        return msg.reply(
            data={"status": "active" if plan is not None else "off",
                  "spec": plan.spec() if plan is not None else None,
                  "counters": dict(plan.counters)
                  if plan is not None else None,
                  "dedup_hits": self._replay.hits},
            rank=self.rank)

    def _set_fault_plan(self, plan: FaultPlan | None) -> None:
        self._fault_plan = plan
        self.channel.fault_plan = plan
        # The training-integrity guard reads the plan through the
        # module-level slot (corrupt specs fire inside user-code train
        # loops, which never see the Worker instance).
        faults_mod.set_process_plan(plan)
        # kill_at counts messages SINCE THE PLAN WAS INSTALLED (the
        # should_kill contract): a runtime-armed kill_at=5 must mean
        # "the 5th message from now", not an absolute since-spawn index
        # the session has long passed.
        self._msg_seen = 0
        self._install_freeze_hook(plan)

    def _handle_guard(self, msg: Message) -> Message:
        """``%dist_guard``: report / toggle / audit the training-
        integrity guard (resilience/trainguard.py).  ``audit`` runs a
        replica-consistency audit on the live guard NOW — only safe
        when every rank receives it (send_to_all), since the audit's
        all-gather must be entered by the whole world."""
        from ..resilience import trainguard
        data = msg.data or {}
        action = data.get("action", "status")
        if action in ("on", "off"):
            trainguard.set_enabled(action == "on")
            self._flight.record("guard_toggle", enabled=action == "on")
            return msg.reply(data={"status": action,
                                   **trainguard.status()},
                             rank=self.rank)
        if action == "audit":
            g = trainguard._ACTIVE
            if g is None:
                return msg.reply(data={"error": "no live TrainGuard "
                                       "in this process"},
                                 rank=self.rank)
            try:
                v = g.audit()
            except Exception as e:
                return msg.reply(data={"error": f"audit failed: "
                                       f"{type(e).__name__}: {e}"},
                                 rank=self.rank)
            return msg.reply(data={"status": "audited",
                                   "ok": v.ok,
                                   "majority_rank": v.majority_rank,
                                   "minority": list(v.minority),
                                   **trainguard.status()},
                             rank=self.rank)
        return msg.reply(data=trainguard.status(), rank=self.rank)

    def _install_freeze_hook(self, plan: FaultPlan | None) -> None:
        """Wire the plan's collective-freeze fault into the guard: a
        chosen rank blocks at a chosen collective entry — alive,
        heartbeating, making no progress — the deterministic stand-in
        for a wedged rank the hang watchdog exists to catch.  The
        sleep runs inside the cell's interrupt window, so the
        escalation ladder's %dist_interrupt breaks it."""
        if plan is None or not plan.has_freeze():
            collective_guard.set_freeze_hook(None)
            return

        def _freeze(op: str, seq: int) -> None:
            wait = plan.should_freeze(self.rank, seq)
            if wait is None:
                return
            self._flight.record("fault_freeze", op=op, seq=seq,
                                freeze_s=wait)
            self._flight.flush()
            time.sleep(wait)

        collective_guard.set_freeze_hook(_freeze)

    def _handle_get_namespace_info(self, msg: Message) -> Message:
        return msg.reply(
            data={"namespace_info": introspect.describe_namespace(
                self._ns_for(msg.tenant)), "status": "success"},
            rank=self.rank)

    def _handle_checkpoint(self, msg: Message) -> Message:
        """Save/restore named namespace entries (SURVEY §5.4 upgrade —
        the reference has no checkpoint subsystem at all).

        ``background: true`` on a save starts
        :func:`~.checkpoint.save_async` and returns immediately (the
        worker stays responsive while the device→host drain and disk
        IO run on a thread); ``action: "status"`` polls the in-flight
        save — pending / done-with-summary / failed-with-error."""
        from . import checkpoint

        action = msg.data.get("action")
        names = msg.data.get("names")
        if action == "status":
            h = self._ckpt_async
            if h is None:
                return msg.reply(data={"status": "idle"}, rank=self.rank)
            if not h.done():
                return msg.reply(data={"status": "pending"},
                                 rank=self.rank)
            self._ckpt_async = None
            try:
                summary = h.wait(0)
            except Exception as e:
                return msg.reply(data={"error": f"async checkpoint "
                                                f"failed: {e}"},
                                 rank=self.rank)
            return msg.reply(data={"status": "done", "summary": summary},
                             rank=self.rank)
        path = msg.data["path"]
        self._flight.record("checkpoint", action=action, path=path,
                            background=bool(msg.data.get("background")))
        if action == "save":
            if not names:
                return msg.reply(
                    data={"error": "checkpoint save requires a non-empty "
                                   "list of names"}, rank=self.rank)
            if msg.data.get("background"):
                prev = self._ckpt_async
                if prev is not None and not prev.done():
                    return msg.reply(
                        data={"error": "a background checkpoint is "
                                       "already in flight (poll it "
                                       "with %dist_checkpoint "
                                       "--status first)"},
                        rank=self.rank)
                reply: dict = {"status": "started", "summary": {}}
                if prev is not None:
                    # Completed but never polled: its outcome —
                    # especially a FAILURE — must not vanish silently.
                    try:
                        prev.wait(0)
                    except Exception as e:
                        reply["previous_error"] = (
                            f"previous background checkpoint failed "
                            f"unpolled: {e}")
                self._ckpt_async = checkpoint.save_async(
                    path, self.namespace, names, rank=self.rank,
                    world_size=self.world_size)
                return msg.reply(data=reply, rank=self.rank)
            with obs_spans.maybe_span("checkpoint/save",
                                      kind="checkpoint",
                                      attrs={"path": path}):
                summary = checkpoint.save(path, self.namespace, names,
                                          rank=self.rank,
                                          world_size=self.world_size)
        elif action == "restore":
            with obs_spans.maybe_span("checkpoint/restore",
                                      kind="checkpoint",
                                      attrs={"path": path}):
                summary = checkpoint.restore(path, self.namespace, names,
                                             rank=self.rank)
        else:
            return msg.reply(data={"error": f"unknown checkpoint action "
                                            f"{action!r}"}, rank=self.rank)
        return msg.reply(data={"status": action, "summary": summary},
                         rank=self.rank)

    def _handle_profile(self, msg: Message) -> Message:
        """jax.profiler start/stop, idempotent.  ``_profile_dir`` is
        the source of truth for "a trace is running" — a second start
        and a stop-without-start reply with a clear ``{status, error}``
        instead of the opaque profiler traceback, and stop reports the
        directory the trace was actually STARTED with rather than
        trusting the stop message's ``log_dir``."""
        import jax
        action = msg.data.get("action")
        if action == "start":
            if self._profile_dir is not None:
                return msg.reply(
                    data={"status": "profiling",
                          "log_dir": self._profile_dir,
                          "error": "a profiler trace is already running "
                                   f"(started with {self._profile_dir}); "
                                   "stop it first"},
                    rank=self.rank)
            log_dir = f"{msg.data.get('log_dir', '/tmp/nbd_profile')}" \
                      f"/rank{self.rank}"
            try:
                jax.profiler.start_trace(log_dir)
            except Exception as e:
                return msg.reply(data={"status": "idle",
                                       "error": f"start_trace failed: {e}"},
                                 rank=self.rank)
            self._profile_dir = log_dir
            return msg.reply(data={"status": "profiling",
                                   "log_dir": log_dir}, rank=self.rank)
        if action == "stop":
            if self._profile_dir is None:
                return msg.reply(
                    data={"status": "idle",
                          "error": "no profiler trace is running"},
                    rank=self.rank)
            log_dir, self._profile_dir = self._profile_dir, None
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                return msg.reply(data={"status": "idle",
                                       "log_dir": log_dir,
                                       "error": f"stop_trace failed: {e}"},
                                 rank=self.rank)
            return msg.reply(data={"status": "stopped",
                                   "log_dir": log_dir}, rank=self.rank)
        return msg.reply(data={"error": f"unknown profile action "
                                        f"{action!r}"}, rank=self.rank)

    # ------------------------------------------------------------------
    # observability handlers (ISSUE 2)

    def _handle_trace(self, msg: Message) -> Message:
        """Span-trace control: ``start`` (adopting the coordinator's
        trace id so all processes share one), ``stop``, ``dump``
        (spans + instants + this plan's fault events, for the merged
        export), ``status``."""
        data = msg.data or {}
        action = data.get("action", "status")
        tr = self._tracer
        if action == "start":
            tid = tr.start(trace_id=data.get("trace_id"))
            return msg.reply(data={"status": "tracing", "trace_id": tid},
                             rank=self.rank)
        if action == "stop":
            n = tr.stop()
            return msg.reply(data={"status": "stopped", "spans": n},
                             rank=self.rank)
        if action == "dump":
            plan = self._fault_plan
            return msg.reply(
                data={"status": "ok", "trace": tr.dump(),
                      "fault_events": plan.events() if plan is not None
                      else []},
                rank=self.rank)
        return msg.reply(
            data={"status": "tracing" if tr.enabled else "off",
                  "spans": len(tr), "trace_id": tr.trace_id},
            rank=self.rank)

    def _handle_metrics(self, msg: Message) -> Message:
        """Snapshot the process metrics registry, mirroring the
        resilience counters (dedup hits, fault injections) into it
        first so one export carries everything."""
        reg = obs_metrics.registry()
        reg.gauge("nbd_dedup_hits",
                  "redelivered requests answered from the replay "
                  "cache").set(self._replay.hits)
        # Flight-ring health (ISSUE 13 satellite): utilization, wraps,
        # overwritten/truncated/dropped — evidence-loss visibility.
        flightrec.export_health(reg)
        plan = self._fault_plan
        if plan is not None:
            for action, n in plan.counters.items():
                reg.gauge("nbd_fault_injections",
                          "fault-plan decisions by action",
                          {"action": action}).set(n)
        if (msg.data or {}).get("format") == "prometheus":
            return msg.reply(data={"status": "ok",
                                   "text": reg.prometheus_text()},
                             rank=self.rank)
        return msg.reply(data={"status": "ok", "metrics": reg.to_json()},
                         rank=self.rank)

    # ------------------------------------------------------------------
    # durable sessions (ISSUE 4): hello/mailbox handlers + orphan grace

    def _handle_hello(self, msg: Message) -> Message:
        """Session handover: a (re)attaching coordinator proves the
        session token and presents its epoch.  An epoch >= ours is
        adopted (frames from any older coordinator are rejected from
        then on); a LOWER one is itself stale — two kernels racing to
        attach resolve to whichever bumped the manifest last."""
        data = msg.data or {}
        if self._session_token and data.get("token") != self._session_token:
            self._flight.record("hello_rejected", reason="token")
            return msg.reply(data={"error": "session token mismatch "
                                            "(not this fleet's session)"},
                             rank=self.rank)
        try:
            epoch = int(data.get("epoch") or 0)
        except (TypeError, ValueError):
            return msg.reply(data={"error": "bad epoch"}, rank=self.rank)
        if epoch < self._epoch:
            self._flight.record("hello_rejected", reason="stale_epoch",
                                offered=epoch, epoch=self._epoch)
            return msg.reply(
                data={"error": f"stale epoch {epoch} < {self._epoch}"},
                rank=self.rank)
        prev, self._epoch = self._epoch, epoch
        # Multi-host session bootstrap: workers spawned through an
        # agent/ssh plan carry no NBD_SESSION_TOKEN env — the first
        # hello supplies it (later hellos are then token-verified), and
        # mirrors the session manifest so the orphan reconnect loop can
        # discover a replacement endpoint WITHOUT the shared run-dir
        # filesystem durable sessions assume on one host.
        if self._session_token is None and data.get("token"):
            self._session_token = str(data["token"])
        mirror = data.get("manifest")
        if isinstance(mirror, dict):
            self._manifest_mirror = mirror
        self._flight.record("hello", epoch=epoch, prev_epoch=prev)
        return msg.reply(
            data={"status": "ok", "rank": self.rank, "pid": os.getpid(),
                  "epoch": epoch, "world_size": self.world_size,
                  "parked": self._mailbox.ids(),
                  "dedup_hits": self._replay.hits,
                  "namespace_size": len(self.namespace)},
            rank=self.rank)

    def _handle_mailbox(self, msg: Message) -> Message:
        """Parked-result redelivery.  ``drain`` claims every parked
        reply (destructive — exactly once; a REDELIVERED drain is
        answered from the replay cache, which caches this very reply);
        ``claim`` takes one by msg_id; default reports state."""
        action = (msg.data or {}).get("action", "status")
        if action == "drain":
            claimed = self._mailbox.claim_all()
            try:
                reply = msg.reply(
                    data={"status": "ok",
                          "results": {mid: getattr(r, "data", None)
                                      for mid, r in claimed.items()}},
                    rank=self.rank)
            except BaseException:
                # Destructive claim: repark before unwinding or the
                # parked results are gone and the reattaching
                # coordinator's drain finds an empty box.
                for mid, r in claimed.items():
                    self._mailbox.park(mid, r)
                raise
            self._flight.record("mailbox_drained", n=len(claimed))
            return reply
        if action == "claim":
            r = self._mailbox.claim((msg.data or {}).get("msg_id", ""))
            return msg.reply(
                data={"status": "ok",
                      "result": getattr(r, "data", None)},
                rank=self.rank)
        return msg.reply(
            data={"status": "ok", "parked": self._mailbox.ids(),
                  "counters": self._mailbox.counters()},
            rank=self.rank)

    def _handle_tenant_gc(self, msg: Message) -> Message:
        """Drop an evicted tenant's namespace.  The gateway broadcasts
        this when a clean detach frees the tenant's admission slot —
        without it the namespace (and every device array in it) lives
        forever, and a LATER unrelated tenant reusing the name would
        inherit the old tenant's state."""
        name = (msg.data or {}).get("tenant")
        existed = name in self._tenant_ns
        if existed:
            del self._tenant_ns[name]
            self._flight.record("tenant_ns_dropped", tenant=name)
        return msg.reply(data={"status": "ok", "existed": existed},
                         rank=self.rank)

    # ------------------------------------------------------------------
    # serving loop (ISSUE 11): the gateway drives a DecodeServer here

    def _handle_serve_open(self, msg: Message) -> Message:
        """Build (or reset) this rank's :class:`DecodeServer` for a
        serving tenant from names in that tenant's namespace.  The
        gateway opens the decode rank lazily and re-opens on the next
        live rank after a failover — the namespace (params/config) is
        already seeded on every rank by the serve_start model-spec
        cell, so any rank can take over."""
        from ..models import DecodeServer

        data = msg.data or {}
        tenant = data.get("tenant") or msg.tenant
        ns = self._ns_for(tenant)
        pname = data.get("params") or "params"
        cname = data.get("cfg") or "cfg"
        if pname not in ns or cname not in ns:
            return msg.reply(
                data={"error": f"serving namespace is missing "
                               f"{pname!r}/{cname!r} — run the model "
                               f"spec first (%dist_serve start)"},
                rank=self.rank)
        # Serving fast path (ISSUE 17): paged KV geometry + chunked
        # prefill, forwarded from the gateway's serve_open.  A chunk
        # size implies interleaved prefill — long prompts advance one
        # chunk per tick between decode steps so TPOT stays bounded.
        kw: dict = {}
        if data.get("kv_block_tokens"):
            kw["kv_block_tokens"] = int(data["kv_block_tokens"])
            if data.get("kv_blocks"):
                kw["kv_blocks"] = int(data["kv_blocks"])
        if data.get("prefill_chunk"):
            kw["prefill_chunk"] = int(data["prefill_chunk"])
            kw["interleave_prefill"] = True
        if data.get("kv_quantized"):
            kw["kv_quantized"] = True
        # Shard the decode across this rank's addressable devices via
        # NamedSharding when the KV heads divide evenly (a local
        # tensor-parallel mesh; CPU CI has one device -> no mesh).
        try:
            import jax
            local = jax.local_devices()
            n_kv = int(getattr(ns[cname], "n_kv_heads", 0) or 0)
            if len(local) > 1 and n_kv and n_kv % len(local) == 0:
                from ..parallel.mesh import make_mesh
                kw["mesh"] = make_mesh({"tp": len(local)},
                                       devices=local)
        except Exception:
            pass
        try:
            server = DecodeServer(
                ns[pname], ns[cname],
                max_batch=int(data.get("max_batch") or 8),
                max_len=int(data.get("max_len") or 512),
                pad_to=int(data.get("pad_to") or 16),
                eos_id=data.get("eos_id"),
                temperature=float(data.get("temperature") or 0.0),
                **kw)
        except Exception as e:
            return msg.reply(data={"error": f"DecodeServer build "
                                            f"failed: {e}"},
                             rank=self.rank)
        self._serve[tenant] = _WorkerServe(server)
        self._publish_serve_snap()
        self._flight.record("serve_open", tenant=tenant,
                            max_batch=server._B, max_len=server._T)
        return msg.reply(data={"status": "open", "slots": server._B},
                         rank=self.rank)

    def _handle_serve_step(self, msg: Message) -> Message:
        """One decode tick: admit new requests, run up to ``steps``
        decode steps, reply with per-request emissions AT OFFSETS.
        ``release`` frees finished requests' host-side records.  The
        reply is cached by the replay cache like any mutating request,
        so a redelivered tick never decodes twice."""
        data = msg.data or {}
        tenant = data.get("tenant") or msg.tenant
        st = self._serve.get(tenant)
        if st is None:
            return msg.reply(
                data={"error": "no serving loop open on this rank "
                               "(serve_open first)"},
                rank=self.rank)
        errors: dict[str, str] = {}
        for a in data.get("admit") or ():
            rid = a.get("rid")
            try:
                local = st.server.submit([int(t) for t in a["prompt"]],
                                         int(a["max_new"]))
            except Exception as e:
                errors[rid] = f"{type(e).__name__}: {e}"
                continue
            st.rids[rid] = local
            st.sent[rid] = 0
        for rid in data.get("release") or ():
            local = st.rids.pop(rid, None)
            st.sent.pop(rid, None)
            if local is not None:
                try:
                    st.server.release(local)
                except (KeyError, ValueError):
                    # Still pending or mid-(chunked-)prefill: cancel
                    # instead — frees its queue entry and KV blocks.
                    try:
                        st.server.cancel(local)
                    except Exception:
                        pass
        steps = max(0, int(data.get("steps") or 0))
        t_step0 = time.perf_counter()
        for _ in range(steps):
            if st.server.done():
                break
            st.server.step()
        step_s = time.perf_counter() - t_step0
        emitted: dict[str, dict] = {}
        finished: list[str] = []
        for rid, local in st.rids.items():
            out = st.server.outputs.get(local, [])
            o = st.sent.get(rid, 0)
            if len(out) > o:
                emitted[rid] = {"o": o, "t": [int(t) for t in out[o:]]}
                st.tokens_total += len(out) - o
                st.sent[rid] = len(out)
            if local in st.server.finished:
                finished.append(rid)
        st.note_rate()
        self._publish_serve_snap()
        # Tick telemetry (ISSUE 18): compute seconds, the tick's
        # prefill/decode token split (deltas of the server's
        # cumulative counters), and per-request prefill progress —
        # the gateway's serving observatory clock-corrects the wall
        # stamp and attributes the compute to active requests.
        pf_tot = getattr(st.server, "prefill_tokens_total", 0)
        dc_tot = getattr(st.server, "decode_tokens_total", 0)
        pf_d, dc_d = pf_tot - st.pf_seen, dc_tot - st.dc_seen
        st.pf_seen, st.dc_seen = pf_tot, dc_tot
        local_rids = {v: k for k, v in st.rids.items()}
        pfp = {local_rids[lid]: [int(w), int(n)]
               for lid, (w, n) in st.server.prefill_progress().items()
               if lid in local_rids}
        return msg.reply(
            data={"status": "ok", "emitted": emitted,
                  "finished": finished, "errors": errors,
                  "active": st.server.n_active,
                  "slots": st.server._B,
                  "pending": len(st.server._pending),
                  "tick": {"now": time.time(),
                           "step_s": round(step_s, 6),
                           "pf": int(pf_d), "dc": int(dc_d)},
                  "pfp": pfp},
            rank=self.rank)

    def _handle_serve_close(self, msg: Message) -> Message:
        tenant = (msg.data or {}).get("tenant") or msg.tenant
        existed = tenant in self._serve
        if existed:
            del self._serve[tenant]
            self._flight.record("serve_close", tenant=tenant)
        self._publish_serve_snap()
        return msg.reply(data={"status": "ok", "existed": existed},
                         rank=self.rank)

    def _publish_serve_snap(self) -> None:
        """Atomically rebind the heartbeat's serving-telemetry view
        (tokens total, tokens/s, KV-slot occupancy) — the heartbeat
        thread reads the snapshot, never the live dict."""
        if not self._serve:
            self._serve_snap = None
            return
        tot = occ = slots = 0
        kv_used = kv_total = 0
        tps = 0.0
        frag = None
        for st in self._serve.values():
            tot += st.tokens_total
            occ += st.server.n_active
            slots += st.server._B
            tps += st.tokens_per_s()
            kv = st.server.kv_snapshot()
            if kv is not None:
                kv_used += kv["used"]
                kv_total += kv["blocks"]
                # Largest contiguous free run, min across tenants —
                # the most fragmented pool is the binding constraint
                # (%dist_top frag column, ISSUE 18).
                run = kv.get("largest_run")
                if run is not None:
                    frag = run if frag is None else min(frag, run)
        self._serve_snap = {"tok": tot, "tps": round(tps, 2),
                            "occ": occ, "slots": slots,
                            **({"kvb": [kv_used, kv_total]}
                               if kv_total else {}),
                            **({"frag": frag}
                               if frag is not None else {})}

    def _park(self, msg_type: str, msg_id: str, reply: Message) -> None:
        """Park a reply for redelivery to a future coordinator.
        Read-only replies are skipped (re-probing is safe and their
        staleness makes redelivery noise); mutating results — exactly
        what must not be lost or re-executed — are kept."""
        if msg_type in _READ_ONLY or msg_type in (
                "hello", "mailbox", "tenant_gc",
                # Serving ticks are NOT parked: the gateway's journal
                # is the authoritative stream record, and a successor
                # gateway re-opens a fresh DecodeServer and re-admits
                # from it — a parked tick reply would be stale noise.
                "serve_open", "serve_step", "serve_close"):
            return
        self._mailbox.park(msg_id, reply)
        obs_metrics.registry().counter(
            "nbd_mailbox_parked",
            "replies parked for redelivery after coordinator "
            "loss").inc()
        self._flight.record("mailbox_parked", msg_id=msg_id,
                            type=msg_type)

    def _say(self, text: str) -> None:
        """Orphan-path stdout: the spawning coordinator owned our
        stdout pipe, so after ITS death a plain print raises
        BrokenPipeError — precisely on the code path that exists to
        survive that death."""
        try:
            print(text, flush=True)
        except OSError:
            pass

    def _manifest_dial_host(self, ctl: dict) -> str:
        """The address this worker should dial from a manifest control
        block.  Manifests written on the coordinator's host may record
        a loopback dial address (fine for same-host workers); a worker
        that originally dialed a non-loopback address must keep doing
        so — its loopback is a different machine."""
        host = ctl.get("host") or self._coordinator_host
        if host in ("127.0.0.1", "localhost") \
                and self._coordinator_host not in ("127.0.0.1",
                                                   "localhost"):
            return self._coordinator_host
        return host

    def _coordinator_endpoint(self) -> tuple[str, int, bool]:
        """Where the reconnect loop should dial: the session manifest's
        endpoint when one exists for OUR session (a reattaching
        coordinator that couldn't re-bind the old port publishes its
        replacement there), else the hello-mirrored manifest (multi-
        host worlds share no run-dir filesystem), else the spawn-time
        endpoint.

        The third element is ``expect_hello``: True when the manifest
        epoch is AHEAD of ours — a new coordinator has claimed the
        fleet and will hello promptly, so a listener at that endpoint
        that never sends a frame is an impostor (an unrelated process
        on a recycled port), not a coordinator.  A same-epoch endpoint
        is the ORIGINAL coordinator (transient reconnect) and may
        legitimately be idle, so no traffic is demanded of it."""
        d = knobs.get_str("NBD_RUN_DIR")
        candidates = []
        if d:
            try:
                from ..resilience.session import read_manifest
                candidates.append(read_manifest(d))
            except Exception:
                pass
        candidates.append(self._manifest_mirror)
        for m in candidates:
            if m is None or not isinstance(m, dict):
                continue
            if self._session_token \
                    and m.get("token") != self._session_token:
                continue
            ctl = m.get("control") or {}
            try:
                return (self._manifest_dial_host(ctl),
                        int(ctl.get("port") or self._control_port),
                        int(m.get("epoch") or 0) > self._epoch)
            except (TypeError, ValueError):
                continue
        return self._coordinator_host, self._control_port, False

    def _enter_orphan_and_wait(self) -> bool:
        """The coordinator is gone: park the result it may never have
        read, then poll the control endpoint until a fresh coordinator
        listens there (True — resume serving) or the TTL expires
        (False — self-terminate).  The heartbeat thread keeps running
        throughout; its sends start succeeding the moment the channel
        is swapped, which is also the new coordinator's liveness
        signal."""
        ttl = self._orphan_ttl
        if ttl <= 0 or self._shutdown.is_set():
            return False
        last, self._last_reply = self._last_reply, None
        if last is not None:
            # This reply's send "succeeded" into a socket whose reader
            # may already have been dead — keep it claimable.
            self._park(*last)
        self._orphaned = True
        obs_metrics.registry().counter(
            "nbd_orphan_transitions",
            "orphan state machine transitions",
            {"event": "entered"}).inc()
        self._flight.record("orphan_entered", ttl_s=ttl,
                            parked=len(self._mailbox))
        self._flight.flush()
        self._say(f"[worker {self.rank}] coordinator lost — orphaned, "
                  f"awaiting reattach for {ttl:.0f}s")
        deadline = time.monotonic() + ttl
        while not self._shutdown.is_set():
            plan = self._fault_plan
            if (plan is not None and plan.has_links()
                    and plan.link_blocked(self._host_label,
                                          self._coord_label)):
                # The injected partition is still open: locally the
                # dial would succeed (there is no real cable to cut),
                # which would void the emulation — wait it out, still
                # inside THIS episode's TTL.
                if time.monotonic() >= deadline:
                    break
                self._shutdown.wait(ORPHAN_RECONNECT_POLL_S)
                continue
            host, port, expect_hello = self._coordinator_endpoint()
            try:
                ch = WorkerChannel(host, port, rank=self.rank,
                                   auth_token=self._auth_token,
                                   connect_timeout=5.0)
            except Exception:
                ch = None
            if ch is not None and expect_hello:
                # A NEW coordinator published this endpoint (manifest
                # epoch ahead of ours): its hello must arrive or this
                # listener isn't it — a bare TCP accept must not count
                # as a reattach, or an unrelated process on a recycled
                # port would absorb the worker forever and void the
                # TTL contract.  The wait stays inside THIS episode's
                # deadline, so a silent impostor can't extend grace.
                step = min(30.0, max(1.0, deadline - time.monotonic()))
                try:
                    self._resume_msg = ch.recv(timeout=step)
                except Exception:
                    try:
                        ch.close()
                    except Exception:
                        pass
                    ch = None
            if ch is not None:
                ch.fault_plan = self._fault_plan
                ch.local_host = self._host_label
                ch.peer_host = self._coord_label
                old, self.channel = self.channel, ch
                try:
                    old.close()
                except Exception:
                    pass
                self._orphaned = False
                self._hb_fail_streak = 0
                obs_metrics.registry().counter(
                    "nbd_orphan_transitions",
                    "orphan state machine transitions",
                    {"event": "reattached"}).inc()
                self._flight.record("orphan_reattached",
                                    host=host, port=port)
                self._say(f"[worker {self.rank}] reattached to "
                          f"coordinator at {host}:{port}")
                return True
            if time.monotonic() >= deadline:
                break
            self._shutdown.wait(ORPHAN_RECONNECT_POLL_S)
        obs_metrics.registry().counter(
            "nbd_orphan_transitions",
            "orphan state machine transitions",
            {"event": "expired"}).inc()
        self._flight.record("orphan_expired", ttl_s=ttl,
                            parked=len(self._mailbox))
        self._flight.flush()
        self._say(f"[worker {self.rank}] orphan TTL expired unclaimed "
                  "— self-terminating")
        return False

    # ------------------------------------------------------------------

    def run(self) -> None:
        """Serial request loop (reference: worker.py:181-246).  One request
        at a time per worker — ordering is the concurrency model."""
        handlers = {
            "execute": self._handle_execute,
            "get_var": self._handle_get_var,
            "set_var": self._handle_set_var,
            "sync": self._handle_sync,
            "get_status": self._handle_get_status,
            "get_namespace_info": self._handle_get_namespace_info,
            "profile": self._handle_profile,
            "checkpoint": self._handle_checkpoint,
            "chaos": self._handle_chaos,
            "guard": self._handle_guard,
            "trace": self._handle_trace,
            "metrics": self._handle_metrics,
            "hello": self._handle_hello,
            "mailbox": self._handle_mailbox,
            "tenant_gc": self._handle_tenant_gc,
            "serve_open": self._handle_serve_open,
            "serve_step": self._handle_serve_step,
            "serve_close": self._handle_serve_close,
            "xfer_begin": self._handle_xfer_begin,
            "xfer_chunk": self._handle_xfer_chunk,
            "xfer_commit": self._handle_xfer_commit,
            "xfer_pull_begin": self._handle_xfer_pull_begin,
            "xfer_read": self._handle_xfer_read,
            "xfer_pull_end": self._handle_xfer_pull_end,
        }
        # Interrupt discipline: SIGINT (%dist_interrupt / forwarded
        # Ctrl-C) may only surface inside the two *interruptible*
        # windows — the idle recv select (aborts nothing, loop
        # continues) and the handler body (user code; execute converts
        # it to an error reply).  Everywhere else — dispatch
        # bookkeeping, reply construction, the reply send — the gated
        # handler records it as pending for the next window, so a
        # request can never lose its reply and a frame can never be
        # torn mid-write.  (A dropped reply would hang the coordinator
        # forever in the default timeout=None mode.)  The gate decides
        # in the Python handler itself, which CPython always runs on
        # the main thread — so it holds no matter which OS thread the
        # kernel picked for delivery (XLA/gloo pools spawned during
        # user code inherit an unblocked mask; a pthread-mask scheme
        # is defeated exactly there — see runtime/interrupt.py).
        gate = self._gate
        while not self._shutdown.is_set():
            try:
                # The channel scopes the gate's window to its select
                # wait: bytes can never be lost to an interrupt
                # mid-read (see WorkerChannel.recv); KI surfaces only
                # here.  A frame consumed while VALIDATING a reconnect
                # (the new coordinator's hello) is served first.
                msg = self._resume_msg or self.channel.recv(gate=gate)
                self._resume_msg = None
            except TransportError as e:
                # Coordinator gone.  Flight-record the EOF (with the
                # error text: a postmortem distinguishes "link
                # flapped" — eof then reattach — from "peer died":
                # eof then orphan expiry), then enter orphan grace and
                # wait for a fresh coordinator; only a TTL expiry (or
                # TTL 0) ends this process.
                self._flight.record("transport_eof",
                                    error=str(e)[:120],
                                    host=self._coordinator_host)
                if self._enter_orphan_and_wait():
                    continue
                break
            except KeyboardInterrupt:
                continue  # idle interrupt: nothing to abort
            # Latency observatory (ISSUE 13): the coordinator flagged
            # this request for stage stamping (`lt: 1`).  One flag
            # check when off — no stamps, no reply header, wire format
            # byte-identical.
            stamp = msg.latency is not None
            t_dq = time.time() if stamp else 0.0
            self._msg_seen += 1
            # A new request proves the coordinator consumed our last
            # reply (the serial request-response protocol: it only
            # sends the next request after reading the previous
            # response), so that reply no longer needs orphan-entry
            # parking — without this, every later orphanhood would
            # repark (and the next attach redeliver) a result the dead
            # coordinator already displayed.  The genuinely in-flight
            # request is still covered: its own reply send fails and
            # parks directly.
            self._last_reply = None
            # Flight event BEFORE the kill check: when an injected (or
            # real) preemption lands mid-request, the ring of the dead
            # process still names the fatal message — the postmortem's
            # anchor fact.
            self._flight.record("dispatch", msg_id=msg.msg_id,
                                type=msg.msg_type, attempt=msg.attempt)
            plan = self._fault_plan
            if plan is not None and plan.should_kill(self.rank,
                                                     self._msg_seen):
                # Injected preemption: die the way a preempted TPU VM
                # does — no teardown, no reply, mid-request.  (No flush
                # needed: the mmap's dirty pages outlive the process.)
                os.kill(os.getpid(), 9)  # SIGKILL
            # Epoch fence (durable sessions): after a reattach raised
            # our session epoch, frames stamped with an older one come
            # from a coordinator that no longer owns this fleet — a
            # stale kernel must be able to learn that, but never to
            # execute, mutate, or SHUT DOWN the fleet (checked before
            # the shutdown branch on purpose).  Only a hello can raise
            # the epoch, so it is exempt here.
            if (msg.epoch is not None and self._epoch
                    and msg.epoch < self._epoch
                    and msg.msg_type != "hello"):
                obs_metrics.registry().counter(
                    "nbd_epoch_rejected",
                    "frames rejected from a stale-epoch "
                    "coordinator").inc()
                self._flight.record("epoch_rejected", msg_id=msg.msg_id,
                                    type=msg.msg_type,
                                    frame_epoch=msg.epoch,
                                    epoch=self._epoch)
                try:
                    self.channel.send(msg.reply(
                        data={"error": f"stale coordinator epoch "
                                       f"{msg.epoch} (this fleet was "
                                       f"reattached at epoch "
                                       f"{self._epoch}); request "
                                       f"ignored",
                              "stale_epoch": True},
                        rank=self.rank))
                except Exception:
                    pass
                continue
            if msg.msg_type == "shutdown":
                break  # no response, by protocol (reference: worker.py:205)
            cached = self._replay.get(msg.msg_id)
            if cached is not None:
                # Redelivered request (retry layer or duplicated
                # frame): answer from the replay cache — NEVER run a
                # request twice (a re-run execute would double-apply
                # user state mutations).
                self._tracer.instant(f"dedup/{msg.msg_type}",
                                     kind="dedup",
                                     attrs={"msg_id": msg.msg_id,
                                            "attempt": msg.attempt})
                self._flight.record("dedup_hit", msg_id=msg.msg_id,
                                    attempt=msg.attempt)
                # Re-stamp with the CURRENT epoch: a reply cached under
                # a previous tenancy but redelivered to the coordinator
                # that legitimately adopted this worker is canonical,
                # not stale — only a worker still LIVING in the old
                # epoch sends old stamps.
                if self._epoch:
                    cached.epoch = self._epoch
                try:
                    self.channel.send(cached)
                except Exception:
                    # Channel died under the resend: keep the reply
                    # claimable and let recv surface the orphan path.
                    self._park(msg.msg_type, msg.msg_id, cached)
                continue
            handler = handlers.get(msg.msg_type)
            # Per-cell deadline budget (%%distributed --deadline S):
            # rides the execute payload, echoed back on heartbeats so
            # the coordinator's watchdog can escalate a cell that blew
            # its own budget without any coordinator-side bookkeeping.
            deadline = None
            if isinstance(msg.data, dict):
                d = msg.data.get("deadline_s")
                if d is not None:
                    try:
                        deadline = float(d)
                    except (TypeError, ValueError):
                        deadline = None
            self._busy = (msg.msg_type, time.monotonic(), msg.msg_id,
                          deadline, msg.tenant)
            # Dispatch span: a child of the coordinator's send span
            # when the request carried the wire trace context, a root
            # span otherwise.  Activated around the handler so inner
            # spans (cell execution, checkpoint IO, collectives called
            # from user code) nest under it.
            tr = self._tracer
            span = None
            if tr.enabled:
                ctx = msg.trace or {}
                span_attrs = {"msg_id": msg.msg_id,
                              "attempt": msg.attempt}
                if msg.tenant is not None:
                    # Multi-tenant postmortems: export.py folds this
                    # into a per-tenant Perfetto track.
                    span_attrs["tenant"] = msg.tenant
                span = tr.begin(f"handle/{msg.msg_type}", kind="worker",
                                trace_id=ctx.get("tid"),
                                parent_id=ctx.get("sid"),
                                attrs=span_attrs)
            # Stage stamps: handler entry/exit bracket the execute
            # work; the compile-seconds delta (the jax.monitoring
            # listener telemetry already installed) splits XLA compile
            # out of it, so a cold cell's first run attributes its
            # compile as its own stage.
            cs0 = obs_telemetry.compile_seconds() if stamp else 0.0
            xs = time.time() if stamp else 0.0
            xe = 0.0
            try:
                if handler is None:
                    reply = msg.reply(
                        data={"error": f"unknown message type "
                                       f"{msg.msg_type!r}"},
                        rank=self.rank)
                elif gate.main_thread():
                    with gate.window(), tr.activate(span):
                        reply = handler(msg)
                else:
                    with tr.activate(span):
                        reply = handler(msg)
            except KeyboardInterrupt:
                # Interrupt racing a non-execute handler: report and
                # keep serving (execute handles its own, in executor).
                reply = msg.reply(data={"error": "KeyboardInterrupt"},
                                  rank=self.rank)
            except Exception as e:
                reply = msg.reply(
                    data={"error": str(e),
                          "traceback": traceback.format_exc()},
                    rank=self.rank)
            finally:
                if stamp:
                    xe = time.time()
                self._busy = None
                tr.end(span)
            if stamp:
                # Worker-clock stage stamps, riding home in the
                # reply's `lt` header: dequeue, handler entry/exit,
                # compile seconds inside the handler, reply build.
                # The coordinator corrects them onto its timebase with
                # the clock estimator's per-rank offset.
                reply.latency = {
                    "dq": round(t_dq, 6), "xs": round(xs, 6),
                    "xe": round(xe, 6),
                    "cs": round(
                        obs_telemetry.compile_seconds() - cs0, 6),
                    "rs": round(time.time(), 6),
                }
            # Epoch-stamp the reply (worker→coordinator direction): a
            # coordinator that healed replacements while we were
            # partitioned away must reject THIS tenancy's results
            # rather than double-apply them (unstamped when epoch 0 —
            # pre-epoch sessions keep their wire format).
            if self._epoch and reply.epoch is None:
                reply.epoch = self._epoch
            self._replay.put(msg, reply)
            try:
                self.channel.send(reply)  # gate closed: frame is atomic
                self._last_reply = (msg.msg_type, msg.msg_id, reply)
            except Exception:
                # No coordinator to land the result on: park it for
                # redelivery (mutating types) and loop — the next recv
                # raises TransportError, which is the orphan entry.
                self._park(msg.msg_type, msg.msg_id, reply)
                continue
            if self._install_plan is not None:
                # A %dist_chaos 'set' armed during this request: its
                # ack is on the wire, start injecting now.
                self._set_fault_plan(self._install_plan[0])
                self._install_plan = None

    def shutdown(self) -> None:
        """Teardown (reference: worker.py:569-580)."""
        self._flight.record("worker_shutdown", rank=self.rank)
        self._flight.flush()
        self._shutdown.set()
        try:
            self.channel.close()
        except Exception:
            pass
        if self.world_size > 1:
            try:
                self._jax.distributed.shutdown()
            except Exception:
                pass


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="nbdistributed_tpu worker")
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--world-size", type=int, required=True)
    p.add_argument("--coordinator-host", default="127.0.0.1")
    p.add_argument("--control-port", type=int, required=True)
    p.add_argument("--dist-port", type=int, default=None,
                   help="jax.distributed coordinator port (omit for "
                        "single-process worlds)")
    p.add_argument("--dist-host", default=None,
                   help="jax.distributed coordinator host = rank 0's "
                        "host (default: --coordinator-host)")
    p.add_argument("--backend", default=None, choices=[None, "cpu", "tpu"],
                   help="force a JAX platform (cpu for tests/CI)")
    args = p.parse_args(argv)

    # Install the interrupt gate (closed) before the slow init phase.
    # The HELLO (readiness signal) goes out during __init__, so a
    # %dist_interrupt can arrive while this process is still seeding
    # its namespace — before run() establishes the window discipline.
    # A closed gate makes such an early interrupt *pending* until the
    # first idle recv window, where it aborts nothing and the loop
    # continues — instead of killing a half-initialized worker.
    gate = InterruptGate()
    if threading.current_thread() is threading.main_thread():
        gate.install()

    worker = DistributedWorker(
        rank=args.rank, world_size=args.world_size,
        coordinator_host=args.coordinator_host,
        control_port=args.control_port, dist_port=args.dist_port,
        backend=args.backend, dist_host=args.dist_host, gate=gate,
        # NBD_FAULT_PLAN (JSON spec): deterministic fault injection
        # from process start — how CI chaos tests seed a worker.
        fault_plan=FaultPlan.from_env())
    try:
        worker.run()
    finally:
        worker.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
