"""Worker runtime (layer L1, SURVEY §1): REPL executor, namespace
introspection, per-rank worker process."""

from .executor import execute_cell

__all__ = ["execute_cell"]
