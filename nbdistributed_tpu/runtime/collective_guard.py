"""Runtime collective-hazard guard — SURVEY §5.2's coordinator-side
check, upgraded to call-time enforcement.

The magic layer's pre-flight regex scan (magics/magic.py) warns on
textual matches only: it misses aliased or indirect collective calls
and fires on comments/strings.  This module is the RUNTIME truth:

* the coordinator stamps every execute request with its target ranks
  (``{"code": ..., "target_ranks": [...]}``);
* the worker publishes them for the duration of the cell
  (:func:`begin_cell` / :func:`end_cell` around the executor);
* every eager world-collective (parallel/collectives.py) calls
  :func:`check` on entry.

A world-collective entered by a strict subset of the mesh can never
complete — the absent ranks never join — so :func:`check` raises
:class:`CollectiveHazardError` immediately; the error surfaces
through the normal per-rank error path BEFORE the control plane
would hang waiting on a reply that cannot come.  (In-jit
``lax.psum`` over a worker-local device mesh is a different thing —
device-level, completes locally — and is deliberately not guarded.)

The cell's collective call count and code hash also ride the execute
response (``collective_ops`` / ``cell_sha1``), giving the
coordinator a per-cell record of which ranks ran collective-bearing
code; the magic layer warns on subset records too, covering calls
that happen to complete locally (e.g. a single-process world where
``all_reduce`` is the identity).

The worker's message loop is serial, so plain module state suffices;
a user thread calling a collective outside any cell sees inactive
state and passes.

Beyond the hazard check, this module is also the worker-side half of
the **hang watchdog** (ISSUE 5): every guarded entry advances a
monotonic per-process collective sequence and publishes a compact
``(seq, op, entered-at, in-flight)`` snapshot that the heartbeat
thread piggybacks on its pings.  The coordinator's watchdog compares
these positions across ranks — "ranks 0–2 entered ``all_reduce`` #7,
rank 3 never did" is the signature of a wedged rank that heartbeats
alone can never show (the process is alive; it is just stuck).  The
snapshot is a single tuple replaced atomically, so the heartbeat
thread's read can never tear against the main thread's write.
"""

from __future__ import annotations

import contextlib
import hashlib
import time


class CollectiveHazardError(RuntimeError):
    """A world-collective was invoked from a cell running on a strict
    subset of the mesh — raised at call time instead of deadlocking
    the cluster."""


_state: dict = {"targets": None, "world": 0, "ops": 0, "nested": 0}

# Collective progress stream (hang watchdog, ISSUE 5).  ``_snap`` is
# the atomically-replaced snapshot tuple ``(seq, op, entered_at_mono,
# in_flight)``; ``seq`` is monotonic over the PROCESS lifetime (not
# reset per cell) so the coordinator can order positions across cells
# without extra bookkeeping.  ``_freeze_hook`` is the chaos harness's
# injection point: called at every guarded entry with (op, seq) and
# may block — how a test freezes a rank "inside" a collective.
_snap: tuple | None = None
_freeze_hook = None


def set_freeze_hook(fn) -> None:
    """Install (or clear, with ``None``) the chaos freeze hook — a
    callable ``(op, seq)`` run at each guarded collective entry, on
    the cell's own thread, allowed to sleep.  Wired by the worker from
    its :class:`~nbdistributed_tpu.resilience.faults.FaultPlan`."""
    global _freeze_hook
    _freeze_hook = fn


def progress() -> dict | None:
    """Compact position-in-the-collective-stream snapshot for the
    heartbeat piggyback: ``{"seq", "op", "in", "age", "cops"}`` —
    global sequence number, last op entered, whether the rank is
    still inside it, seconds since entry (monotonic clock), and the
    current cell's op count.  ``None`` before the first collective
    (keeps idle pings small)."""
    s = _snap
    if s is None:
        return None
    seq, op, t, in_flight = s
    return {"seq": seq, "op": op, "in": in_flight,
            "age": round(time.monotonic() - t, 3),
            "cops": _state["ops"]}


@contextlib.contextmanager
def nested():
    """Context manager for composite collectives (scatter/gather/
    reduce) delegating to guarded primitives: the composite counts
    itself once via :func:`check`, then suppresses the inner
    primitives' counts so one user-level call records one op (the
    subset raise already happened at the composite's own check)."""
    _state["nested"] += 1
    try:
        yield
    finally:
        _state["nested"] -= 1


def begin_cell(targets, world: int) -> None:
    """Publish the current cell's target ranks (``None`` = unknown —
    legacy raw-string execute requests — which disables the subset
    check but keeps the op count)."""
    _state["targets"] = None if targets is None else sorted(targets)
    _state["world"] = int(world)
    _state["ops"] = 0


def end_cell() -> int:
    """Clear the cell context; returns the number of eager
    world-collective calls the cell made."""
    ops = _state["ops"]
    _state["targets"], _state["world"], _state["ops"] = None, 0, 0
    _state["nested"] = 0
    return ops


def cell_hash(code: str) -> str:
    """Stable short id for a cell's source, reported alongside the
    collective count so the coordinator can correlate executions of
    the same cell across ranks."""
    return hashlib.sha1(code.encode()).hexdigest()[:12]


def check(op: str) -> None:
    """Entry hook for each eager world-collective.  Advances the
    progress stream (the watchdog's skew signal) BEFORE the hazard
    check so even a call that raises is on record, then runs the
    chaos freeze hook — which may block this rank right here, the
    deterministic stand-in for "wedged inside a collective"."""
    global _snap
    if _state["nested"]:
        return                  # implementation detail of a composite
    _state["ops"] += 1
    prev = _snap
    seq = (prev[0] if prev is not None else 0) + 1
    _snap = (seq, op, time.monotonic(), True)
    fz = _freeze_hook
    if fz is not None:
        fz(op, seq)
    targets, world = _state["targets"], _state["world"]
    if targets is not None and world and len(targets) < world:
        raise CollectiveHazardError(
            f"{op}() called from a cell running on ranks {targets} — "
            f"a strict subset of the {world}-rank mesh.  A "
            f"world-collective entered by a subset never completes "
            f"(the other ranks never join) and would deadlock the "
            f"cluster; run the cell on all ranks, or keep subset "
            f"cells to rank-local work.")


def done(op: str) -> None:
    """Exit hook for each eager world-collective (called by the
    ``_instrumented`` wrapper in a ``finally``, so an op that raised
    — hazard error, interrupt — is still marked not-in-flight).
    Nested composite internals are suppressed like :func:`check`."""
    global _snap
    if _state["nested"]:
        return
    s = _snap
    if s is not None and s[3]:
        _snap = (s[0], s[1], s[2], False)


def reset_progress() -> None:
    """Test helper: forget the progress stream (and any freeze hook)
    so suites that re-enter worlds start from seq 0."""
    global _snap, _freeze_hook
    _snap = None
    _freeze_hook = None
