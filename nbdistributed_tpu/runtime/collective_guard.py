"""Runtime collective-hazard guard — SURVEY §5.2's coordinator-side
check, upgraded to call-time enforcement.

The magic layer's pre-flight regex scan (magics/magic.py) warns on
textual matches only: it misses aliased or indirect collective calls
and fires on comments/strings.  This module is the RUNTIME truth:

* the coordinator stamps every execute request with its target ranks
  (``{"code": ..., "target_ranks": [...]}``);
* the worker publishes them for the duration of the cell
  (:func:`begin_cell` / :func:`end_cell` around the executor);
* every eager world-collective (parallel/collectives.py) calls
  :func:`check` on entry.

A world-collective entered by a strict subset of the mesh can never
complete — the absent ranks never join — so :func:`check` raises
:class:`CollectiveHazardError` immediately; the error surfaces
through the normal per-rank error path BEFORE the control plane
would hang waiting on a reply that cannot come.  (In-jit
``lax.psum`` over a worker-local device mesh is a different thing —
device-level, completes locally — and is deliberately not guarded.)

The cell's collective call count and code hash also ride the execute
response (``collective_ops`` / ``cell_sha1``), giving the
coordinator a per-cell record of which ranks ran collective-bearing
code; the magic layer warns on subset records too, covering calls
that happen to complete locally (e.g. a single-process world where
``all_reduce`` is the identity).

The worker's message loop is serial, so plain module state suffices;
a user thread calling a collective outside any cell sees inactive
state and passes.
"""

from __future__ import annotations

import contextlib
import hashlib


class CollectiveHazardError(RuntimeError):
    """A world-collective was invoked from a cell running on a strict
    subset of the mesh — raised at call time instead of deadlocking
    the cluster."""


_state: dict = {"targets": None, "world": 0, "ops": 0, "nested": 0}


@contextlib.contextmanager
def nested():
    """Context manager for composite collectives (scatter/gather/
    reduce) delegating to guarded primitives: the composite counts
    itself once via :func:`check`, then suppresses the inner
    primitives' counts so one user-level call records one op (the
    subset raise already happened at the composite's own check)."""
    _state["nested"] += 1
    try:
        yield
    finally:
        _state["nested"] -= 1


def begin_cell(targets, world: int) -> None:
    """Publish the current cell's target ranks (``None`` = unknown —
    legacy raw-string execute requests — which disables the subset
    check but keeps the op count)."""
    _state["targets"] = None if targets is None else sorted(targets)
    _state["world"] = int(world)
    _state["ops"] = 0


def end_cell() -> int:
    """Clear the cell context; returns the number of eager
    world-collective calls the cell made."""
    ops = _state["ops"]
    _state["targets"], _state["world"], _state["ops"] = None, 0, 0
    _state["nested"] = 0
    return ops


def cell_hash(code: str) -> str:
    """Stable short id for a cell's source, reported alongside the
    collective count so the coordinator can correlate executions of
    the same cell across ranks."""
    return hashlib.sha1(code.encode()).hexdigest()[:12]


def check(op: str) -> None:
    """Entry hook for each eager world-collective."""
    if _state["nested"]:
        return                  # implementation detail of a composite
    _state["ops"] += 1
    targets, world = _state["targets"], _state["world"]
    if targets is not None and world and len(targets) < world:
        raise CollectiveHazardError(
            f"{op}() called from a cell running on ranks {targets} — "
            f"a strict subset of the {world}-rank mesh.  A "
            f"world-collective entered by a subset never completes "
            f"(the other ranks never join) and would deadlock the "
            f"cluster; run the cell on all ranks, or keep subset "
            f"cells to rank-local work.")
