"""Worker-side SIGINT discipline: a Python-level gated handler.

``%dist_interrupt`` (and a forwarded Ctrl-C) delivers SIGINT to worker
processes (the Jupyter abort idiom; the reference framework's only
remedy for a stuck cell is destroying the cluster — reference:
magic.py:963-1003).  The worker must convert it into "abort the running
cell, keep serving" without ever (a) losing a reply — a dropped reply
hangs the coordinator forever in the default ``timeout=None`` mode —
or (b) tearing a half-written control-plane frame.

An earlier design scoped SIGINT with ``pthread_sigmask``: blocked in
the main thread except inside two windows (the idle recv ``select`` and
the user-code handler call).  That discipline has a structural hole in
any process with native threadpools: **a pthread mask only controls OS
delivery to that one thread, not CPython's signal handling.**  Threads
spawned lazily *during user code* — XLA compilation pools, gloo
collective threads, created inside the unmasked window — inherit an
unblocked SIGINT mask.  The kernel then delivers a process-directed
SIGINT to one of *them* while the main thread is "masked"; CPython's
C-level handler trips its process-global flag regardless, and the main
thread raises KeyboardInterrupt at its next bytecode — in the middle of
dispatch bookkeeping or the reply send, where a BaseException escapes
the run loop and tears the worker down.  (Reproduced deterministically:
one jitted matmul spawns five SIGINT-unblocked threads.)  That was the
round-2 interrupt-storm tail race: it needed cells that had compiled
something — which is why it only surfaced in loaded module runs, never
in 1200 standalone storm cycles.

This module replaces the pthread masks with a **gate checked in the
Python handler itself**.  CPython guarantees signal handlers execute in
the main thread, no matter which OS thread received the signal — so the
raise-or-defer decision is made exactly once, in Python, at handler
time:

* gate **open** (interruptible window)  -> raise ``KeyboardInterrupt``;
* gate **closed**                       -> record it as *pending*; the
  next window entry (or :meth:`shielded` exit) raises it.

Late handler runs are automatically safe: the decision happens when the
handler *runs*, not when the signal arrived, so a SIGINT that lands on
the last bytecode of a window and whose handler only executes after the
window closed becomes pending instead of escaping.  No mask, no flush,
no thread can defeat it.

All gate state is touched only by the main thread (the handler runs
there by CPython's guarantee, and windows are a main-thread-loop
construct), so the flags need no locking.
"""

from __future__ import annotations

import signal
import threading
from contextlib import contextmanager


class InterruptGate:
    """Decides, inside the SIGINT handler, whether to raise or defer."""

    def __init__(self):
        self._open = False
        self.pending = False
        self.installed = False

    # ------------------------------------------------------------------

    def install(self) -> "InterruptGate":
        """Install the gated handler (main thread only; call before any
        slow init so an early ``%dist_interrupt`` defers instead of
        killing a half-initialized worker)."""
        signal.signal(signal.SIGINT, self._handler)
        self.installed = True
        return self

    def _handler(self, signum, frame) -> None:
        if self._open:
            raise KeyboardInterrupt
        self.pending = True

    # ------------------------------------------------------------------

    @contextmanager
    def window(self):
        """An *interruptible* section: a pending interrupt is raised at
        entry; SIGINT inside raises ``KeyboardInterrupt`` at the point
        of execution; the gate closes again on exit (even via the raise
        itself)."""
        self._open = True
        try:
            if self.pending:
                self.pending = False
                raise KeyboardInterrupt
            yield
        finally:
            self._open = False

    @contextmanager
    def shielded(self):
        """An *uninterruptible* sub-section inside a window — e.g. a
        control-plane send mid-cell, which must never abandon a half-
        written frame.  A SIGINT during the block becomes pending and is
        raised at exit, after the protected operation completed, so the
        interrupt still aborts the surrounding cell promptly."""
        was = self._open
        self._open = False
        try:
            yield
        finally:
            if was:
                self._open = True
                if self.pending:
                    self.pending = False
                    raise KeyboardInterrupt

    # ------------------------------------------------------------------

    def main_thread(self) -> bool:
        """Gate operations are meaningful only on the main thread (the
        handler runs there); other threads must bypass the gate."""
        return threading.current_thread() is threading.main_thread()
