"""On-chip flash-attention block-size sweep.

Run on a live TPU (takes ~5-10 min of compiles):

    python tune_flash.py

Sweeps (block_q, block_k) for the flash kernel on the bench shapes,
timing with the chained-dependency pattern (each scan step's q depends
on the previous output; per-call time = (long-short chain)/delta with a
host fetch at the end) — the only timing that survives the axon
tunnel's async-ack behavior (see .claude/skills/verify/SKILL.md).

Prints per-config timings and **writes the tuned tables to
``nbdistributed_tpu/ops/tuned_blocks.json``** (see ``ops/_tuned.py``)
so every later process picks them up automatically — the sweep runs
unattended in a tunnel window, nobody is around to paste tables.
Also prints the tuned-vs-XLA speedup for BASELINE.md.

``NBD_TUNE_CPU_SMOKE=1`` shrinks the sweep to one tiny shape, lifts
the TPU gate, and writes the table to /tmp — an end-to-end harness
check runnable in CI (a sweep-script bug must not be discovered
during the live window it exists to exploit).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.ops.attention import flash_attention

SMOKE = bool(os.environ.get("NBD_TUNE_CPU_SMOKE"))

SHAPES = [
    # (name, B, S, H, Hkv, D) — the round-2 GQA bench shape first.
    ("gqa_bench", 4, 2048, 8, 2, 128),
    ("mha_r1", 4, 2048, 8, 8, 128),
    ("long_gqa", 1, 8192, 8, 2, 128),
]
BLOCKS = (128, 256, 512)
DECODE_SHAPES = [
    # (name, B, T, H, Hkv, D)
    ("smol_decode", 1, 2048, 9, 3, 64),
    ("llama7b_decode", 1, 2048, 32, 32, 128),
    ("gqa_long_decode", 1, 8192, 32, 8, 128),
]
if SMOKE:
    SHAPES = [("smoke", 1, 256, 2, 1, 64)]
    BLOCKS = (128, 256)
    DECODE_SHAPES = [("smoke_decode", 1, 256, 2, 2, 64)]


# The chained-delta protocol (fresh-input medians, value fetches,
# (long-short)/delta) lives in ops/timing.py — the SAME code path the
# bench flash cell and the watcher's preflight probe use, so a sweep
# measures exactly the program the bench times.  A <= 0 return means
# noise won; callers retry once then skip the row.
from nbdistributed_tpu.ops.timing import chained_delta_ms


def chain_ms(f, q, k, v, n1=2, n2=18):
    return chained_delta_ms(lambda qc: f(qc, k, v), q,
                            n1=n1, n2=n2)[0]


def grad_chain_ms(f, q, k, v, n1=2, n2=10):
    def step(qc):
        return jax.grad(lambda qq: f(qq, k, v).astype(
            jnp.float32).sum())(qc)

    return chained_delta_ms(step, q, n1=n1, n2=n2)[0]


def main() -> int:
    if jax.default_backend() != "tpu" and not SMOKE:
        print("tune_flash.py needs a live TPU "
              f"(backend={jax.default_backend()})", file=sys.stderr)
        return 1
    results = {}
    flash_tbl: dict = {}
    decode_tbl: dict = {}

    def checkpoint_tables():
        """Write the accumulated tables after EVERY shape: tunnel
        windows die mid-sweep (2026-08-01 did), and a partial table
        that includes the headline gqa entry beats a lost sweep.
        MERGED over the existing on-disk table — an early checkpoint
        must never gut a previous window's complete table down to the
        one shape measured so far (save() replaces the whole file)."""
        if flash_tbl or decode_tbl:
            from nbdistributed_tpu.ops import _tuned
            path = "/tmp/tuned_blocks_smoke.json" if SMOKE else None
            old_flash, old_decode = _tuned.load(path)
            p = _tuned.save(
                {**old_flash, **flash_tbl},
                {**old_decode, **decode_tbl},
                meta={"measured_at": time.strftime(
                          "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                      "device": jax.devices()[0].device_kind},
                path=path)
            results["tuned_blocks_path"] = p
            print(f"[tune] checkpointed {p}", file=sys.stderr)

    def valid(ms):
        return ms is not None and ms > 0

    for name, B, S, H, Hkv, D in SHAPES:
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D),
                              jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Hkv, D),
                              jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Hkv, D),
                              jnp.bfloat16)
        # XLA reference FIRST: a mid-sweep tunnel death still leaves
        # the comparison for whatever configs landed.  Same
        # noise-retry-then-None contract as the kernel rows — a spike
        # on a ref sample must not publish a negative "speedup".
        def _ref(q_, k_, v_):
            return attention_reference(q_, k_, v_, causal=True)
        ref_fwd = chain_ms(_ref, q, k, v)
        if not valid(ref_fwd):
            ref_fwd = chain_ms(_ref, q, k, v)
        ref_fb = grad_chain_ms(_ref, q, k, v)
        if not valid(ref_fb):
            ref_fb = grad_chain_ms(_ref, q, k, v)
        print(f"[{name}] XLA ref: fwd {ref_fwd:.3f} ms, fwd+bwd "
              f"{ref_fb:.3f} ms", file=sys.stderr)
        rows = []
        for bq in BLOCKS:
            for bk in BLOCKS:
                if bq > S or bk > S:
                    continue
                fl = functools.partial(flash_attention, causal=True,
                                       block_q=bq, block_k=bk)
                try:
                    fwd = chain_ms(fl, q, k, v)
                    if not valid(fwd):      # noise won: one retry
                        fwd = chain_ms(fl, q, k, v)
                except Exception as e:  # Mosaic rejects some shapes
                    print(f"[{name}] bq={bq} bk={bk}: FAILED {e}",
                          file=sys.stderr)
                    continue
                rows.append({"bq": bq, "bk": bk,
                             "fwd_ms": (round(fwd, 3) if valid(fwd)
                                        else None)})
                print(f"[{name}] bq={bq} bk={bk}: fwd {fwd:.3f} ms",
                      file=sys.stderr)
        ok_rows = [r for r in rows if valid(r["fwd_ms"])]
        if not ok_rows:
            # Every config failed to compile or measure: record that
            # and keep the other shapes' results.
            results[name] = {"shape": f"B{B} S{S} H{H} Hkv{Hkv} D{D}",
                             "rows": rows,
                             "error": "no block config measured"}
            continue
        # fwd+bwd sweep only for the top fwd configs: the bwd kernel
        # compiles are the expensive half of the sweep, and a config
        # outside the fwd top-3 never wins the combined time.
        ok_rows.sort(key=lambda r: r["fwd_ms"])
        for r in ok_rows[:3]:
            fl = functools.partial(flash_attention, causal=True,
                                   block_q=r["bq"], block_k=r["bk"])
            try:
                fb = grad_chain_ms(fl, q, k, v)
                if not valid(fb):
                    fb = grad_chain_ms(fl, q, k, v)
            except Exception as e:
                print(f"[{name}] bq={r['bq']} bk={r['bk']}: "
                      f"bwd FAILED {e}", file=sys.stderr)
                continue
            r["fwd_bwd_ms"] = round(fb, 3) if valid(fb) else None
            print(f"[{name}] bq={r['bq']} bk={r['bk']}: fwd+bwd "
                  f"{fb:.3f} ms", file=sys.stderr)
        with_fb = [r for r in ok_rows if valid(r.get("fwd_bwd_ms"))]
        best = (min(with_fb, key=lambda r: r["fwd_bwd_ms"])
                if with_fb else ok_rows[0])
        results[name] = {
            "shape": f"B{B} S{S} H{H} Hkv{Hkv} D{D} bf16 causal",
            "rows": rows,
            "xla_ref": {"fwd_ms": (round(ref_fwd, 3)
                                   if valid(ref_fwd) else None),
                        "fwd_bwd_ms": (round(ref_fb, 3)
                                       if valid(ref_fb) else None)},
            "best": best,
            "tuned_speedup_fwd": (round(ref_fwd / best["fwd_ms"], 3)
                                  if valid(ref_fwd) else None),
            "tuned_speedup_fwd_bwd": (
                round(ref_fb / best["fwd_bwd_ms"], 3)
                if valid(ref_fb) and valid(best.get("fwd_bwd_ms"))
                else None),
            # TUNED_BLOCKS key: (Sq, Sk, head_dim, gqa_group).
            "tuned_entry": {f"({S}, {S}, {D}, {H // Hkv})":
                            f"({best['bq']}, {best['bk']})"},
        }
        flash_tbl[(S, S, D, H // Hkv)] = (best["bq"], best["bk"])
        print(f"[{name}] best flash bq={best['bq']} bk={best['bk']}",
              file=sys.stderr)
        checkpoint_tables()
    # ---- decode kernel sweep: block_k over realistic cache shapes.
    from nbdistributed_tpu.ops.decode import flash_decode_attention

    for name, B, T, H, Hkv, D in DECODE_SHAPES:
        q = jax.random.normal(jax.random.PRNGKey(0), (B, H, D),
                              jnp.bfloat16)
        kc = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, T, D),
                               jnp.bfloat16)
        vc = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, T, D),
                               jnp.bfloat16)
        pos = jnp.full((B,), T - 1, jnp.int32)
        rows = []
        for bk in BLOCKS:
            if bk > T:
                continue
            try:
                ms = chain_ms(
                    lambda qc, k_, v_: flash_decode_attention(
                        qc, k_, v_, pos, block_k=bk),
                    q, kc, vc, n1=4, n2=36)
                if not valid(ms):           # noise won: one retry
                    ms = chain_ms(
                        lambda qc, k_, v_: flash_decode_attention(
                            qc, k_, v_, pos, block_k=bk),
                        q, kc, vc, n1=4, n2=36)
            except Exception as e:
                print(f"[{name}] block_k={bk}: FAILED {e}",
                      file=sys.stderr)
                continue
            if valid(ms):
                rows.append({"block_k": bk, "ms": round(ms, 4)})
            print(f"[{name}] block_k={bk}: {ms:.4f} ms",
                  file=sys.stderr)
        if not rows:
            results[name] = {"error": "no block_k measured"}
            continue
        best = min(rows, key=lambda r: r["ms"])
        results[name] = {
            "shape": f"B{B} T{T} H{H} Hkv{Hkv} D{D} bf16",
            "rows": rows, "best": best,
            # DECODE_TUNED_BLOCKS key: (T, head_dim, gqa_group).
            "tuned_entry": {f"({T}, {D}, {H // Hkv})":
                            best["block_k"]},
        }
        decode_tbl[(T, D, H // Hkv)] = best["block_k"]
        checkpoint_tables()

    print(json.dumps(results, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
