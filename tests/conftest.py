"""Test bootstrap: force the CPU backend with 8 virtual devices.

The container's sitecustomize pre-imports JAX with the axon TPU platform
in every Python process, so plain env vars in this file are too late for
platform selection — but backends initialize lazily, so a config update
before the first device query still wins.  Subprocess workers spawned by
integration tests get a scrubbed env via
``nbdistributed_tpu.manager.topology.cpu_worker_env`` instead.

Set ``NBD_TEST_TPU=1`` to leave the platform alone and run the suite on
the real chip (only meaningful for the single-device kernel/model tests;
Mosaic enforces block-shape rules that CPU interpret mode does not, so
an on-chip pass of ``tests/unit/test_attention.py`` etc. is stronger
evidence than the CPU run).
"""

import os
import sys

if not os.environ.get("NBD_TEST_TPU"):
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

REPO_ROOT = os.path.dirname(os.path.abspath(__file__ + "/.."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# ---------------------------------------------------------------------
# Segfault mitigation for long single-process runs: XLA's CPU backend
# intermittently crashed inside backend_compile_and_load at ~80% of the
# full suite (two different tests, both clean in isolation, box idle,
# RAM free) — consistent with per-process accumulation of hundreds of
# compiled executables, not with any single test.  Dropping executable
# references periodically keeps the accumulation bounded; every test
# after a clear simply recompiles (slower, correct).
_CLEAR_EVERY = int(os.environ.get("NBD_TEST_CLEAR_CACHES_EVERY", "150"))
_test_counter = {"n": 0}


def pytest_runtest_teardown(item, nextitem):
    _test_counter["n"] += 1
    if _CLEAR_EVERY and _test_counter["n"] % _CLEAR_EVERY == 0:
        try:
            import jax

            jax.clear_caches()
        except Exception:
            pass
