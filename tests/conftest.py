"""Test bootstrap: force the CPU backend with 8 virtual devices.

The container's sitecustomize pre-imports JAX with the axon TPU platform
in every Python process, so plain env vars in this file are too late for
platform selection — but backends initialize lazily, so a config update
before the first device query still wins.  Subprocess workers spawned by
integration tests get a scrubbed env instead (see helpers below).
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

REPO_ROOT = os.path.dirname(os.path.abspath(__file__ + "/.."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def cpu_worker_env(extra: dict | None = None) -> dict:
    """Environment for spawned worker subprocesses: CPU backend, no axon
    TPU registration, gloo cross-process collectives."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon sitecustomize
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    env.pop("XLA_FLAGS", None)  # one device per worker process
    if extra:
        env.update(extra)
    return env
