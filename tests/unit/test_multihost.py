"""Multi-host launch plans: pure-logic tier (no processes)."""

import sys

import pytest

from nbdistributed_tpu.manager import multihost
from nbdistributed_tpu.manager.multihost import (HostSpec, make_launch_plan,
                                                 parse_hosts, ssh_argv)


def test_parse_hosts_forms():
    assert parse_hosts("h1,h2:4,local:2") == [
        HostSpec("h1", 1), HostSpec("h2", 4), HostSpec("local", 2)]


@pytest.mark.parametrize("bad", ["", ":3", "h1:x", "h1:0", "h1:-2"])
def test_parse_hosts_rejects(bad):
    with pytest.raises(ValueError):
        parse_hosts(bad)


@pytest.mark.parametrize("dup", ["h1,h1", "h1,h2,h1:3", "local,local:2"])
def test_parse_hosts_rejects_duplicate_hosts(dup):
    """'h1,h1:2' is always a typo: the plan would double-book one box
    and the intended merge is ambiguous — refuse loudly."""
    with pytest.raises(ValueError, match="more than once"):
        parse_hosts(dup)


def test_make_launch_plan_rejects_duplicate_hostspecs():
    """Hand-built HostSpec lists get the same guard as the spec
    string."""
    with pytest.raises(ValueError, match="duplicate host"):
        make_launch_plan([HostSpec("h1"), HostSpec("h1", 2)],
                         coordinator_host="10.0.0.9", control_port=1,
                         dist_port=2, backend="cpu")


def test_plan_ranks_are_dense_and_unique():
    plan = make_launch_plan(
        [HostSpec("a", 2), HostSpec("b", 3), HostSpec("local", 1)],
        coordinator_host="10.0.0.9", control_port=1, dist_port=2,
        backend="cpu")
    assert [l.rank for l in plan] == list(range(6))
    # Every worker knows its host label (link shaping / diagnosis).
    for launch in plan:
        assert dict(launch.env)["NBD_HOST"] == launch.host


def test_parse_agents_forms_and_rejects():
    from nbdistributed_tpu.manager.hostagent import parse_agents
    assert parse_agents(None) == {}
    assert parse_agents("h1=10.0.0.2:7411,h2=10.0.0.3:8000") == {
        "h1": ("10.0.0.2", 7411), "h2": ("10.0.0.3", 8000)}
    assert parse_agents({"h1": ("a", 1)}) == {"h1": ("a", 1)}
    for bad in ("h1", "h1=addr", "h1=addr:xx", "=a:1",
                "h1=a:1,h1=b:2"):
        with pytest.raises(ValueError):
            parse_agents(bad)


def test_plan_assigns_ranks_host_major():
    plan = make_launch_plan(
        [HostSpec("a", 2), HostSpec("b", 1)], coordinator_host="10.0.0.9",
        control_port=7000, dist_port=7001, backend="cpu")
    assert [(l.rank, l.host) for l in plan] == [(0, "a"), (1, "a"),
                                                (2, "b")]
    for l in plan:
        argv = list(l.argv)
        assert argv[:3] == [sys.executable, "-m",
                            "nbdistributed_tpu.runtime.worker"]
        assert argv[argv.index("--rank") + 1] == str(l.rank)
        assert argv[argv.index("--world-size") + 1] == "3"
        assert argv[argv.index("--coordinator-host") + 1] == "10.0.0.9"
        assert argv[argv.index("--dist-port") + 1] == "7001"


def test_plan_rejects_loopback_coordinator_with_remote_hosts():
    with pytest.raises(ValueError, match="loopback"):
        make_launch_plan([HostSpec("remote1")],
                         coordinator_host="127.0.0.1", control_port=1,
                         dist_port=2, backend="tpu")


def test_plan_allows_loopback_for_all_local():
    plan = make_launch_plan([HostSpec("local", 2)],
                            coordinator_host="127.0.0.1", control_port=1,
                            dist_port=2, backend="cpu")
    assert len(plan) == 2
    assert dict(plan[0].env)["JAX_PLATFORMS"] == "cpu"


@pytest.mark.parametrize("host", ["podhost", "local"])
def test_tpu_plan_rejects_multiple_workers_per_host(host):
    with pytest.raises(ValueError, match="one worker per host"):
        make_launch_plan([HostSpec(host, 4)],
                         coordinator_host="10.0.0.9", control_port=1,
                         dist_port=2, backend="tpu")


def test_tpu_plan_ships_no_carving_env():
    plan = make_launch_plan([HostSpec("h1"), HostSpec("h2")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    # Only the host labels ride a TPU plan's env — no chip carving.
    for launch in plan:
        env = dict(launch.env)
        assert env.pop("NBD_HOST") == launch.host
        assert env.pop("NBD_COORD_HOST")
        assert env == {}


def test_dist_host_is_rank0_host_for_remote_plans():
    """jax.distributed's coordination service runs in rank 0's process,
    so the rendezvous address must be rank 0's host — not the kernel."""
    plan = make_launch_plan([HostSpec("tpu-w-0"), HostSpec("tpu-w-1")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    for l in plan:
        argv = list(l.argv)
        assert argv[argv.index("--dist-host") + 1] == "tpu-w-0"
        assert argv[argv.index("--coordinator-host") + 1] == "10.0.0.9"


def test_dist_host_is_coordinator_when_rank0_local():
    plan = make_launch_plan([HostSpec("local"), HostSpec("tpu-w-1")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    argv = list(plan[0].argv)
    assert argv[argv.index("--dist-host") + 1] == "10.0.0.9"


def test_ssh_argv_quotes_and_targets_host():
    plan = make_launch_plan([HostSpec("tpu-w-3")],
                            coordinator_host="10.0.0.9", control_port=70,
                            dist_port=None, backend="cpu")
    argv = ssh_argv(plan[0])
    assert argv[0] == "ssh" and "tpu-w-3" in argv
    remote = argv[-1]
    assert remote.startswith("exec env ")
    assert "JAX_PLATFORMS=cpu" in remote
    assert "--rank 0" in remote and "--control-port 70" in remote
