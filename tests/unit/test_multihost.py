"""Multi-host launch plans: pure-logic tier (no processes)."""

import sys

import pytest

from nbdistributed_tpu.manager import multihost
from nbdistributed_tpu.manager.multihost import (HostSpec, make_launch_plan,
                                                 parse_hosts, ssh_argv)


def test_parse_hosts_forms():
    assert parse_hosts("h1,h2:4,local:2") == [
        HostSpec("h1", 1), HostSpec("h2", 4), HostSpec("local", 2)]


@pytest.mark.parametrize("bad", ["", ":3", "h1:x", "h1:0", "h1:-2"])
def test_parse_hosts_rejects(bad):
    with pytest.raises(ValueError):
        parse_hosts(bad)


def test_plan_assigns_ranks_host_major():
    plan = make_launch_plan(
        [HostSpec("a", 2), HostSpec("b", 1)], coordinator_host="10.0.0.9",
        control_port=7000, dist_port=7001, backend="cpu")
    assert [(l.rank, l.host) for l in plan] == [(0, "a"), (1, "a"),
                                                (2, "b")]
    for l in plan:
        argv = list(l.argv)
        assert argv[:3] == [sys.executable, "-m",
                            "nbdistributed_tpu.runtime.worker"]
        assert argv[argv.index("--rank") + 1] == str(l.rank)
        assert argv[argv.index("--world-size") + 1] == "3"
        assert argv[argv.index("--coordinator-host") + 1] == "10.0.0.9"
        assert argv[argv.index("--dist-port") + 1] == "7001"


def test_plan_rejects_loopback_coordinator_with_remote_hosts():
    with pytest.raises(ValueError, match="loopback"):
        make_launch_plan([HostSpec("remote1")],
                         coordinator_host="127.0.0.1", control_port=1,
                         dist_port=2, backend="tpu")


def test_plan_allows_loopback_for_all_local():
    plan = make_launch_plan([HostSpec("local", 2)],
                            coordinator_host="127.0.0.1", control_port=1,
                            dist_port=2, backend="cpu")
    assert len(plan) == 2
    assert dict(plan[0].env)["JAX_PLATFORMS"] == "cpu"


@pytest.mark.parametrize("host", ["podhost", "local"])
def test_tpu_plan_rejects_multiple_workers_per_host(host):
    with pytest.raises(ValueError, match="one worker per host"):
        make_launch_plan([HostSpec(host, 4)],
                         coordinator_host="10.0.0.9", control_port=1,
                         dist_port=2, backend="tpu")


def test_tpu_plan_ships_no_carving_env():
    plan = make_launch_plan([HostSpec("h1"), HostSpec("h2")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    assert all(l.env == () for l in plan)


def test_dist_host_is_rank0_host_for_remote_plans():
    """jax.distributed's coordination service runs in rank 0's process,
    so the rendezvous address must be rank 0's host — not the kernel."""
    plan = make_launch_plan([HostSpec("tpu-w-0"), HostSpec("tpu-w-1")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    for l in plan:
        argv = list(l.argv)
        assert argv[argv.index("--dist-host") + 1] == "tpu-w-0"
        assert argv[argv.index("--coordinator-host") + 1] == "10.0.0.9"


def test_dist_host_is_coordinator_when_rank0_local():
    plan = make_launch_plan([HostSpec("local"), HostSpec("tpu-w-1")],
                            coordinator_host="10.0.0.9", control_port=1,
                            dist_port=2, backend="tpu")
    argv = list(plan[0].argv)
    assert argv[argv.index("--dist-host") + 1] == "10.0.0.9"


def test_ssh_argv_quotes_and_targets_host():
    plan = make_launch_plan([HostSpec("tpu-w-3")],
                            coordinator_host="10.0.0.9", control_port=70,
                            dist_port=None, backend="cpu")
    argv = ssh_argv(plan[0])
    assert argv[0] == "ssh" and "tpu-w-3" in argv
    remote = argv[-1]
    assert remote.startswith("exec env ")
    assert "JAX_PLATFORMS=cpu" in remote
    assert "--rank 0" in remote and "--control-port 70" in remote
