"""Runtime collective-hazard guard: eager world-collectives must raise
at CALL time when invoked from a cell running on a strict subset of
the mesh (they would otherwise deadlock — the absent ranks never
join), and the executor response must carry the runtime collective
count + cell hash for the coordinator's per-cell record."""

import pytest

from nbdistributed_tpu.runtime import collective_guard as cg

pytestmark = [pytest.mark.unit]


def teardown_function(_fn):
    cg.end_cell()          # never leak cell state between tests


def test_subset_cell_raises_at_call_time():
    cg.begin_cell([0], world=4)
    with pytest.raises(cg.CollectiveHazardError, match="deadlock"):
        cg.check("all_reduce")


def test_full_mesh_cell_passes_and_counts():
    cg.begin_cell([0, 1, 2, 3], world=4)
    cg.check("all_reduce")
    cg.check("barrier")
    assert cg.end_cell() == 2


def test_unknown_targets_pass():
    """Raw-string execute requests (bench cells, direct callers)
    carry no target info: the guard must not fire."""
    cg.begin_cell(None, world=4)
    cg.check("all_reduce")
    assert cg.end_cell() == 1


def test_inactive_outside_cells():
    """A collective called outside any cell (worker sync handler,
    user threads) sees inactive state and passes."""
    cg.check("barrier")            # no begin_cell - must not raise


def test_single_process_world_passes():
    cg.begin_cell([0], world=1)
    cg.check("all_reduce")
    assert cg.end_cell() == 1


def test_eager_collectives_call_guard(monkeypatch):
    """The real collectives module consults the guard before any
    communication: with subset state active, a 1-process all_reduce
    (normally an identity) must raise — proving the hook fires ahead
    of the transport, where the multi-process case would block."""
    from nbdistributed_tpu.parallel import collectives

    cg.begin_cell([0], world=2)
    try:
        for fn, args in ((collectives.all_reduce, (1.0,)),
                         (collectives.all_gather, (1.0,)),
                         (collectives.broadcast, (1.0,)),
                         (collectives.barrier, ()),
                         (collectives.reduce_scatter, ([1.0, 2.0],)),
                         (collectives.all_reduce_quantized, (1.0,))):
            with pytest.raises(cg.CollectiveHazardError):
                fn(*args)
    finally:
        cg.end_cell()


def test_cell_hash_stable():
    assert cg.cell_hash("x = 1") == cg.cell_hash("x = 1")
    assert cg.cell_hash("x = 1") != cg.cell_hash("x = 2")
    assert len(cg.cell_hash("anything")) == 12


def test_executor_response_carries_count(monkeypatch):
    """Worker-level wiring: _handle_execute publishes targets, runs
    the cell, and stamps collective_ops + cell_sha1 on the reply."""
    from nbdistributed_tpu.messaging.codec import Message
    from nbdistributed_tpu.runtime import worker as worker_mod

    from nbdistributed_tpu.observability.flightrec import _NullRecorder

    class _W:
        rank = 0
        world_size = 2
        namespace = {"cg": cg}
        _stream = staticmethod(lambda text, kind: None)
        _flight = _NullRecorder()
        # Untagged requests resolve to the base namespace (tenant
        # namespaces are the gateway suite's concern).
        _ns_for = worker_mod.DistributedWorker._ns_for

    handle = worker_mod.DistributedWorker._handle_execute
    w = _W()
    msg = Message(msg_type="execute",
                  data={"code": "cg.check('fake_op')\n1+1",
                        "target_ranks": [0, 1]})
    reply = handle(w, msg)
    assert reply.data["status"] == "success"
    assert reply.data["collective_ops"] == 1
    assert reply.data["cell_sha1"] == cg.cell_hash(
        "cg.check('fake_op')\n1+1")
    # Subset targets: the in-cell collective raises -> error reply,
    # which still arrives (never a hang) and still carries the count.
    msg2 = Message(msg_type="execute",
                   data={"code": "cg.check('fake_op')",
                         "target_ranks": [0]})
    reply2 = handle(w, msg2)
    assert "CollectiveHazard" in reply2.data.get("traceback", "")
    assert reply2.data["collective_ops"] == 1


def test_composite_collectives_count_once():
    """dist.scatter/gather/reduce delegate to guarded primitives but
    one user-level call must record ONE op (the nested() suppression),
    and the subset raise names the composite, not the inner op."""
    import jax.numpy as jnp

    from nbdistributed_tpu.parallel import collectives

    cg.begin_cell([0, 1], world=2)  # full mesh: counts, no raise
    # world_size()==1 here (unit env), so the w==1 identity path runs
    # after the guard check — the count is what we're testing.
    collectives.gather(jnp.ones(2))
    collectives.reduce(jnp.ones(2))
    assert cg.end_cell() == 2
    cg.begin_cell([0], world=2)
    with pytest.raises(cg.CollectiveHazardError, match="gather"):
        collectives.gather(jnp.ones(2))
    cg.end_cell()
