"""Model-integrated sequence parallelism: forward/train with attention
routed through ring or Ulysses must match the plain model exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from nbdistributed_tpu.models import (SeqParallel, forward, init_params,
                                      loss_fn, make_train_step,
                                      param_shardings, tiny_config)
from nbdistributed_tpu.parallel import mesh as mesh_mod

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size)
    return cfg, params, tokens


def _sharded(mesh, tokens, params, cfg):
    tok_s = jax.device_put(
        tokens, NamedSharding(mesh, P(None, "sp")))
    p_s = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_shardings(cfg)))
    return tok_s, p_s


@pytest.mark.parametrize("method,n_sp", [("ring", 4), ("ulysses", 2)])
def test_sp_forward_matches_plain(setup, method, n_sp):
    cfg, params, tokens = setup
    ref = forward(params, tokens, cfg)
    mesh = mesh_mod.make_mesh({"sp": n_sp, "tp": 1},
                              devices=jax.devices()[:n_sp])
    sp = SeqParallel(mesh=mesh, method=method, use_flash=False)
    tok_s, p_s = _sharded(mesh, tokens, params, cfg)
    got = jax.jit(lambda p, t: forward(p, t, cfg, sp=sp))(p_s, tok_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_sp_flash_forward_matches_plain(setup):
    """The Pallas inner path (interpret mode on CPU) through the model."""
    cfg, params, tokens = setup
    ref = forward(params, tokens, cfg)
    mesh = mesh_mod.make_mesh({"sp": 2, "tp": 1}, devices=jax.devices()[:2])
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=True)
    tok_s, p_s = _sharded(mesh, tokens, params, cfg)
    got = jax.jit(lambda p, t: forward(p, t, cfg, sp=sp))(p_s, tok_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_sp_train_step_matches_plain(setup):
    cfg, params, tokens = setup
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_p, _, ref_loss = jax.jit(make_train_step(cfg, opt))(
        params, opt.init(params), batch)

    mesh = mesh_mod.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=False)
    tok_s = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
    p_s = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_shardings(cfg)))
    step = jax.jit(make_train_step(cfg, opt, sp=sp))
    got_p, _, got_loss = step(p_s, opt.init(p_s), {"tokens": tok_s})
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4),
        got_p, ref_p)


def test_sp_sliding_window_matches_plain(setup):
    """Sliding-window attention under SP (ring and Ulysses) must match
    the plain sliding-window model."""
    import dataclasses
    cfg, params, tokens = setup
    cfg_w = dataclasses.replace(cfg, sliding_window=7)
    ref = forward(params, tokens, cfg_w)
    for method, n_sp in (("ring", 4), ("ulysses", 2)):
        mesh = mesh_mod.make_mesh({"sp": n_sp, "tp": 1},
                                  devices=jax.devices()[:n_sp])
        sp = SeqParallel(mesh=mesh, method=method, use_flash=False)
        tok_s, p_s = _sharded(mesh, tokens, params, cfg_w)
        got = jax.jit(lambda p, t: forward(p, t, cfg_w, sp=sp))(p_s,
                                                                tok_s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=method)


def test_sp_moe_dropless_matches_plain():
    """Sequence parallelism composes with the hierarchical dropless-EP
    path: moe_forward threads token_axes=("dp", sp.axis) so the
    routing sorts run on (dp, sp)-sharded token blocks (no per-layer
    activation all-gather over sp), and at lossless capacity the
    dp×sp×ep result matches the replicated model."""
    from nbdistributed_tpu.models import (init_moe_model, moe_forward,
                                          moe_model_shardings,
                                          tiny_moe_config)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          moe_dispatch="dropless",
                          capacity_factor=2.0)     # lossless (E/k = 2)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size)
    ref, aux_ref = moe_forward(params, tokens, cfg)
    mesh = mesh_mod.make_mesh({"dp": 2, "sp": 2, "ep": 2},
                              devices=jax.devices()[:8])
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=False)
    tok_s = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
    p_s = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        moe_model_shardings(cfg, tp_axis=None)))
    got, aux = jax.jit(lambda p, t: moe_forward(
        p, t, cfg, mesh=mesh, sp=sp))(p_s, tok_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_sp_bad_method():
    with pytest.raises(ValueError, match="unknown SeqParallel method"):
        SeqParallel(mesh=None, method="nope")


def test_ring_dp_tp_composition_exact():
    """ring_attention with batch_axis/head_axis on a dp×sp×tp mesh must
    match the single-device reference exactly."""
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel.ring import ring_attention

    mesh = mesh_mod.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    B, S, H, Hkv, D = 2, 16, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True)
    q_s = jax.device_put(q, NamedSharding(mesh, P("dp", "sp", "tp")))
    k_s = jax.device_put(k, NamedSharding(mesh, P("dp", "sp", "tp")))
    v_s = jax.device_put(v, NamedSharding(mesh, P("dp", "sp", "tp")))
    got = ring_attention(q_s, k_s, v_s, mesh, axis="sp",
                         batch_axis="dp", head_axis="tp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_dp_tp_composition_exact():
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel.ulysses import ulysses_attention

    mesh = mesh_mod.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    B, S, H, Hkv, D = 2, 16, 8, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True)
    q_s = jax.device_put(q, NamedSharding(mesh, P("dp", "sp", "tp")))
    k_s = jax.device_put(k, NamedSharding(mesh, P("dp", "sp", "tp")))
    v_s = jax.device_put(v, NamedSharding(mesh, P("dp", "sp", "tp")))
    got = ulysses_attention(q_s, k_s, v_s, mesh, axis="sp",
                            batch_axis="dp", head_axis="tp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_head_axis_validation():
    from nbdistributed_tpu.parallel.ring import ring_attention
    from nbdistributed_tpu.parallel.ulysses import ulysses_attention

    mesh = mesh_mod.make_mesh({"sp": 2, "tp": 4})
    B, S, D = 1, 8, 8
    q = jnp.zeros((B, S, 4, D))
    kv = jnp.zeros((B, S, 2, D))   # Hkv=2 not divisible by tp=4
    with pytest.raises(ValueError, match="head_axis"):
        ring_attention(q, kv, kv, mesh, axis="sp", head_axis="tp")
    with pytest.raises(ValueError, match="head_axis"):
        ulysses_attention(q, kv, kv, mesh, axis="sp", head_axis="tp")
