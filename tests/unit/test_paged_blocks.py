"""Paged KV block allocator + loadgen report schema (ISSUE 17).

Pure-logic units, no jax: the block-accounting arithmetic the gateway
admission gate and the worker device pool both run, and the pinned
machine-readable report surface of the closed-loop load generator.
"""

import pytest

from nbdistributed_tpu.serving_fast import (BlockAllocator,
                                            BlocksExhausted,
                                            LoadConfig, blocks_needed,
                                            score_slo, synth_schedule,
                                            validate_report)
from nbdistributed_tpu.serving_fast.loadgen import percentile, run_load

pytestmark = [pytest.mark.unit, pytest.mark.serve]


# ----------------------------------------------------------------------
# blocks_needed


def test_blocks_needed_ceil():
    assert blocks_needed(0, 8) == 0
    assert blocks_needed(-3, 8) == 0
    assert blocks_needed(1, 8) == 1
    assert blocks_needed(8, 8) == 1
    assert blocks_needed(9, 8) == 2
    assert blocks_needed(64, 8) == 8
    assert blocks_needed(65, 8) == 9


# ----------------------------------------------------------------------
# alloc / free / reuse


def test_alloc_free_reuse_deterministic():
    a = BlockAllocator(8, 4)
    t1 = a.alloc("r1", 3)
    assert t1 == [0, 1, 2]
    t2 = a.alloc("r2", 2)
    assert t2 == [3, 4]
    assert a.used_blocks == 5 and a.free_blocks == 3
    a.check()
    # Free r1; the free list re-sorts so the NEXT alloc takes the
    # lowest ids — allocation order is a pure function of history.
    assert a.free("r1") == 3
    t3 = a.alloc("r3", 4)
    assert t3 == [0, 1, 2, 5]
    a.check()
    # Double-free is a safe no-op (release may race a finish).
    assert a.free("r1") == 0
    a.check()


def test_alloc_all_or_nothing_and_double_admission():
    a = BlockAllocator(4, 4)
    a.alloc("r1", 2)
    # Exhaustion: explicit verdict carrying need/free, nothing taken.
    with pytest.raises(BlocksExhausted) as exc:
        a.alloc("r2", 3)
    assert exc.value.need == 3 and exc.value.free == 2
    assert a.free_blocks == 2       # the failed alloc took nothing
    a.check()
    # Double-admission is a caller bug, not a capacity condition.
    with pytest.raises(ValueError):
        a.alloc("r1", 1)
    a.check()


def test_block_table_growth():
    a = BlockAllocator(6, 4)
    a.alloc("r1", 2)
    grown = a.extend("r1", 2)
    assert grown == [2, 3]
    assert a.table("r1") == [0, 1, 2, 3]
    assert a.owner_blocks("r1") == 4
    with pytest.raises(BlocksExhausted):
        a.extend("r1", 3)
    assert a.table("r1") == [0, 1, 2, 3]    # all-or-nothing
    with pytest.raises(KeyError):
        a.extend("ghost", 1)
    a.check()


def test_can_fit_matches_alloc_verdict():
    a = BlockAllocator(4, 8)
    assert a.can_fit(32)            # 4 blocks exactly
    assert not a.can_fit(33)        # needs 5
    a.alloc("r1", 3)
    assert a.can_fit(8) and not a.can_fit(9)


# ----------------------------------------------------------------------
# defrag


def test_defrag_compacts_and_conserves():
    a = BlockAllocator(10, 4)
    a.alloc("r1", 3)                # [0,1,2]
    a.alloc("r2", 3)                # [3,4,5]
    a.alloc("r3", 2)                # [6,7]
    a.free("r2")
    a.check()
    before = {o: a.owner_blocks(o) for o in a.owners()}
    moves = a.defrag()
    a.check()
    # Only genuinely moving blocks appear in the map; live blocks are
    # dense from 0, owner tables keep their logical order and sizes.
    assert moves == {6: 3, 7: 4}
    assert a.table("r1") == [0, 1, 2]
    assert a.table("r3") == [3, 4]
    assert {o: a.owner_blocks(o) for o in a.owners()} == before
    assert a.free_blocks == 5
    # Post-defrag allocation continues from the compacted frontier.
    assert a.alloc("r4", 2) == [5, 6]
    a.check()


def test_defrag_noop_when_dense():
    a = BlockAllocator(4, 4)
    a.alloc("r1", 2)
    assert a.defrag() == {}
    a.check()


def test_reset_returns_everything():
    a = BlockAllocator(5, 4)
    a.alloc("r1", 4)
    a.reset()
    assert a.free_blocks == 5 and a.owners() == []
    a.check()


def test_snapshot_shape():
    a = BlockAllocator(6, 8)
    a.alloc("r1", 2)
    a.alloc("r2", 1)
    snap = a.snapshot()
    assert snap == {"blocks": 6, "block_tokens": 8, "used": 3,
                    "free": 3, "largest_run": 3,
                    "owners": {"r1": 2, "r2": 1}}


def test_ctor_validation():
    with pytest.raises(ValueError):
        BlockAllocator(0, 4)
    with pytest.raises(ValueError):
        BlockAllocator(4, 0)


# ----------------------------------------------------------------------
# loadgen: deterministic schedule


def test_schedule_deterministic_and_in_window():
    cfg = LoadConfig(rps=10.0, duration_s=3.0, seed=42)
    p1 = synth_schedule(cfg)
    p2 = synth_schedule(LoadConfig(rps=10.0, duration_s=3.0, seed=42))
    assert p1 == p2
    assert p1                       # 10 rps * 3 s: surely non-empty
    assert all(0 <= it["at"] < 3.0 for it in p1)
    ats = [it["at"] for it in p1]
    assert ats == sorted(ats)
    for it in p1:
        assert 4 <= len(it["prompt"]) <= 16
        assert 4 <= it["max_new"] <= 16
        assert all(1 <= t < cfg.vocab for t in it["prompt"])
    # A different seed offers different work.
    assert p1 != synth_schedule(
        LoadConfig(rps=10.0, duration_s=3.0, seed=43))


def test_schedule_uniform_gap():
    cfg = LoadConfig(rps=4.0, duration_s=2.0, arrival="uniform")
    plan = synth_schedule(cfg)
    gaps = {round(b["at"] - a["at"], 9)
            for a, b in zip(plan, plan[1:])}
    assert gaps == {0.25}


def test_config_validation():
    with pytest.raises(ValueError):
        LoadConfig(rps=0)
    with pytest.raises(ValueError):
        LoadConfig(arrival="bursty")
    with pytest.raises(ValueError):
        LoadConfig(prompt_len=(0, 4))
    with pytest.raises(ValueError):
        LoadConfig(max_new=(5, 4))


# ----------------------------------------------------------------------
# loadgen: report schema (pinned), conservation, SLO scoring


class InstantTransport:
    """Terminalizes every accepted request on the first poll: enough
    to drive a real ``run_load`` pass in milliseconds."""

    def __init__(self, *, reject_every: int = 0):
        self.n = 0
        self.reject_every = reject_every
        self.open: dict[str, dict] = {}

    def submit(self, prompt, max_new, priority=0):
        self.n += 1
        if self.reject_every and self.n % self.reject_every == 0:
            return {"status": "shed", "reason": "queue-full"}
        rid = f"r{self.n}"
        self.open[rid] = {"rid": rid, "done": True,
                          "status": "completed",
                          "tokens": list(range(max_new))}
        return {"status": "accepted", "rid": rid}

    def result(self, rid):
        return self.open[rid]

    def status(self):
        return {"slo": {"ttft": {"p99": 0.001}}}


def _tiny_cfg(**kw):
    kw.setdefault("rps", 200.0)
    kw.setdefault("duration_s", 0.05)
    kw.setdefault("drain_s", 5.0)
    kw.setdefault("poll_s", 0.001)
    return LoadConfig(**kw)


def test_report_schema_pinned_and_conserved():
    tr = InstantTransport(reject_every=3)
    rep = run_load(tr, _tiny_cfg(seed=1))
    validate_report(rep)            # raises on any schema violation
    assert rep["offered"] == (rep["completed"] + rep["failed"]
                              + rep["shed"] + rep["rejected"]
                              + rep["hung"])
    assert rep["shed"] > 0 and rep["completed"] > 0
    assert rep["hung"] == 0
    assert rep["server_slo"] == {"ttft": {"p99": 0.001}}
    assert rep["slo"]["pass"] is True     # no targets, nothing hung
    # The pinned surface: removing/renaming any of these is a breaking
    # change this test exists to catch.
    for k in ("schema", "config", "offered", "accepted", "rejected",
              "shed", "completed", "failed", "hung", "shed_rate",
              "tokens_total", "tokens_per_s", "duration_s", "client",
              "server_slo", "slo"):
        assert k in rep, k


def test_validate_report_rejects_broken_conservation():
    rep = run_load(InstantTransport(), _tiny_cfg(seed=2))
    validate_report(rep)
    rep["completed"] += 1           # a silently-duplicated verdict
    with pytest.raises(ValueError, match="conservation"):
        validate_report(rep)
    rep["completed"] -= 1
    del rep["tokens_per_s"]
    with pytest.raises(ValueError, match="missing"):
        validate_report(rep)


def test_report_detail_per_request():
    rep = run_load(InstantTransport(reject_every=4),
                   _tiny_cfg(seed=5, detail=True))
    validate_report(rep)            # "requests" is additive, not pinned
    reqs = rep["requests"]
    assert len(reqs) == rep["offered"]
    assert [r["i"] for r in reqs] == sorted(r["i"] for r in reqs)
    comp = [r for r in reqs if r["status"] == "completed"]
    assert comp and all(r["tokens"] for r in comp)
    assert all(r["rid"] is None for r in reqs
               if r["status"] == "shed")


def test_score_slo_hung_always_fails():
    rep = run_load(InstantTransport(), _tiny_cfg(seed=3))
    assert rep["slo"]["pass"] is True
    rep["hung"] = 1
    verdict = score_slo(rep, _tiny_cfg(seed=3))
    assert verdict["pass"] is False
    assert any(c["metric"] == "hung" and not c["ok"]
               for c in verdict["checks"])


def test_score_slo_targets():
    cfg = _tiny_cfg(seed=4, slo_ttft_p99_ms=1e6)
    rep = run_load(InstantTransport(), cfg)
    assert rep["slo"]["pass"] is True
    tight = _tiny_cfg(seed=4, slo_ttft_p99_ms=0.0)
    assert score_slo(rep, tight)["pass"] is False


def test_percentile_nearest_rank():
    vals = [float(i) for i in range(1, 101)]
    assert percentile(vals, 0.50) == 50.0
    assert percentile(vals, 0.99) == 99.0
    assert percentile(vals, 1.0) == 100.0
    assert percentile([7.0], 0.99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 0.5)
