"""Host-agent protocol unit tests: spawn, push-based death-watch,
signals, stdio tails, and the partition-safe link-loss semantics —
all in-process against a real HostAgent on loopback (the full
multi-address / multi-host path lives in
tests/integration/test_multihost_partition.py)."""

import signal
import sys
import time

import pytest

from nbdistributed_tpu.manager.hostagent import (AgentClient, HostAgent,
                                                 _AgentWorker,
                                                 _AgentWorkerIO)

pytestmark = pytest.mark.faults


@pytest.fixture
def agent(tmp_path):
    a = HostAgent("127.0.0.1", 0, auth_token="agent-secret",
                  host_label="hostX", run_dir=str(tmp_path / "run"))
    yield a
    a.close()


@pytest.fixture
def client(agent):
    c = AgentClient("127.0.0.1", agent.port, auth_token="agent-secret")
    yield c
    c.close()


def _wait(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_ping_reports_identity(agent, client):
    resp = client.request("ping", {})
    assert resp.data["host"] == "hostX"
    assert resp.data["run_dir"] == agent.run_dir


def test_spawn_forks_outside_the_deathwatch_lock(agent, client,
                                                 monkeypatch):
    """Popen (fork+exec, possibly slow) must not run under the
    agent's ``_lock`` — the ISSUE 10 blocking-call-under-lock fix: a
    stalled spawn used to wedge the death-watch scan and the
    poll/ping handlers behind process creation."""
    import subprocess as _sp
    from nbdistributed_tpu.manager import hostagent as ha_mod
    real_popen = _sp.Popen
    held: list[bool] = []

    def _probe_popen(*args, **kwargs):
        # Lock.acquire(blocking=False) succeeds iff nobody holds it.
        free = agent._lock.acquire(blocking=False)
        if free:
            agent._lock.release()
        held.append(not free)
        return real_popen(*args, **kwargs)

    monkeypatch.setattr(ha_mod.subprocess, "Popen", _probe_popen)
    pid = client.spawn(7, [sys.executable, "-c", "pass"], {})
    assert pid > 0
    assert held == [False], "Popen ran while agent._lock was held"


def test_deathwatch_skips_rank_with_spawn_in_flight(agent):
    """A rank whose replacement Popen is in flight must not have the
    superseded dead process's exit recorded/pushed — without the
    suppression the freshly spawned worker reads as instantly dead
    manager-side (the ISSUE 10 review fix)."""
    import subprocess
    corpse = subprocess.Popen([sys.executable, "-c", "pass"])
    corpse.wait()
    with agent._lock:
        agent._procs[3] = corpse
        agent._spawning.add(3)
    try:
        assert agent._scan_exits_once() == []   # suppressed mid-spawn
        assert 3 not in agent._exits
        with agent._lock:
            agent._spawning.discard(3)
        assert agent._scan_exits_once() == [(3, 0)]  # recorded after
    finally:
        with agent._lock:
            agent._procs.pop(3, None)
            agent._exits.pop(3, None)


def test_spawn_exit_pushed_and_tail(agent, client):
    pid = client.spawn(0, [sys.executable, "-c",
                           "print('agent-child-out'); "
                           "import time; time.sleep(0.2)"], {})
    w = _AgentWorker(client, 0, pid)
    assert w.pid == pid
    # The exit arrives by PUSH (worker_exit), no poll request needed.
    assert _wait(lambda: w.poll() is not None), "exit never reported"
    assert w.poll() == 0
    io = _AgentWorkerIO(client, 0)
    assert "agent-child-out" in io.tail()


def test_spawn_env_and_run_dir(agent, client):
    pid = client.spawn(1, [sys.executable, "-c",
                           "import os; print('RD=' +"
                           " os.environ.get('NBD_RUN_DIR', '') +"
                           " ' HL=' + os.environ.get('NBD_HOST', ''))"],
                       {"NBD_HOST": "hostX"})
    w = _AgentWorker(client, 1, pid)
    assert _wait(lambda: w.poll() is not None)
    tail = _AgentWorkerIO(client, 1).tail()
    # The agent's OWN run dir wins (per-host black boxes), and the
    # plan's host label rides through.
    assert f"RD={agent.run_dir}" in tail
    assert "HL=hostX" in tail


def test_signal_terminates_worker(agent, client):
    pid = client.spawn(0, [sys.executable, "-c",
                           "import time; time.sleep(60)"], {})
    w = _AgentWorker(client, 0, pid)
    time.sleep(0.3)
    assert client.signal(0, signal.SIGTERM)
    assert _wait(lambda: w.poll() is not None), "SIGTERM never landed"
    assert w.poll() != 0


def test_duplicate_rank_spawn_refused(agent, client):
    client.spawn(0, [sys.executable, "-c",
                     "import time; time.sleep(30)"], {})
    with pytest.raises(RuntimeError, match="already running"):
        client.spawn(0, [sys.executable, "-c", "pass"], {})
    client.signal(0, signal.SIGKILL)


def test_reconnect_resyncs_exits_missed_during_outage(agent, client):
    """An exit that happens while the client link is down (its push
    notice has nowhere to land) must be folded in by the
    fire-and-forget resync after the redial — and the resync must not
    deadlock the recv thread it runs on."""
    pid = client.spawn(0, [sys.executable, "-c",
                           "import time; time.sleep(1.0)"], {})
    w = _AgentWorker(client, 0, pid)
    assert w.poll() is None
    # Sever the link out from under the client; the worker exits
    # during the outage, the agent's push finds no live connection.
    client._ch._sock.close()
    assert _wait(lambda: not client.link_up or client.reconnects > 0)
    assert _wait(lambda: w.poll() is not None, timeout=20.0), \
        "exit during the outage was never resynced after reconnect"
    assert w.poll() == 0
    assert client.reconnects >= 1


def test_link_loss_means_unknown_not_dead(agent, client):
    """The partition-safety contract: when the agent link drops, a
    live worker's poll() answers None (alive/unknown) — never a
    phantom exit code that would trigger N spurious heals."""
    pid = client.spawn(0, [sys.executable, "-c",
                           "import time; time.sleep(30)"], {})
    w = _AgentWorker(client, 0, pid)
    assert w.poll() is None
    agent.close(reap=False)   # the link dies; the worker does not
    assert _wait(lambda: not client.link_up), "link loss undetected"
    for _ in range(5):
        assert w.poll() is None
        time.sleep(0.05)
    # Requests now fail fast instead of hanging.
    from nbdistributed_tpu.messaging.transport import TransportError
    with pytest.raises((TransportError, TimeoutError)):
        client.request("ping", {}, timeout=2.0)
    # Manual cleanup: the agent was closed without reaping.
    import os
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        pass


def test_reap_waits_out_sigkilled_children(agent, client):
    """ISSUE 15 lifecycle fix: a child that ignores SIGTERM is
    SIGKILLed by reap — and must then be waited (no zombie: the
    death-watch records each exit once and never polls again) with
    its stdout pipe fd dropped."""
    client.spawn(3, [sys.executable, "-c",
                     "import signal, time; "
                     "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                     "print('armored', flush=True); "
                     "time.sleep(120)"], {})
    proc = agent._procs[3]
    assert _wait(lambda: "armored" in agent._io[3].tail())
    resp = client.request("reap", {})
    assert resp.data["reaped"] == 1
    # returncode read WITHOUT poll(): it is set only if the agent
    # itself already reaped the corpse (poll() would waitpid here and
    # mask a zombie leak).
    assert proc.returncode is not None
    assert proc.stdout.closed


def test_close_joins_lock_taking_threads(tmp_path):
    """ISSUE 15 lifecycle fix: closing the agent and its client reaps
    the death-watch / recv threads — both take self._lock, and a
    daemon thread holding a lock into interpreter teardown deadlocks
    atexit work."""
    a = HostAgent("127.0.0.1", 0, auth_token="s",
                  run_dir=str(tmp_path / "run"))
    c = AgentClient("127.0.0.1", a.port, auth_token="s")
    recv_thread, monitor = c._thread, a._monitor
    c.close()
    recv_thread.join(timeout=4.0)
    assert not recv_thread.is_alive()
    a.close()
    monitor.join(timeout=3.0)
    assert not monitor.is_alive()
