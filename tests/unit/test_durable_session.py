"""Unit tests for durable sessions (ISSUE 4): the session manifest
round-trip, the parked-result mailbox, the codec's epoch header, the
stale-run GC, and ProcessManager adoption of externally-discovered
pids."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from nbdistributed_tpu.manager.process_manager import (_AdoptedProcess,
                                                       ProcessManager)
from nbdistributed_tpu.messaging import Message, decode, encode
from nbdistributed_tpu.resilience import ResultMailbox, session

pytestmark = [pytest.mark.unit, pytest.mark.faults]


# ----------------------------------------------------------------------
# manifest round-trip

def _manifest(**kw):
    base = dict(world_size=2, control_host="127.0.0.1",
                control_port=5123, token="tok123", epoch=1,
                pids={0: 100, 1: 101}, backend="cpu", dist_port=5999,
                init_line="-n 2 --backend cpu")
    base.update(kw)
    return session.make_manifest(**base)


def test_manifest_roundtrip(tmp_path):
    d = str(tmp_path / "run")
    path = session.write_manifest(d, _manifest())
    assert os.path.basename(path) == session.MANIFEST_NAME
    assert not os.path.exists(path + ".tmp")  # atomic replace
    m = session.read_manifest(d)
    assert m["world_size"] == 2
    assert m["control"] == {"host": "127.0.0.1", "port": 5123,
                            "bind_host": "127.0.0.1"}
    assert m["token"] == "tok123" and m["epoch"] == 1
    assert m["pids"] == {"0": 100, "1": 101}  # JSON string keys
    assert m["init_line"] == "-n 2 --backend cpu"
    assert m["updated_ts"] > 0


def test_manifest_update_and_epoch_bump(tmp_path):
    d = str(tmp_path / "run")
    session.write_manifest(d, _manifest())
    m = session.update_manifest(d, epoch=2,
                                control={"host": "127.0.0.1",
                                         "port": 6000,
                                         "bind_host": "127.0.0.1"})
    assert m["epoch"] == 2 and m["control"]["port"] == 6000
    # unrelated fields survive the read-modify-write
    assert session.read_manifest(d)["token"] == "tok123"


def test_manifest_missing_and_corrupt(tmp_path):
    assert session.read_manifest(str(tmp_path / "nope")) is None
    d = str(tmp_path / "bad")
    os.makedirs(d)
    with open(session.manifest_path(d), "w") as f:
        f.write("{torn json")
    assert session.read_manifest(d) is None
    assert session.update_manifest(d, epoch=9) is None


def test_end_session_removes_manifest(tmp_path):
    d = str(tmp_path / "run")
    session.write_manifest(d, _manifest())
    assert session.end_session(d) is True
    assert session.read_manifest(d) is None
    assert session.end_session(d) is False  # already gone
    assert session.end_session(None) is False


def test_token_mint_and_fingerprint():
    a, b = session.mint_token(), session.mint_token()
    assert a != b and len(a) == 16
    assert session.token_fingerprint(a) != session.token_fingerprint(b)
    assert len(session.token_fingerprint(a)) == 8
    assert a not in session.token_fingerprint(a)  # never the secret
    assert session.token_fingerprint(None) == "-"


def test_live_pids_filters_dead(tmp_path):
    m = _manifest(pids={0: os.getpid(), 1: 2 ** 22 + 12345})
    live = session.live_pids(m)
    assert live == {0: os.getpid()}
    m["pids"]["2"] = "garbage"
    assert session.live_pids(m) == {0: os.getpid()}


# ----------------------------------------------------------------------
# result mailbox

def _reply(mid, data):
    return Message(msg_type="response", msg_id=mid, data=data)


def test_mailbox_park_claim_exactly_once():
    box = ResultMailbox()
    box.park("m1", _reply("m1", {"output": "1"}))
    box.park("m2", _reply("m2", {"output": "2"}))
    assert box.ids() == ["m1", "m2"] and len(box) == 2
    r = box.claim("m1")
    assert r.data == {"output": "1"}
    assert box.claim("m1") is None  # destructive: exactly once
    rest = box.claim_all()
    assert list(rest) == ["m2"] and len(box) == 0
    assert box.claim_all() == {}
    c = box.counters()
    assert c["parked"] == 2 and c["claimed"] == 2 and c["evicted"] == 0


def test_mailbox_capacity_evicts_oldest():
    box = ResultMailbox(capacity=3)
    for i in range(5):
        box.park(f"m{i}", _reply(f"m{i}", {"output": str(i)}))
    assert box.ids() == ["m2", "m3", "m4"]
    assert box.counters()["evicted"] == 2


def test_mailbox_byte_bound_keeps_newest():
    box = ResultMailbox(capacity=100, max_total_bytes=2000)
    for i in range(5):
        box.park(f"m{i}", _reply(f"m{i}", {"output": "x" * 900}))
    assert "m4" in box.ids() and len(box) <= 3
    # a single oversized entry is still kept (it is the in-flight
    # cell's result — the thing reattach exists to recover)
    big = ResultMailbox(capacity=4, max_total_bytes=100)
    big.park("huge", _reply("huge", {"output": "y" * 10_000}))
    assert big.ids() == ["huge"]


def test_mailbox_repark_same_id_refreshes():
    box = ResultMailbox()
    box.park("m", _reply("m", {"output": "old"}))
    box.park("m", _reply("m", {"output": "new"}))
    assert len(box) == 1
    assert box.claim("m").data == {"output": "new"}


def test_worker_drain_reparks_when_reply_construction_raises():
    """ISSUE 15 lifecycle fix: the worker's drain claim is
    destructive, so a raise between ``claim_all`` and the reply
    leaving the handler must repark — or the parked results are gone
    and the reattaching coordinator's drain finds an empty box."""
    from nbdistributed_tpu.runtime.worker import DistributedWorker

    w = DistributedWorker.__new__(DistributedWorker)
    w.rank = 0
    w._mailbox = ResultMailbox()
    w._flight = type("F", (), {"record":
                               staticmethod(lambda *a, **k: None)})()
    w._mailbox.park("m1", _reply("m1", {"output": "precious"}))

    class _Msg:
        data = {"action": "drain"}

        def reply(self, **kw):
            raise RuntimeError("encode blew up")

    with pytest.raises(RuntimeError, match="encode blew up"):
        w._handle_mailbox(_Msg())
    assert w._mailbox.ids() == ["m1"]          # reparked, not lost
    assert w._mailbox.claim("m1").data == {"output": "precious"}


# ----------------------------------------------------------------------
# codec epoch header

def test_codec_epoch_roundtrip_and_absent_when_unset():
    msg = Message(msg_type="execute", data={"code": "1"}, epoch=3)
    out = decode(encode(msg))
    assert out.epoch == 3 and out.msg_id == msg.msg_id
    plain = Message(msg_type="execute", data={"code": "1"})
    frame = encode(plain)
    assert decode(frame).epoch is None
    # unstamped frames keep the pre-epoch wire format byte-for-byte
    assert b'"ep"' not in frame
    # replies never inherit the request's epoch
    assert msg.reply(data={}).epoch is None


# ----------------------------------------------------------------------
# stale-run GC

def _mk_run(root, name, *, pids, age_s, manifest=True):
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    ref = d
    if manifest:
        session.write_manifest(d, _manifest(pids=pids))
        ref = session.manifest_path(d)
    old = time.time() - age_s
    os.utime(ref, (old, old))
    return d


def test_gc_sweeps_only_stale_dead_runs(tmp_path, monkeypatch):
    root = str(tmp_path / "nbd_runs")
    stale = _mk_run(root, "run-old-dead", pids={0: 2 ** 22 + 1},
                    age_s=7200)
    live = _mk_run(root, "run-old-live", pids={0: os.getpid()},
                   age_s=7200)
    fresh = _mk_run(root, "run-fresh-dead", pids={0: 2 ** 22 + 2},
                    age_s=10)
    bare = _mk_run(root, "run-bare", pids={}, age_s=7200,
                   manifest=False)
    current = _mk_run(root, "run-current", pids={0: 2 ** 22 + 3},
                      age_s=7200)
    monkeypatch.setenv("NBD_RUN_DIR", current)

    dry = session.gc_runs(root, ttl_s=3600, dry_run=True)
    assert sorted(dry["swept"]) == sorted([stale, bare])
    assert all(os.path.isdir(d) for d in (stale, live, fresh, bare))

    res = session.gc_runs(root, ttl_s=3600)
    assert sorted(res["swept"]) == sorted([stale, bare])
    assert not os.path.exists(stale) and not os.path.exists(bare)
    # live pid, fresh mtime, and the current run dir all survive
    assert os.path.isdir(live) and os.path.isdir(fresh)
    assert os.path.isdir(current)
    assert current in res["kept"]


def test_gc_missing_root_is_empty(tmp_path):
    res = session.gc_runs(str(tmp_path / "absent"), ttl_s=1)
    assert res["swept"] == [] and res["errors"] == []


def test_discover_run_dir_prefers_env_then_newest(tmp_path,
                                                  monkeypatch):
    root = str(tmp_path / "nbd_runs")
    older = _mk_run(root, "run-a", pids={0: os.getpid()}, age_s=100)
    newer = _mk_run(root, "run-b", pids={0: os.getpid()}, age_s=0)
    _mk_run(root, "run-dead", pids={0: 2 ** 22 + 9}, age_s=0)
    monkeypatch.delenv("NBD_RUN_DIR", raising=False)
    monkeypatch.setattr(session, "default_runs_root", lambda: root)
    assert session.discover_run_dir() == newer
    monkeypatch.setenv("NBD_RUN_DIR", older)
    assert session.discover_run_dir() == older


# ----------------------------------------------------------------------
# attach lock (split-brain guard) + attach failure hygiene

def test_attach_lock_contested_stale_and_release(tmp_path):
    d = str(tmp_path)
    lock = session._acquire_attach_lock(d)
    # held by a live pid (ours): a second claimant must fail loudly
    with pytest.raises(RuntimeError, match="another coordinator"):
        session._acquire_attach_lock(d)
    session._release_attach_lock(lock)
    # a dead holder's abandoned lock is broken and re-claimed
    with open(os.path.join(d, session.LOCK_NAME), "w") as f:
        f.write(str(2 ** 22 + 99))
    lock2 = session._acquire_attach_lock(d)
    assert int(open(lock2).read()) == os.getpid()
    session._release_attach_lock(lock2)
    session._release_attach_lock(lock2)  # idempotent


def test_attach_failure_restores_env_and_releases_lock(tmp_path,
                                                       monkeypatch):
    """A failed attach must not leave this kernel pointed at a fleet
    it never joined (a later %dist_init would clobber its manifest),
    must release the epoch lock, and must not kill the fleet."""
    d = str(tmp_path / "run")
    session.write_manifest(d, _manifest(world_size=1,
                                        pids={0: os.getpid()},
                                        control_port=0))
    monkeypatch.setenv("NBD_RUN_DIR", "/somewhere/else")
    with pytest.raises(TimeoutError):
        # our own pid poses as the worker; it never dials the control
        # plane, so the readiness wait times out
        session.attach(d, attach_timeout=0.1)
    assert os.environ["NBD_RUN_DIR"] == "/somewhere/else"
    assert not os.path.exists(os.path.join(d, session.LOCK_NAME))
    # the epoch claim itself is durable (manifest already bumped) so a
    # retry claims the NEXT epoch — but the fleet was left untouched
    assert session.read_manifest(d)["epoch"] == 2


# ----------------------------------------------------------------------
# ProcessManager adoption

def test_adopted_process_polls_liveness():
    alive = _AdoptedProcess(os.getpid())
    assert alive.poll() is None
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    gone = _AdoptedProcess(child.pid)
    assert gone.poll() == -1  # exit code of a non-child is unknowable
    assert gone.poll() == -1  # stable after first detection
    assert gone.wait(timeout=1) == -1


def test_process_manager_adopt_and_death_watch():
    child = subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(60)"],
                             start_new_session=True)
    pm = ProcessManager()
    deaths = []
    pm.add_death_callback(lambda r, rc: deaths.append((r, rc)))
    try:
        pm.adopt({0: child.pid}, backend="cpu", dist_port=None)
        assert pm.world_size == 1 and pm.backend == "cpu"
        assert pm.alive_ranks() == [0]
        assert pm.is_running()
        assert "adopted" in pm.io[0].tail()
        with pytest.raises(RuntimeError):
            pm.adopt({1: os.getpid()})  # already running
        os.kill(child.pid, signal.SIGKILL)
        child.wait()  # reap so signal-0 stops seeing it
        deadline = time.time() + 10
        while not deaths and time.time() < deadline:
            time.sleep(0.05)
        assert deaths == [(0, -1)]
        assert pm.alive_ranks() == []
    finally:
        pm.shutdown()
        if child.poll() is None:
            child.kill()


# ----------------------------------------------------------------------
# refresh_after_heal manifest upkeep

class _FakeComm:
    def __init__(self, port, epoch, n):
        self.port = port
        self.session_epoch = epoch
        self.num_workers = n


class _FakePm:
    def __init__(self, pids):
        self.processes = {r: _AdoptedProcess(p)
                          for r, p in pids.items()}


def test_refresh_after_heal_updates_pids_and_port(tmp_path,
                                                  monkeypatch):
    d = str(tmp_path / "run")
    session.write_manifest(d, _manifest())
    monkeypatch.setenv("NBD_RUN_DIR", d)
    m = session.refresh_after_heal(_FakeComm(7777, 3, 2),
                                   _FakePm({0: 200, 1: 201}))
    assert m["pids"] == {"0": 200, "1": 201}
    assert m["control"]["port"] == 7777
    assert m["epoch"] == 3
    monkeypatch.delenv("NBD_RUN_DIR")
    assert session.refresh_after_heal(_FakeComm(1, 1, 1),
                                      _FakePm({})) is None
