"""Unit tests for the async pipelined executor (ISSUE 14).

The window state machine runs against a fake comm with hand-fired
handles and an injectable clock — zero sleeps, every admission
decision and future transition driven explicitly.  The worker-side
step loop (``execute_repeat``) and the overlap-aware latency
attribution (``note_worker_free``) are covered pure as well.
"""

import threading
import time

import pytest

from nbdistributed_tpu.analysis import infer_effects
from nbdistributed_tpu.magics.proxies import CellFuture
from nbdistributed_tpu.messaging.pipeline import (AsyncExecutor,
                                                  classify_entry)
from nbdistributed_tpu.observability.latency import LatencyObservatory
from nbdistributed_tpu.runtime import executor as rt_executor

pytestmark = [pytest.mark.unit, pytest.mark.pipeline]


# ----------------------------------------------------------------------
# fakes


class FakeMsg:
    def __init__(self, data):
        self.data = data


class FakeHandle:
    _n = 0

    def __init__(self):
        FakeHandle._n += 1
        self.msg_id = f"fake-{FakeHandle._n}"
        self.error = None
        self._result = None
        self._cbs = []
        self._ev = threading.Event()

    @property
    def results(self):
        return self._result

    def add_done_callback(self, cb):
        if self._ev.is_set():
            cb(self)
        else:
            self._cbs.append(cb)

    def fire(self, results=None, error=None):
        self.error = error
        self._result = {r: FakeMsg(d) for r, d in
                        (results or {}).items()}
        self._ev.set()
        for cb in list(self._cbs):
            cb(self)

    def wait(self, timeout=...):
        self._ev.wait(None if timeout in (..., None) else timeout)
        if self.error:
            raise self.error
        return self._result


class FakeLat:
    def __init__(self):
        self.freed = []

    def note_worker_free(self, msg_id, t=None):
        self.freed.append(msg_id)


class FakeComm:
    def __init__(self):
        self.handles = []
        self.payloads = []
        self.lat = FakeLat()

    def submit(self, ranks, msg_type, payload, on_done=None, **kw):
        h = FakeHandle()
        self.handles.append(h)
        self.payloads.append(payload)
        if on_done is not None:
            h.add_done_callback(on_done)
        return h


def fp(code):
    """Footprint entry of one cell, as the preflight store records it."""
    return infer_effects(code).as_dict()


OK = {0: {"output": "1", "status": "success"}}


# ----------------------------------------------------------------------
# admission gating


def test_independent_free_cells_fill_the_window():
    ex = AsyncExecutor(FakeComm(), window=3)
    for i in range(3):
        ex.submit_cell(f"a{i} = {i}", [0], entry=fp(f"a{i} = {i}"))
    assert ex.depth == 3
    assert ex.try_admit(fp("zz = 9")) is not None  # window full
    assert "window full" in ex.try_admit(fp("zz = 9"))


def test_raw_hazard_blocks_admission():
    ex = AsyncExecutor(FakeComm(), window=4)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    reason = ex.try_admit(fp("b = a + 1"))          # RAW on a
    assert reason is not None and "hazard" in reason and "a" in reason


def test_war_and_waw_hazards_block_admission():
    ex = AsyncExecutor(FakeComm(), window=4)
    ex.submit_cell("x = q + 1", [0], entry=fp("x = q + 1"))  # reads q
    assert "hazard" in ex.try_admit(fp("q = 7"))             # WAR on q
    assert "hazard" in ex.try_admit(fp("x = 0"))             # WAW on x


def test_independent_names_admit_alongside():
    ex = AsyncExecutor(FakeComm(), window=4)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    assert ex.try_admit(fp("b = 2")) is None


def test_one_collective_stream_invariant():
    ex = AsyncExecutor(FakeComm(), window=4)
    bearing = fp("r = all_reduce(x)")
    assert classify_entry(bearing) == "bearing"
    ex.submit_cell("r = all_reduce(x)", [0], entry=bearing)
    # A second bearing cell (no name hazard: different names) is held
    # by the collective gate, not the DAG.
    other = fp("s = all_reduce(y)")
    reason = ex.try_admit(other)
    assert reason is not None and "one-collective-stream" in reason
    # A proven-free cell overlaps the bearing one.
    assert ex.try_admit(fp("b = 2")) is None


def test_opaque_drains_the_window():
    ex = AsyncExecutor(FakeComm(), window=4)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    opaque = fp("exec('x = 1')")
    assert opaque["opaque"]
    reason = ex.try_admit(opaque)
    assert reason is not None
    # And nothing joins a window holding an opaque cell.
    comm = FakeComm()
    ex2 = AsyncExecutor(comm, window=4)
    ex2.submit_cell("exec('x = 1')", [0], entry=opaque)
    assert "hazard" in ex2.try_admit(fp("b = 2")) \
        or "opaque" in ex2.try_admit(fp("b = 2"))


def test_missing_entry_treated_opaque():
    ex = AsyncExecutor(FakeComm(), window=4)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    assert ex.try_admit(None) is not None


def test_held_submission_admits_after_completion():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=4)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    got = []

    def sub():
        got.append(ex.submit_cell("b = a + 1", [0],
                                  entry=fp("b = a + 1")))

    t = threading.Thread(target=sub, daemon=True)
    t.start()
    time.sleep(0.15)
    assert len(comm.handles) == 1          # still held at the gate
    comm.handles[0].fire(OK)               # predecessor completes
    t.join(3)
    assert not t.is_alive()
    assert len(comm.handles) == 2          # dependent streamed after
    comm.handles[1].fire(OK)
    assert got[0].state == "done"
    assert ex.depth == 0
    assert ex.snapshot()["held_total"] == 1


# ----------------------------------------------------------------------
# futures: resolution, errors, consumption contract


def test_future_resolves_with_results():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=2)
    fut = ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    assert fut.state == "pending"
    assert "in flight" in repr(fut)
    comm.handles[0].fire(OK)
    assert fut.state == "done"
    assert fut.result()[0]["output"] == "1"


def test_error_future_propagation_and_warn_once():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=2)
    fut = ex.submit_cell("boom", [0], entry=fp("boom"))
    comm.handles[0].fire({0: {"error": "NameError: boom"}})
    assert fut.state == "error"
    assert not fut.consumed
    # The next-cell warn pass surfaces it exactly once.
    warned = ex.unconsumed_errors()
    assert warned == [fut]
    assert ex.unconsumed_errors() == []
    # The error itself stays touchable.
    with pytest.raises(RuntimeError, match="NameError"):
        fut.result()
    assert fut.consumed


def test_consumed_error_not_warned():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=2)
    fut = ex.submit_cell("boom", [0], entry=fp("boom"))
    comm.handles[0].fire({0: {"error": "NameError: boom"}})
    with pytest.raises(RuntimeError):
        fut.result()
    assert ex.unconsumed_errors() == []


def test_double_resolve_is_idempotent():
    fut = CellFuture("x = 1", 1, [0])
    assert fut.resolve({0: {"output": "1"}}) is True
    assert fut.resolve({0: {"output": "2"}}) is False
    assert fut.result()[0]["output"] == "1"
    assert fut.reject(RuntimeError("late")) is False
    assert fut.state == "done"
    # And the mirrored order: reject first, resolve can't flip it.
    f2 = CellFuture("y = 1", 2, [0])
    assert f2.reject(RuntimeError("dead")) is True
    assert f2.resolve({0: {"output": "1"}}) is False
    assert f2.state == "error"


def test_transport_failure_rejects_future():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=2)
    fut = ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    comm.handles[0].fire(error=RuntimeError("worker 0 died"))
    assert fut.state == "error"
    with pytest.raises(RuntimeError, match="died"):
        fut.result()


def test_interrupt_with_three_in_flight():
    """All three windowed cells abort (interrupt error replies) —
    every future resolves errored, the window empties, and the next
    cell warns about the unconsumed errors."""
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=3)
    futs = [ex.submit_cell(f"a{i} = {i}", [0],
                           entry=fp(f"a{i} = {i}")) for i in range(3)]
    assert ex.depth == 3
    for h in comm.handles:
        h.fire({0: {"error": "KeyboardInterrupt (cell interrupted by "
                             "%dist_interrupt)"}})
    assert ex.depth == 0
    assert all(f.state == "error" for f in futs)
    assert len(ex.unconsumed_errors()) == 3


def test_snapshot_names_collective_holder():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=4)
    ex.submit_cell("b = 2", [0], entry=fp("b = 2"))
    fut = ex.submit_cell("r = all_reduce(x)", [0],
                         entry=fp("r = all_reduce(x)"))
    snap = ex.snapshot()
    assert snap["depth"] == 2
    assert snap["collective_holder"] == fut.seq
    states = {c["seq"]: c["collective"] for c in snap["cells"]}
    assert states[fut.seq] == "bearing"


def test_drain_returns_settled_futures():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=3)
    f1 = ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    f2 = ex.submit_cell("b = 2", [0], entry=fp("b = 2"))
    t = threading.Timer(
        0.05, lambda: [h.fire(OK) for h in list(comm.handles)])
    t.start()
    futs = ex.drain()                          # replies land mid-drain
    assert set(futs) == {f1, f2}
    assert f1.state == "done" and f2.state == "done"
    assert ex.depth == 0


def test_bounded_drain_leaves_pending_cells_in_flight():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=2)
    fut = ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    futs = ex.drain(timeout=0.05)
    assert futs == [fut]
    assert fut.state == "pending"
    assert ex.depth == 1                       # NOT aborted
    comm.handles[0].fire(OK)
    assert fut.state == "done"


# ----------------------------------------------------------------------
# overlap-aware latency attribution


def test_completion_restamps_successors_grant():
    comm = FakeComm()
    ex = AsyncExecutor(comm, window=3)
    ex.submit_cell("a = 1", [0], entry=fp("a = 1"))
    f2 = ex.submit_cell("b = 2", [0], entry=fp("b = 2"))
    comm.handles[0].fire(OK)
    # The predecessor's completion moved the successor's grant stamp.
    assert comm.lat.freed == [f2.msg_id]


def test_note_worker_free_moves_queue_not_wire():
    clock = [1000.0]
    lat = LatencyObservatory(enabled=True, ring=16,
                             now=lambda: clock[0])
    lat.begin("m1", "execute", None)
    lat.note_grant("m1")
    # The worker only dequeues at t=1002 (predecessor ran 2s); the
    # executor stamps worker-free at that moment.
    clock[0] = 1002.0
    lat.note_worker_free("m1")

    class R:
        latency = {"dq": 1002.01, "xs": 1002.02, "xe": 1002.5,
                   "cs": 0.0, "rs": 1002.51}
        recv_ts = 1002.52

    clock[0] = 1002.53
    rec = lat.complete("m1", {0: R()}, lambda r: 0.0)
    st = rec["stages"]
    assert st["queue"] == pytest.approx(2.0, abs=0.01)
    assert st["wire"] < 0.1                    # no double count
    assert sum(st.values()) == pytest.approx(rec["e2e"], rel=0.1)


def test_note_worker_free_never_moves_backwards():
    clock = [1000.0]
    lat = LatencyObservatory(enabled=True, ring=16,
                             now=lambda: clock[0])
    lat.begin("m1", "execute", None)
    clock[0] = 1005.0
    lat.note_grant("m1")
    clock[0] = 1001.0                          # stale stamp
    lat.note_worker_free("m1")
    with lat._lock:
        assert lat._pending["m1"].t_grant == 1005.0


# ----------------------------------------------------------------------
# worker-side step loops (execute_repeat)


def test_repeat_runs_k_steps_with_persistent_state():
    ns = {}
    out = rt_executor.execute_repeat(
        "cnt = cnt + 1 if 'cnt' in globals() else 1\ncnt",
        ns, repeat=5)
    assert out["status"] == "success"
    assert out["steps"] == 5
    assert ns["cnt"] == 5
    assert out["last_scalar"] == 5.0
    assert not out["stopped_early"]
    # The trailing expression echoes ONCE (the last step's value).
    assert out["output"].strip() == "5"


def test_repeat_until_stops_early():
    ns = {}
    out = rt_executor.execute_repeat(
        "n = n + 1 if 'n' in globals() else 1",
        ns, repeat=100, until="n >= 7")
    assert out["steps"] == 7
    assert out["stopped_early"]
    assert ns["n"] == 7


def test_repeat_progress_callback_per_step():
    seen = []
    rt_executor.execute_repeat(
        "z = 1\n0.25", {},
        repeat=3,
        progress=lambda i, k, last, sps: seen.append((i, k, last)))
    assert seen == [(1, 3, 0.25), (2, 3, 0.25), (3, 3, 0.25)]


def test_repeat_error_reports_step_index():
    ns = {}
    out = rt_executor.execute_repeat(
        "m = m + 1 if 'm' in globals() else 1\n"
        "if m == 3:\n    raise ValueError('boom')",
        ns, repeat=10)
    assert "boom" in out["error"]
    assert "step 3/10" in out["error"]
    assert out["steps"] == 2                   # completed steps only
    assert ns["m"] == 3


def test_repeat_compiles_once():
    """The loop body is compiled once — a step count in the thousands
    stays cheap (the compile-once contract, not a perf benchmark)."""
    calls = []
    real_compile = rt_executor.compile if hasattr(
        rt_executor, "compile") else compile
    ns = {"hits": calls}
    out = rt_executor.execute_repeat(
        "hits.append(1)", ns, repeat=50)
    assert out["steps"] == 50 and len(calls) == 50
    # Non-scalar / no trailing expr: no scalar reported.
    assert out["last_scalar"] is None
    assert real_compile  # silences the unused guard


def test_repeat_scalar_ignores_bools():
    out = rt_executor.execute_repeat("True", {}, repeat=2)
    assert out["last_scalar"] is None


# ----------------------------------------------------------------------
# PendingHandle.pump: the async window's retry/deadline driver


class _StubListener:
    def __init__(self):
        self.sent = []

    def send_to_ranks(self, ranks, msg):
        self.sent.append((list(ranks), msg.attempt))


class _StubFlight:
    def record(self, *a, **k):
        pass


class _StubComm:
    def __init__(self, policy):
        self._lock = threading.Lock()
        self._pending = {}
        self.retries_sent = 0
        self.retries_by_rank = {}
        self.flight = _StubFlight()
        self._listener = _StubListener()
        self._policy = policy
        self.tracer = None
        self.scheduler = None

    def retry_for(self, msg_type):
        return self._policy

    def _finish(self, handle, error):
        pass  # bookkeeping stubbed: pump/deadline behavior is the SUT


def _handle(policy, timeout=None, sent_ago=0.0):
    from nbdistributed_tpu.messaging.codec import Message
    from nbdistributed_tpu.messaging.coordinator import (PendingHandle,
                                                         _Pending)
    comm = _StubComm(policy)
    msg = Message(msg_type="execute", data={"code": "x"})
    pending = _Pending({0}, "execute")
    pending.sent_at = time.time() - sent_ago
    deadline = (None if timeout is None
                else time.monotonic() + timeout)
    h = PendingHandle(comm, msg, "execute", [0], pending, None,
                      timeout, deadline, None, None)
    return comm, h


def test_pump_redelivers_when_due():
    from nbdistributed_tpu.resilience.retry import RetryPolicy
    pol = RetryPolicy(attempt_timeout_s=0.05, attempts=3, backoff_base_s=0.05,
                      jitter=0.0)
    comm, h = _handle(pol, sent_ago=10.0)       # long overdue
    h.pump()
    assert comm._listener.sent == [([0], 1)]    # one redelivery
    assert comm.retries_sent == 1
    # Attempts are bounded by the policy.
    h.pump()
    h.pump()
    assert len(comm._listener.sent) == 2        # attempts=3 → 2 resends
    h.pump()
    assert len(comm._listener.sent) == 2


def test_pump_not_due_yet_sends_nothing():
    from nbdistributed_tpu.resilience.retry import RetryPolicy
    pol = RetryPolicy(attempt_timeout_s=60.0, attempts=3, backoff_base_s=60.0,
                      jitter=0.0)
    comm, h = _handle(pol, sent_ago=0.0)
    h.pump()
    assert comm._listener.sent == []


def test_pump_fails_handle_on_blown_deadline():
    from nbdistributed_tpu.resilience.retry import RetryPolicy
    pol = RetryPolicy()                          # retries disabled
    comm, h = _handle(pol, timeout=-0.001)       # already expired
    rejected = []
    h.add_done_callback(lambda hh: rejected.append(hh.error))
    h.pump()
    assert h.done()
    assert isinstance(h.error, TimeoutError)
    assert rejected and isinstance(rejected[0], TimeoutError)


def test_pump_noop_after_terminal():
    from nbdistributed_tpu.resilience.retry import RetryPolicy
    pol = RetryPolicy(attempt_timeout_s=0.05, attempts=3, backoff_base_s=0.05,
                      jitter=0.0)
    comm, h = _handle(pol, sent_ago=10.0)
    h._fail(RuntimeError("dead"))
    h.pump()
    assert comm._listener.sent == []


def test_until_outer_quote_pair_strip():
    """The magic strips exactly ONE matching outer quote pair from
    --until (IPython keeps quotes); an expression that merely ENDS in
    a quote keeps its inner quoting intact."""
    def strip(u):
        u = u.strip()
        if len(u) >= 2 and u[0] == u[-1] and u[0] in "'\"":
            u = u[1:-1]
        return u
    assert strip("'loss < 0.1'") == "loss < 0.1"
    assert strip('"loss < 0.1"') == "loss < 0.1"
    assert strip("\"phase == 'done'\"") == "phase == 'done'"
    assert strip("loss < 0.1") == "loss < 0.1"


def test_classify_entry_mirrors_effects():
    assert classify_entry(fp("a = 1")) == "free"
    assert classify_entry(fp("all_reduce(x)")) == "bearing"
    assert classify_entry(fp("exec('x')")) == "unknown"
    assert classify_entry(None) == "unknown"
