"""Unit tests for the session gateway (ISSUE 8).

Pure state-machine coverage: the :class:`Scheduler` is driven with a
fake clock and zero sleeps (fairness, priority, FIFO order, queue
position, backpressure, overload shedding, tenant in-flight caps),
the :class:`TenantRegistry` through its hello/fence/detach lifecycle
(admission headcount, token hijack rejection, epoch fencing), and the
gateway-manifest liveness probe ``gc_runs`` relies on.  One scripted
in-process world (no JAX, no subprocesses) pins the no-forked-path
guarantee: the single-kernel ``CommunicationManager`` routes execute
requests through the same extracted scheduler a pool uses.
"""

import os
import threading

import pytest

from nbdistributed_tpu.gateway.daemon import (gateway_alive,
                                              gateway_manifest_path,
                                              read_gateway_manifest)
from nbdistributed_tpu.gateway.scheduler import (ACTIVE, DONE, QUEUED,
                                                 REJECTED, SHED,
                                                 CellRejected,
                                                 CellShed, SchedPolicy,
                                                 Scheduler)
from nbdistributed_tpu.gateway.tenancy import (TenantRegistry,
                                               TenantRejected)

pytestmark = [pytest.mark.unit, pytest.mark.gateway]


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make(mode="fair", slots=1, inflight=0, depth=0, clock=None):
    return Scheduler(SchedPolicy(mode, slots, inflight, depth),
                     now=clock or FakeClock())


# ----------------------------------------------------------------------
# scheduler: dispatch / queue / order


def test_default_policy_is_preexisting_behavior():
    """The single-kernel default: unlimited FIFO, every submit
    dispatches immediately — the pre-gateway contract."""
    s = Scheduler()
    assert s.policy.mode == "fifo"
    assert s.policy.mesh_slots == 0
    for i in range(10):
        t = s.submit("local", f"m{i}")
        assert t.verdict == {"status": "dispatch"}
        assert t.state == ACTIVE
        assert t.event.is_set()
    assert s.snapshot()["active"] == 10


def test_single_slot_queues_with_explicit_position():
    s = make(slots=1)
    first = s.submit("a", "m0")
    assert first.verdict["status"] == "dispatch"
    q1 = s.submit("a", "m1")
    q2 = s.submit("b", "m2")
    assert q1.verdict == {"status": "queued", "position": 1}
    assert q2.verdict == {"status": "queued", "position": 2}
    assert not q1.event.is_set()
    assert s.position("m2") == 2


def test_fifo_dispatch_order_on_complete():
    s = make(mode="fifo", slots=1)
    s.submit("a", "m0")
    ticks = [s.submit("t", f"m{i}") for i in range(1, 4)]
    done = []
    for expect in ("m1", "m2", "m3"):
        promoted = s.complete(done[-1] if done else "m0")
        assert [t.msg_id for t in promoted] == [expect]
        assert promoted[0].state == ACTIVE
        assert promoted[0].event.is_set()
        done.append(expect)
    # m1 and m2 were completed along the way; m3 still holds the slot.
    assert [t.state for t in ticks] == [DONE, DONE, ACTIVE]


def test_fair_mode_priority_wins_first():
    s = make(mode="fair", slots=1)
    s.submit("a", "m0")
    s.submit("low", "lo", priority=0)
    s.submit("high", "hi", priority=5)
    promoted = s.complete("m0")
    assert promoted[0].msg_id == "hi"


def test_fair_mode_least_served_tenant_interleaves():
    """A batch tenant's flood must not starve the interactive tenant:
    after the flood tenant has been served more, the other tenant's
    queued cell wins the next slot."""
    s = make(mode="fair", slots=1)
    s.submit("batch", "b0")
    for i in range(1, 4):
        s.submit("batch", f"b{i}")
    s.submit("interactive", "i0")
    # batch served=1, interactive served=0 -> i0 wins despite arriving
    # after b1..b3.
    promoted = s.complete("b0")
    assert promoted[0].msg_id == "i0"
    # Now both served=1; arrival order breaks the tie.
    promoted = s.complete("i0")
    assert promoted[0].msg_id == "b1"


def test_fifo_mode_ignores_priority():
    s = make(mode="fifo", slots=1)
    s.submit("a", "m0")
    s.submit("a", "lo", priority=0)
    s.submit("a", "hi", priority=99)
    assert s.complete("m0")[0].msg_id == "lo"


# ----------------------------------------------------------------------
# scheduler: admission control + overload


def test_tenant_inflight_cap_rejects_with_reason():
    s = make(slots=0, inflight=2)
    s.submit("a", "m0")
    s.submit("a", "m1")
    t = s.submit("a", "m2")
    assert t.state == REJECTED       # not SHED: distinct terminal state
    assert t.verdict["status"] == "rejected"
    assert t.verdict["reason"] == "tenant-inflight-cap"
    assert t.verdict["limit"] == 2
    assert t.event.is_set()          # submitter learns immediately
    # Another tenant is NOT capped by a's usage.
    assert s.submit("b", "m3").verdict["status"] == "dispatch"
    snap = s.snapshot()
    assert snap["tenants"]["a"]["rejected"] == 1


def test_inflight_cap_counts_queued_plus_active():
    s = make(slots=1, inflight=2)
    s.submit("a", "m0")              # active
    s.submit("a", "m1")              # queued
    assert s.submit("a", "m2").verdict["status"] == "rejected"
    # Completing frees the cap.
    s.complete("m0")
    assert s.submit("a", "m3").verdict["status"] == "queued"


def test_overload_sheds_lowest_priority_youngest():
    s = make(slots=1, depth=2)
    s.submit("a", "m0")
    old = s.submit("a", "q-old", priority=0)
    hi = s.submit("b", "q-hi", priority=3)
    # Queue is at depth 2; this overflow submit (priority 0, youngest
    # among the priority-0 cells) is itself the shedding victim.
    late = s.submit("c", "q-late", priority=0)
    assert late.state == SHED
    assert late.verdict["status"] == "shed"
    assert late.verdict["reason"] == "overload"
    assert late.event.is_set()
    # Older and higher-priority queued work survived.
    assert old.state == QUEUED and hi.state == QUEUED
    assert s.shed_total == 1


def test_overload_shed_victim_can_be_another_tenants_cell():
    """A high-priority overflow submit evicts the lowest-priority
    queued cell instead of being refused itself — and the verdict
    names the victim so the gateway can notify its tenant."""
    s = make(slots=1, depth=2)
    s.submit("a", "m0")
    victim = s.submit("lowprio", "q-low", priority=0)
    s.submit("b", "q-mid", priority=1)
    vip = s.submit("vip", "q-vip", priority=9)
    assert vip.state == QUEUED
    assert victim.state == SHED
    assert victim.event.is_set()
    # Victim summaries are JSON-safe (no live Ticket objects leak
    # into a verdict dict that may cross the wire).
    assert {"tenant": "lowprio", "msg_id": "q-low",
            "priority": 0} in vip.verdict["victims"]
    import json
    json.dumps(vip.verdict)
    snap = s.snapshot()
    assert snap["tenants"]["lowprio"]["shed"] == 1
    assert snap["queued"] == 2


def test_cancel_queued_and_active():
    s = make(slots=1)
    s.submit("a", "m0")
    q = s.submit("a", "m1")
    assert s.cancel("m1") is True     # withdrawn from the queue
    assert q.state == DONE
    assert s.cancel("m1") is False
    # Cancelling the ACTIVE cell frees its slot and promotes.
    q2 = s.submit("a", "m2")
    assert q2.state == QUEUED
    assert s.cancel("m0") is True
    assert q2.state == ACTIVE


def test_complete_frees_slot_even_without_queue():
    s = make(slots=1)
    s.submit("a", "m0")
    assert s.complete("m0") == []
    snap = s.snapshot()
    assert snap["active"] == 0
    assert snap["tenants"]["a"]["completed"] == 1
    assert s.submit("a", "m1").verdict["status"] == "dispatch"


def test_snapshot_shape():
    clock = FakeClock()
    s = make(mode="fair", slots=1, inflight=4, depth=8, clock=clock)
    s.submit("a", "m0")
    s.submit("b", "m1")
    snap = s.snapshot()
    assert snap["policy"] == {"mode": "fair", "mesh_slots": 1,
                              "tenant_inflight": 4, "queue_depth": 8,
                              "effects": False}
    assert snap["queued"] == 1 and snap["active"] == 1
    assert snap["tenants"]["a"]["served"] == 1
    assert snap["tenants"]["b"]["queued"] == 1


def test_bad_mode_rejected():
    with pytest.raises(ValueError):
        SchedPolicy("round-robin")


# ----------------------------------------------------------------------
# scheduler: effects-aware admission (ISSUE 9)


def make_fx(mode="fifo", slots=2, depth=0, effects=True):
    return Scheduler(SchedPolicy(mode, slots, 0, depth,
                                 effects=effects), now=FakeClock())


def test_effects_policy_from_env():
    p = SchedPolicy.pool_from_env(env={"NBD_POOL_SCHED_EFFECTS": "1"})
    assert p.effects is True
    p = SchedPolicy.pool_from_env(env={})
    assert p.effects is False
    assert p.describe()["effects"] is False


def test_proven_free_cell_overlaps_bearing_cell():
    s = make_fx()
    b0 = s.submit("a", "b0", collective="bearing")
    assert b0.verdict["status"] == "dispatch"
    f1 = s.submit("b", "f1", collective="free")
    assert f1.verdict["status"] == "dispatch"   # the overlap itself
    assert s.snapshot()["active"] == 2


def test_second_bearing_cell_serializes_with_named_reason():
    s = make_fx()
    s.submit("a", "b0", collective="bearing")
    held = s.submit("b", "b1", collective="bearing")
    assert held.state == QUEUED
    assert held.verdict["status"] == "queued"
    assert held.verdict["reason"].startswith(
        "serialized: collective-bearing")
    assert s.snapshot()["effects_serialized_total"] == 1
    # Completing the active bearing cell promotes the held one.
    s.complete("b0")
    assert held.state == ACTIVE


def test_unknown_footprint_serializes_with_canonical_reason():
    s = make_fx()
    s.submit("a", "b0", collective="bearing")
    held = s.submit("b", "u1", collective="unknown")
    assert held.verdict["reason"].startswith(
        "serialized: collective footprint unknown")
    # …and an unknown cell on the mesh blocks a bearing one too.
    s2 = make_fx()
    s2.submit("a", "u0", collective="unknown")
    held2 = s2.submit("b", "b1", collective="bearing")
    assert "serialized" in held2.verdict["reason"]


def test_free_cell_promotes_around_held_bearing_cell():
    """Overlap is the point: a proven-free cell submitted BEHIND an
    effects-held cell still takes a free slot instead of convoying."""
    s = make_fx()
    s.submit("a", "b0", collective="bearing")
    held = s.submit("b", "b1", collective="bearing")
    assert held.state == QUEUED
    f = s.submit("c", "f1", collective="free")
    assert f.state == ACTIVE           # jumped the held cell
    # The instantly-granted ticket's verdict is DISPATCH, not a stale
    # queued notice for a cell that never waited.
    assert f.verdict["status"] == "dispatch", f.verdict
    assert held.state == QUEUED        # still waiting for b0
    s.complete("f1")
    assert held.state == QUEUED
    s.complete("b0")
    assert held.state == ACTIVE


def test_bearing_cell_may_start_over_free_cells_only():
    s = make_fx(slots=4)
    s.submit("a", "f0", collective="free")
    s.submit("a", "f1", collective="free")
    b = s.submit("b", "b0", collective="bearing")
    assert b.verdict["status"] == "dispatch"   # only free cells active
    b2 = s.submit("c", "b1", collective="bearing")
    assert "serialized" in b2.verdict["reason"]


def test_effects_gate_inert_when_off_or_serial():
    # Off: two bearing cells overlap (the documented legacy hazard).
    s = make_fx(effects=False)
    s.submit("a", "b0", collective="bearing")
    assert s.submit("b", "b1",
                    collective="bearing").verdict["status"] == \
        "dispatch"
    # Serial mesh: the slot bound serializes everything anyway — the
    # gate must not add reasons (no overlap to prove safe).
    s = make_fx(slots=1)
    s.submit("a", "b0", collective="bearing")
    q = s.submit("b", "f1", collective="free")
    assert q.verdict["status"] == "queued"
    assert "reason" not in q.verdict


def test_default_submit_class_is_unknown_and_legacy_path_unchanged():
    # Single-kernel default policy: unlimited FIFO, effects off —
    # submits without a collective class keep pre-ISSUE-9 behavior.
    s = Scheduler()
    t = s.submit("local", "m0")
    assert t.collective == "unknown"
    assert t.verdict == {"status": "dispatch"}


def test_effects_serialized_cell_sheds_normally_under_depth():
    # The effects queue path still honors queue-depth shedding.
    s = make_fx(mode="fifo", slots=2, depth=1)
    s.submit("a", "b0", collective="bearing")
    held = s.submit("b", "b1", collective="bearing")
    assert held.state == QUEUED
    late = s.submit("c", "b2", collective="bearing", priority=0)
    assert late.state == SHED


# ----------------------------------------------------------------------
# tenancy: hello / fence / detach


def test_hello_admits_and_mints_token():
    reg = TenantRegistry(max_tenants=2)
    t, reply = reg.hello("alice", None, client_id=7)
    assert reply["status"] == "admitted"
    assert reply["tenant"] == "alice"
    assert t.token and reply["token"] == t.token
    assert t.epoch == 1 and reply["epoch"] == 1
    assert reg.by_client(7) is t


def test_admission_headcount_bound():
    reg = TenantRegistry(max_tenants=2)
    reg.hello("a", None, 1)
    reg.hello("b", None, 2)
    with pytest.raises(TenantRejected) as ei:
        reg.hello("c", None, 3)
    assert "max_tenants=2" in str(ei.value)
    # An EXISTING tenant's reattach is never blocked by the headcount.
    t, reply = reg.hello("a", reg.get("a").token, 4)
    assert reply["status"] == "reattached"


def test_wrong_token_cannot_hijack_a_tenant_name():
    reg = TenantRegistry()
    reg.hello("alice", None, 1)
    for bad in (None, "", "wrong-token"):
        with pytest.raises(TenantRejected):
            reg.hello("alice", bad, 2)
    assert reg.get("alice").epoch == 1   # hijack attempts bump nothing


def test_reattach_bumps_epoch_and_fences_old_connection():
    reg = TenantRegistry()
    t, _ = reg.hello("alice", None, client_id=1)
    token = t.token
    t2, reply = reg.hello("alice", token, client_id=2, priority=7)
    assert t2 is t
    assert reply["status"] == "reattached"
    assert t.epoch == 2 and t.reattaches == 1
    # A DECLARED priority wins on reattach: `%dist_attach --priority N`
    # after a crash must not silently keep the old one...
    assert t.priority == 7
    # The crashed kernel's frames (stamped epoch 1) are now stale...
    assert reg.fence(t, 1) is True
    assert reg.fence(t, 2) is False
    # ...and unstamped frames are never fenced (same contract as the
    # session-epoch fence).
    assert reg.fence(t, None) is False
    # The OLD client id still resolves to the tenant on purpose — the
    # fence must answer its frames with stale_epoch, not "no hello".
    assert reg.by_client(1) is t
    assert reg.by_client(2) is t
    # An OMITTED priority (None, the argparse default) keeps the
    # current value instead of demoting the tenant to 0 on every
    # plain reattach.
    reg.hello("alice", token, client_id=3)
    assert t.priority == 7


def test_detach_keeps_tenant_state_for_reattach():
    reg = TenantRegistry()
    t, _ = reg.hello("alice", None, client_id=1)
    t.mailbox.park("mid-1", object())
    gone = reg.detach_client(1)
    assert gone is t
    assert t.client_id is None and not t.attached
    assert reg.get("alice") is t          # name + token + mailbox live
    assert len(t.mailbox) == 1
    assert reg.by_client(1) is None
    # A stale detach (old client id after a reattach rebound it) must
    # not clear the LIVE connection.
    reg.hello("alice", t.token, client_id=2)
    assert reg.detach_client(1) is None
    assert t.client_id == 2
    # Crash-then-reattach ordering: the tenant reattaches as client 3
    # BEFORE the dead client 2's EOF lands.  The late EOF must not
    # read as a detach of the (re)attached tenant.
    reg.hello("alice", t.token, client_id=3)
    assert reg.by_client(2) is t          # old id kept for the fence
    assert reg.detach_client(2) is None   # superseded, not a detach
    assert t.client_id == 3 and t.attached
    assert reg.detach_client(3) is t      # the live conn going IS one


def test_clean_detach_evicts_only_idle_unattached_tenants():
    """Eviction frees the admission slot for rotating tenant names —
    but never while attached, and never with recoverable state."""
    reg = TenantRegistry(max_tenants=1)
    t, _ = reg.hello("alice", None, client_id=1)
    assert reg.evict("alice") is False          # still attached
    reg.detach_client(1)
    t.mailbox.park("m1", object())
    # The daemon's guard (empty mailbox) lives daemon-side; the
    # registry itself only refuses attached tenants — drain first.
    t.mailbox.claim_all()
    assert reg.evict("alice") is True
    assert reg.get("alice") is None
    assert reg.evict("alice") is False          # idempotent
    # The freed slot admits a NEW name; the old name returns fresh
    # (new token, epoch 1) rather than being refused forever.
    b, _ = reg.hello("bob", None, client_id=2)
    reg.detach_client(2)
    assert reg.evict("bob") is True
    t2, reply = reg.hello("alice", None, client_id=3)
    assert reply["status"] == "admitted" and t2.epoch == 1
    assert t2.token != t.token


def test_scheduler_tenant_idle():
    s = make(slots=1)
    assert s.tenant_idle("a") is True           # never seen
    s.submit("a", "m0")                         # active
    q = s.submit("a", "m1")                     # queued
    assert s.tenant_idle("a") is False
    s.complete("m0")                            # promotes m1
    assert q.state == ACTIVE
    assert s.tenant_idle("a") is False
    s.complete("m1")
    assert s.tenant_idle("a") is True


def test_mailbox_partitions_are_per_tenant():
    reg = TenantRegistry()
    a, _ = reg.hello("a", None, 1)
    b, _ = reg.hello("b", None, 2)
    a.mailbox.park("m1", "ra")
    b.mailbox.park("m2", "rb")
    assert a.mailbox.claim_all() == {"m1": "ra"}
    assert a.mailbox.claim_all() == {}     # exactly once
    assert len(b.mailbox) == 1             # untouched by a's drain


def test_manifest_block_records_token_epoch_attached():
    reg = TenantRegistry()
    t, _ = reg.hello("alice", None, 1)
    reg.hello("alice", t.token, 2)
    reg.detach_client(2)
    blk = reg.manifest_block()
    assert blk == {"alice": {"token": t.token, "epoch": 2,
                             "attached": False}}


# ----------------------------------------------------------------------
# gateway manifest liveness (the gc_runs skip probe)


def test_gateway_alive_probe(tmp_path):
    d = str(tmp_path)
    assert read_gateway_manifest(d) is None
    assert gateway_alive(None) is False
    with open(gateway_manifest_path(d), "w") as f:
        f.write('{"kind": "gateway", "pid": %d}' % os.getpid())
    assert gateway_alive(read_gateway_manifest(d)) is True
    # A dead pid (or garbage) keeps nothing.
    for content in ('{"pid": 2147483646}', '{"pid": "x"}', "{torn"):
        with open(gateway_manifest_path(d), "w") as f:
            f.write(content)
        assert gateway_alive(read_gateway_manifest(d)) is False


def test_gc_runs_keeps_live_gateway_dir(tmp_path, monkeypatch):
    from nbdistributed_tpu.resilience.session import gc_runs
    monkeypatch.delenv("NBD_RUN_DIR", raising=False)
    root = tmp_path / "runs"
    live = root / "pool-live"
    stale = root / "stale"
    live.mkdir(parents=True)
    stale.mkdir()
    with open(gateway_manifest_path(str(live)), "w") as f:
        f.write('{"kind": "gateway", "pid": %d}' % os.getpid())
    old = 1_000_000.0
    os.utime(str(live), (old, old))
    os.utime(str(stale), (old, old))
    res = gc_runs(str(root), ttl_s=60, dry_run=True)
    assert str(stale) in res["swept"]
    assert str(live) in res["kept"]
    assert "live gateway daemon" in res["kept_why"][str(live)]


# ----------------------------------------------------------------------
# no forked code path: the single-kernel CommunicationManager routes
# execute through the extracted scheduler


class _ScriptedWorker:
    """Minimal worker loop answering via a handler fn (the
    test_coordinator.py pattern, no JAX / subprocesses)."""

    def __init__(self, port, rank, handler):
        from nbdistributed_tpu.messaging import WorkerChannel
        self.chan = WorkerChannel("127.0.0.1", port, rank=rank)
        self.rank = rank
        self.handler = handler
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while True:
            try:
                msg = self.chan.recv()
            except Exception:
                return
            out = self.handler(self.rank, msg)
            if out is not None:
                try:
                    self.chan.send(msg.reply(data=out, rank=self.rank))
                except Exception:
                    return  # channel closed by teardown mid-reply

    def close(self):
        self.chan.close()


def test_single_kernel_path_routes_through_scheduler():
    from nbdistributed_tpu.messaging import CommunicationManager

    mgr = CommunicationManager(num_workers=1, timeout=10)
    w = None
    try:
        w = _ScriptedWorker(mgr.port, 0,
                            lambda rank, msg: {"output": "ok"})
        mgr.wait_for_workers(timeout=10)
        assert isinstance(mgr.scheduler, Scheduler)
        resp = mgr.send_to_ranks([0], "execute", {"code": "pass"})
        assert resp[0].data == {"output": "ok"}
        snap = mgr.scheduler.snapshot()
        # The implicit single tenant is accounted like any pool tenant.
        assert snap["tenants"]["local"]["completed"] == 1
        assert snap["active"] == 0
    finally:
        if w is not None:
            w.close()
        mgr.shutdown()


def test_bounded_scheduler_raises_shed_and_rejected_through_manager():
    """A pool-shaped policy on the manager surfaces CellShed /
    CellRejected to the caller instead of silently blocking."""
    import time

    from nbdistributed_tpu.messaging import CommunicationManager

    release = threading.Event()

    def handler(rank, msg):
        if msg.msg_type != "execute":
            return {"output": "?"}
        release.wait(15)
        return {"output": "done"}

    mgr = CommunicationManager(
        num_workers=1, timeout=20,
        scheduler=Scheduler(SchedPolicy("fair", mesh_slots=1,
                                        tenant_inflight=2,
                                        queue_depth=1)))
    w = None
    try:
        w = _ScriptedWorker(mgr.port, 0, handler)
        mgr.wait_for_workers(timeout=10)
        errs: dict = {}
        positions: list = []

        def submit(mid, tenant, prio=0):
            try:
                mgr.send_to_ranks(
                    [0], "execute", {"code": "slow"}, tenant=tenant,
                    priority=prio, msg_id=mid,
                    on_verdict=lambda t: positions.append(
                        t.verdict.get("position")))
            except Exception as e:
                errs[mid] = e

        t1 = threading.Thread(target=submit, args=("m0", "a"))
        t1.start()
        t0 = time.time()
        while mgr.scheduler.snapshot()["active"] < 1:
            assert time.time() - t0 < 5
            time.sleep(0.01)
        # Queue depth 1: m1 queues (explicit position), m2 overflows
        # and is shed (same priority, youngest).
        t2 = threading.Thread(target=submit, args=("m1", "a"))
        t2.start()
        t0 = time.time()
        while mgr.scheduler.snapshot()["queued"] < 1:
            assert time.time() - t0 < 5
            time.sleep(0.01)
        submit("m2", "b")
        assert isinstance(errs["m2"], CellShed)
        # Tenant a is now at its inflight cap (1 active + 1 queued).
        submit("m3", "a")
        assert isinstance(errs["m3"], CellRejected)
        release.set()
        t1.join(10)
        t2.join(10)
        assert "m0" not in errs and "m1" not in errs
        assert 1 in positions            # m1's explicit queue position
    finally:
        release.set()
        if w is not None:
            w.close()
        mgr.shutdown()


def test_pool_from_env_typo_degrades_to_fair():
    """Knobs convention: an env typo must degrade, not kill the
    daemon at SchedPolicy construction."""
    p = SchedPolicy.pool_from_env(env={"NBD_POOL_SCHED": "fare"})
    assert p.mode == "fair"
    p = SchedPolicy.pool_from_env(env={"NBD_POOL_SCHED": "fifo"})
    assert p.mode == "fifo"


def test_deliver_parks_when_submitting_connection_superseded():
    """A cell in flight across a reattach must PARK its result: the
    tenant's live connection is a NEW kernel with no waiter for that
    msg_id — a 'successful' send there is a silent client-side drop,
    and the mailbox drain on the next attach would never see it."""
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.gateway.tenancy import Tenant

    class _Flight:
        def __init__(self):
            self.events = []

        def record(self, kind, **kw):
            self.events.append((kind, kw))

    class _Reply:
        msg_id = "cell-1"

    d = object.__new__(GatewayDaemon)
    d._lock = threading.Lock()
    d.flight = _Flight()
    sent = []
    d._send_to_client = lambda cid, reply: (sent.append(
        (cid, getattr(reply, "msg_type", "reply"))) or True)

    t = Tenant("alice", "tok")
    t.client_id = 2                      # reattached connection
    # Submitted on connection 1, which the reattach superseded: park —
    # and nudge the LIVE connection with a parked_notice, because its
    # hello's parked list predates this park (without the nudge
    # nothing would ever drain it).
    d._deliver(t, _Reply(), submit_cid=1)
    assert sent == [(2, "parked_notice")]
    assert t.mailbox.ids() == ["cell-1"]
    assert t.parked_total == 1
    # Same connection still live: deliver straight through.
    r2 = _Reply()
    r2.msg_id = "cell-2"
    d._deliver(t, r2, submit_cid=2)
    assert sent[-1][0] == 2 and sent[-1][1] != "parked_notice"
    assert t.mailbox.ids() == ["cell-1"]


def test_serve_count_blocks_eviction_window():
    """The serve counter brackets the whole execute→_deliver span —
    including the gap after scheduler.complete() where the reply is
    mid-park — and drops on success AND failure, so a clean detach
    can only evict a tenant with truly nothing in flight."""
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon

    d = object.__new__(GatewayDaemon)
    d._lock = threading.Lock()
    d._serving = {"alice": 1}            # the listener's increment
    seen = []

    def inner_ok(tenant, msg, cid):
        seen.append(d._serving.get("alice"))   # still held mid-serve

    class _T:
        name = "alice"

    d._serve_execute_inner = inner_ok
    d._serve_execute(_T(), None, 1)
    assert seen == [1]
    assert d._serving == {}              # released after delivery

    d._serving = {"alice": 2}            # two cells in flight

    def inner_boom(tenant, msg, cid):
        raise RuntimeError("worker died")

    d._serve_execute_inner = inner_boom
    with pytest.raises(RuntimeError):
        d._serve_execute(_T(), None, 1)
    assert d._serving == {"alice": 1}    # failure still releases ONE


def test_forget_tenant_drops_stats_only_when_idle():
    """Eviction must also forget the scheduler's per-tenant stats —
    otherwise a re-admitted name inherits the old ``served`` count
    (fair mode would deprioritize a genuinely fresh tenant) and the
    dict grows one entry per departed name forever."""
    s = make(slots=1)
    s.submit("a", "m0")
    assert s.forget_tenant("a") is False        # active: refused
    s.complete("m0")
    assert "a" in s.snapshot()["tenants"]
    assert s.forget_tenant("a") is True
    assert "a" not in s.snapshot()["tenants"]
    assert s.forget_tenant("a") is True         # unknown == forgotten
    # A re-admitted same-name tenant starts with fresh fair-share
    # standing, not the evicted tenant's served count.
    s.submit("a", "m1")
    assert s.snapshot()["tenants"]["a"]["served"] == 1


def test_evict_gated_on_namespace_gc():
    """A failed tenant_gc broadcast must NOT free the tenant's name:
    the namespaces survive on the live ranks, and a future same-name
    tenant would execute its first cell inside the departed tenant's
    state.  Dead ranks are excluded from the broadcast (their process
    took the namespace dicts with it) and never block eviction."""
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon

    class _Flight:
        def record(self, kind, **kw):
            pass

    class _Sched:
        def __init__(self):
            self.forgot = []

        def forget_tenant(self, name):
            self.forgot.append(name)

        def tenant_idle(self, name):
            return True

    class _Comm:
        def __init__(self, dead=(), fail_times=0):
            self._deadset, self.sent = set(dead), []
            self.fail_times = fail_times
            self.scheduler = _Sched()

        def dead_ranks(self):
            return set(self._deadset)

        def send_to_ranks(self, ranks, *a, **kw):
            if self.fail_times > 0:
                self.fail_times -= 1
                raise RuntimeError("request timed out")
            self.sent.append(list(ranks))

    class _T:
        client_id = None
        mailbox = ()                     # len() == 0: nothing parked

    class _Reg:
        def __init__(self, tenant=_T()):
            self.evicted = []
            self.tenant = tenant

        def get(self, name):
            return self.tenant

        def evict(self, name):
            self.evicted.append(name)
            return True

    def mk(comm, reg=None, closed=False):
        d = object.__new__(GatewayDaemon)
        d._lock = threading.Lock()
        d.flight = _Flight()
        d.comm = comm
        d.world_size = 4
        d.registry = reg or _Reg()
        d._write_manifest = lambda: None
        d._closed = threading.Event()
        if closed:
            d._closed.set()
        return d

    # Persistent gc failure: the retry loop parks on _closed.wait —
    # a closing daemon stops retrying and the slot survives the miss.
    d = mk(_Comm(fail_times=99), closed=True)
    d._evict_after_gc("alice")
    assert d.registry.evicted == []          # slot survives a gc miss

    # A reattach mid-retry stops the gc: the namespace is live again.
    live = _T()
    live.client_id = 7
    d = mk(_Comm(fail_times=99), reg=_Reg(live))
    d._evict_after_gc("alice")
    assert d.registry.evicted == []

    # Even on gc SUCCESS the evict re-checks: a tenant that came back
    # (or crashed again leaving parked work) during the broadcast
    # window keeps its slot, token, and mailbox.
    d = mk(_Comm(), reg=_Reg(live))
    d._evict_after_gc("alice")
    assert d.registry.evicted == []
    parked = _T()
    parked.mailbox = ("m1",)
    d = mk(_Comm(), reg=_Reg(parked))
    d._evict_after_gc("alice")
    assert d.registry.evicted == []

    # Transient failure (busy mesh): the retry lands the gc and THEN
    # evicts — a one-shot give-up leaked the slot forever.
    c = _Comm(fail_times=1)
    d = mk(c)
    d._evict_after_gc("alice")
    assert c.sent                            # retried to success
    assert d.registry.evicted == ["alice"]

    c = _Comm(dead={2})
    d = mk(c)
    d._evict_after_gc("alice")
    assert c.sent == [[0, 1, 3]]             # dead rank 2 excluded
    assert d.registry.evicted == ["alice"]
    assert c.scheduler.forgot == ["alice"]


def test_serve_mailbox_releases_counter():
    """The mailbox drain runs off the listener thread bracketed by
    the same serve counter as execute (a slow client's blocked drain
    reply must not let a racing detach evict the tenant mid-claim),
    and the counter drops on success AND failure."""
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon

    class _T:
        name = "alice"

    d = object.__new__(GatewayDaemon)
    d._lock = threading.Lock()
    d._serving = {"alice": 1}                # the listener's increment
    held = []
    d._handle_mailbox = lambda cid, t, m: held.append(
        d._serving.get("alice"))
    d._serve_mailbox(_T(), None, 7)
    assert held == [1]                       # held across the serve
    assert d._serving == {}

    d._serving = {"alice": 1}

    def boom(cid, t, m):
        raise RuntimeError("socket died")

    d._handle_mailbox = boom
    with pytest.raises(RuntimeError):
        d._serve_mailbox(_T(), None, 7)
    assert d._serving == {}                  # failure still releases


def test_gateway_drain_reparks_when_serve_thread_raises():
    """ISSUE 15 lifecycle fix (bracket-discipline finding): the
    gateway drain's ``claim_all`` is destructive, so a serve thread
    that throws between the claim and the send (reply construction,
    encode) must repark before unwinding — or the tenant's parked
    results are lost on BOTH sides and exactly-once becomes
    at-most-once."""
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.messaging.codec import Message
    from nbdistributed_tpu.resilience import ResultMailbox

    class _T:
        name = "alice"
        mailbox = ResultMailbox()

    _T.mailbox.park("m1", Message(msg_type="response",
                                  data={"output": "precious"}))
    d = object.__new__(GatewayDaemon)
    d._lock = threading.Lock()
    events = []
    d.flight = type("F", (), {"record": staticmethod(
        lambda kind, **kw: events.append(kind))})()

    def _boom(cid, m):
        raise RuntimeError("encode blew up")

    d._send_to_client = _boom
    msg = Message(msg_type="mailbox", data={"action": "drain"})
    with pytest.raises(RuntimeError, match="encode blew up"):
        d._handle_mailbox(7, _T(), msg)
    assert _T.mailbox.ids() == ["m1"]          # reparked, not lost
    assert "tenant_mailbox_reparked" in events


def test_tenant_client_close_joins_reader_thread():
    """ISSUE 15 lifecycle fix (shutdown-completeness finding): a
    closed TenantClient must not leave its lock-taking reader thread
    running into interpreter teardown."""
    from nbdistributed_tpu.gateway.client import TenantClient

    tc = object.__new__(TenantClient)
    tc._closed = False
    tc._dead = None
    unblock = threading.Event()
    tc._ch = type("Ch", (), {"close":
                             staticmethod(lambda: unblock.set())})()
    tc._reader = threading.Thread(target=unblock.wait, daemon=True)
    tc._reader.start()
    tc.close()
    tc._reader.join(timeout=2.0)
    assert not tc._reader.is_alive()


def test_tenant_client_close_from_reader_thread_never_self_joins():
    """close() can be invoked from a reader-thread callback; a thread
    cannot join itself, so the guard must skip the join rather than
    raise RuntimeError."""
    from nbdistributed_tpu.gateway.client import TenantClient

    tc = object.__new__(TenantClient)
    tc._closed = False
    tc._dead = None
    tc._ch = type("Ch", (), {"close": staticmethod(lambda: None)})()
    done = []

    def _run():
        tc.close()
        done.append(True)

    t = threading.Thread(target=_run, daemon=True)
    tc._reader = t
    t.start()
    t.join(timeout=2.0)
    assert done == [True]
