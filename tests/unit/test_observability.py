"""Unit tests for the observability layer (ISSUE 2): span tracer
semantics, metrics registry (counter/histogram + Prometheus golden),
NTP-style clock-offset estimation on synthetic RTTs, Chrome-trace
export roundtrip, and the codec's optional ``tr`` header."""

import json
import struct

import pytest

from nbdistributed_tpu.messaging import codec
from nbdistributed_tpu.observability.clock import ClockEstimator
from nbdistributed_tpu.observability.export import merge_trace, save_trace
from nbdistributed_tpu.observability.metrics import (MetricsRegistry,
                                                     registry)
from nbdistributed_tpu.observability.spans import Tracer

pytestmark = [pytest.mark.unit, pytest.mark.obs]


# ---------------------------------------------------------------------
# spans


def test_tracer_disabled_records_nothing():
    tr = Tracer()
    assert tr.begin("x") is None
    with tr.span("y") as s:
        assert s is None
    tr.instant("z")
    assert len(tr) == 0
    assert tr.context() is None  # no wire header when off


def test_tracer_nesting_and_ids():
    tr = Tracer()
    tid = tr.start()
    with tr.span("outer", kind="coordinator") as outer:
        assert outer.trace_id == tid
        with tr.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == tid
    dump = tr.dump()
    assert {s["name"] for s in dump["spans"]} == {"outer", "inner"}
    # inner ended first (stack order) and both have durations set
    assert all(s["dur"] >= 0.0 for s in dump["spans"])


def test_tracer_explicit_wire_parent_wins():
    tr = Tracer()
    tr.start()
    sp = tr.begin("handle/execute", trace_id="remotetid", parent_id="abc")
    tr.end(sp)
    d = tr.dump()["spans"][0]
    assert d["trace_id"] == "remotetid" and d["parent_id"] == "abc"


def test_tracer_activate_crosses_threads():
    import threading
    tr = Tracer()
    tr.start()
    parent = tr.begin("cell/distributed")
    child_parent = []

    def work():
        with tr.activate(parent):
            sp = tr.begin("send/execute")
            child_parent.append(sp.parent_id)
            tr.end(sp)

    t = threading.Thread(target=work)
    t.start()
    t.join()
    tr.end(parent)
    assert child_parent == [parent.span_id]


def test_tracer_start_clears_and_stop_keeps():
    tr = Tracer()
    tr.start()
    tr.end(tr.begin("a"))
    assert tr.stop() == 1
    assert len(tr) == 1          # buffered for dump after stop
    tr.start()
    assert len(tr) == 0          # new session clears


def test_tracer_span_cap():
    from nbdistributed_tpu.observability import spans as spans_mod
    tr = Tracer()
    tr.start()
    old = spans_mod.MAX_SPANS
    spans_mod.MAX_SPANS = 3  # the cap is read at end() time
    try:
        for _ in range(5):
            tr.end(tr.begin("s"))
    finally:
        spans_mod.MAX_SPANS = old
    assert len(tr) == 3
    assert tr.dump()["dropped"] == 2


def test_context_carries_current_span():
    tr = Tracer()
    tr.start()
    with tr.span("outer") as s:
        ctx = tr.context()
        assert ctx == {"tid": s.trace_id, "sid": s.span_id}


# ---------------------------------------------------------------------
# codec tr header


def test_codec_trace_header_roundtrip():
    m = codec.Message(msg_type="execute", data={"code": "1"},
                      trace={"tid": "t1", "sid": "s1"})
    out = codec.decode(codec.encode(m))
    assert out.trace == {"tid": "t1", "sid": "s1"}


def test_codec_no_trace_no_header():
    """The acceptance bar: no wire header emitted unless a trace is
    active — untraced frames stay byte-identical to the old format."""
    frame = codec.encode(codec.Message(msg_type="execute", data="x"))
    hlen = struct.unpack_from("<4sIQ", frame, 0)[1]
    header = json.loads(bytes(frame[codec.HEADER_SIZE:
                                    codec.HEADER_SIZE + hlen]))
    assert "tr" not in header
    assert codec.decode(frame).trace is None


# ---------------------------------------------------------------------
# metrics registry


def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("hits", "help text")
    c.inc()
    c.inc(2)
    assert reg.counter("hits").value == 3  # get-or-create returns same
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        reg.gauge("hits")  # kind clash is an error, not silent


def test_labeled_series_are_independent():
    reg = MetricsRegistry()
    reg.counter("msgs", labels={"dir": "tx"}).inc(5)
    reg.counter("msgs", labels={"dir": "rx"}).inc(7)
    j = reg.to_json()
    assert j["counters"]['msgs{dir="tx"}'] == 5
    assert j["counters"]['msgs{dir="rx"}'] == 7


def test_histogram_bucket_placement():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    cum = dict(h.cumulative())
    assert cum["0.01"] == 1
    assert cum["0.1"] == 3
    assert cum["1"] == 4
    assert cum["+Inf"] == 5
    assert h.count == 5
    assert abs(h.sum - 5.605) < 1e-9


def test_prometheus_text_golden():
    reg = MetricsRegistry()
    reg.counter("nbd_wire_bytes_total", "bytes moved",
                {"dir": "tx"}).inc(1024)
    reg.gauge("nbd_dedup_hits").set(2)
    reg.histogram("nbd_cell_seconds", "cell time",
                  buckets=(0.1, 1.0)).observe(0.5)
    # Golden: series sorted by name, HELP only where help was given.
    expected = (
        '# HELP nbd_cell_seconds cell time\n'
        '# TYPE nbd_cell_seconds histogram\n'
        'nbd_cell_seconds_bucket{le="0.1"} 0\n'
        'nbd_cell_seconds_bucket{le="1"} 1\n'
        'nbd_cell_seconds_bucket{le="+Inf"} 1\n'
        'nbd_cell_seconds_sum 0.5\n'
        'nbd_cell_seconds_count 1\n'
        '# TYPE nbd_dedup_hits gauge\n'
        'nbd_dedup_hits 2\n'
        '# HELP nbd_wire_bytes_total bytes moved\n'
        '# TYPE nbd_wire_bytes_total counter\n'
        'nbd_wire_bytes_total{dir="tx"} 1024\n'
    )
    assert reg.prometheus_text() == expected


def test_wire_hook_counts_actual_socket_writes():
    """tx is counted per ACTUAL socket write (fan-out = one per rank;
    chaos drops = zero, duplicates = two), rx per decoded frame."""
    import threading

    from nbdistributed_tpu.messaging.transport import (
        CoordinatorListener, WorkerChannel)
    from nbdistributed_tpu.observability.metrics import install_wire_hook
    from nbdistributed_tpu.resilience.faults import FaultPlan

    install_wire_hook()
    reg = registry()

    def total(name, direction):
        return sum(v for k, v in reg.to_json()["counters"].items()
                   if k.startswith(name) and f'dir="{direction}"' in k)

    listener = CoordinatorListener()
    connected = threading.Event()
    ranks: set = set()

    def on_conn(r):
        ranks.add(r)
        if len(ranks) == 2:
            connected.set()

    listener.on_connect = on_conn
    listener.start()
    chans = [WorkerChannel("127.0.0.1", listener.port, rank=r)
             for r in (0, 1)]
    try:
        assert connected.wait(10)
        # fan-out: one encode, TWO socket writes -> two tx counts
        before = total("nbd_wire_messages_total", "tx")
        listener.send_to_ranks([0, 1],
                               codec.Message(msg_type="execute", data="x"))
        assert total("nbd_wire_messages_total", "tx") == before + 2
        # duplicate plan: one send call -> two actual writes
        listener.fault_plan = FaultPlan(duplicate=1.0, exempt=())
        before = total("nbd_wire_messages_total", "tx")
        listener.send_to_rank(0, codec.Message(msg_type="execute"))
        assert total("nbd_wire_messages_total", "tx") == before + 2
        # drop plan: the frame never touched a socket -> zero counts
        listener.fault_plan = FaultPlan(drop=1.0, exempt=())
        before = total("nbd_wire_messages_total", "tx")
        listener.send_to_rank(0, codec.Message(msg_type="execute"))
        assert total("nbd_wire_messages_total", "tx") == before
        listener.fault_plan = None
        # rx side: each frame the worker channel decodes counts once
        before = total("nbd_wire_messages_total", "rx")
        before_b = total("nbd_wire_bytes_total", "rx")
        msg = chans[0].recv(timeout=10)
        assert msg.msg_type == "execute"
        assert total("nbd_wire_messages_total", "rx") == before + 1
        assert total("nbd_wire_bytes_total", "rx") > before_b
    finally:
        for c in chans:
            c.close()
        listener.close()


# ---------------------------------------------------------------------
# clock offset estimation


def test_clock_estimator_recovers_offset_from_noisy_rtts():
    import random
    rng = random.Random(7)
    est = ClockEstimator()
    true_offset = 0.350  # worker clock runs 350 ms ahead
    t = 1000.0
    for _ in range(200):
        t += rng.uniform(0.01, 0.05)
        # asymmetric network + handler time: the reply stamp sits
        # somewhere inside the interval, not at the midpoint
        up = rng.uniform(0.0005, 0.003)
        handler = rng.expovariate(1 / 0.002)
        down = rng.uniform(0.0005, 0.003)
        t_send = t
        t_remote = t_send + up + handler + true_offset
        t_recv = t_send + up + handler + down
        est.add(1, t_send, t_remote, t_recv)
    assert abs(est.offset(1) - true_offset) < 0.005
    stats = est.stats()[1]
    assert stats["samples"] == 200
    assert stats["min_rtt_s"] is not None


def test_clock_estimator_defaults_and_negative_rtt():
    est = ClockEstimator()
    assert est.offset(3) == 0.0          # no samples: identity merge
    est.add(0, 100.0, 100.5, 99.0)       # clock stepped: rejected
    assert est.offsets() == {}
    est.add(0, 100.0, 100.2, 100.01)
    assert abs(est.offset(0) - 0.195) < 1e-9


def test_clock_estimator_keeps_lowest_rtt_samples():
    est = ClockEstimator(keep=2)
    # Two clean samples with offset ~0.1, then many inflated ones with
    # a wild offset — the min-RTT filter must ignore the inflated ones.
    est.add(0, 0.0, 0.105, 0.01)
    est.add(0, 1.0, 1.105, 1.01)
    for i in range(20):
        est.add(0, 10.0 + i, 15.0 + i, 12.0 + i)  # rtt 2s, offset 4s
    assert abs(est.offset(0) - 0.1) < 1e-6


# ---------------------------------------------------------------------
# chrome trace export


def _dump_with(spans, instants=(), trace_id="t0"):
    return {"trace_id": trace_id, "spans": list(spans),
            "instants": list(instants), "dropped": 0}


def test_merge_trace_is_valid_chrome_format(tmp_path):
    coord = _dump_with([
        {"name": "send/execute", "kind": "coordinator", "tid": 0,
         "trace_id": "t0", "span_id": "c1", "t0": 100.0, "dur": 0.5},
    ])
    ranks = {
        r: _dump_with([
            {"name": "handle/execute", "kind": "worker", "tid": 0,
             "trace_id": "t0", "span_id": f"w{r}", "parent_id": "c1",
             "t0": 100.25 + 0.2, "dur": 0.1},
        ])
        for r in (0, 1)
    }
    merged = merge_trace(coord, ranks, {0: 0.2, 1: 0.2},
                         coordinator_faults=[
                             {"ts": 100.1, "index": 3,
                              "actions": ["drop"], "kind": "execute"}])
    evs = merged["traceEvents"]
    # every event is well-formed chrome-trace (metadata events carry
    # no timestamp, by the format)
    for e in evs:
        assert {"name", "ph", "pid"} <= set(e)
        if e["ph"] != "M":
            assert "ts" in e
    spans = [e for e in evs if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {-1, 0, 1}
    # clock correction puts the worker span INSIDE the coordinator one
    c = next(e for e in spans if e["pid"] == -1)
    for r in (0, 1):
        w = next(e for e in spans if e["pid"] == r)
        assert c["ts"] <= w["ts"] <= c["ts"] + c["dur"]
        # parent/span ids surfaced for Perfetto's detail pane
        assert w["args"]["parent_id"] == "c1"
    faults = [e for e in evs if e["ph"] == "i" and e["cat"] == "fault"]
    assert len(faults) == 1 and faults[0]["name"] == "fault:drop"
    # file roundtrip: valid JSON, event count excludes metadata
    path = str(tmp_path / "trace.json")
    n = save_trace(path, merged)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["traceEvents"] == evs
    assert n == len([e for e in evs if e["ph"] != "M"])


def test_merge_trace_rebases_timestamps():
    coord = _dump_with([{"name": "a", "kind": "", "tid": 0,
                         "trace_id": "t", "span_id": "s",
                         "t0": 1.75e9, "dur": 0.001}])
    merged = merge_trace(coord, {}, {})
    ev = [e for e in merged["traceEvents"] if e["ph"] == "X"][0]
    assert ev["ts"] == 0.0  # rebased to the earliest event
    assert merged["otherData"]["base_unix_s"] == 1.75e9


def test_merge_trace_empty_inputs():
    merged = merge_trace(None, {}, {})
    assert merged["traceEvents"] == []


def test_fault_plan_records_timestamped_events():
    from nbdistributed_tpu.resilience.faults import FaultPlan
    plan = FaultPlan(seed=3, drop=1.0)  # every frame dropped
    sent = []
    plan.transmit(b"xxxx", sent.append, kind="execute")
    assert sent == []
    evs = plan.events()
    assert len(evs) == 1
    assert evs[0]["actions"] == ["drop"]
    assert evs[0]["kind"] == "execute"
    assert evs[0]["ts"] > 0
