"""Unit tests for the elastic-pool substrate (ISSUE 16): the
fake-clock ``PoolAutoscaler`` policy (hysteresis, min/max clamp,
cooldown, no flap on a single spike), the generation-stamped
``PoolMembership`` transitions, the scheduler's pause/drain gate,
and the ``gc_runs`` mid-resize keep-rule."""

import os
import time

import pytest

from nbdistributed_tpu.gateway.membership import (ACTIVE, DRAINING,
                                                  PoolMembership)
from nbdistributed_tpu.gateway.scheduler import SchedPolicy, Scheduler
from nbdistributed_tpu.resilience.autoscaler import (AutoscalePolicy,
                                                     Decision,
                                                     PoolAutoscaler)

pytestmark = [pytest.mark.unit, pytest.mark.elastic]


# ----------------------------------------------------------------------
# AutoscalePolicy env parsing

def test_autoscale_policy_env():
    p = AutoscalePolicy.from_env(env={})
    assert (p.min_workers, p.max_workers) == (1, 8)
    p = AutoscalePolicy.from_env(env={
        "NBD_AUTOSCALE_MIN": "2", "NBD_AUTOSCALE_MAX": "16",
        "NBD_AUTOSCALE_UP_QUEUE": "1",
        "NBD_AUTOSCALE_SUSTAIN_S": "3",
        "NBD_AUTOSCALE_COOLDOWN_S": "7",
        "NBD_AUTOSCALE_IDLE_S": "30"})
    assert (p.min_workers, p.max_workers) == (2, 16)
    assert (p.up_queue, p.sustain_s, p.cooldown_s, p.idle_s) \
        == (1, 3.0, 7.0, 30.0)
    # Malformed values degrade to defaults, not crashes.
    p = AutoscalePolicy.from_env(env={"NBD_AUTOSCALE_SUSTAIN_S": "x"})
    assert p.sustain_s == 15.0
    assert "band" in p.describe()


# ----------------------------------------------------------------------
# PoolAutoscaler decisions (pure fake clock)

def _scaler(**kw):
    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 8)
    kw.setdefault("sustain_s", 10.0)
    kw.setdefault("idle_s", 60.0)
    kw.setdefault("cooldown_s", 30.0)
    return PoolAutoscaler(AutoscalePolicy(**kw))


def test_grow_requires_sustained_pressure():
    a = _scaler()
    # Pressure appears at t=0 — nothing fires until sustain_s elapses.
    assert a.observe(0.0, world_size=2, queued=10) is None
    assert a.observe(5.0, world_size=2, queued=10) is None
    d = a.observe(10.0, world_size=2, queued=10)
    assert isinstance(d, Decision) and d.action == "grow"
    assert d.target == 4 and "queue" in d.reason


def test_single_spike_does_not_flap():
    a = _scaler()
    assert a.observe(0.0, world_size=2, queued=10) is None
    # The spike clears: the persistence clock resets...
    assert a.observe(5.0, world_size=2, queued=0, active=1) is None
    # ...so renewed pressure must sustain afresh.
    assert a.observe(6.0, world_size=2, queued=10) is None
    assert a.observe(12.0, world_size=2, queued=10) is None
    assert a.observe(16.0, world_size=2, queued=10).action == "grow"


def test_backlog_and_p95_signals():
    a = _scaler()
    a.observe(0.0, world_size=2, backlog=100)
    d = a.observe(10.0, world_size=2, backlog=100)
    assert d.action == "grow" and "backlog" in d.reason
    a = _scaler()
    a.observe(0.0, world_size=2, queue_p95_s=9.0)
    d = a.observe(10.0, world_size=2, queue_p95_s=9.0)
    assert d.action == "grow" and "p95" in d.reason


def test_cooldown_blackout():
    a = _scaler()
    a.observe(0.0, world_size=2, queued=10)
    assert a.observe(10.0, world_size=2, queued=10).action == "grow"
    a.note_resized(11.0)
    # Sustained pressure inside the cooldown window: no decision.
    a.observe(12.0, world_size=4, queued=10)
    assert a.observe(30.0, world_size=4, queued=10) is None
    # After the window the clock must STILL sustain (note_resized
    # dropped it), so the first post-cooldown look arms, not fires.
    assert a.observe(45.0, world_size=4, queued=10) is None
    assert a.observe(55.0, world_size=4, queued=10).action == "grow"


def test_shrink_after_sustained_idle_and_min_clamp():
    a = _scaler()
    assert a.observe(0.0, world_size=4) is None
    assert a.observe(30.0, world_size=4) is None
    d = a.observe(60.0, world_size=4)
    assert d.action == "shrink" and d.target == 2
    # Any activity resets the idle clock.
    a = _scaler()
    a.observe(0.0, world_size=4)
    a.observe(30.0, world_size=4, active=1)
    assert a.observe(60.0, world_size=4) is None
    # At min, sustained idle decides nothing.
    a = _scaler(min_workers=2)
    a.observe(0.0, world_size=2)
    assert a.observe(600.0, world_size=2) is None


def test_band_clamp_is_unconditional():
    a = _scaler(min_workers=2, max_workers=4)
    d = a.observe(0.0, world_size=1)
    assert d.action == "grow" and d.target == 2
    d = a.observe(0.0, world_size=9, queued=50)
    assert d.action == "shrink" and d.target == 4
    # Grow target clamps at max even under pressure.
    a = _scaler(max_workers=3)
    a.observe(0.0, world_size=2, queued=10)
    d = a.observe(10.0, world_size=2, queued=10)
    assert d.target == 3
    # At max, pressure decides nothing.
    a = _scaler(max_workers=2)
    a.observe(0.0, world_size=2, queued=10)
    assert a.observe(100.0, world_size=2, queued=10) is None


# ----------------------------------------------------------------------
# Autoscaler audit trail (ISSUE 18): every observe() leaves one
# structured record — verdict or hold — naming the inputs that drove
# it, and a returned Decision carries its record for flight recording.


def test_audit_record_on_every_observation():
    a = _scaler()
    a.observe(0.0, world_size=2, queued=10, backlog=3,
              queue_p95_s=1.5)
    a.observe(5.0, world_size=2, queued=10)
    d = a.observe(10.0, world_size=2, queued=10)
    recs = a.decisions()
    assert len(recs) == 3
    # Hold records name the armed pressure + sustain clock.
    hold = recs[0]
    assert hold["verdict"] == "hold" and hold["target"] is None
    assert hold["inputs"] == {"queued": 10, "active": 0, "backlog": 3,
                              "queue_p95_s": 1.5}
    assert any("queue" in s for s in hold["pressure"])
    assert recs[1]["sustain_s"] == 5.0
    # The fired decision's record is the SAME dict the daemon flight-
    # records, with the verdict filled in.
    fired = recs[2]
    assert fired is d.record
    assert fired["verdict"] == "grow" and fired["target"] == 4
    assert fired["reason"] == d.reason and not fired["clamp"]
    assert fired["sustain_s"] == 10.0
    # decisions(last=N) trims from the old end.
    assert a.decisions(1) == [fired]


def test_audit_records_cooldown_and_clamp():
    a = _scaler(min_workers=2)
    d = a.observe(0.0, world_size=1)          # band clamp
    assert d.record["clamp"] and d.record["verdict"] == "grow"
    a.note_resized(1.0)
    a.observe(2.0, world_size=2, queued=50)   # inside cooldown
    rec = a.decisions()[-1]
    assert rec["verdict"] == "hold" and rec["reason"] == "cooldown"
    assert rec["cooldown_s"] > 0


def test_audit_idle_clock_reaches_shrink_record():
    a = _scaler()
    a.observe(0.0, world_size=4)
    a.observe(30.0, world_size=4)
    d = a.observe(60.0, world_size=4)
    assert d.action == "shrink"
    assert d.record["idle_for_s"] == 60.0
    assert d.record["pressure"] == []


# ----------------------------------------------------------------------
# PoolMembership

def test_membership_seed_and_describe():
    m = PoolMembership(2, epoch=1, now=5.0)
    assert m.generation == 1 and m.epoch == 1
    assert m.active_ranks() == [0, 1] and not m.draining
    d = m.describe()
    assert d["ranks"]["0"]["join_epoch"] == 1
    assert d["ranks"]["1"]["state"] == ACTIVE
    assert d["transition"] is None


def test_membership_resize_cycle():
    m = PoolMembership(2, epoch=1)
    plan = m.begin_resize(4, 2, reason="pressure", now=10.0)
    assert plan["from_world"] == 2 and plan["to_world"] == 4
    assert m.draining and m.rank_state(0) == DRAINING
    assert m.active_ranks() == []
    # Only one transition at a time.
    with pytest.raises(RuntimeError, match="already in flight"):
        m.begin_resize(3, 3)
    gen = m.complete_resize(4, 2, now=11.0)
    assert gen == 2 and m.generation == 2 and m.epoch == 2
    assert m.active_ranks() == [0, 1, 2, 3] and not m.draining
    assert m.describe()["ranks"]["3"]["join_epoch"] == 2
    # The retired epoch-set stays queryable for late-frame forensics.
    assert m.epoch_set(1) == [0, 1]
    assert m.epoch_set(2) == [0, 1, 2, 3]
    assert m.describe()["retired_epochs"] == [1]


def test_membership_abort_restores_active():
    m = PoolMembership(2, epoch=1)
    m.begin_resize(4, 2)
    m.abort_resize()
    assert not m.draining and m.active_ranks() == [0, 1]
    assert m.generation == 1 and m.epoch == 1


# ----------------------------------------------------------------------
# Scheduler pause/drain gate

def _sched(**kw):
    kw.setdefault("mesh_slots", 1)
    return Scheduler(SchedPolicy(**kw))


def test_scheduler_pause_queues_instead_of_granting():
    s = _sched()
    s.pause("resize")
    t = s.submit("a", "m1", priority=0)
    assert not t.event.is_set()          # held, not granted
    snap = s.snapshot()
    assert snap["paused"] == "resize" and snap["queued"] == 1
    assert s.active_count() == 0
    s.resume()
    assert t.event.wait(2.0) and t.state == "active"
    assert s.snapshot()["paused"] is None


def test_scheduler_pause_blocks_promotion():
    s = _sched()
    t1 = s.submit("a", "m1")
    assert t1.verdict["status"] == "dispatch"
    t2 = s.submit("a", "m2")
    assert not t2.event.is_set()
    s.pause("resize")
    s.complete("m1")
    assert s.active_count() == 0
    assert not t2.event.is_set()         # drained: nothing promotes
    s.resume()
    assert t2.event.wait(2.0) and t2.state == "active"


# ----------------------------------------------------------------------
# gc_runs keep-rule for pools mid-resize

def test_gc_keeps_recent_gateway_manifest(tmp_path, monkeypatch):
    import json

    from nbdistributed_tpu.resilience import session as session_mod

    monkeypatch.delenv("NBD_RUN_DIR", raising=False)
    root = tmp_path / "runs"
    d = root / "pool-x"
    d.mkdir(parents=True)
    now = time.time()
    # A gateway manifest whose pid is DEAD (the daemon is mid-restart
    # for a resize) but whose epoch was bumped moments ago.
    (d / "gateway.json").write_text(json.dumps({
        "kind": "gateway", "pid": 2 ** 30, "epoch": 2,
        "updated_ts": now - 5.0}))
    os.utime(d, (now - 7200, now - 7200))
    res = session_mod.gc_runs(str(root), ttl_s=60.0, dry_run=True,
                              now=now)
    assert str(d) in res["kept"]
    assert "resize" in res["kept_why"][str(d)]
    # Once the restart window has passed with the daemon still dead,
    # the ordinary TTL sweep applies again.
    (d / "gateway.json").write_text(json.dumps({
        "kind": "gateway", "pid": 2 ** 30, "epoch": 2,
        "updated_ts": now - 9000}))
    os.utime(d / "gateway.json", (now - 9000, now - 9000))
    os.utime(d, (now - 9000, now - 9000))
    res = session_mod.gc_runs(str(root), ttl_s=60.0, dry_run=True,
                              now=now)
    assert str(d) in res["swept"]
