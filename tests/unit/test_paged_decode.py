"""Paged KV-cache decode (ISSUE 17): the device half of the block
allocator.  gather∘scatter over table-selected blocks is an identity
on live rows, so paged greedy serving must be BIT-IDENTICAL to solo
``generate()`` — with dense admission order, quantized caches, and
chunked/interleaved prefill all invisible to the numerics — while the
allocator-backed pool recycles blocks across requests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import generate, init_params, tiny_config
from nbdistributed_tpu.models.serving import DecodeServer

# Heavy interpret-mode model tests: excluded from the fast
# product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.serve, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def solo(params, cfg, prompt, n, **kw):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], cfg,
                   n, **kw)
    return [int(t) for t in np.asarray(out)[0][len(prompt):]]


def test_paged_staggered_matches_solo_generate(setup):
    """Staggered admission into a paged 2-slot pool: every request's
    greedy stream equals its standalone generate() run — paging must
    change capacity accounting only, never tokens."""
    cfg, params = setup
    reqs = [([5, 9, 2], 7), ([7, 1, 3, 11, 4], 5), ([2, 2], 6)]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4,
                       kv_block_tokens=8)
    r0 = srv.submit(*reqs[0])
    srv.step()
    r1 = srv.submit(*reqs[1])
    srv.step()
    r2 = srv.submit(*reqs[2])          # queues until a slot frees
    srv.run_until_done(max_steps=100)
    for rid, (prompt, n) in zip((r0, r1, r2), reqs):
        assert srv.outputs[rid] == solo(params, cfg, prompt, n), rid
    # Every block returned to the pool at finish.
    snap = srv.kv_snapshot()
    assert snap["used"] == 0 and snap["owners"] == {}


def test_paged_block_starved_pool_recycles(setup):
    """A pool with only enough blocks for ONE worst-case request at a
    time: later submissions park as pending (the self-healing
    admission backstop) and admit as finishing requests free their
    blocks — all complete, all bit-exact."""
    cfg, params = setup
    reqs = [([i + 1, i + 2], 4) for i in range(4)]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=16, pad_to=4,
                       kv_block_tokens=8,
                       kv_blocks=1)        # ceil((2+4)/8) = 1 block
    rids = [srv.submit(*r) for r in reqs]
    assert srv.kv_snapshot()["used"] == 1  # one admitted, three park
    srv.run_until_done(max_steps=200)
    for rid, (prompt, n) in zip(rids, reqs):
        assert srv.outputs[rid] == solo(params, cfg, prompt, n)
    assert srv.kv_snapshot()["used"] == 0


def test_paged_int8_kv_matches_int8_generate(setup):
    """Paged + int8-quantized KV: gather/scatter moves the quantized
    payload and its scales together, so the stream equals the dense
    int8 reference token for token (the quantized round-trip adds no
    further error)."""
    cfg, params = setup
    prompt, n = [5, 9, 2, 7], 6
    ref = solo(params, cfg, prompt, n, kv_quantized=True)
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4,
                       kv_quantized=True, kv_block_tokens=8)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[rid] == ref


def test_paged_interleaved_chunked_prefill_matches_solo(setup):
    """A long prompt streamed in 4-token chunks BETWEEN decode ticks
    of an already-active request: both streams bit-identical to their
    solo runs — the chunk boundary is KV-exact and interleaving
    changes latency shape only."""
    cfg, params = setup
    short, long = ([5, 9, 2], 6), ([7, 1, 3, 11, 4, 2, 8, 6, 1, 9,
                                    4, 4, 2, 7], 5)
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4,
                       kv_block_tokens=8, prefill_chunk=4,
                       interleave_prefill=True)
    r_short = srv.submit(*short)
    srv.step()                         # short is decoding
    r_long = srv.submit(*long)         # streams in one chunk per step
    srv.run_until_done(max_steps=100)
    assert srv.outputs[r_short] == solo(params, cfg, *short)
    assert srv.outputs[r_long] == solo(params, cfg, *long)


def test_cancel_frees_blocks_immediately(setup):
    """A cancelled mid-decode request must return its blocks NOW (a
    shed request cannot pin KV until its stream would have ended) and
    the freed blocks must admit the next request."""
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=16, pad_to=4,
                       kv_block_tokens=8, kv_blocks=1)
    r0 = srv.submit([5, 9], 6)         # 8 tokens = the whole pool
    srv.step()
    assert srv.kv_snapshot()["used"] == 1
    assert srv.cancel(r0) is True
    assert srv.kv_snapshot()["used"] == 0
    assert srv.cancel(r0) is False     # already finished: no-op
    r1 = srv.submit([3, 1], 4)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[r1] == solo(params, cfg, [3, 1], 4)


def test_kv_snapshot_surface(setup):
    """The snapshot the heartbeat telemetry reads: paged servers
    report block occupancy with per-request owner counts; dense
    servers report None."""
    cfg, params = setup
    dense = DecodeServer(params, cfg, max_batch=1, max_len=16,
                         pad_to=4)
    assert dense.kv_snapshot() is None
    srv = DecodeServer(params, cfg, max_batch=2, max_len=16, pad_to=4,
                       kv_block_tokens=4)
    rid = srv.submit([5, 9, 2], 4)     # ceil((3+4)/4) = 2 blocks
    srv.step()
    snap = srv.kv_snapshot()
    assert snap["block_tokens"] == 4
    assert snap["blocks"] == 2 * (16 // 4)   # dense-capacity default
    assert snap["used"] == 2 and snap["owners"] == {str(rid): 2} \
        or snap["owners"] == {rid: 2}


def test_paged_validation(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="kv_block_tokens"):
        DecodeServer(params, cfg, max_batch=1, max_len=16,
                     kv_block_tokens=0)
    with pytest.raises(ValueError, match="kv_blocks"):
        DecodeServer(params, cfg, max_batch=1, max_len=16,
                     kv_blocks=4)
    with pytest.raises(ValueError, match="interleave_prefill"):
        DecodeServer(params, cfg, max_batch=1, max_len=16,
                     interleave_prefill=True)
