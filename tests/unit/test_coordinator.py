"""Unit tests for request/response correlation in CommunicationManager.

Exercises the coordinator against scripted in-process worker channels —
no JAX, no subprocesses (the reference never had tests at this layer at
all; SURVEY §4).
"""

import threading
import time

import pytest

from nbdistributed_tpu.messaging import (
    CommunicationManager, Message, WorkerChannel, WorkerDied)


class ScriptedWorker:
    """Minimal worker loop: answers every request via a handler fn."""

    def __init__(self, port, rank, handler):
        self.chan = WorkerChannel("127.0.0.1", port, rank=rank)
        self.rank = rank
        self.handler = handler
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while True:
            try:
                msg = self.chan.recv()
            except Exception:
                return
            if msg.msg_type == "__stop__":
                return
            out = self.handler(self.rank, msg)
            if out is not None:
                try:
                    self.chan.send(msg.reply(data=out, rank=self.rank))
                except Exception:
                    return  # channel closed by test teardown mid-reply

    def close(self):
        self.chan.close()


@pytest.fixture
def world():
    mgr = CommunicationManager(num_workers=3, timeout=10)
    workers = [ScriptedWorker(mgr.port, r, lambda rank, m: {"echo": m.data,
                                                            "rank": rank})
               for r in range(3)]
    mgr.wait_for_workers(timeout=10)
    yield mgr, workers
    for w in workers:
        w.close()
    mgr.shutdown()


def test_broadcast_collects_all(world):
    mgr, _ = world
    out = mgr.send_to_all("execute", "code")
    assert sorted(out) == [0, 1, 2]
    assert out[1].data == {"echo": "code", "rank": 1}


def test_subset_request_no_fullworld_wait(world):
    """Targeted requests complete from subset responses alone (the
    reference busy-polled here, communication.py:348-359)."""
    mgr, _ = world
    t0 = time.time()
    out = mgr.send_to_ranks([0, 2], "execute", "x")
    assert sorted(out) == [0, 2]
    assert time.time() - t0 < 5


def test_single_rank(world):
    mgr, _ = world
    msg = mgr.send_to_rank(1, "status")
    assert msg.data["rank"] == 1


def test_timeout_lists_missing_ranks():
    mgr = CommunicationManager(num_workers=2, timeout=0.3)
    # rank 0 answers, rank 1 stays silent
    w0 = ScriptedWorker(mgr.port, 0, lambda r, m: {"ok": True})
    w1 = ScriptedWorker(mgr.port, 1, lambda r, m: None)
    mgr.wait_for_workers(timeout=10)
    with pytest.raises(TimeoutError, match=r"\[1\]"):
        mgr.send_to_all("execute", "x")
    w0.close(); w1.close(); mgr.shutdown()


def test_worker_death_aborts_pending_request():
    """No-timeout mode must not hang when a worker dies (the reference
    hangs forever: communication.py:263-269)."""
    mgr = CommunicationManager(num_workers=2, timeout=None)
    w0 = ScriptedWorker(mgr.port, 0, lambda r, m: {"ok": True})
    slow_release = threading.Event()
    def slow_handler(r, m):
        slow_release.wait(30)
        return {"ok": True}
    w1 = ScriptedWorker(mgr.port, 1, slow_handler)
    mgr.wait_for_workers(timeout=10)

    def kill_soon():
        time.sleep(0.3)
        w1.close()  # socket drop == process death from coordinator's view
    threading.Thread(target=kill_soon, daemon=True).start()
    t0 = time.time()
    with pytest.raises(WorkerDied):
        mgr.send_to_all("execute", "x")
    assert time.time() - t0 < 10
    slow_release.set()
    w0.close(); mgr.shutdown()


def test_request_to_known_dead_worker_fails_fast():
    mgr = CommunicationManager(num_workers=1, timeout=None)
    w0 = ScriptedWorker(mgr.port, 0, lambda r, m: {"ok": True})
    mgr.wait_for_workers(timeout=10)
    w0.close()
    deadline = time.time() + 5
    while 0 in mgr.connected_ranks() and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(WorkerDied):
        mgr.send_to_all("execute", "x")
    mgr.shutdown()


def test_stream_output_routed_to_callback(world):
    mgr, workers = world
    got = []
    mgr.set_output_callback(lambda rank, data: got.append((rank, data)))
    workers[2].chan.send(Message(
        msg_type="stream_output", rank=2,
        data={"text": "hello\n", "stream": "stdout"}))
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    assert got == [(2, {"text": "hello\n", "stream": "stdout"})]


def test_late_response_after_timeout_is_dropped():
    mgr = CommunicationManager(num_workers=1, timeout=0.2)
    delay = 0.6
    def slow(r, m):
        time.sleep(delay)
        return {"late": True}
    w0 = ScriptedWorker(mgr.port, 0, slow)
    mgr.wait_for_workers(timeout=10)
    with pytest.raises(TimeoutError):
        mgr.send_to_all("execute", "x")
    time.sleep(delay)  # late reply arrives, must be silently dropped
    out = mgr.send_to_all("execute", "y", timeout=5)
    assert out[0].data == {"late": True}
    w0.close(); mgr.shutdown()
