"""Unit tests for REPL executor semantics (reference: worker.py:248-387
defines the contract; SURVEY §4 calls for porting these semantics exactly)."""

import sys

from nbdistributed_tpu.runtime.executor import execute_cell


def run(code, ns=None, streams=None):
    ns = ns if ns is not None else {}
    out = execute_cell(code, ns,
                       (lambda t, k: streams.append((k, t)))
                       if streams is not None else None)
    return out, ns


def test_single_expression_echo():
    out, _ = run("1 + 1")
    assert out["status"] == "success"
    assert out["output"] == "2"


def test_statements_then_expression():
    out, ns = run("x = 10\ny = x * 2\ny + 1")
    assert out["output"] == "21"
    assert ns["x"] == 10 and ns["y"] == 20


def test_plain_statements_no_echo():
    out, ns = run("x = 5")
    assert out["output"] == ""
    assert ns["x"] == 5


def test_none_result_not_echoed():
    out, _ = run("print('hi')\nNone")
    assert out["output"].strip() == "hi"


def test_namespace_persists_across_cells():
    ns = {}
    run("a = 1", ns)
    run("b = a + 1", ns)
    out, _ = run("a + b", ns)
    assert out["output"] == "3"


def test_print_streams_immediately_and_in_order():
    streams = []
    out, _ = run("print('first')\nprint('second')\n'result!'", streams=streams)
    kinds = [k for k, _ in streams]
    texts = [t.strip() for _, t in streams if t.strip()]
    assert texts == ["first", "second", "'result!'"]
    assert kinds[-1] == "result"
    assert "first" in out["output"] and out["output"].endswith("'result!'")


def test_blank_writes_not_streamed():
    streams = []
    run("print()", streams=streams)
    assert all(t.strip() for _, t in streams)


def test_error_returns_traceback_and_restores_stdout():
    before = sys.stdout
    out, _ = run("1 / 0")
    assert sys.stdout is before
    assert "ZeroDivisionError" in out["traceback"]
    assert out["error"]


def test_syntax_error_reported():
    out, _ = run("def broken(:")
    assert "SyntaxError" in out["traceback"]


def test_stdout_restored_after_success():
    before = sys.stdout
    run("print('x')")
    assert sys.stdout is before


def test_multiline_function_definition_and_call():
    ns = {}
    run("def f(a):\n    return a * 3", ns)
    out, _ = run("f(7)", ns)
    assert out["output"] == "21"


def test_duration_measured():
    out, _ = run("import time\ntime.sleep(0.05)")
    assert out["duration_s"] >= 0.05


def test_exception_mid_stream_keeps_prior_output():
    streams = []
    out, _ = run("print('before')\nraise ValueError('boom')",
                 streams=streams)
    assert any("before" in t for _, t in streams)
    assert out["error"] == "boom"


def test_loop_prints_stream_per_iteration():
    streams = []
    run("for i in range(3):\n    print(i)", streams=streams)
    texts = [t.strip() for _, t in streams if t.strip()]
    assert texts == ["0", "1", "2"]


def test_last_expression_object_reprs():
    out, _ = run("class Q:\n    def __repr__(self):\n        return '<Q!>'\nQ()")
    assert out["output"] == "<Q!>"
