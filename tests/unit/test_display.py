"""Streaming display + error reporting tests."""

from nbdistributed_tpu.magics.display import StreamDisplay, print_rank_errors
from nbdistributed_tpu.messaging import Message


def collect():
    out = []
    return out, lambda s: out.append(s)


def test_rank_headers_group_consecutive_output():
    out, p = collect()
    d = StreamDisplay(print_fn=p)
    d.feed(0, {"text": "a\n", "stream": "stdout"})
    d.feed(0, {"text": "b\n", "stream": "stdout"})
    d.feed(1, {"text": "c\n", "stream": "stdout"})
    d.drain()
    assert "".join(out) == "🔹 Rank 0:\na\nb\n🔹 Rank 1:\nc\n"


def test_incremental_drain_no_duplicates():
    out, p = collect()
    d = StreamDisplay(print_fn=p)
    d.feed(0, {"text": "first\n", "stream": "stdout"})
    assert d.drain() is True
    assert d.drain() is False
    d.feed(0, {"text": "second\n", "stream": "stdout"})
    d.drain()
    joined = "".join(out)
    assert joined.count("first") == 1 and joined.count("second") == 1
    assert joined.count("Rank 0") == 1  # same rank -> one header


def test_blank_and_noise_filtered():
    out, p = collect()
    d = StreamDisplay(print_fn=p)
    d.feed(0, {"text": "   \n", "stream": "stdout"})
    d.feed(0, {"text": "<IPython.core.display.Javascript object>\n",
               "stream": "stdout"})
    d.drain()
    assert out == []


def test_print_rank_errors_only_failures():
    out, p = collect()
    responses = {
        0: Message(msg_type="response", rank=0,
                   data={"output": "4", "status": "success"}),
        1: Message(msg_type="response", rank=1,
                   data={"error": "boom", "traceback": "Trace...\n"}),
    }
    failed = print_rank_errors(responses, print_fn=p)
    joined = "".join(out)
    assert failed == 1
    assert "Rank 1" in joined and "boom" in joined
    assert "Rank 0" not in joined
