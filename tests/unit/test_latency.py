"""Unit tests for the latency observatory (ISSUE 13): the ``lt`` wire
header's absent-when-off contract, clock-corrected monotone stage
ordering on synthetic skewed clocks, the Prometheus golden for the new
log-scale histograms, the exposition-text validator, the scrape
endpoint's routes + token gating, and the skew/flight-health
satellites."""

import json
import time
import types
import urllib.error
import urllib.request

import pytest

from nbdistributed_tpu.messaging import codec
from nbdistributed_tpu.observability import latency as lat_mod
from nbdistributed_tpu.observability.httpd import MetricsHTTPD
from nbdistributed_tpu.observability.latency import (
    STAGES, LatencyObservatory, format_stage_table, format_waterfall,
    skew_warnings)
from nbdistributed_tpu.observability.metrics import (
    LATENCY_BUCKETS, MetricsRegistry, validate_prometheus_text)

pytestmark = [pytest.mark.unit, pytest.mark.obs]


# ---------------------------------------------------------------------
# wire header: absent when off, round-trips when on


def test_lt_header_absent_when_unset():
    frame = codec.encode(codec.Message(msg_type="execute",
                                       data={"code": "x"}))
    assert b'"lt"' not in frame
    assert codec.decode(frame).latency is None


def test_lt_header_roundtrip():
    stamps = {"dq": 1.5, "xs": 2.5, "xe": 3.5, "cs": 0.25, "rs": 4.0}
    frame = codec.encode(codec.Message(msg_type="response",
                                       data={}, latency=stamps))
    assert codec.decode(frame).latency == stamps
    # request side: the flag form
    req = codec.encode(codec.Message(msg_type="execute", data={},
                                     latency=1))
    assert codec.decode(req).latency == 1


def test_reply_does_not_inherit_latency_flag():
    msg = codec.Message(msg_type="execute", data={}, latency=1)
    assert msg.reply(data={}).latency is None


# ---------------------------------------------------------------------
# observatory record construction


def _reply(stamps, recv):
    m = types.SimpleNamespace()
    m.latency = stamps
    m.recv_ts = recv
    return m


def _drive(obs, *, offset=0.0, skew=0.0, rank=0, base=1000.0):
    """One synthetic request: coordinator timeline at ``base``; the
    worker clock runs ``skew`` seconds ahead; ``offset`` is what the
    estimator believes the skew is."""
    clock = {"t": base}
    obs._now = lambda: clock["t"]
    obs.begin("m1", "execute", None, vet_s=0.001)
    clock["t"] = base + 0.002          # queued for 2 ms
    obs.note_grant("m1")
    # worker-side chain, stamped on the worker's (skewed) clock
    stamps = {"dq": base + 0.003 + skew, "xs": base + 0.004 + skew,
              "xe": base + 0.010 + skew, "cs": 0.002,
              "rs": base + 0.0101 + skew}
    clock["t"] = base + 0.012
    rec = obs.complete("m1", {rank: _reply(stamps, base + 0.011)},
                       lambda r: offset, t_deliver=base + 0.012)
    return rec


def test_stage_chain_monotone_and_sums_to_e2e():
    obs = LatencyObservatory(enabled=True, registry=MetricsRegistry())
    rec = _drive(obs)
    assert set(rec["stages"]) == set(STAGES)
    assert all(v >= 0 for v in rec["stages"].values())
    assert sum(rec["stages"].values()) == pytest.approx(rec["e2e"],
                                                        rel=1e-6)
    # compile split out of execute: handler was 6 ms, 2 ms compiling
    assert rec["stages"]["compile"] == pytest.approx(0.002)
    assert rec["stages"]["execute"] == pytest.approx(0.004)
    assert rec["stages"]["vet"] == pytest.approx(0.001)
    assert rec["stages"]["queue"] == pytest.approx(0.002)


def test_skewed_clock_corrected_stages_stay_monotone():
    """A worker clock 5 s ahead, perfectly estimated: corrected stages
    equal the unskewed ones.  Under-estimated skew clamps at zero
    instead of going negative."""
    reg = MetricsRegistry()
    ref = _drive(LatencyObservatory(enabled=True, registry=reg))
    corrected = _drive(LatencyObservatory(enabled=True, registry=reg),
                       skew=5.0, offset=5.0)
    for s in STAGES:
        assert corrected["stages"][s] == pytest.approx(
            ref["stages"][s], abs=1e-9)
    # estimator off by the full 5 s (offset=0): raw worker stamps land
    # in the coordinator's future — wire inflates, reply would go
    # NEGATIVE without the clamp
    bad = _drive(LatencyObservatory(enabled=True, registry=reg),
                 skew=5.0, offset=0.0)
    assert all(v >= 0.0 for v in bad["stages"].values())
    # the reply-WIRE split clamps to zero (not negative); only the
    # same-clock (offset-immune) reply-build segment survives
    assert bad["stages"]["reply"] == pytest.approx(0.0001, abs=1e-9)
    # mis-estimation skews the wire/reply split, never the sum
    assert sum(bad["stages"].values()) == pytest.approx(bad["e2e"],
                                                       rel=1e-6)


def test_disabled_observatory_records_nothing():
    obs = LatencyObservatory(enabled=False, registry=MetricsRegistry())
    obs.begin("m1", "execute")
    obs.note_grant("m1")
    assert obs.complete("m1", {}, lambda r: 0.0) is None
    assert obs.records() == [] and obs.summary()["count"] == 0


def test_drop_forgets_pending_and_counts():
    obs = LatencyObservatory(enabled=True, registry=MetricsRegistry())
    obs.begin("m1", "execute")
    obs.drop("m1")
    assert obs.dropped == 1
    assert obs.complete("m1", {}, lambda r: 0.0) is None
    # stampless replies (a worker predating the feature) drop too
    obs.begin("m2", "execute")
    m = types.SimpleNamespace()
    assert obs.complete("m2", {0: m}, lambda r: 0.0) is None
    assert obs.dropped == 2


def test_ring_bounded_and_summary_percentiles():
    obs = LatencyObservatory(enabled=True, ring=8,
                             registry=MetricsRegistry())
    for i in range(20):
        obs._now = time.time
        obs.begin(f"m{i}", "execute")
        obs.note_grant(f"m{i}")
        now = time.time()
        st = {"dq": now, "xs": now, "xe": now + 0.001 * (i + 1),
              "cs": 0.0}
        obs.complete(f"m{i}", {0: _reply(st, now + 0.001 * (i + 1))},
                     lambda r: 0.0)
    assert len(obs.records()) == 8
    s = obs.summary()
    assert s["count"] == 8
    assert s["stages"]["execute"]["p99"] >= \
        s["stages"]["execute"]["p50"] > 0
    assert s["e2e_ms"]["mean"] > 0


def test_histograms_feed_registry_with_latency_buckets():
    reg = MetricsRegistry()
    obs = LatencyObservatory(enabled=True, registry=reg)
    _drive(obs)
    text = reg.prometheus_text()
    assert "# TYPE nbd_stage_seconds histogram" in text
    for s in STAGES:
        assert f'nbd_stage_seconds_count{{stage="{s}"}} 1' in text
    assert "# TYPE nbd_cell_e2e_seconds histogram" in text
    # log-scale preset: the 100 µs bucket exists on the wire text
    assert 'le="0.0001"' in text
    assert validate_prometheus_text(text) == []


def test_tenant_label_on_e2e_histogram():
    reg = MetricsRegistry()
    obs = LatencyObservatory(enabled=True, registry=reg)
    clock = {"t": 100.0}
    obs._now = lambda: clock["t"]
    obs.begin("m1", "execute", "nb1")
    obs.note_grant("m1")
    st = {"dq": 100.0, "xs": 100.0, "xe": 100.001, "cs": 0.0}
    obs.complete("m1", {0: _reply(st, 100.002)}, lambda r: 0.0,
                 t_deliver=100.003)
    text = reg.prometheus_text()
    assert 'nbd_cell_e2e_seconds_count{tenant="nb1"} 1' in text
    # eviction hygiene: the tenant's series is removable
    assert reg.remove_label_series("tenant", "nb1") >= 1
    assert 'tenant="nb1"' not in reg.prometheus_text()


def test_stage_spans_mirrored_into_trace():
    from nbdistributed_tpu.observability.spans import Tracer
    reg = MetricsRegistry()
    obs = LatencyObservatory(enabled=True, registry=reg)
    tr = Tracer()
    tr.start()
    clock = {"t": 100.0}
    obs._now = lambda: clock["t"]
    obs.begin("m1", "execute", None, vet_s=0.001)
    clock["t"] = 100.002
    obs.note_grant("m1")
    st = {"dq": 100.003, "xs": 100.004, "xe": 100.010, "cs": 0.002}
    obs.complete("m1", {0: _reply(st, 100.011)}, lambda r: 0.0,
                 t_deliver=100.012, tracer=tr,
                 parent={"tid": "T", "sid": "S"})
    spans = tr.dump()["spans"]
    names = {s["name"] for s in spans}
    assert {"stage/vet", "stage/queue", "stage/execute",
            "stage/compile", "stage/reply"} <= names
    assert all(s["parent_id"] == "S" and s["trace_id"] == "T"
               for s in spans)
    # contiguous: each stage starts where the previous ended
    ordered = sorted(spans, key=lambda s: s["t0"])
    for a, b in zip(ordered, ordered[1:-1]):
        assert b["t0"] == pytest.approx(a["t0"] + a["dur"], abs=1e-9)


# ---------------------------------------------------------------------
# rendering


def test_format_stage_table_and_waterfall():
    obs = LatencyObservatory(enabled=True, registry=MetricsRegistry())
    assert "no completed cells" in format_stage_table(obs.summary())
    _drive(obs)
    table = format_stage_table(obs.summary())
    for s in STAGES:
        assert s in table
    wf = format_waterfall(obs.records(1))
    assert "e2e" in wf and "execute" in wf and "█" in wf


# ---------------------------------------------------------------------
# exposition validator


def test_validate_prometheus_text_flags_garbage():
    good = MetricsRegistry()
    good.counter("a_total", "help").inc()
    good.histogram("h_seconds", "help",
                   buckets=LATENCY_BUCKETS).observe(0.01)
    assert validate_prometheus_text(good.prometheus_text()) == []
    assert validate_prometheus_text("not a metric line!\n")
    assert validate_prometheus_text("orphan_sample 1\n")  # no TYPE
    assert validate_prometheus_text("# TYPE x bogus_kind\n")


# ---------------------------------------------------------------------
# clock-skew + flight-health satellites


def test_skew_warning_threshold():
    stats = {0: {"offset_s": 0.002, "min_rtt_s": 0.001, "samples": 9},
             1: {"offset_s": -0.120, "min_rtt_s": 0.001, "samples": 9}}
    warns = skew_warnings(stats, threshold_ms=50.0)
    assert len(warns) == 1 and "rank 1" in warns[0]
    assert "-120.0 ms" in warns[0]
    assert skew_warnings(stats, threshold_ms=0) == []
    assert skew_warnings(stats, threshold_ms=500.0) == []


def test_export_clock_metrics_gauges():
    reg = MetricsRegistry()

    class _Clock:
        @staticmethod
        def stats():
            return {2: {"offset_s": 0.05, "min_rtt_s": 0.003,
                        "samples": 4}}

    lat_mod.export_clock_metrics(_Clock(), reg)
    text = reg.prometheus_text()
    assert 'nbd_clock_offset_seconds{rank="2"} 0.05' in text
    assert 'nbd_clock_min_rtt_seconds{rank="2"} 0.003' in text


def test_flight_health_counters(tmp_path):
    from nbdistributed_tpu.observability.flightrec import FlightRecorder
    rec = FlightRecorder(str(tmp_path / "x.ring"), ring_bytes=1)
    # ring_bytes is clamped to 4 max-size records; spam until it wraps
    for i in range(600):
        rec.record("ev", i=i, pad="y" * 100)
    h = rec.health()
    assert h["records"] == 600
    assert h["wraps"] >= 1
    assert h["overwritten"] > 0
    assert h["utilization"] == 1.0  # wrapped: appends destroy history
    # oversize payload counts as truncated
    rec.record("big", blob="z" * 10000)
    assert rec.health()["truncated"] == 1
    rec.close()


# ---------------------------------------------------------------------
# scrape endpoint


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read(), r.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type")


@pytest.fixture
def httpd():
    servers = []

    def make(**kw):
        kw.setdefault("collect_metrics",
                      lambda: "# TYPE up gauge\nup 1\n")
        kw.setdefault("collect_health", lambda: {"status": "ok"})
        kw.setdefault("collect_latency",
                      lambda: {"summary": {"count": 1}, "records": []})
        srv = MetricsHTTPD(port=0, **kw)
        servers.append(srv)
        return srv

    yield make
    for s in servers:
        s.close()


def test_httpd_routes(httpd):
    srv = httpd()
    base = f"http://127.0.0.1:{srv.port}"
    code, body, ctype = _get(f"{base}/metrics")
    assert code == 200 and body == b"# TYPE up gauge\nup 1\n"
    assert "version=0.0.4" in ctype
    code, body, _ = _get(f"{base}/healthz")
    assert code == 200 and json.loads(body) == {"status": "ok"}
    code, body, _ = _get(f"{base}/latency.json")
    assert code == 200 and json.loads(body)["summary"]["count"] == 1
    assert _get(f"{base}/nope")[0] == 404


def test_httpd_token_gating(httpd):
    srv = httpd(token="s3cret")
    base = f"http://127.0.0.1:{srv.port}"
    assert _get(f"{base}/metrics")[0] == 401
    assert _get(f"{base}/latency.json")[0] == 401
    assert _get(f"{base}/metrics?token=wrong")[0] == 401
    assert _get(f"{base}/metrics?token=s3cret")[0] == 200
    req = urllib.request.Request(
        f"{base}/latency.json",
        headers={"Authorization": "Bearer s3cret"})
    with urllib.request.urlopen(req, timeout=5) as r:
        assert r.status == 200
    # health is NEVER gated: the LB prober holds no secrets
    assert _get(f"{base}/healthz")[0] == 200


def test_httpd_collector_failure_is_500_not_crash(httpd):
    def boom():
        raise RuntimeError("collector exploded")

    srv = httpd(collect_metrics=boom)
    base = f"http://127.0.0.1:{srv.port}"
    code, body, _ = _get(f"{base}/metrics")
    assert code == 500 and b"collector exploded" in body
    # the server survives for the next scrape
    assert _get(f"{base}/healthz")[0] == 200


# ---------------------------------------------------------------------
# comm-bound collectors (the /metrics worker-view merge)


def test_collectors_for_comm_merge_worker_telemetry():
    from nbdistributed_tpu.observability.httpd import collectors_for_comm

    class _Clock:
        @staticmethod
        def stats():
            return {0: {"offset_s": 0.001, "min_rtt_s": 0.0005,
                        "samples": 3}}

        @staticmethod
        def offset(_r):
            return 0.001

    class _Comm:
        num_workers = 2
        clock = _Clock()
        lat = LatencyObservatory(enabled=True,
                                 registry=MetricsRegistry())

        @staticmethod
        def last_seen(r):
            return time.time() - 0.5 if r == 0 else None

        @staticmethod
        def last_telemetry(r):
            if r != 0:
                return None
            return {"ts": time.time(),
                    "hbm": [{"id": 0, "in_use": 1000, "peak": 2000,
                             "limit": 4000}],
                    "bufs": 7, "compiles": 3, "compile_s": 1.5,
                    "dedup": 2, "msgs": 40}

        @staticmethod
        def dead_ranks():
            return {1}

        @staticmethod
        def connected_ranks():
            return [0]

        @staticmethod
        def pending_snapshot():
            return {}

    cm, ch, cl = collectors_for_comm(
        _Comm(), extra_health=lambda: {"kind": "gateway"})
    text = cm()
    assert validate_prometheus_text(text) == []
    # worker view merged through the telemetry piggyback, rank-labeled
    assert 'nbd_worker_hbm_in_use_bytes{rank="0"} 1000' in text
    assert 'nbd_worker_live_buffers{rank="0"} 7' in text
    assert 'nbd_worker_dedup_hits{rank="0"} 2' in text
    assert 'nbd_clock_offset_seconds{rank="0"} 0.001' in text
    assert "nbd_flight_ring_utilization" in text
    h = ch()
    assert h["status"] == "degraded" and h["dead"] == [1]
    assert h["alive"] == [0] and h["kind"] == "gateway"
    assert cl() == {"summary": {"count": 0, "dropped": 0},
                    "records": []}
