"""Expert parallelism: routing math, dense-vs-dispatched equivalence,
ep-sharded execution, and the MoE model family end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from nbdistributed_tpu.models import (MoEConfig, init_moe_model,
                                      moe_loss_fn, moe_model_shardings,
                                      tiny_moe_config)
from nbdistributed_tpu.parallel import expert, mesh as mesh_mod
from nbdistributed_tpu.parallel.tensor_parallel import apply_shardings

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def test_capacity_rounding():
    assert expert.compute_capacity(64, 4, 2, 1.0) == 32
    assert expert.compute_capacity(64, 4, 2, 1.25) == 40
    # floors at 8 and rounds up to a multiple of 8
    assert expert.compute_capacity(4, 8, 1, 1.0) == 8
    assert expert.compute_capacity(100, 4, 2, 1.0) == 56


def test_top_k_routing_normalized():
    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    gates, idx, probs = expert.top_k_routing(logits, 2)
    assert gates.shape == (16, 2) and idx.shape == (16, 2)
    np.testing.assert_allclose(np.sum(gates, axis=-1), 1.0, rtol=1e-6)
    # top-1 gate is the argmax of the softmax
    np.testing.assert_array_equal(np.asarray(idx[:, 0]),
                                  np.argmax(np.asarray(probs), axis=-1))


def test_dispatch_shapes_and_priority():
    # 4 tokens all routed (top-1) to expert 0, capacity 2: the first two
    # tokens in order win the slots, the rest are dropped.
    gates = jnp.ones((4, 1))
    idx = jnp.zeros((4, 1), jnp.int32)
    dispatch, combine = expert.make_dispatch(gates, idx, n_experts=2,
                                             capacity=2)
    assert dispatch.shape == (4, 2, 2)
    kept = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    np.testing.assert_array_equal(kept, [1, 1, 0, 0])
    # combine carries the gate value in the same slots
    np.testing.assert_allclose(np.asarray(combine),
                               np.asarray(dispatch))


def test_first_choices_outrank_second_choices():
    # token 0 puts expert 0 as SECOND choice; tokens 1-2 put it first.
    # With capacity 2 on expert 0, the two first-choices win even though
    # token 0 comes earlier in token order.
    gates = jnp.full((3, 2), 0.5)
    idx = jnp.array([[1, 0], [0, 1], [0, 1]], jnp.int32)
    dispatch, _ = expert.make_dispatch(gates, idx, n_experts=2,
                                       capacity=2)
    e0 = np.asarray(jnp.sum(dispatch[:, 0, :], axis=-1))
    np.testing.assert_array_equal(e0, [0, 1, 1])


def test_load_balance_loss_uniform_is_one():
    T, E = 512, 4
    probs = jnp.full((T, E), 1.0 / E)
    idx = jnp.stack([jnp.arange(T) % E, (jnp.arange(T) + 1) % E], -1)
    lb = expert.load_balance_loss(probs, idx, E)
    np.testing.assert_allclose(float(lb), 1.0, rtol=1e-5)
    # fully collapsed routing is maximally penalized (= E)
    collapsed = jnp.zeros((T, 2), jnp.int32)
    probs_c = jax.nn.one_hot(jnp.zeros((T,), jnp.int32), E)
    assert float(expert.load_balance_loss(probs_c, collapsed, E)) == E


def test_moe_ffn_matches_dense_routing_reference():
    """With ample capacity (no drops), the dispatched einsum path must
    equal the naive per-token loop over selected experts."""
    key = jax.random.PRNGKey(1)
    D, F, E, T, k = 16, 32, 4, 24, 2
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (T, D), jnp.float32)

    y, aux = expert.moe_ffn(x, params, top_k=k, capacity_factor=4.0)
    assert y.shape == x.shape and np.isfinite(float(aux))

    gates, idx, _ = expert.top_k_routing(
        x @ params["router"], k)
    ref = np.zeros((T, D), np.float32)
    for t in range(T):
        for j in range(k):
            e = int(idx[t, j])
            h = (jax.nn.silu(x[t] @ params["w_gate"][e])
                 * (x[t] @ params["w_up"][e]))
            ref[t] += float(gates[t, j]) * np.asarray(h @ params["w_down"][e])
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("capacity_factor", [0.5, 1.0, 4.0])
def test_sparse_dispatch_matches_dense(capacity_factor):
    """Sort/segment dispatch must equal the dense one-hot oracle at
    equal capacity — including bit-identical DROPS under tight
    capacity (the Switch priority rule: choice-major cumulative
    order), forward and gradients."""
    key = jax.random.PRNGKey(7)
    D, F, E, T, k = 16, 32, 4, 40, 2
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(8), (T, D), jnp.float32)

    y_d, aux_d = expert.moe_ffn(x, params, top_k=k,
                                capacity_factor=capacity_factor)
    y_s, aux_s = expert.moe_ffn(x, params, top_k=k,
                                capacity_factor=capacity_factor,
                                dispatch_mode="sparse")
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d),
                               atol=1e-5, rtol=1e-5)
    assert float(aux_s) == float(aux_d)

    def loss(mode):
        return lambda p, x_: jnp.sum(expert.moe_ffn(
            x_, p, top_k=k, capacity_factor=capacity_factor,
            dispatch_mode=mode)[0] ** 2)

    g_d = jax.grad(loss("dense"), argnums=(0, 1))(params, x)
    g_s = jax.grad(loss("sparse"), argnums=(0, 1))(params, x)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4), g_s, g_d)


def test_sparse_slots_priority_matches_dense_positions():
    """The sorted-segment rank must reproduce make_dispatch's
    cumulative-count position for every kept (token, choice)."""
    idx = jnp.asarray([[0, 1], [0, 2], [0, 1], [1, 0], [2, 2]])
    E, C, T, k = 3, 2, 5, 2
    gates = jnp.ones((T, k)) / k
    dispatch, _ = expert.make_dispatch(gates, idx, E, C)
    slot, tok, keep, _ = expert.sparse_slots(idx, E, C)
    dense_slots = set()
    for t in range(T):
        for e in range(E):
            for c in range(C):
                if float(dispatch[t, e, c]) > 0:
                    dense_slots.add((t, e * C + c))
    sparse_kept = {(int(tok[i]), int(slot[i]))
                   for i in range(k * T) if bool(keep[i])}
    assert sparse_kept == dense_slots


def test_sparse_dispatch_no_quadratic_tensor():
    """The sparse path must not materialize any (T, E, C) or
    (T, k, E, C) tensor — the dense path's quadratic memory."""
    D, F, E, T, k = 16, 32, 8, 64, 2
    params = expert.init_moe_params(jax.random.PRNGKey(9), D, F, E,
                                    dtype=jnp.float32)
    x = jnp.ones((T, D), jnp.float32)
    C = expert.compute_capacity(T, E, k, 1.25)
    jaxpr = str(jax.make_jaxpr(lambda p, x_: expert.moe_ffn(
        x_, p, top_k=k, dispatch_mode="sparse"))(params, x))
    flat = jaxpr.replace(" ", "")
    assert f"[{T},{E},{C}]" not in flat  # avals print as f32[T,E,C]
    assert f"[{T},{k},{E},{C}]" not in flat
    # ... while the dense path does (sanity that the probe works).
    jaxpr_d = str(jax.make_jaxpr(lambda p, x_: expert.moe_ffn(
        x_, p, top_k=k, dispatch_mode="dense"))(params, x))
    assert f"[{T},{E},{C}]" in jaxpr_d.replace(" ", "")


def test_sparse_dispatch_on_ep_mesh():
    """Sparse dispatch under dp×ep GSPMD matches the unsharded dense
    oracle."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    key = jax.random.PRNGKey(10)
    D, F, E, T = 16, 32, 4, 32
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(11), (T, D), jnp.float32)
    expected, _ = expert.moe_ffn(x, params, capacity_factor=4.0)

    mesh = mesh_mod.make_mesh({"dp": 2, "ep": 2},
                              devices=jax.devices()[:4])
    p = apply_shardings(params, mesh, expert.moe_param_shardings())
    got, aux = jax.jit(lambda p, x: expert.moe_ffn(
        x, p, capacity_factor=4.0, mesh=mesh,
        dispatch_mode="sparse"))(p, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)
    assert np.isfinite(float(aux))


@pytest.mark.parametrize("mode", ["dense", "sparse"])
def test_token_mask_no_capacity_footprint(mode):
    """Masked-out tokens must (a) produce zero output and (b) take NO
    expert-capacity slot: at pinned tight capacity, the active rows'
    outputs equal a run where the masked tokens do not exist at all —
    the guarantee batched speculative decoding's frozen streams rely
    on."""
    key = jax.random.PRNGKey(20)
    D, F, E, T, k, C = 16, 32, 4, 16, 2, 2  # tight: actives compete
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(21), (T, D), jnp.float32)
    mask = jnp.arange(T) < T // 2          # first half active

    y_masked, aux_m = expert.moe_ffn(x, params, top_k=k, capacity=C,
                                     dispatch_mode=mode,
                                     token_mask=mask)
    y_solo, aux_s = expert.moe_ffn(x[:T // 2], params, top_k=k,
                                   capacity=C, dispatch_mode=mode)
    np.testing.assert_allclose(np.asarray(y_masked[:T // 2]),
                               np.asarray(y_solo), atol=1e-5,
                               rtol=1e-5)
    # (a) masked rows are exactly zero (pass through the residual).
    np.testing.assert_array_equal(np.asarray(y_masked[T // 2:]),
                                  np.zeros((T // 2, D), np.float32))
    # aux loss excludes masked tokens.
    np.testing.assert_allclose(float(aux_m), float(aux_s), rtol=1e-6)


def test_token_mask_dense_sparse_agree():
    """Both dispatch modes implement the identical mask semantics."""
    key = jax.random.PRNGKey(22)
    D, F, E, T, k = 16, 32, 4, 24, 2
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(23), (T, D), jnp.float32)
    mask = jax.random.bernoulli(jax.random.PRNGKey(24), 0.6, (T,))
    y_d, aux_d = expert.moe_ffn(x, params, top_k=k, capacity=3,
                                token_mask=mask)
    y_s, aux_s = expert.moe_ffn(x, params, top_k=k, capacity=3,
                                dispatch_mode="sparse",
                                token_mask=mask)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d),
                               atol=1e-5, rtol=1e-5)
    assert float(aux_s) == float(aux_d)


def test_moe_model_sparse_dispatch_matches_dense():
    """Model-level: the full MoE transformer's loss is identical under
    either dispatch mode (cfg.moe_dispatch)."""
    import dataclasses

    from nbdistributed_tpu.models import moe_loss_fn, tiny_moe_config
    cfg_d = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    cfg_s = dataclasses.replace(cfg_d, moe_dispatch="sparse")
    params = init_moe_model(jax.random.PRNGKey(12), cfg_d)
    tok = jax.random.randint(jax.random.PRNGKey(13), (2, 16), 0,
                             cfg_d.vocab_size)
    l_d = float(moe_loss_fn(params, {"tokens": tok}, cfg_d))
    l_s = float(moe_loss_fn(params, {"tokens": tok}, cfg_s))
    assert abs(l_d - l_s) < 1e-5, (l_d, l_s)


def test_moe_ffn_ep_sharded_matches_unsharded():
    """Same layer jitted over a dp×ep mesh must give identical output;
    the dispatched activations get an ep sharding."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    key = jax.random.PRNGKey(3)
    D, F, E, T = 16, 32, 4, 32
    params = expert.init_moe_params(key, D, F, E, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(4), (T, D), jnp.float32)
    expected, _ = expert.moe_ffn(x, params, capacity_factor=4.0)

    mesh = mesh_mod.make_mesh({"dp": 2, "ep": 2},
                              devices=jax.devices()[:4])
    rules = expert.moe_param_shardings()
    p = apply_shardings(params, mesh, rules)

    @jax.jit
    def run(p, x):
        y, aux = expert.moe_ffn(x, p, capacity_factor=4.0, mesh=mesh)
        return y, aux

    got, aux = run(p, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_model_trains_on_ep_mesh():
    """Full MoE transformer: loss decreases over a few dp×ep train
    steps with attention replicated and experts ep-sharded."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    mesh = mesh_mod.make_mesh({"dp": 2, "ep": -1})
    rules = moe_model_shardings(cfg, tp_axis=None)
    params = apply_shardings(init_moe_model(jax.random.PRNGKey(0), cfg),
                             mesh, rules)
    opt = optax.adam(1e-3)
    state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size)
    batch = mesh_mod.shard_batch({"tokens": tokens}, mesh)

    @jax.jit
    def step(params, state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: moe_loss_fn(p, batch, cfg, mesh=mesh))(params)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    losses = []
    for _ in range(5):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_mixtral_config_param_count():
    from nbdistributed_tpu.models import mixtral_8x7b_config
    cfg = mixtral_8x7b_config()
    assert cfg.n_experts == 8 and cfg.top_k == 2
    assert cfg.head_dim == 128


def test_moe_seq_parallel_matches_plain():
    """MoE forward with attention routed through the ring (sp mesh)
    must match the plain MoE forward; the expert dispatch is token-wise
    and stays sequence-sharded."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nbdistributed_tpu.models import (SeqParallel, init_moe_model,
                                          moe_forward, moe_loss_fn,
                                          tiny_moe_config)
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mcfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    mp = init_moe_model(jax.random.PRNGKey(0), mcfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                mcfg.vocab_size)
    ref, ref_aux = moe_forward(mp, tokens, mcfg)

    mesh = mesh_mod.make_mesh({"sp": 4, "ep": 2})
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=False)
    tok_s = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    got, got_aux = jax.jit(lambda p, t: moe_forward(
        p, t, mcfg, mesh=mesh, sp=sp))(mp, tok_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    assert np.isclose(float(got_aux), float(ref_aux), atol=1e-5)
    # Loss path (logits shift, S divisible by sp) composes too.
    l = float(moe_loss_fn(mp, {"tokens": tok_s}, mcfg, mesh=mesh,
                          sp=sp))
    assert np.isfinite(l)


def test_moe_packed_documents_match_separate_forwards():
    """Packed-document contract for the MoE family: at LOSSLESS expert
    capacity (so packed-vs-solo capacity differences cannot drop
    tokens) a packed window's logits equal each document forwarded
    alone, and moe_loss_fn consumes batch["segments"]."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.models import (init_moe_model, moe_forward,
                                          moe_loss_fn, packed_positions,
                                          tiny_moe_config)

    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          capacity_factor=2.0)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    la, lb = 14, 10
    d0 = jax.random.randint(jax.random.PRNGKey(1), (1, la), 0,
                            cfg.vocab_size)
    d1 = jax.random.randint(jax.random.PRNGKey(2), (1, lb), 0,
                            cfg.vocab_size)
    packed = jnp.concatenate([d0, d1], axis=1)
    seg = jnp.concatenate([jnp.zeros((1, la), jnp.int32),
                           jnp.ones((1, lb), jnp.int32)], axis=1)
    lp, _ = moe_forward(params, packed, cfg,
                        positions=packed_positions(seg),
                        segment_ids=seg)
    l0, _ = moe_forward(params, d0, cfg)
    l1, _ = moe_forward(params, d1, cfg)
    np.testing.assert_allclose(np.asarray(lp[:, :la]), np.asarray(l0),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lp[:, la:]), np.asarray(l1),
                               atol=2e-5, rtol=2e-5)
    loss = float(moe_loss_fn(params, {"tokens": packed,
                                      "segments": seg}, cfg))
    assert np.isfinite(loss)


class TestDropless:
    """MegaBlocks-style dropless dispatch (jax.lax.ragged_dot)."""

    def _setup(self, T=24, D=16, F=32, E=4, seed=0, dtype=None):
        import jax
        import jax.numpy as jnp

        from nbdistributed_tpu.parallel import expert
        dtype = dtype or jnp.float32
        p = expert.init_moe_params(jax.random.PRNGKey(seed), D, F, E,
                                   dtype=dtype)
        x = jax.random.normal(jax.random.PRNGKey(seed + 1), (T, D),
                              dtype)
        return expert, p, x, E

    def test_matches_dense_at_lossless_capacity(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        expert, p, x, E = self._setup()
        yd, auxd = expert.moe_ffn(x, p, capacity_factor=float(E))
        yl, auxl = expert.moe_ffn(x, p, dispatch_mode="dropless")
        np.testing.assert_allclose(np.asarray(yl), np.asarray(yd),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(auxl), float(auxd), rtol=1e-6)

        gd = jax.grad(lambda x_: jnp.sum(expert.moe_ffn(
            x_, p, capacity_factor=float(E))[0] ** 2))(x)
        gl = jax.grad(lambda x_: jnp.sum(expert.moe_ffn(
            x_, p, dispatch_mode="dropless")[0] ** 2))(x)
        np.testing.assert_allclose(np.asarray(gl), np.asarray(gd),
                                   atol=1e-4, rtol=1e-4)

    def test_no_drops_under_tight_capacity(self):
        """Dense with capacity 8 drops tokens at T=96; dropless must
        equal dense-with-ample-capacity instead."""
        import numpy as np
        expert, p, x, E = self._setup(T=96)
        y_tight, _ = expert.moe_ffn(x, p, capacity=8)
        y_ample, _ = expert.moe_ffn(x, p, capacity=96 * 2)
        y_less, _ = expert.moe_ffn(x, p, dispatch_mode="dropless")
        np.testing.assert_allclose(np.asarray(y_less),
                                   np.asarray(y_ample),
                                   atol=1e-5, rtol=1e-5)
        assert np.abs(np.asarray(y_tight)
                      - np.asarray(y_ample)).max() > 1e-4

    def test_token_mask_zeroes_masked_rows(self):
        import jax.numpy as jnp
        import numpy as np
        expert, p, x, E = self._setup()
        mask = jnp.arange(x.shape[0]) % 3 != 0
        ym, _ = expert.moe_ffn(x, p, dispatch_mode="dropless",
                               token_mask=mask)
        yd, _ = expert.moe_ffn(x, p, capacity_factor=float(E),
                               token_mask=mask)
        np.testing.assert_allclose(np.asarray(ym), np.asarray(yd),
                                   atol=1e-5, rtol=1e-5)
        assert np.abs(np.asarray(ym)[~np.asarray(mask)]).max() == 0

    def test_quantized_experts(self):
        """int8 expert weights route through ragged_dot with per-row
        expert scales; must equal the dense path on the same
        quantized weights at lossless capacity."""
        import numpy as np

        from nbdistributed_tpu.models.quant import quantize_weight
        expert, p, x, E = self._setup()
        pq = dict(p)
        for n in ("w_gate", "w_up", "w_down"):
            pq[n] = quantize_weight(p[n])
        yd, _ = expert.moe_ffn(x, pq, capacity_factor=float(E))
        yl, _ = expert.moe_ffn(x, pq, dispatch_mode="dropless")
        np.testing.assert_allclose(np.asarray(yl), np.asarray(yd),
                                   atol=1e-5, rtol=1e-5)

    def test_ep_mesh_matches_replicated_dropless(self):
        """Shard-capacity hybrid over a dp×ep mesh at lossless shard
        capacity (Cs = kT) must equal the replicated dropless path
        bit-for-bit in forward AND gradients — the exchange and the
        local ragged segments reorder nothing observable."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.tensor_parallel import \
            apply_shardings
        expert, p, x, E = self._setup(T=64)
        y_ref, aux_ref = expert.moe_ffn(x, p, dispatch_mode="dropless")
        g_ref = jax.grad(lambda x_: jnp.sum(expert.moe_ffn(
            x_, p, dispatch_mode="dropless")[0] ** 2))(x)

        mesh = mesh_mod.make_mesh({"dp": 2, "ep": 2},
                                  devices=jax.devices()[:4])
        ps = apply_shardings(p, mesh, expert.moe_param_shardings())
        f = jax.jit(lambda x_, p_: expert.moe_ffn(
            x_, p_, dispatch_mode="dropless", mesh=mesh,
            capacity_factor=float(2 * E)))
        y, aux = f(x, ps)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(aux), float(aux_ref),
                                   rtol=1e-6)
        g = jax.jit(jax.grad(lambda x_: jnp.sum(expert.moe_ffn(
            x_, ps, dispatch_mode="dropless", mesh=mesh,
            capacity_factor=float(2 * E))[0] ** 2)))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=1e-4, rtol=1e-4)

    def test_ep_mesh_shard_overflow_drops_only_tail(self):
        """Under a tight SHARD capacity the hybrid drops exactly the
        sorted tail of each shard's segment; ample shard capacity is
        drop-free even when per-expert capacity at the same total
        would drop (the pooling property)."""
        import jax
        import numpy as np

        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.tensor_parallel import \
            apply_shardings
        expert, p, x, E = self._setup(T=96)
        mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
        ps = apply_shardings(p, mesh, expert.moe_param_shardings())
        y_ref, _ = expert.moe_ffn(x, p, dispatch_mode="dropless")
        # Ample shard capacity: exact.
        y_ample, _ = jax.jit(lambda: expert.moe_ffn(
            x, ps, dispatch_mode="dropless", mesh=mesh,
            capacity=2 * 96))()
        np.testing.assert_allclose(np.asarray(y_ample),
                                   np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)
        # Tight shard capacity: still runs, deviates (rows dropped).
        y_tight, _ = jax.jit(lambda: expert.moe_ffn(
            x, ps, dispatch_mode="dropless", mesh=mesh, capacity=8))()
        assert np.abs(np.asarray(y_tight)
                      - np.asarray(y_ref)).max() > 1e-4

    def test_ep_mesh_token_mask_and_quantized(self):
        """token_mask and int8 expert weights both compose with the
        ep-mesh hybrid."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.models.quant import quantize_weight
        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.tensor_parallel import \
            apply_shardings
        expert, p, x, E = self._setup()
        mask = jnp.arange(x.shape[0]) % 3 != 0
        mesh = mesh_mod.make_mesh({"ep": 2}, devices=jax.devices()[:2])
        pq = dict(p)
        for n in ("w_gate", "w_up", "w_down"):
            pq[n] = quantize_weight(p[n])
        y_ref, _ = expert.moe_ffn(x, pq, dispatch_mode="dropless",
                                  token_mask=mask)
        from nbdistributed_tpu.models.quant import _q_spec
        rules = {n: (_q_spec(s) if n in ("w_gate", "w_up", "w_down")
                     else s)
                 for n, s in expert.moe_param_shardings().items()}
        pqs = apply_shardings(pq, mesh, rules)
        y, _ = jax.jit(lambda: expert.moe_ffn(
            x, pqs, dispatch_mode="dropless", mesh=mesh,
            capacity_factor=float(2 * E), token_mask=mask))()
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)
        assert np.abs(np.asarray(y)[~np.asarray(mask)]).max() == 0

    def test_ep_hier_no_global_collectives_on_token_path(self):
        """The hierarchical dropless-EP exchange keeps every routing
        step per-token-shard local: the program must contain NO
        all_gather and NO all_to_all — the only collective on the
        token path is the combine psum over ep.  Checked structurally
        in the jaxpr (shard_map collectives are explicit there) AND in
        the optimized HLO with the tokens genuinely dp-sharded (GSPMD
        resharding would surface as all-gather there)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.tensor_parallel import \
            apply_shardings
        expert, p, x, E = self._setup(T=64)
        mesh = mesh_mod.make_mesh({"dp": 2, "ep": 2},
                                  devices=jax.devices()[:4])
        ps = apply_shardings(p, mesh, expert.moe_param_shardings())

        def fn(x_, p_):
            return expert.moe_ffn(x_, p_, dispatch_mode="dropless",
                                  mesh=mesh,
                                  capacity_factor=float(2 * E))[0]

        jaxpr = str(jax.make_jaxpr(fn)(x, ps))
        assert "shard_map" in jaxpr
        assert "all_gather" not in jaxpr, jaxpr
        assert "all_to_all" not in jaxpr, jaxpr
        assert "psum" in jaxpr

        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        hlo = jax.jit(fn).lower(xs, ps).compile().as_text()
        assert "all-gather" not in hlo, \
            [l for l in hlo.splitlines() if "all-gather" in l]
        assert "all-to-all" not in hlo, \
            [l for l in hlo.splitlines() if "all-to-all" in l]
        # And the sharded-input program still matches the oracle.
        y = jax.jit(fn)(xs, ps)
        y_ref = expert.moe_ffn(x, p, dispatch_mode="dropless")[0]
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)

        # Same property with the token dim sharded over BOTH dp and a
        # sequence axis (the training layout under sequence
        # parallelism): token_axes=("dp","sp") keeps routing local.
        mesh2 = mesh_mod.make_mesh({"dp": 2, "sp": 2, "ep": 2},
                                   devices=jax.devices()[:8])
        ps2 = apply_shardings(p, mesh2, expert.moe_param_shardings())

        def fn2(x_, p_):
            return expert.moe_ffn(x_, p_, dispatch_mode="dropless",
                                  mesh=mesh2, token_axes=("dp", "sp"),
                                  capacity_factor=float(2 * E))[0]

        xs2 = jax.device_put(
            x, NamedSharding(mesh2, P(("dp", "sp"), None)))
        hlo2 = jax.jit(fn2).lower(xs2, ps2).compile().as_text()
        assert "all-gather" not in hlo2, \
            [l for l in hlo2.splitlines() if "all-gather" in l]
        y2 = jax.jit(fn2)(xs2, ps2)
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)

    def test_ep_mesh_rejects_indivisible_experts(self):
        import jax
        import pytest

        from nbdistributed_tpu.parallel import mesh as mesh_mod
        expert, p, x, E = self._setup()      # E = 4
        mesh = mesh_mod.make_mesh({"ep": 3}, devices=jax.devices()[:3])
        with pytest.raises(ValueError, match="not divisible"):
            expert.moe_ffn(x, p, dispatch_mode="dropless", mesh=mesh)

    def test_model_level_dropless(self):
        """The MoE family runs end-to-end with moe_dispatch='dropless'
        and matches the dense model at lossless capacity."""
        import dataclasses

        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.models import (init_moe_model,
                                              moe_forward,
                                              tiny_moe_config)
        cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                              capacity_factor=2.0)  # lossless (E/k=2)
        params = init_moe_model(jax.random.PRNGKey(0), cfg)
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, 18), 0,
                                 cfg.vocab_size)
        ld, _ = moe_forward(params, tok, cfg)
        ll, _ = moe_forward(params, tok,
                            dataclasses.replace(
                                cfg, moe_dispatch="dropless"))
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ld),
                                   atol=2e-5, rtol=2e-5)
