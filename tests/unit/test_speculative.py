"""Speculative decoding: greedy exactness vs the target's own decode,
self-draft full acceptance, sampled-mode determinism, validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import (TransformerConfig, generate,
                                      init_params, speculative_generate,
                                      tiny_config)

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    draft_cfg = TransformerConfig(
        vocab_size=cfg.vocab_size, d_model=64, n_layers=1, n_heads=2,
        n_kv_heads=2, d_ff=128, max_seq_len=256, dtype=jnp.float32,
        use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft = init_params(jax.random.PRNGKey(1), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 7), 0,
                                cfg.vocab_size)
    return cfg, draft_cfg, params, draft, prompt


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_greedy_exact_vs_target_decode(setup, gamma):
    """Greedy speculative output must be bit-identical to the target's
    own greedy decode, for any draft and any gamma."""
    cfg, draft_cfg, params, draft, prompt = setup
    ref = generate(params, prompt, cfg, max_new_tokens=12)
    got, mean_acc = speculative_generate(
        params, draft, prompt, cfg, draft_cfg, 12, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert 0.0 <= float(mean_acc) <= gamma


def test_int4_draft_exact_and_high_acceptance(setup):
    """The textbook deployment: draft = the int4-quantized target.
    Greedy speculative output stays bit-identical to the target's own
    decode (correctness never depends on the draft), and acceptance
    stays high (the quantized model mostly agrees with itself)."""
    from nbdistributed_tpu.models import quantize_params4
    cfg, _, params, _, prompt = setup
    q4 = quantize_params4(params)
    ref = generate(params, prompt, cfg, max_new_tokens=12)
    got, mean_acc = speculative_generate(
        params, q4, prompt, cfg, cfg, 12, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # Random tiny weights still agree with their own int4 copy most
    # of the time; the bound just pins "not degenerate".
    assert float(mean_acc) >= 1.0


def test_self_draft_accepts_everything(setup):
    """Draft == target: every greedy proposal matches, so every round
    accepts all gamma tokens and output equals target greedy."""
    cfg, _, params, _, prompt = setup
    ref = generate(params, prompt, cfg, max_new_tokens=10)
    got, mean_acc = speculative_generate(
        params, params, prompt, cfg, cfg, 10, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # Every full round accepts all 4; only a final partial round can
    # drag the mean below 4 — it must stay well above 0.
    assert float(mean_acc) == 4.0


def test_sampled_mode_deterministic_and_in_vocab(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    key = jax.random.PRNGKey(9)
    a, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                10, gamma=3, temperature=0.8, key=key)
    b, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                10, gamma=3, temperature=0.8, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 17)
    assert int(jnp.max(a)) < cfg.vocab_size and int(jnp.min(a)) >= 0


def test_jits(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    fn = jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, cfg, draft_cfg, 8, gamma=2))
    got, _ = fn(params, draft, prompt)
    ref = generate(params, prompt, cfg, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_validation(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    with pytest.raises(ValueError, match="at least one stream"):
        speculative_generate(params, draft,
                             jnp.zeros((0, 4), jnp.int32), cfg,
                             draft_cfg, 4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             gamma=0)
    with pytest.raises(ValueError, match="PRNG key"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             temperature=0.5)
    bad_cfg = TransformerConfig(vocab_size=99, d_model=64, n_layers=1,
                                n_heads=2, n_kv_heads=2, d_ff=128)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(params, init_params(jax.random.PRNGKey(3),
                                                 bad_cfg),
                             prompt, cfg, bad_cfg, 4)


@pytest.mark.parametrize("gamma", [1, 3])
def test_batched_greedy_exact_per_stream(setup, gamma):
    """B=4 streams with different prompts: every stream's greedy
    speculative output must be bit-identical to the target's own
    batched greedy decode — per-stream acceptance lengths diverge, so
    this exercises the per-row cache pointers and the frozen-stream
    tail (rows finish in different rounds)."""
    cfg, draft_cfg, params, draft, _ = setup
    prompts = jax.random.randint(jax.random.PRNGKey(11), (4, 7), 0,
                                 cfg.vocab_size)
    ref = generate(params, prompts, cfg, max_new_tokens=12)
    got, mean_acc = speculative_generate(
        params, draft, prompts, cfg, draft_cfg, 12, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert 0.0 <= float(mean_acc) <= gamma


def test_batched_rows_match_single_stream_runs(setup):
    """Greedy: batched rows must equal the same prompts run one at a
    time — batching may not couple streams."""
    cfg, draft_cfg, params, draft, _ = setup
    prompts = jax.random.randint(jax.random.PRNGKey(12), (3, 6), 0,
                                 cfg.vocab_size)
    got, _ = speculative_generate(params, draft, prompts, cfg,
                                  draft_cfg, 9, gamma=2)
    for b in range(3):
        solo, _ = speculative_generate(params, draft, prompts[b:b + 1],
                                       cfg, draft_cfg, 9, gamma=2)
        np.testing.assert_array_equal(np.asarray(got[b]),
                                      np.asarray(solo[0]))


def test_batched_sampled_runs_and_jits(setup):
    cfg, draft_cfg, params, draft, _ = setup
    prompts = jax.random.randint(jax.random.PRNGKey(13), (4, 5), 0,
                                 cfg.vocab_size)
    fn = jax.jit(lambda p, d, t, k: speculative_generate(
        p, d, t, cfg, draft_cfg, 8, gamma=3, temperature=0.7, key=k))
    got, acc = fn(params, draft, prompts, jax.random.PRNGKey(14))
    assert got.shape == (4, 13)
    assert int(jnp.max(got)) < cfg.vocab_size and int(jnp.min(got)) >= 0
    assert 0.0 <= float(acc) <= 3.0


@pytest.mark.parametrize("top_k,top_p", [(None, None), (6, 0.9)])
def test_batched_sampled_preserves_target_distribution(top_k, top_p):
    """Rejection sampling must reproduce the target's sampling
    distribution per stream — including truncation-aware mode, where
    the emitted distribution must equal the *truncated* target's
    (i.e. generate() with the same top_k/top_p).  Small vocab (16) so
    empirical TV distance is resolvable: compare the first
    *speculated* token (position S0+1, decided by the accept/resample
    rule) against target-only sampling over many keys × batch rows."""
    V = 16
    cfg = TransformerConfig(vocab_size=V, d_model=32, n_layers=1,
                            n_heads=2, n_kv_heads=2, d_ff=64,
                            max_seq_len=64, dtype=jnp.float32,
                            use_flash=False)
    draft_cfg = TransformerConfig(vocab_size=V, d_model=16, n_layers=1,
                                  n_heads=1, n_kv_heads=1, d_ff=32,
                                  max_seq_len=64, dtype=jnp.float32,
                                  use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft = init_params(jax.random.PRNGKey(1), draft_cfg)
    B, n_keys, temp = 8, 60, 1.0
    prompt = jnp.tile(jnp.arange(4, dtype=jnp.int32)[None], (B, 1))

    spec = jax.jit(lambda k: speculative_generate(
        params, draft, prompt, cfg, draft_cfg, 2, gamma=2,
        temperature=temp, key=k, top_k=top_k, top_p=top_p)[0][:, 5])
    ref = jax.jit(lambda k: generate(
        params, prompt, cfg, 2, temperature=temp, key=k,
        top_k=top_k, top_p=top_p)[:, 5])

    counts = jnp.zeros((2, V))
    for i in range(n_keys):
        ks, kr = jax.random.split(jax.random.PRNGKey(100 + i))
        counts = counts.at[0].add(
            jnp.bincount(spec(ks), length=V).astype(jnp.float32))
        counts = counts.at[1].add(
            jnp.bincount(ref(kr), length=V).astype(jnp.float32))
    p = counts / counts.sum(axis=1, keepdims=True)
    tv = 0.5 * float(jnp.abs(p[0] - p[1]).sum())
    # n=480 draws over 16 bins: same-distribution empirical TV is
    # ~0.08; a broken accept rule shifts mass far beyond 0.2.
    assert tv < 0.2, (tv, p)


def test_top_k1_sampled_equals_greedy(setup):
    """top_k=1 truncates both distributions to the argmax token, so
    sampled speculative decoding becomes deterministic and must equal
    the target's greedy decode — a sharp end-to-end check of the
    truncation-aware draft/accept/resample path."""
    cfg, draft_cfg, params, draft, prompt = setup
    ref = generate(params, prompt, cfg, max_new_tokens=10)
    got, _ = speculative_generate(
        params, draft, prompt, cfg, draft_cfg, 10, gamma=3,
        temperature=0.7, key=jax.random.PRNGKey(3), top_k=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_truncated_self_draft_accepts_everything(setup):
    """Draft == target under truncation: identical truncated
    distributions give acceptance probability 1 for every proposal."""
    cfg, _, params, _, prompt = setup
    _, mean_acc = speculative_generate(
        params, params, prompt, cfg, cfg, 8, gamma=4, temperature=0.9,
        key=jax.random.PRNGKey(5), top_k=8, top_p=0.95)
    # Tolerance, not equality: batched verify and stepwise draft can
    # tile matmuls differently, leaving pt/pd an ulp apart (the
    # batched-vs-stepwise caveat in the module docstring).
    assert float(mean_acc) >= 4.0 - 1e-5


def test_truncation_validation(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    with pytest.raises(ValueError, match="top_k"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             temperature=1.0, key=jax.random.PRNGKey(0),
                             top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             temperature=1.0, key=jax.random.PRNGKey(0),
                             top_p=1.5)


@pytest.mark.parametrize("B,S0,new,gamma", [
    (1, 1, 1, 1),    # minimal everything: seed token only, loop skipped
    (2, 1, 3, 5),    # gamma > max_new_tokens (overshoot clamping)
    (3, 7, 2, 4),    # one spec round, wide draft past the target count
    (5, 2, 6, 3),    # odd batch, short prompts
])
def test_spec_edge_geometries_exact(setup, B, S0, new, gamma):
    """Boundary shapes for the per-stream pointer math: prompts of one
    token, the degenerate single-token generation (prefill + seed, the
    while-loop never entered), gamma exceeding the remaining target
    count (a final round can overshoot by a whole round — the buffer
    slack and clamped writes must keep committed tokens intact), and
    odd batch sizes.  Greedy output must equal batched greedy decode
    in every geometry."""
    cfg, draft_cfg, params, draft, _ = setup
    prompts = jax.random.randint(jax.random.PRNGKey(40 + B), (B, S0),
                                 0, cfg.vocab_size)
    got, acc = speculative_generate(params, draft, prompts, cfg,
                                    draft_cfg, new, gamma=gamma)
    ref = generate(params, prompts, cfg, max_new_tokens=new)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert got.shape == (B, S0 + new)
    assert 0.0 <= float(acc) <= gamma


def test_spec_oversized_max_len_exact(setup):
    """A max_len far beyond the needed buffer must not disturb the
    position-masked cache reads or the commit arithmetic."""
    cfg, draft_cfg, params, draft, _ = setup
    prompts = jax.random.randint(jax.random.PRNGKey(50), (2, 4), 0,
                                 cfg.vocab_size)
    got, _ = speculative_generate(params, draft, prompts, cfg,
                                  draft_cfg, 6, gamma=2, max_len=128)
    ref = generate(params, prompts, cfg, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_batched_moe_spec_matches_solo():
    """MoE target + draft, batched streams with diverging acceptance:
    each row must equal its solo run.  Frozen streams are masked out of
    expert dispatch (row_mask -> moe_ffn token_mask), so finishing
    early leaves no capacity footprint; capacity is ample here so
    batched-vs-solo capacity formulas agree (the tight-capacity
    no-footprint guarantee is pinned in test_expert.py)."""
    from nbdistributed_tpu.models import init_moe_model
    from nbdistributed_tpu.models.moe import MoEConfig

    cfg = MoEConfig(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
                    n_kv_heads=2, d_ff=64, max_seq_len=64,
                    n_experts=4, top_k=2, capacity_factor=4.0,
                    dtype=jnp.float32, use_flash=False)
    dcfg = MoEConfig(vocab_size=128, d_model=16, n_layers=1, n_heads=1,
                     n_kv_heads=1, d_ff=32, max_seq_len=64,
                     n_experts=2, top_k=1, capacity_factor=4.0,
                     dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    draft = init_moe_model(jax.random.PRNGKey(1), dcfg)
    prompts = jax.random.randint(jax.random.PRNGKey(2), (3, 5), 0,
                                 cfg.vocab_size)
    got, _ = speculative_generate(params, draft, prompts, cfg, dcfg,
                                  8, gamma=2)
    for b in range(3):
        solo, _ = speculative_generate(params, draft,
                                       prompts[b:b + 1], cfg, dcfg,
                                       8, gamma=2)
        np.testing.assert_array_equal(np.asarray(got[b]),
                                      np.asarray(solo[0]), err_msg=str(b))


def test_spec_decode_with_int8_kv(setup):
    """Speculative decoding over int8 KV caches: runs, jits, and for a
    self-draft stays consistent with the int8-cache greedy decode."""
    cfg, draft_cfg, params, draft, prompt = setup
    got, acc = speculative_generate(params, params, prompt, cfg, cfg,
                                    10, gamma=3, kv_quantized=True)
    ref = generate(params, prompt, cfg, max_new_tokens=10,
                   kv_quantized=True)
    assert got.shape == ref.shape
    # Both chains run on int8 caches; self-draft accepts on agreement
    # between quantized verify and quantized draft — demand strong
    # agreement (fp32 tiny model: usually exact).
    agree = float(jnp.mean((got == ref).astype(jnp.float32)))
    assert agree > 0.9, agree
    assert float(acc) > 0
    # Batched int8: per-row quantized cache writes (K/V at (s,0,0),
    # scales at (0,s,0) per row) must behave like the B=1 path.
    prompts = jnp.tile(prompt, (3, 1))
    got_b, _ = speculative_generate(params, params, prompts, cfg, cfg,
                                    10, gamma=3, kv_quantized=True)
    for b in range(3):
        np.testing.assert_array_equal(np.asarray(got_b[b]),
                                      np.asarray(got[0]))
