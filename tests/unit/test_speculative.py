"""Speculative decoding: greedy exactness vs the target's own decode,
self-draft full acceptance, sampled-mode determinism, validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import (TransformerConfig, generate,
                                      init_params, speculative_generate,
                                      tiny_config)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    draft_cfg = TransformerConfig(
        vocab_size=cfg.vocab_size, d_model=64, n_layers=1, n_heads=2,
        n_kv_heads=2, d_ff=128, max_seq_len=256, dtype=jnp.float32,
        use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft = init_params(jax.random.PRNGKey(1), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 7), 0,
                                cfg.vocab_size)
    return cfg, draft_cfg, params, draft, prompt


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_greedy_exact_vs_target_decode(setup, gamma):
    """Greedy speculative output must be bit-identical to the target's
    own greedy decode, for any draft and any gamma."""
    cfg, draft_cfg, params, draft, prompt = setup
    ref = generate(params, prompt, cfg, max_new_tokens=12)
    got, mean_acc = speculative_generate(
        params, draft, prompt, cfg, draft_cfg, 12, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert 0.0 <= float(mean_acc) <= gamma


def test_self_draft_accepts_everything(setup):
    """Draft == target: every greedy proposal matches, so every round
    accepts all gamma tokens and output equals target greedy."""
    cfg, _, params, _, prompt = setup
    ref = generate(params, prompt, cfg, max_new_tokens=10)
    got, mean_acc = speculative_generate(
        params, params, prompt, cfg, cfg, 10, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # Every full round accepts all 4; only a final partial round can
    # drag the mean below 4 — it must stay well above 0.
    assert float(mean_acc) == 4.0


def test_sampled_mode_deterministic_and_in_vocab(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    key = jax.random.PRNGKey(9)
    a, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                10, gamma=3, temperature=0.8, key=key)
    b, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                10, gamma=3, temperature=0.8, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 17)
    assert int(jnp.max(a)) < cfg.vocab_size and int(jnp.min(a)) >= 0


def test_jits(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    fn = jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, cfg, draft_cfg, 8, gamma=2))
    got, _ = fn(params, draft, prompt)
    ref = generate(params, prompt, cfg, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_validation(setup):
    cfg, draft_cfg, params, draft, prompt = setup
    with pytest.raises(ValueError, match="single-stream"):
        speculative_generate(params, draft,
                             jnp.zeros((2, 4), jnp.int32), cfg,
                             draft_cfg, 4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             gamma=0)
    with pytest.raises(ValueError, match="PRNG key"):
        speculative_generate(params, draft, prompt, cfg, draft_cfg, 4,
                             temperature=0.5)
    bad_cfg = TransformerConfig(vocab_size=99, d_model=64, n_layers=1,
                                n_heads=2, n_kv_heads=2, d_ff=128)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(params, init_params(jax.random.PRNGKey(3),
                                                 bad_cfg),
                             prompt, cfg, bad_cfg, 4)


def test_spec_decode_with_int8_kv(setup):
    """Speculative decoding over int8 KV caches: runs, jits, and for a
    self-draft stays consistent with the int8-cache greedy decode."""
    cfg, draft_cfg, params, draft, prompt = setup
    got, acc = speculative_generate(params, params, prompt, cfg, cfg,
                                    10, gamma=3, kv_quantized=True)
    ref = generate(params, prompt, cfg, max_new_tokens=10,
                   kv_quantized=True)
    assert got.shape == ref.shape
    # Both chains run on int8 caches; self-draft accepts on agreement
    # between quantized verify and quantized draft — demand strong
    # agreement (fp32 tiny model: usually exact).
    agree = float(jnp.mean((got == ref).astype(jnp.float32)))
    assert agree > 0.9, agree
    assert float(acc) > 0
