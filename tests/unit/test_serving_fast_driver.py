"""Multi-rank continuous batching + block-bounded admission
(ISSUE 17): the ServingManager driving SEVERAL decode ranks at once
against a fake comm — placement across ranks, per-rank failover
surgery (only the dead rank's requests replay), KV-block admission
verdicts, and journal durability with a multi-rank plane.

The fake workers decode the same deterministic position-weighted
stream as ``test_serving_plane`` so every exactness assertion is
closed-form.
"""

from __future__ import annotations

import threading
import time
import types

import pytest

from nbdistributed_tpu.gateway.serving import ServingManager
from nbdistributed_tpu.messaging.coordinator import WorkerDied

pytestmark = [pytest.mark.unit, pytest.mark.serve, pytest.mark.gateway]


def next_tok(seq: list[int]) -> int:
    return (sum((i + 1) * t for i, t in enumerate(seq)) + 7) % 50


def expected_stream(prompt: list[int], n: int) -> list[int]:
    seq = list(prompt)
    out = []
    for _ in range(n):
        t = next_tok(seq)
        out.append(t)
        seq.append(t)
    return out


class FakeComm:
    """Like test_serving_plane's fake, with per-RANK step attribution:
    ``steps_seen`` records ``(rank, payload)`` and ``active_seen``
    records each tick's concurrent stream count, so multi-rank
    placement and block-bounded admission are directly assertable."""

    def __init__(self, num_workers: int = 3, per_tick: int = 2,
                 tick_delay: float = 0.0):
        self.num_workers = num_workers
        self.per_tick = per_tick
        self.tick_delay = tick_delay
        self._dead: set[int] = set()
        self._lock = threading.Lock()
        self._srv: dict[int, dict] = {}
        self._replay: dict[str, dict] = {}
        self.steps_seen: list[tuple[int, dict]] = []
        self.active_seen: list[tuple[int, int]] = []

    def dead_ranks(self):
        return set(self._dead)

    def kill(self, rank: int):
        with self._lock:
            self._dead.add(rank)
            self._srv.pop(rank, None)

    def post(self, ranks, msg_type, data=None):
        pass

    def send_to_ranks(self, ranks, msg_type, data=None, *, tenant=None,
                      priority=0, msg_id=None, timeout=None,
                      on_verdict=None, collective="unknown",
                      bufs=None):
        [rank] = ranks
        if rank in self._dead:
            raise WorkerDied(f"workers [{rank}] are dead")
        if msg_type == "execute":
            return {rank: types.SimpleNamespace(data={"output": "ok"})}
        if msg_type == "serve_open":
            self._srv[rank] = {}
            return {rank: types.SimpleNamespace(
                data={"status": "open"})}
        if msg_type == "serve_close":
            self._srv.pop(rank, None)
            return {rank: types.SimpleNamespace(data={"status": "ok"})}
        assert msg_type == "serve_step"
        if self.tick_delay:
            time.sleep(self.tick_delay)
            if rank in self._dead:
                raise WorkerDied(f"workers {ranks} are dead")
        if msg_id in self._replay:
            return {rank: types.SimpleNamespace(
                data=self._replay[msg_id])}
        srv = self._srv.setdefault(rank, {})
        self.steps_seen.append((rank, dict(data)))
        for a in data.get("admit") or ():
            srv[a["rid"]] = {"seq": list(a["prompt"]), "emitted": 0,
                             "base_len": len(a["prompt"]),
                             "max": a["max_new"]}
        for rid in data.get("release") or ():
            srv.pop(rid, None)
        self.active_seen.append((rank, len(srv)))
        emitted, finished = {}, []
        for rid, st in srv.items():
            if st["emitted"] >= st["max"]:
                finished.append(rid)
                continue
            o = st["emitted"]
            new = []
            for _ in range(min(self.per_tick,
                               st["max"] - st["emitted"])):
                t = next_tok(st["seq"])
                st["seq"].append(t)
                new.append(t)
            st["emitted"] += len(new)
            emitted[rid] = {"o": o, "t": list(new)}
            if st["emitted"] >= st["max"]:
                finished.append(rid)
        reply = {"status": "ok", "emitted": emitted,
                 "finished": finished, "errors": {},
                 "active": len(srv), "slots": 8, "pending": 0}
        if msg_id is not None:
            self._replay[msg_id] = reply
        return {rank: types.SimpleNamespace(data=reply)}


def make_mgr(tmp_path, comm, **kw):
    delivered: list = []
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("steps", 1)
    kw.setdefault("step_timeout", 5.0)
    kw.setdefault("queue_depth", 8)
    kw.setdefault("inflight", 16)
    kw.setdefault("decode_ranks", 2)
    mgr = ServingManager(
        comm, str(tmp_path), world_size=comm.num_workers,
        deliver=lambda t, m: delivered.append((t, m)),
        notify=lambda _t, _m: None, **kw)
    return mgr, delivered


def wait_done(mgr, rids, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(mgr.result(r)["done"] for r in rids):
            return
        time.sleep(0.02)
    raise AssertionError(
        f"requests not done: "
        f"{({r: mgr.result(r) for r in rids})}; {mgr.describe()}")


def admits_by_rank(comm) -> dict[int, list[str]]:
    out: dict[int, list[str]] = {}
    for rank, data in comm.steps_seen:
        for a in data.get("admit") or ():
            out.setdefault(rank, []).append(a["rid"])
    return out


# ----------------------------------------------------------------------


def test_multi_rank_decode_uses_both_ranks_exactly(tmp_path):
    """decode_ranks=2 on a 3-rank world: requests shard across ranks
    2 and 1 (rank 0 stays clear — it hosts jax.distributed), BOTH
    ranks demonstrably decode, and every stream is bit-identical to
    the single-rank reference."""
    comm = FakeComm(num_workers=3, per_tick=1, tick_delay=0.01)
    mgr, delivered = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        prompts = [[5, 9, 2], [7, 1], [3, 4, 8], [2, 6]]
        rids = [mgr.submit("t1", p, 5)["rid"] for p in prompts]
        wait_done(mgr, rids)
        for rid, p in zip(rids, prompts):
            r = mgr.result(rid)
            assert r["status"] == "completed"
            assert r["tokens"] == expected_stream(p, 5), rid
        # Per-rank telemetry: both decode ranks took admissions (4
        # requests into 2 slots/rank cannot fit on one), none leaked
        # onto rank 0.
        by_rank = admits_by_rank(comm)
        assert set(by_rank) == {1, 2}, by_rank
        assert sorted(r for rs in by_rank.values() for r in rs) \
            == sorted(rids)
        d = mgr.describe()
        assert d["decode_ranks"] == [1, 2]
        assert d["decode_rank"] == 2          # legacy headline rank
        assert set(d["ranks"]) == {"1", "2"}
        assert d["failovers"] == 0 and d["dup_dropped"] == 0
        done_rids = [m.data["rid"] for _t, m in delivered
                     if m.msg_type == "serve_done"]
        assert sorted(done_rids) == sorted(rids)
    finally:
        mgr.stop()


def test_single_rank_loss_replays_only_its_requests(tmp_path):
    """SIGKILL ONE of two decode ranks mid-stream: only the dead
    rank's requests re-admit from the journal (the survivor's streams
    are never disturbed), and every stream stays bit-exact."""
    comm = FakeComm(num_workers=3, per_tick=1, tick_delay=0.05)
    mgr, _d = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        prompts = [[5, 9, 2], [7, 1], [3, 4, 8], [2, 6]]
        rids = [mgr.submit("t1", p, 8)["rid"] for p in prompts]
        deadline = time.monotonic() + 10
        while any(len(mgr.result(r)["tokens"]) < 2 for r in rids):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        on_dead = set(admits_by_rank(comm).get(2, ()))
        assert on_dead, "rank 2 never took a request"
        comm.kill(2)
        wait_done(mgr, rids)
        for rid, p in zip(rids, prompts):
            r = mgr.result(rid)
            assert r["status"] == "completed"
            assert r["tokens"] == expected_stream(p, 8), rid
        d = mgr.describe()
        assert d["failovers"] >= 1
        assert 1 <= d["replayed"] <= len(on_dead)
        assert d["dup_dropped"] == 0
        # The failover pulled in rank 0: the two highest LIVE ranks.
        assert d["decode_ranks"] == [0, 1]
        # Re-admissions (prompt grew by the emitted prefix) happened
        # ONLY for requests the dead rank held.
        readmitted = {a["rid"] for _rank, data in comm.steps_seen
                      for a in (data.get("admit") or ())
                      if len(a["prompt"]) > len(prompts[
                          rids.index(a["rid"])])}
        assert readmitted and readmitted <= on_dead, \
            (readmitted, on_dead)
    finally:
        mgr.stop()


def test_kv_exhausted_submit_verdict(tmp_path):
    """A request whose worst-case block need exceeds a whole rank's
    pool can never be placed: refused AT SUBMIT with an explicit
    kv-exhausted verdict instead of starving in the queue."""
    comm = FakeComm()
    mgr, _d = make_mgr(tmp_path, comm, kv_block_tokens=4, kv_blocks=2)
    # Driver not started: the verdict is synchronous and
    # deterministic.  2 blocks/rank * 4 tok = 8 tokens of capacity.
    v = mgr.submit("t1", [1] * 6, 6)          # needs 3 blocks
    assert v["status"] == "rejected"
    assert v["reason"] == "kv-exhausted"
    assert "3 KV blocks" in v["error"]
    # A fitting request is still admitted.
    assert mgr.submit("t1", [1, 2], 4)["status"] == "accepted"
    mgr.stop()


def test_block_bounded_admission_defers_not_drops(tmp_path):
    """Free sequence slots but NO free blocks: admission defers (the
    finer-grained block gate under the scheduler ticket) and resumes
    as finishing requests free their blocks — nothing sheds, nothing
    hangs, streams stay exact."""
    comm = FakeComm(num_workers=2, per_tick=1, tick_delay=0.01)
    # One decode rank, 4 sequence slots, but a 1-block pool: only one
    # request's worst case (<= 8 tokens) fits at a time.
    mgr, _d = make_mgr(tmp_path, comm, decode_ranks=1, max_batch=4,
                       kv_block_tokens=8, kv_blocks=1)
    mgr.start()
    try:
        reqs = [([i + 1, i + 2], 4) for i in range(3)]
        rids = [mgr.submit("t1", p, n)["rid"] for p, n in reqs]
        wait_done(mgr, rids)
        for rid, (p, n) in zip(rids, reqs):
            r = mgr.result(rid)
            assert r["status"] == "completed"
            assert r["tokens"] == expected_stream(p, n), rid
        # The block gate, not the slot count, bounded concurrency.
        assert max(n for _rank, n in comm.active_seen) == 1
        d = mgr.describe()
        assert d["shed"] == 0 and d["rejected"] == 0
        assert d["completed"] == 3
        # Every block returned to the gateway's accounting pool.
        assert d["kv"] == {"block_tokens": 8, "blocks_per_rank": 1,
                           "used": 0, "free": 1, "tenants": {}}
    finally:
        mgr.stop()


def test_describe_kv_and_per_rank_occupancy(tmp_path):
    """The status surface mid-decode: per-rank placed/kv_used
    telemetry and per-submitting-tenant block counts."""
    comm = FakeComm(num_workers=3, per_tick=1, tick_delay=0.05)
    mgr, _d = make_mgr(tmp_path, comm, kv_block_tokens=8)
    mgr.start()
    try:
        rids = [mgr.submit("tA", [5, 9, 2], 8)["rid"],
                mgr.submit("tB", [7, 1], 8)["rid"]]
        deadline = time.monotonic() + 10
        while any(len(mgr.result(r)["tokens"]) < 1 for r in rids):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        d = mgr.describe()
        assert d["kv"]["block_tokens"] == 8
        # 2 slots/rank * ceil(64/8) blocks each (dense capacity).
        assert d["kv"]["blocks_per_rank"] == 2 * 8
        assert d["kv"]["used"] >= 2           # both requests hold KV
        # Per-tenant attribution: each submitted one live request.
        assert set(d["kv"]["tenants"]) == {"tA", "tB"}
        assert sum(v["kv_used"] for v in d["ranks"].values()) \
            == d["kv"]["used"]
        assert sum(v["placed"] for v in d["ranks"].values()) == 2
        wait_done(mgr, rids)
        assert mgr.describe()["kv"]["used"] == 0
    finally:
        mgr.stop()


def test_successor_plane_recovers_journal_multi_rank(tmp_path):
    """Gateway-death durability is preserved under multi-rank decode:
    a NEW manager over the same run dir re-enters every unfinished
    request across a FRESH 2-rank plane and completes it exactly."""
    comm_a = FakeComm(num_workers=3, per_tick=1, tick_delay=0.05)
    mgr_a, _d = make_mgr(tmp_path, comm_a)
    mgr_a.start()
    prompts = [[5, 9, 2], [7, 1]]
    rids = [mgr_a.submit("t1", p, 8)["rid"] for p in prompts]
    deadline = time.monotonic() + 10
    while any(len(mgr_a.result(r)["tokens"]) < 2 for r in rids):
        assert time.monotonic() < deadline
        time.sleep(0.02)
    mgr_a.stop(close_workers=False)   # daemon dies mid-stream
    for rid in rids:
        assert 0 < len(mgr_a.result(rid)["tokens"]) < 8

    comm_b = FakeComm(num_workers=3)
    mgr_b, delivered = make_mgr(tmp_path, comm_b)
    mgr_b.start()
    try:
        wait_done(mgr_b, rids)
        for rid, p in zip(rids, prompts):
            r = mgr_b.result(rid)
            assert r["status"] == "completed"
            assert r["tokens"] == expected_stream(p, 8)
        d = mgr_b.describe()
        assert d["replayed"] >= len(rids) and d["dup_dropped"] == 0
        assert sorted(m.data["rid"] for _t, m in delivered
                      if m.msg_type == "serve_done") == sorted(rids)
    finally:
        mgr_b.stop()
