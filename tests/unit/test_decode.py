"""Pallas flash-decode kernel: exact vs the einsum cached-attention
path, GQA grouping, ragged cache lengths, and the generation wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops.decode import flash_decode_attention

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def reference(q, kc, vc, pos):
    B, H, D = q.shape
    Hkv, T = kc.shape[1], kc.shape[2]
    group = H // Hkv
    qg = q.reshape(B, Hkv, group, D).astype(jnp.float32) / np.sqrt(D)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kc.astype(jnp.float32))
    mask = jnp.arange(T)[None, None, None, :] <= pos[:, None, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    o = jnp.einsum("bkgt,bktd->bkgd", p, vc.astype(jnp.float32))
    return o.reshape(B, H, D).astype(q.dtype)


@pytest.mark.parametrize("T,pos", [(40, [10, 25]), (128, [0, 127]),
                                   (37, [36, 5]),
                                   # overlapping final block: T > 128,
                                   # not a block multiple (the old gcd
                                   # fallback collapsed these to 1-wide
                                   # blocks)
                                   (129, [128, 60]), (200, [199, 130]),
                                   # T = block_k + 1 with pos at both
                                   # extremes: first slot only, and the
                                   # lone slot owned by the final block
                                   (129, [0, 128])])
def test_decode_matches_reference(T, pos):
    B, H, Hkv, D = 2, 8, 4, 16
    kc = jax.random.normal(jax.random.PRNGKey(0), (B, Hkv, T, D))
    vc = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, T, D))
    q = jax.random.normal(jax.random.PRNGKey(2), (B, H, D))
    pos = jnp.asarray(pos, jnp.int32)
    out = flash_decode_attention(q, kc, vc, pos)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference(q, kc, vc, pos)),
                               atol=1e-5, rtol=1e-5)


def test_decode_mha_no_grouping():
    B, T, H, D = 1, 64, 4, 32
    kc = jax.random.normal(jax.random.PRNGKey(3), (B, H, T, D))
    vc = jax.random.normal(jax.random.PRNGKey(4), (B, H, T, D))
    q = jax.random.normal(jax.random.PRNGKey(5), (B, H, D))
    pos = jnp.asarray([40], jnp.int32)
    out = flash_decode_attention(q, kc, vc, pos)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference(q, kc, vc, pos)),
                               atol=1e-5, rtol=1e-5)


def test_decode_rejects_indivisible_heads():
    kc = jnp.zeros((1, 3, 16, 8))
    with pytest.raises(ValueError, match="divisible"):
        flash_decode_attention(jnp.zeros((1, 8, 8)), kc, kc,
                               jnp.zeros((1,), jnp.int32))


def test_generation_uses_kernel_and_matches_einsum_path(monkeypatch):
    """use_flash=True routes decode through the Pallas kernel; tokens
    must match the einsum path exactly (greedy, fp32).  A spy pins the
    routing so the comparison can't pass vacuously."""
    from nbdistributed_tpu.models import generate, init_params, tiny_config
    from nbdistributed_tpu.ops import decode as decode_mod

    calls = []
    real = decode_mod.flash_decode_attention

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(decode_mod, "flash_decode_attention", spy)

    cfg_ein = tiny_config(dtype=jnp.float32, use_flash=False)
    cfg_flash = tiny_config(dtype=jnp.float32, use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg_ein)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                cfg_ein.vocab_size)
    a = generate(params, prompt, cfg_ein, max_new_tokens=8)
    assert not calls, "einsum config must not touch the kernel"
    b = generate(params, prompt, cfg_flash, max_new_tokens=8)
    assert calls, "use_flash config must route decode through the kernel"
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decode_kernel_on_tp_mesh(monkeypatch):
    """The Pallas decode kernel runs under GSPMD on a 4-way tp mesh
    (shard_map over batch/dp and heads/tp): tokens must match the
    einsum mesh path exactly, and the spy pins the kernel routing."""
    from nbdistributed_tpu.models import generate, init_params, tiny_config
    from nbdistributed_tpu.models.transformer import param_shardings
    from nbdistributed_tpu.ops import decode as decode_mod
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    calls = []
    real = decode_mod.flash_decode_attention

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(decode_mod, "flash_decode_attention", spy)

    mesh = mesh_mod.make_mesh({"tp": 4}, devices=jax.devices()[:4])
    base = tiny_config(dtype=jnp.float32, use_flash=False)
    mk = lambda flash: type(base)(**{**base.__dict__,
                                     "n_heads": 8, "n_kv_heads": 4,
                                     "use_flash": flash})
    cfg_ein, cfg_flash = mk(False), mk(True)
    params = tensor_parallel.apply_shardings(
        init_params(jax.random.PRNGKey(0), cfg_ein), mesh,
        param_shardings(cfg_ein))
    prompt = jnp.array([[5, 9, 2], [7, 1, 3]], jnp.int32)

    te = generate(params, prompt, cfg_ein, max_new_tokens=10, mesh=mesh)
    assert not calls, "einsum path must not touch the kernel"
    tf = generate(params, prompt, cfg_flash, max_new_tokens=10,
                  mesh=mesh)
    assert calls, "flash path must route through the Pallas kernel"
    np.testing.assert_array_equal(np.asarray(te), np.asarray(tf))


@pytest.mark.parametrize("T,pos,window", [(200, [199, 130], 64),
                                          (129, [128, 60], 32),
                                          (64, [63, 10], 16)])
def test_decode_sliding_window(T, pos, window):
    """Windowed decode: only the last `window` cache slots attend;
    out-of-band blocks are skipped in the kernel, not just masked."""
    B, H, Hkv, D = 2, 8, 4, 16
    kc = jax.random.normal(jax.random.PRNGKey(0), (B, Hkv, T, D))
    vc = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, T, D))
    q = jax.random.normal(jax.random.PRNGKey(2), (B, H, D))
    pos = jnp.asarray(pos, jnp.int32)
    out = flash_decode_attention(q, kc, vc, pos, window=window)

    # Oracle: windowed softmax over the cache.
    group = H // Hkv
    qg = q.reshape(B, Hkv, group, D).astype(jnp.float32) / np.sqrt(D)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kc.astype(jnp.float32))
    t = jnp.arange(T)
    keep = ((t[None, :] <= pos[:, None])
            & (t[None, :] > pos[:, None] - window))
    s = jnp.where(keep[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    ref = jnp.einsum("bkgt,bktd->bkgd", p,
                     vc.astype(jnp.float32)).reshape(B, H, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_windowed_generation_flash_matches_einsum(monkeypatch):
    """sliding_window generation must route through the kernel and
    produce the same greedy tokens as the einsum path."""
    from nbdistributed_tpu.models import generate, init_params, tiny_config
    from nbdistributed_tpu.ops import decode as decode_mod

    calls = []
    real = decode_mod.flash_decode_attention

    def spy(*a, **k):
        calls.append(k.get("window"))
        return real(*a, **k)

    monkeypatch.setattr(decode_mod, "flash_decode_attention", spy)
    base = tiny_config(dtype=jnp.float32, use_flash=False)
    mk = lambda flash: type(base)(**{**base.__dict__,
                                     "sliding_window": 24,
                                     "use_flash": flash})
    params = init_params(jax.random.PRNGKey(0), mk(False))
    prompt = jnp.array([[5, 9, 2], [7, 1, 3]], jnp.int32)
    te = generate(params, prompt, mk(False), max_new_tokens=40)
    assert not calls
    tf = generate(params, prompt, mk(True), max_new_tokens=40)
    assert calls and all(w == 24 for w in calls)
    np.testing.assert_array_equal(np.asarray(te), np.asarray(tf))


def test_decode_kernel_int8_cache_matches_dequantized_oracle():
    """The in-kernel scale commute must equal attention over the
    dequantized cache (same math, different association order)."""
    import jax
    import jax.numpy as jnp
    from nbdistributed_tpu.models.generate import (_cached_attention,
                                                   _dequantize_kv,
                                                   _quantize_kv)
    from nbdistributed_tpu.ops.decode import flash_decode_attention

    B, T, H, Hkv, D = 2, 129, 8, 2, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32)
    pos = jnp.asarray([T - 1, 77], jnp.int32)

    k8, k_s = _quantize_kv(k)
    v8, v_s = _quantize_kv(v)
    got = flash_decode_attention(q, k8, v8, pos, k_s=k_s, v_s=v_s)

    # Oracle: dequantize, then exact masked attention.
    kd = _dequantize_kv(k8, k_s)
    vd = _dequantize_kv(v8, v_s)
    scale = 1.0 / np.sqrt(D)
    ref = _cached_attention(q[:, None], kd, vd, pos[:, None],
                            scale).reshape(B, H, D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_kernel_int8_requires_both_scales():
    import jax.numpy as jnp
    import pytest
    from nbdistributed_tpu.ops.decode import flash_decode_attention
    q = jnp.zeros((1, 4, 8))
    kc = jnp.zeros((1, 2, 16, 8), jnp.int8)
    s = jnp.zeros((1, 2, 16, 1))
    with pytest.raises(ValueError, match="both k_s and v_s"):
        flash_decode_attention(q, kc, kc, jnp.zeros((1,), jnp.int32),
                               k_s=s)


class _RecordingTable(dict):
    """dict that records .get keys — proves the lookup actually fired
    with the expected key (numerics alone cannot: a silently-missed
    lookup falls back to the same default)."""

    def __init__(self, *a):
        super().__init__(*a)
        self.keys_seen = []

    def get(self, k, default=None):
        self.keys_seen.append(k)
        return super().get(k, default)


def test_decode_tuned_block_table_consulted():
    """block_k=None resolves through DECODE_TUNED_BLOCKS[(T, D, group)]
    with a 128 fallback; the lookup must fire with that exact key, and
    a tuned entry must change nothing numerically."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nbdistributed_tpu.ops import decode as dec

    B, T, H, Hkv, D = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    kc = jax.random.normal(ks[1], (B, Hkv, T, D))
    vc = jax.random.normal(ks[2], (B, Hkv, T, D))
    pos = jnp.full((B,), T - 1, jnp.int32)
    default = dec.flash_decode_attention(q, kc, vc, pos)
    key = (T, D, H // Hkv)
    orig = dec.DECODE_TUNED_BLOCKS
    table = _RecordingTable({key: 32})
    dec.DECODE_TUNED_BLOCKS = table
    try:
        tuned = dec.flash_decode_attention(q, kc, vc, pos)
    finally:
        dec.DECODE_TUNED_BLOCKS = orig
    assert key in table.keys_seen, table.keys_seen
    np.testing.assert_allclose(np.asarray(tuned), np.asarray(default),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------
# sequence-parallel decode: the cache's token axis sharded over sp,
# shards combined by log-sum-exp (the flash inter-block combine run
# across chips)

def test_decode_lse_matches_reference():
    """return_lse must equal log-sum-exp of the masked scores, and an
    all-masked query must report NEG_INF with a zero output row."""
    from nbdistributed_tpu.ops.decode import flash_decode_attention

    B, H, Hkv, T, D = 2, 4, 2, 96, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    kc = jax.random.normal(ks[1], (B, Hkv, T, D))
    vc = jax.random.normal(ks[2], (B, Hkv, T, D))
    pos = jnp.asarray([40, 95], jnp.int32)
    o, lse = flash_decode_attention(q, kc, vc, pos, block_k=32,
                                    return_lse=True)
    np.testing.assert_allclose(
        np.asarray(o),
        np.asarray(flash_decode_attention(q, kc, vc, pos, block_k=32)),
        rtol=1e-6)
    scale = 1.0 / np.sqrt(D)
    for b in range(B):
        for h in range(H):
            kv = h // (H // Hkv)
            s = (np.asarray(q[b, h]) * scale) @ np.asarray(kc[b, kv]).T
            s = s[: int(pos[b]) + 1]
            ref = float(np.log(np.exp(s - s.max()).sum()) + s.max())
            np.testing.assert_allclose(float(lse[b, h]), ref, rtol=1e-5)
    o3, lse3 = flash_decode_attention(
        q, kc, vc, jnp.asarray([-1, -1], jnp.int32), block_k=32,
        return_lse=True)
    assert float(lse3.max()) < -1e29
    assert float(np.abs(np.asarray(o3)).max()) == 0.0


@pytest.mark.parametrize("window", [None, 48])
def test_sp_sharded_decode_matches_single_device(window):
    """Cache token axis sharded over sp=4: the lse-combined sharded
    kernel must equal the single-device kernel (window composes —
    its bound is offset-invariant in local coordinates)."""
    from nbdistributed_tpu.models.generate import _flash_decode_on_mesh
    from nbdistributed_tpu.ops.decode import flash_decode_attention
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    B, H, Hkv, T, D = 2, 4, 2, 128, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    kc = jax.random.normal(ks[1], (B, Hkv, T, D))
    vc = jax.random.normal(ks[2], (B, Hkv, T, D))
    pos = jnp.asarray([90, 127], jnp.int32)
    ref = flash_decode_attention(q, kc, vc, pos, window=window)
    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    got = jax.jit(lambda: _flash_decode_on_mesh(
        q, kc, vc, pos, mesh, 1.0 / np.sqrt(D), window))()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_sp_sharded_decode_int8_cache():
    """int8 cache scales shard along the token axis with the cache."""
    from nbdistributed_tpu.models.generate import (_flash_decode_on_mesh,
                                                   _quantize_kv)
    from nbdistributed_tpu.ops.decode import flash_decode_attention
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    B, H, Hkv, T, D = 2, 4, 2, 128, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    k8, k_s = _quantize_kv(jax.random.normal(ks[1], (B, Hkv, T, D)))
    v8, v_s = _quantize_kv(jax.random.normal(ks[2], (B, Hkv, T, D)))
    pos = jnp.asarray([70, 127], jnp.int32)
    ref = flash_decode_attention(q, k8, v8, pos, k_s=k_s, v_s=v_s)
    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    got = jax.jit(lambda: _flash_decode_on_mesh(
        q, k8, v8, pos, mesh, 1.0 / np.sqrt(D), None, k_s, v_s))()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_generate_on_sp_mesh_matches_single_device():
    """End-to-end: generate() with the KV cache sharded dp×tp×sp must
    reproduce the single-device greedy decode (cache writes cross the
    sp shard boundary via GSPMD; reads combine by lse)."""
    from nbdistributed_tpu.models import generate, init_params, tiny_config
    from nbdistributed_tpu.models.transformer import param_shardings
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "sp": 2},
                              devices=jax.devices()[:8])
    cfg = tiny_config(dtype=jnp.float32, use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ps = tensor_parallel.apply_shardings(params, mesh,
                                         param_shardings(cfg))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    import dataclasses
    ref = generate(params, prompt,
                   dataclasses.replace(cfg, use_flash=False), 10)
    got = generate(ps, prompt, cfg, 10, mesh=mesh, max_len=32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_sp_sharded_decode_partial_final_block():
    """Regression (round-4 review): an sp shard's LOCAL position can
    exceed its cache slice length, which used to leave the padded
    tail of a partial final block unmasked (valid > seq_k → NaN from
    Pallas block padding).  t_loc=192 with block_k=128 forces a
    partial final block; pos=380 overshoots shard 0 by 188."""
    from nbdistributed_tpu.models.generate import _flash_decode_on_mesh
    from nbdistributed_tpu.ops.decode import flash_decode_attention
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    B, H, Hkv, T, D = 1, 2, 1, 384, 16
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    kc = jax.random.normal(ks[1], (B, Hkv, T, D))
    vc = jax.random.normal(ks[2], (B, Hkv, T, D))
    pos = jnp.asarray([380], jnp.int32)
    ref = flash_decode_attention(q, kc, vc, pos, block_k=128)
    mesh = mesh_mod.make_mesh({"sp": 2}, devices=jax.devices()[:2])
    got = jax.jit(lambda: _flash_decode_on_mesh(
        q, kc, vc, pos, mesh, 1.0 / np.sqrt(D)))()
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # Windowed variant at the same geometry (window bound must stay
    # on the unclamped local position).
    ref_w = flash_decode_attention(q, kc, vc, pos, block_k=128,
                                   window=100)
    got_w = jax.jit(lambda: _flash_decode_on_mesh(
        q, kc, vc, pos, mesh, 1.0 / np.sqrt(D), 100))()
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               atol=1e-5, rtol=1e-5)


def test_speculative_on_sp_mesh_matches_greedy():
    """Batched speculative decoding with the KV caches sharded over
    dp×sp: greedy spec must reproduce the target's greedy decode (the
    S=1 draft steps ride the sp-sharded kernel; the verify forward
    runs the einsum cache path under GSPMD)."""
    from nbdistributed_tpu.models import (generate, init_params,
                                          speculative_generate,
                                          tiny_config)
    from nbdistributed_tpu.models.transformer import param_shardings
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "sp": 2},
                              devices=jax.devices()[:8])
    cfg = tiny_config(dtype=jnp.float32, use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ps = tensor_parallel.apply_shardings(params, mesh,
                                         param_shardings(cfg))
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 6), 0,
                                cfg.vocab_size)
    import dataclasses
    ref = generate(params, prompt,
                   dataclasses.replace(cfg, use_flash=False), 8)
    got, acc = speculative_generate(ps, ps, prompt, cfg, cfg, 8,
                                    gamma=3, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert float(acc) == 3.0


def test_decode_server_on_sp_mesh():
    """DecodeServer with its cache pool sharded dp×sp: outputs match
    solo decode (slot admission writes cross sp shard boundaries via
    GSPMD; reads combine by lse)."""
    from nbdistributed_tpu.models import generate, init_params, tiny_config
    from nbdistributed_tpu.models.serving import DecodeServer
    from nbdistributed_tpu.models.transformer import param_shardings
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "sp": 2},
                              devices=jax.devices()[:8])
    cfg = tiny_config(dtype=jnp.float32, use_flash=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ps = tensor_parallel.apply_shardings(params, mesh,
                                         param_shardings(cfg))
    srv = DecodeServer(ps, cfg, max_batch=2, max_len=32, pad_to=4,
                       mesh=mesh)
    import dataclasses
    cfg_ref = dataclasses.replace(cfg, use_flash=False)
    reqs = [([5, 9, 2], 6), ([7, 1, 3, 11], 5)]
    rids = [srv.submit(*r) for r in reqs]
    srv.run_until_done(max_steps=40)
    for rid, (prompt, n) in zip(rids, reqs):
        solo = generate(params, jnp.asarray([prompt], jnp.int32),
                        cfg_ref, n)
        assert srv.outputs[rid] == [int(t) for t in
                                    solo[0, len(prompt):]]


def test_sp_sharded_decode_window_entirely_past_shard():
    """Round-4 review band: with a sliding window, an sp shard whose
    entire slice lies BELOW the window (lo >= valid_k) must contribute
    nothing — the block guard must skip it outright rather than run an
    empty-mask block whose garbage only underflow discards.  T=384,
    sp=2, window=100, pos=300: shard 0's keys [0,192) are all below
    lo=201."""
    from nbdistributed_tpu.models.generate import _flash_decode_on_mesh
    from nbdistributed_tpu.ops.decode import flash_decode_attention
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    B, H, Hkv, T, D = 1, 2, 1, 384, 16
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (B, H, D))
    kc = jax.random.normal(ks[1], (B, Hkv, T, D))
    vc = jax.random.normal(ks[2], (B, Hkv, T, D))
    mesh = mesh_mod.make_mesh({"sp": 2}, devices=jax.devices()[:2])
    for p in (300, 291, 355):          # across the hazardous band
        pos = jnp.asarray([p], jnp.int32)
        ref = flash_decode_attention(q, kc, vc, pos, block_k=128,
                                     window=100)
        got = jax.jit(lambda pos=pos: _flash_decode_on_mesh(
            q, kc, vc, pos, mesh, 1.0 / np.sqrt(D), 100))()
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
