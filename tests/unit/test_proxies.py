"""IDE proxy generation tests (reference behavior: magic.py:1131-1314)."""

import jax
import pytest

from nbdistributed_tpu.magics import proxies


def test_array_proxy_is_shape_dtype_struct():
    p, ok = proxies.make_proxy("w", {"kind": "array", "shape": [2, 3],
                                     "dtype": "float32"})
    assert ok
    assert isinstance(p, jax.ShapeDtypeStruct)
    assert p.shape == (2, 3) and str(p.dtype) == "float32"


def test_bfloat16_array_proxy_falls_back():
    p, ok = proxies.make_proxy("w", {"kind": "array", "shape": [4],
                                     "dtype": "bfloat16"})
    assert ok and p.shape == (4,)


def test_scalar_proxy_reconstructs_value():
    p, ok = proxies.make_proxy("x", {"kind": "scalar", "type": "int",
                                     "repr": "42"})
    assert ok and p == 42


def test_callable_stub_raises_with_guidance():
    desc = {"kind": "callable", "signature": "(a, b=1)", "doc": "adds",
            "name": "f"}
    stub, ok = proxies.make_proxy("f", desc)
    assert ok
    assert "(a, b=1)" in stub.__doc__
    with pytest.raises(RuntimeError, match="workers"):
        stub(1, 2)


def test_module_proxy_real_import():
    p, ok = proxies.make_proxy("json", {"kind": "module", "name": "json"})
    import json as real_json
    assert ok and p is real_json


def test_module_proxy_placeholder_for_missing():
    p, ok = proxies.make_proxy("ghost", {"kind": "module",
                                         "name": "no_such_module_xyz"})
    assert ok and p.__name__ == "no_such_module_xyz"


def test_class_proxy():
    p, ok = proxies.make_proxy("Net", {"kind": "class", "name": "Net",
                                       "module": "models"})
    assert ok and isinstance(p, type) and p.__name__ == "Net"


def test_container_proxy_repr():
    p, ok = proxies.make_proxy("xs", {"kind": "container", "type": "list",
                                      "len": 7})
    assert ok and "list" in repr(p) and "7" in repr(p)


def test_sync_respects_user_variables():
    user_ns = {"mine": "precious"}
    reg = {}
    info = {"mine": {"kind": "scalar", "type": "int", "repr": "1"},
            "theirs": {"kind": "scalar", "type": "int", "repr": "2"}}
    n = proxies.sync_namespace(user_ns, info, reg)
    assert user_ns["mine"] == "precious"  # never clobbered
    assert user_ns["theirs"] == 2
    assert n == 1


def test_sync_user_created_shapedtypestruct_untouched():
    """A user's own ShapeDtypeStruct must survive syncs — ownership is
    identity-tracked, not type-sniffed."""
    import jax
    import numpy as np
    spec = jax.ShapeDtypeStruct((8,), np.float32)
    user_ns = {"spec": spec}
    reg = {}
    proxies.sync_namespace(user_ns, {}, reg)
    assert user_ns["spec"] is spec
    proxies.sync_namespace(
        user_ns, {"spec": {"kind": "array", "shape": [2],
                           "dtype": "float32"}}, reg)
    assert user_ns["spec"] is spec  # still the user's object


def test_sync_removes_stale_proxies():
    user_ns = {}
    reg = {}
    proxies.sync_namespace(user_ns, {"tmp": {"kind": "array", "shape": [1],
                                             "dtype": "float32"}}, reg)
    assert "tmp" in user_ns
    proxies.sync_namespace(user_ns, {}, reg)
    assert "tmp" not in user_ns and reg == {}


def test_sync_refreshes_owned_proxies():
    user_ns = {}
    reg = {}
    proxies.sync_namespace(
        user_ns, {"w": {"kind": "array", "shape": [2], "dtype": "float32"}},
        reg)
    proxies.sync_namespace(
        user_ns, {"w": {"kind": "array", "shape": [9], "dtype": "float32"}},
        reg)
    assert user_ns["w"].shape == (9,)  # owned proxies track remote changes


def test_sync_user_overwrite_reclaims_name():
    user_ns = {}
    reg = {}
    proxies.sync_namespace(
        user_ns, {"w": {"kind": "array", "shape": [2], "dtype": "float32"}},
        reg)
    user_ns["w"] = "user took this name"
    proxies.sync_namespace(
        user_ns, {"w": {"kind": "array", "shape": [9], "dtype": "float32"}},
        reg)
    assert user_ns["w"] == "user took this name"
    assert "w" not in reg


def test_sync_skips_seeded_and_private_names():
    user_ns = {}
    reg = {}
    info = {"jax": {"kind": "module", "name": "jax"},
            "rank": {"kind": "scalar", "type": "int", "repr": "0"},
            "all_reduce": {"kind": "callable", "signature": "(x)",
                           "name": "all_reduce"},
            "_hidden": {"kind": "scalar", "type": "int", "repr": "1"},
            "ok": {"kind": "scalar", "type": "int", "repr": "3"}}
    n = proxies.sync_namespace(user_ns, info, reg)
    assert n == 1
    assert set(user_ns) == {"ok"}


def test_remove_proxies_clears_owned_only():
    user_ns = {}
    reg = {}
    proxies.sync_namespace(
        user_ns, {"w": {"kind": "array", "shape": [2], "dtype": "float32"},
                  "f": {"kind": "callable", "signature": "()", "name": "f"}},
        reg)
    user_ns["w"] = "reclaimed"
    proxies.remove_proxies(user_ns, reg)
    assert user_ns == {"w": "reclaimed"}
    assert reg == {}
