"""Flash attention vs reference oracle (interpret mode on CPU exercises
the identical kernel code path that compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops import attention_reference, flash_attention

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    B, S, H, D = 2, 128, 4, 64
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out = flash_attention(q, k, v, causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq", [65, 100, 192, 255])
def test_flash_non_divisible_seq_lengths(causal, seq):
    """Sequence lengths that don't divide the block size must be exact —
    dynamic-slice clamping once silently double-counted keys here."""
    B, H, D = 1, 2, 32
    q, k, v = (rand((B, seq, H, D), i + 20) for i in range(3))
    out = flash_attention(q, k, v, causal, None, 64, 64)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_multiblock_seq():
    """Sequence longer than one block exercises the online-softmax
    recurrence across k-blocks."""
    B, S, H, D = 1, 256, 2, 32
    q, k, v = (rand((B, S, H, D), i + 10) for i in range(3))
    out = flash_attention(q, k, v, True, None, 64, 64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gqa():
    B, S, H, Hkv, D = 1, 64, 8, 2, 32
    q = rand((B, S, H, D), 0)
    k = rand((B, S, Hkv, D), 1)
    v = rand((B, S, Hkv, D), 2)
    out = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_bfloat16():
    B, S, H, D = 1, 64, 2, 64
    q, k, v = (rand((B, S, H, D), i, jnp.bfloat16) for i in range(3))
    out = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)


def test_flash_gradients_match_reference():
    B, S, H, D = 1, 64, 2, 32
    q, k, v = (rand((B, S, H, D), i + 5) for i in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_flash_causality_enforced():
    """Output at position t must not depend on inputs after t."""
    B, S, H, D = 1, 64, 1, 16
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out1 = flash_attention(q, k, v, True)
    k2 = k.at[:, -1].set(999.0)
    v2 = v.at[:, -1].set(999.0)
    out2 = flash_attention(q, k2, v2, True)
    np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                               np.asarray(out2[:, :-1]), atol=1e-5)


def test_flash_jit_compatible():
    B, S, H, D = 1, 64, 2, 32
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v))
    np.testing.assert_allclose(
        np.asarray(jitted(q, k, v)),
        np.asarray(attention_reference(q, k, v)), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("H,Hkv", [(2, 2), (8, 2)])
def test_flash_bwd_blockwise_gqa(causal, H, Hkv):
    """The Pallas backward (dq/dk/dv kernels off the saved logsumexp)
    must match reference grads for causal x GQA combinations."""
    B, S, D = 2, 128, 32
    q = rand((B, S, H, D), 30)
    k = rand((B, S, Hkv, D), 31)
    v = rand((B, S, Hkv, D), 32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 64, 64)
                       * jnp.cos(jnp.arange(D)))

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal)
                       * jnp.cos(jnp.arange(D)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
            err_msg=f"d{name} mismatch (causal={causal}, "
                    f"H={H}, Hkv={Hkv})")


@pytest.mark.parametrize("Sq,Sk", [(65, 100), (100, 65), (128, 255)])
def test_flash_bwd_ragged_and_cross_lengths(Sq, Sk):
    """Non-block-multiple and unequal Sq/Sk: padded rows/keys must
    contribute exactly zero gradient."""
    B, H, D = 1, 2, 32
    q = rand((B, Sq, H, D), 40)
    k = rand((B, Sk, H, D), 41)
    v = rand((B, Sk, H, D), 42)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, False, None, 64, 64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=False) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
            err_msg=f"d{name} mismatch (Sq={Sq}, Sk={Sk})")


class TestSlidingWindow:
    """Mistral-style sliding-window attention: both passes prune
    out-of-band blocks and must stay exact vs the windowed oracle."""

    def _oracle(self, q, k, v, window):
        """Windowed softmax attention from first principles."""
        B, S, H, D = q.shape
        Hkv = k.shape[2]
        kk = jnp.repeat(k, H // Hkv, axis=2)
        vv = jnp.repeat(v, H // Hkv, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(D)
        qi = jnp.arange(S)[:, None]
        ki = jnp.arange(S)[None, :]
        keep = (ki <= qi) & (ki > qi - window)
        logits = jnp.where(keep, logits, -1e30)
        p = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vv)

    @pytest.mark.parametrize("window", [16, 64, 100])
    def test_reference_matches_oracle(self, window):
        B, S, H, D = 1, 128, 2, 32
        q, k, v = (rand((B, S, H, D), i + 70) for i in range(3))
        got = attention_reference(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._oracle(q, k, v, window)),
            atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("window,S", [(16, 128), (64, 200), (128, 256)])
    def test_flash_matches_reference(self, window, S):
        """Windows crossing block boundaries, non-multiple lengths."""
        B, H, Hkv, D = 1, 4, 2, 32
        q = rand((B, S, H, D), 80)
        k = rand((B, S, Hkv, D), 81)
        v = rand((B, S, Hkv, D), 82)
        got = flash_attention(q, k, v, True, None, 64, 64, window)
        ref = attention_reference(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_window_gradients(self):
        B, S, H, Hkv, D, W = 1, 128, 4, 2, 32, 48
        q = rand((B, S, H, D), 90)
        k = rand((B, S, Hkv, D), 91)
        v = rand((B, S, Hkv, D), 92)

        def loss_f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, None, 64, 64, W) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(attention_reference(
                q, k, v, causal=True, window=W) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4,
                                       err_msg=f"d{name} mismatch")

    def test_window_requires_causal(self):
        q = rand((1, 32, 2, 16), 0)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, False, None, 32, 32, 16)
        with pytest.raises(ValueError, match="causal"):
            attention_reference(q, q, q, causal=False, window=16)


def test_flash_tuned_block_table_consulted():
    """block_q/block_k=None resolve through TUNED_BLOCKS[(Sq, Sk, D,
    group)] with a 128 fallback; a tuned entry must change nothing
    numerically (forward and gradients)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nbdistributed_tpu.ops import attention as att

    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    loss = lambda q_: jnp.sum(att.flash_attention(q_, k, v, True) ** 2)
    default, g_default = jax.value_and_grad(loss)(q)
    key = (S, S, D, H // Hkv)

    class _Recording(dict):
        keys_seen: list = []

        def get(self, k_, d=None):
            _Recording.keys_seen.append(k_)
            return super().get(k_, d)

    orig = att.TUNED_BLOCKS
    att.TUNED_BLOCKS = _Recording({key: (32, 32)})
    try:
        tuned, g_tuned = jax.value_and_grad(loss)(q)
    finally:
        att.TUNED_BLOCKS = orig
    # The lookup must have fired with the exact (Sq, Sk, D, group) key
    # (numerics alone cannot prove it: a missed lookup falls back to
    # the same 128 default).
    assert key in _Recording.keys_seen, _Recording.keys_seen
    np.testing.assert_allclose(float(tuned), float(default), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g_tuned),
                               np.asarray(g_default), atol=1e-5,
                               rtol=1e-5)


class TestSegmentIds:
    """Packed-document masking: queries attend only same-segment keys,
    in the flash kernel (both passes) and the reference."""

    def _inputs(self, B=2, S=96, H=4, Hkv=2, D=16, n_docs=3, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
        # Random doc boundaries -> non-decreasing segment ids.
        bounds = jax.random.randint(ks[3], (B, S), 0, n_docs)
        seg = jnp.sort(bounds, axis=1)
        return q, k, v, seg

    def test_reference_equals_per_document_attention(self):
        """The packed reference must equal attending each document
        independently and concatenating — the ground-truth semantics
        of segment masking."""
        q, k, v, _ = self._inputs(B=1, S=48)
        seg = jnp.asarray([[0] * 20 + [1] * 17 + [2] * 11])
        packed = attention_reference(q, k, v, causal=True,
                                     segment_ids=seg)
        parts = []
        for lo, hi in ((0, 20), (20, 37), (37, 48)):
            parts.append(attention_reference(
                q[:, lo:hi], k[:, lo:hi], v[:, lo:hi], causal=True))
        np.testing.assert_allclose(np.asarray(packed),
                                   np.asarray(jnp.concatenate(parts, 1)),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_flash_matches_reference(self, causal):
        q, k, v, seg = self._inputs()
        out = flash_attention(q, k, v, causal, None, 32, 32,
                              segment_ids=seg)
        ref = attention_reference(q, k, v, causal=causal,
                                  segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_flash_non_multiple_seq(self):
        q, k, v, seg = self._inputs(S=77)
        out = flash_attention(q, k, v, True, None, 32, 32,
                              segment_ids=seg)
        ref = attention_reference(q, k, v, causal=True,
                                  segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_flash_gradients_match_reference(self):
        """dq/dk/dv through both Pallas backward kernels must match
        autodiff through the masked reference."""
        q, k, v, seg = self._inputs(S=64)

        def loss_f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, None, 32, 32,
                                           segment_ids=seg) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(attention_reference(
                q, k, v, causal=True, segment_ids=seg) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")

    def test_no_cross_document_leak(self):
        """Perturbing document 0's keys/values must not change
        document 1's outputs at all — the leak pack_tokens windows had
        without segment masking."""
        q, k, v, _ = self._inputs(B=1, S=64)
        seg = jnp.asarray([[0] * 32 + [1] * 32])
        base = flash_attention(q, k, v, True, None, 32, 32,
                               segment_ids=seg)
        k2 = k.at[:, :32].add(7.0)
        v2 = v.at[:, :32].add(-3.0)
        pert = flash_attention(q, k2, v2, True, None, 32, 32,
                               segment_ids=seg)
        np.testing.assert_array_equal(np.asarray(base[:, 32:]),
                                      np.asarray(pert[:, 32:]))
        assert np.abs(np.asarray(base[:, :32])
                      - np.asarray(pert[:, :32])).max() > 1e-3

    def test_rejects_cross_length(self):
        q, k, v, seg = self._inputs(S=64)
        with pytest.raises(ValueError, match="Sq == Sk"):
            flash_attention(q[:, :32], k, v, True, None, 32, 32,
                            segment_ids=seg[:, :32])

    def test_negative_segment_ids_are_ordinary_values(self):
        """User ids may be any integers (equality defines membership):
        ids colliding with the pad sentinels must behave identically —
        padded keys are excluded by the validity mask, not by the
        sentinel values (S=77 forces real key padding)."""
        q, k, v, _ = self._inputs(B=1, S=77)
        seg_pos = jnp.asarray([[0] * 40 + [1] * 37])
        seg_neg = jnp.asarray([[-2] * 40 + [-1] * 37])  # same structure
        a = flash_attention(q, k, v, True, None, 32, 32,
                            segment_ids=seg_pos)
        b = flash_attention(q, k, v, True, None, 32, 32,
                            segment_ids=seg_neg)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_segments_compose_with_sliding_window(self):
        """window AND segment masks AND together: both kernel passes
        must match the reference with both constraints active."""
        q, k, v, seg = self._inputs(S=96)
        W = 24
        out = flash_attention(q, k, v, True, None, 32, 32, W,
                              segment_ids=seg)
        ref = attention_reference(q, k, v, causal=True, window=W,
                                  segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        g = jax.grad(lambda q_: jnp.sum(flash_attention(
            q_, k, v, True, None, 32, 32, W,
            segment_ids=seg) ** 2))(q)
        gr = jax.grad(lambda q_: jnp.sum(attention_reference(
            q_, k, v, causal=True, window=W,
            segment_ids=seg) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=1e-4, rtol=1e-4)


def test_tuned_blocks_file_roundtrip(tmp_path):
    """tune_flash.py persists its tables via ops._tuned; the kernels
    load them at import.  Save/load must round-trip tuple keys, and a
    corrupt or missing file must degrade to empty tables."""
    from nbdistributed_tpu.ops import _tuned

    p = str(tmp_path / "tuned.json")
    flash = {(2048, 2048, 128, 4): (256, 512)}
    decode = {(2048, 128, 4): 256}
    _tuned.save(flash, decode, meta={"device": "test"}, path=p)
    f, d = _tuned.load(p)
    assert f == flash and d == decode
    assert _tuned.load(str(tmp_path / "absent.json")) == ({}, {})
    (tmp_path / "bad.json").write_text("{not json")
    assert _tuned.load(str(tmp_path / "bad.json")) == ({}, {})
    # Valid JSON, wrong schema: top level or sub-tables not dicts —
    # must degrade to defaults, never crash import of ops.attention.
    for bad in ('["a list"]', '{"flash": [1,2]}', '{"decode": 7}',
                '{"flash": {"x": 1}}', '{"flash": {"1,2": null}}'):
        (tmp_path / "schema.json").write_text(bad)
        assert _tuned.load(str(tmp_path / "schema.json")) == ({}, {})
