"""Unit tests for the streaming bulk-transfer plane (ISSUE 20,
nbdistributed_tpu/messaging/xfer.py) and its mailbox-spill and
chunk-fault satellites.

The protocol tests run the REAL engine (push_flat / pull_value) and
the REAL worker endpoint against an in-process loopback comm whose
every frame rides the production codec (encode → decode,
allow_pickle=False), so read-only decode views, the ``xf`` chunk
header, and the buffer planes behave exactly as on the wire — only
the socket is missing.
"""

import os
import zlib

import numpy as np
import pytest

from nbdistributed_tpu.messaging import xfer
from nbdistributed_tpu.messaging.codec import (Message, decode, encode,
                                               unflatten_pytree_wire)

pytestmark = [pytest.mark.unit, pytest.mark.xfer]


# ----------------------------------------------------------------------
# loopback comm


class LoopHandle:
    def __init__(self, msg, replies):
        self.msg = msg
        self._replies = replies

    def wait(self, timeout=None):
        return self._replies


class LoopComm:
    """In-process comm driving per-rank :class:`XferEndpoint`\\ s
    through a full codec round-trip per frame (request AND reply)."""

    def __init__(self, world: int = 1):
        self.world = world
        self.endpoints = {r: xfer.XferEndpoint(r)
                          for r in range(world)}
        self.ns = {r: {} for r in range(world)}
        self.corrupt_once: set = set()   # (rank, seq) -> flip one bit
        self.chunk_log: list = []        # (rank, seq) delivered chunks

    def _handle(self, rank: int, msg: Message) -> Message:
        ep = self.endpoints[rank]
        mt = msg.msg_type
        if mt == "xfer_begin":
            return ep.handle_begin(msg)
        if mt == "xfer_chunk":
            self.chunk_log.append((rank, (msg.xfer or {}).get("s")))
            return ep.handle_chunk(msg)
        if mt == "xfer_commit":
            ns = self.ns[rank]

            def bind(st):
                if st.kind == "file":
                    d = os.path.dirname(os.path.abspath(st.dest))
                    os.makedirs(d, exist_ok=True)
                    st.sink.arrays["f0"].tofile(st.dest)
                    return lambda: os.path.exists(st.dest)
                value = unflatten_pytree_wire(
                    st.meta, st.sink.arrays, lambda a, j: a)
                ns[st.name] = value
                vid, name = id(value), st.name
                return lambda: id(ns.get(name)) == vid

            return ep.handle_commit(msg, bind)
        if mt == "xfer_pull_begin":
            return ep.handle_pull_begin(msg, self.ns[rank])
        if mt == "xfer_read":
            return ep.handle_read(msg)
        if mt == "xfer_pull_end":
            return ep.handle_pull_end(msg)
        raise AssertionError(f"unexpected msg_type {mt}")

    def _roundtrip(self, rank: int, msg: Message) -> Message:
        wire = encode(msg, allow_pickle=False)
        key = (rank, (msg.xfer or {}).get("s"))
        if msg.msg_type == "xfer_chunk" and key in self.corrupt_once:
            self.corrupt_once.discard(key)
            mut = bytearray(wire)
            mut[-1] ^= 0x40      # trailing payload byte, header-safe
            wire = bytes(mut)
        reply = self._handle(rank, decode(wire, allow_pickle=False))
        return decode(encode(reply, allow_pickle=False),
                      allow_pickle=False)

    def submit(self, ranks, msg_type, data, *, bufs=None, xfer=None,
               tenant=None, timeout=None, **kw):
        replies = {}
        msg = None
        for r in ranks:
            msg = Message(msg_type=msg_type, data=data,
                          bufs=dict(bufs or {}), tenant=tenant)
            if xfer is not None:
                msg.xfer = xfer
            replies[r] = self._roundtrip(r, msg)
        return LoopHandle(msg, replies)

    def send_to_ranks(self, ranks, msg_type, data, *, bufs=None,
                      tenant=None, timeout=None, **kw):
        return self.submit(ranks, msg_type, data, bufs=bufs,
                           tenant=tenant).wait()

    def send_to_rank(self, rank, msg_type, data, **kw):
        return self.send_to_ranks([rank], msg_type, data, **kw)[rank]


@pytest.fixture
def small_chunks(monkeypatch):
    """64 KiB chunks (the floor) + a small inline threshold so modest
    payloads exercise the full chunked protocol."""
    monkeypatch.setenv("NBD_XFER_CHUNK_BYTES", "65536")
    monkeypatch.setenv("NBD_XFER_THRESHOLD_BYTES", "4096")
    monkeypatch.setenv("NBD_XFER_WINDOW", "4")


def mixed_tree():
    rng = np.random.default_rng(7)
    return {"w": rng.standard_normal((300, 70)).astype(np.float32),
            "nested": [np.arange(17, dtype=np.int64),
                       {"b": np.float64(3.25)}],
            "zero_d": np.array(1.5, dtype=np.float16),
            "empty": np.empty((0, 4), dtype=np.float32),
            "label": "step100", "n": 12}


def tree_equal(a, b):
    if isinstance(a, dict):
        assert sorted(a) == sorted(b)
        for k in a:
            tree_equal(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            tree_equal(x, y)
    elif isinstance(a, np.ndarray) or hasattr(a, "dtype"):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    else:
        assert a == b


# ----------------------------------------------------------------------
# chunker primitives


def test_chunk_source_sink_roundtrip_mixed_pytree():
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    meta, bufs = flatten_pytree_wire(mixed_tree())
    src = xfer.ChunkSource(bufs)
    csize = 4096
    n = src.n_chunks(csize)
    assert n == -(-src.total // csize)
    sink = xfer.ChunkSink(src.descs, src.total, n, csize)
    for seq in range(n):
        sink.write(seq, src.read(seq, csize))
    assert sink.complete() and sink.have == n
    got = unflatten_pytree_wire(meta, sink.arrays, lambda a, j: a)
    tree_equal(got, mixed_tree())


def test_chunk_source_gather_matches_logical_stream():
    bufs = {"a": np.arange(10, dtype=np.uint8),
            "b": np.arange(7, dtype=np.uint8) + 100,
            "c": np.empty(0, dtype=np.uint8)}
    src = xfer.ChunkSource(bufs)
    stream = b"".join(np.asarray(v).tobytes() for v in bufs.values())
    assert src.total == len(stream) == 17
    for csize in (1, 3, 5, 16, 17, 64):
        got = b"".join(src.read(s, csize)
                       for s in range(src.n_chunks(csize)))
        assert got == stream, csize


def test_chunk_crcs_and_bitmap_roundtrip():
    src = xfer.ChunkSource({"a": np.arange(1000, dtype=np.float64)})
    csize = 512
    crcs = src.crcs(csize)
    n = src.n_chunks(csize)
    assert len(crcs) == n
    assert all(zlib.crc32(src.read(s, csize)) == crcs[s]
               for s in range(n))
    sink = xfer.ChunkSink(src.descs, src.total, n, csize)
    for seq in range(0, n, 2):          # even chunks only
        sink.write(seq, src.read(seq, csize))
    missing = xfer.missing_from_bitmap(sink.bitmap_hex(), n)
    assert missing == sink.missing() == list(range(1, n, 2))
    assert xfer.missing_from_bitmap("", n) == list(range(n))
    assert xfer.missing_from_bitmap("zz-not-hex", n) == list(range(n))


def test_transfer_id_content_addressed():
    src = xfer.ChunkSource({"a": np.arange(100, dtype=np.int32)})
    crcs = src.crcs(64)
    one = xfer.transfer_id("var", "x", src.total, 64, crcs)
    two = xfer.transfer_id("var", "x", src.total, 64, crcs)
    assert one == two and one.startswith("x") and len(one) == 17
    assert xfer.transfer_id("var", "y", src.total, 64, crcs) != one
    assert xfer.transfer_id("file", "x", src.total, 64, crcs) != one
    assert xfer.transfer_id("var", "x", src.total, 64,
                            [crcs[0] ^ 1, *crcs[1:]]) != one


def test_scaled_timeout_floor_and_rate(monkeypatch):
    monkeypatch.setenv("NBD_XFER_MIN_TIMEOUT_S", "60")
    monkeypatch.setenv("NBD_XFER_MIN_BYTES_PER_S", str(1 << 20))
    assert xfer.scaled_timeout(0) == 60.0
    assert xfer.scaled_timeout(10 << 20) == 60.0   # under the floor
    assert xfer.scaled_timeout(1 << 30) == 1024.0  # 1 GiB at 1 MiB/s
    assert xfer.scaled_timeout(0, floor=5.0) == 5.0


def test_approx_nbytes():
    assert xfer.approx_nbytes(np.zeros((4, 4), np.float32)) == 64
    assert xfer.approx_nbytes({"a": np.zeros(8, np.float64),
                               "b": [np.zeros(2, np.int8), "s", 3],
                               "c": b"xyz"}) == 64 + 2 + 3
    assert xfer.approx_nbytes(object()) == 0


def test_compression_roundtrip_and_stored_escape():
    compressible = bytes(1000)
    enc, payload = xfer.compress_chunk("zlib", compressible)
    assert enc == "zlib" and len(payload) < len(compressible)
    assert xfer.decompress_chunk(enc, payload,
                                 len(compressible)) == compressible
    rng = np.random.default_rng(0)
    noise = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
    enc, payload = xfer.compress_chunk("zlib", noise)
    assert enc == "stored" and payload == noise   # escape hatch
    assert xfer.decompress_chunk("stored", noise, len(noise)) == noise
    with pytest.raises(xfer.XferError):
        xfer.decompress_chunk("martian", b"x", 1)
    assert "zlib" in xfer.available_codecs()


def test_window_bounds_inflight_bytes():
    drained = []
    win = xfer._Window(4)
    for seq in range(32):
        win.admit(LoopHandle(None, {}), 100, seq, [0],
                  lambda h, s, r: drained.append(s))
        assert win.inflight_bytes <= 4 * 100
    win.drain_all(lambda h, s, r: drained.append(s))
    assert drained == list(range(32))     # oldest-first, all drained
    assert win.inflight_bytes == 0
    assert win.peak_bytes <= 4 * 100


# ----------------------------------------------------------------------
# push engine + endpoint, over the loopback codec


def test_push_pull_loopback_bit_identical(small_chunks):
    comm = LoopComm(world=2)
    tree = mixed_tree()
    stats = xfer.push_value(comm, [0, 1], "params", tree)
    assert stats["chunks"] > 1 and stats["resent_chunks"] == 0
    assert stats["applies"] == {0: 1, 1: 1}
    for r in (0, 1):
        tree_equal(comm.ns[r]["params"], tree)
        assert comm.endpoints[r].counters["applies"] == 1
    # window x chunk bound, deterministic half of the acceptance bar
    assert stats["inflight_peak_bytes"] <= 4 * 65536


def test_push_exactly_once_across_repeats(small_chunks):
    comm = LoopComm(world=1)
    tree = {"a": np.arange(50_000, dtype=np.float32)}
    one = xfer.push_value(comm, [0], "t", tree)
    assert one["already_done"] == []
    two = xfer.push_value(comm, [0], "t", tree)
    # Same content-addressed xid: the receiver answers begin with
    # done=True and the second push moves ZERO chunks.
    assert two["xid"] == one["xid"]
    assert two["already_done"] == [0]
    assert two["chunks"] == one["chunks"]  # layout, not wire traffic
    assert comm.endpoints[0].counters["applies"] == 1
    assert len(comm.chunk_log) == one["chunks"]


def test_push_memo_dropped_when_binding_drifts(small_chunks):
    """Exactly-once is per content per BINDING: rebinding or deleting
    the variable worker-side invalidates the completed-xid memo, so a
    deliberate re-push of the same content RESTORES the value instead
    of no-oping forever (found by the round-16 verify drive)."""
    comm = LoopComm(world=1)
    tree = {"a": np.arange(50_000, dtype=np.float32)}
    one = xfer.push_value(comm, [0], "t", tree)
    assert one["applies"] == {0: 1}
    # drift #1: the user rebinds the variable to something else
    comm.ns[0]["t"] = {"a": comm.ns[0]["t"]["a"] * 2.0}
    two = xfer.push_value(comm, [0], "t", tree)
    assert two["xid"] == one["xid"]
    assert two["already_done"] == [] and two["applies"] == {0: 1}
    np.testing.assert_array_equal(comm.ns[0]["t"]["a"], tree["a"])
    # untouched binding: the memo answers and nothing moves
    wire_before = len(comm.chunk_log)
    three = xfer.push_value(comm, [0], "t", tree)
    assert three["already_done"] == [0]
    assert len(comm.chunk_log) == wire_before
    # drift #2: deletion also drops the memo
    del comm.ns[0]["t"]
    four = xfer.push_value(comm, [0], "t", tree)
    assert four["already_done"] == [] and four["applies"] == {0: 1}
    assert comm.endpoints[0].counters["applies"] == 3


def test_push_resume_only_missing_chunks(small_chunks):
    comm = LoopComm(world=1)
    tree = {"a": np.arange(120_000, dtype=np.float32)}
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    meta, bufs = flatten_pytree_wire(tree)
    src = xfer.ChunkSource(bufs)
    csize = xfer.chunk_bytes()
    n = src.n_chunks(csize)
    assert n >= 4
    crcs = src.crcs(csize)
    xid = xfer.transfer_id("var", "t", src.total, csize, crcs)
    # A "previous coordinator" that died after delivering the first
    # half: begin + chunks [0, n//2), then nothing.
    comm.send_to_ranks([0], "xfer_begin",
                       {"xid": xid, "kind": "var", "name": "t",
                        "dest": None, "total": src.total,
                        "chunk_bytes": csize, "n_chunks": n,
                        "meta": meta, "descs": src.descs})
    for seq in range(n // 2):
        comm.submit([0], "xfer_chunk", None,
                    bufs={"c": src.read(seq, csize)},
                    xfer={"x": xid, "s": seq, "c": crcs[seq],
                          "e": "stored",
                          "r": len(src.read(seq, csize))})
    comm.chunk_log.clear()
    # The fresh coordinator pushes the same value: content-addressed
    # xid → the receiver's bitmap names the tail, and ONLY the tail
    # moves.
    stats = xfer.push_flat(comm, [0], "var", "t", meta, bufs)
    assert stats["xid"] == xid
    assert stats["resumed_chunks"] == n // 2
    assert sorted(s for _, s in comm.chunk_log) == list(range(n // 2,
                                                              n))
    assert comm.endpoints[0].counters["applies"] == 1
    tree_equal(comm.ns[0]["t"], tree)


def test_push_corrupted_chunk_refused_and_resent(small_chunks):
    comm = LoopComm(world=1)
    comm.corrupt_once.add((0, 1))   # chunk 1 arrives bit-flipped once
    tree = {"a": np.arange(100_000, dtype=np.float32)}
    stats = xfer.push_value(comm, [0], "t", tree)
    assert stats["resent_chunks"] == 1
    assert comm.endpoints[0].counters["crc_rejects"] == 1
    assert comm.endpoints[0].counters["applies"] == 1
    tree_equal(comm.ns[0]["t"], tree)


def test_push_duplicate_chunk_is_idempotent(small_chunks):
    comm = LoopComm(world=1)
    tree = {"a": np.arange(60_000, dtype=np.float32)}
    xfer.push_value(comm, [0], "t", tree)
    ep = comm.endpoints[0]
    assert ep.counters["dup_chunks"] == 0
    # Replay one delivered chunk under a fresh msg_id post-commit:
    # the completed memo answers it without touching state.
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    meta, bufs = flatten_pytree_wire(tree)
    src = xfer.ChunkSource(bufs)
    csize = xfer.chunk_bytes()
    crcs = src.crcs(csize)
    xid = xfer.transfer_id("var", "t", src.total, csize, crcs)
    h = comm.submit([0], "xfer_chunk", None,
                    bufs={"c": src.read(0, csize)},
                    xfer={"x": xid, "s": 0, "c": crcs[0],
                          "e": "stored", "r": len(src.read(0, csize))})
    assert h.wait()[0].data.get("done") is True
    assert ep.counters["applies"] == 1


def test_push_fallback_for_non_wire_values():
    comm = LoopComm(world=1)
    with pytest.raises(xfer.XferFallback):
        xfer.push_value(comm, [0], "t", {"fn": lambda: 1})
    with pytest.raises(xfer.XferFallback):
        xfer.push_value(comm, [0], "t", 42)   # no array leaves


def test_pull_inline_small_and_readonly(small_chunks, monkeypatch):
    monkeypatch.setenv("NBD_XFER_THRESHOLD_BYTES", str(1 << 20))
    comm = LoopComm(world=1)
    comm.ns[0]["v"] = {"a": np.arange(100, dtype=np.float32)}
    ro, stats = xfer.pull_value(comm, 0, "v", readonly=True)
    assert stats["inline"] and stats["chunks"] == 0
    assert not ro["a"].flags.writeable      # decode view, zero-copy
    rw, _ = xfer.pull_value(comm, 0, "v")
    assert rw["a"].flags.writeable
    rw["a"][0] = -1                         # mutable like any value
    np.testing.assert_array_equal(ro["a"][1:], rw["a"][1:])


def test_pull_chunked_large_bit_identical(small_chunks):
    comm = LoopComm(world=1)
    tree = mixed_tree()
    comm.ns[0]["params"] = tree
    got, stats = xfer.pull_value(comm, 0, "params")
    assert stats["chunks"] > 1 and not stats["inline"]
    assert stats["resent_chunks"] == 0
    tree_equal(got, tree)
    # pull_end freed the outbound snapshot
    assert len(comm.endpoints[0].outbound) == 0
    assert stats["inflight_peak_bytes"] <= 4 * 65536
    # default binding is writable; --readonly freezes the destination
    # arrays even on the chunked path (no decode views exist there)
    assert got["w"].flags.writeable
    ro, rstats = xfer.pull_value(comm, 0, "params", readonly=True)
    assert not rstats["inline"] and rstats["readonly"]
    assert not ro["w"].flags.writeable
    tree_equal(ro, tree)


def test_pull_fallback_and_unknown_name(small_chunks):
    comm = LoopComm(world=1)
    comm.ns[0]["n"] = 7
    with pytest.raises(xfer.XferFallback):
        xfer.pull_value(comm, 0, "n")
    with pytest.raises(xfer.XferError):
        xfer.pull_value(comm, 0, "nope")


def test_push_file_pull_file_roundtrip(small_chunks, tmp_path):
    comm = LoopComm(world=1)
    src = tmp_path / "arrays.npz"
    blob = np.random.default_rng(1).integers(
        0, 256, 200_000, dtype=np.uint8).tobytes()
    src.write_bytes(blob)
    dest = tmp_path / "remote" / "arrays.npz"
    stats = xfer.push_file(comm, [0], str(src), str(dest))
    assert stats["bytes"] == len(blob) and stats["chunks"] > 1
    assert dest.read_bytes() == blob
    back = tmp_path / "back.npz"
    stats = xfer.pull_file(comm, 0, str(dest), str(back))
    assert back.read_bytes() == blob
    with pytest.raises(xfer.XferError):
        xfer.pull_file(comm, 0, str(tmp_path / "ghost"), str(back))


def test_inbound_eviction_cap(small_chunks, monkeypatch):
    monkeypatch.setenv("NBD_XFER_INBOUND_MAX", "2")
    comm = LoopComm(world=1)
    for i in range(3):
        comm.send_to_ranks(
            [0], "xfer_begin",
            {"xid": f"x{i:016d}", "kind": "var", "name": f"v{i}",
             "dest": None, "total": 4, "chunk_bytes": 65536,
             "n_chunks": 1,
             "meta": {"k": "leaf", "buf": "a", "jax": False},
             "descs": [{"b": "a", "dtype": "float32",
                        "shape": [1], "len": 4}]})
    ep = comm.endpoints[0]
    assert len(ep.inbound) == 2
    assert ep.counters["evicted"] == 1
    st = ep.status()
    assert st["begins"] == 3 and st["inbound"] == 2


def test_retry_classifies_xfer_as_bulk():
    from nbdistributed_tpu.resilience.retry import BULK_TYPES, class_of
    for t in xfer.XFER_TYPES:
        assert t in BULK_TYPES and class_of(t) == "bulk"


# ----------------------------------------------------------------------
# mailbox spill (bounded-memory delivery)


def big_reply(nbytes: int) -> Message:
    return Message(msg_type="response", data={"status": "ok"},
                   bufs={"value": np.zeros(nbytes, dtype=np.uint8)})


def test_mailbox_spills_oversized_reply_to_disk(tmp_path):
    from nbdistributed_tpu.resilience.dedup import ResultMailbox
    box = ResultMailbox(spill_dir=str(tmp_path / "spill"),
                        spill_entry_bytes=64 << 10)
    box.park("m1", big_reply(1 << 20))
    assert box.counters()["spilled"] == 1
    files = os.listdir(tmp_path / "spill")
    assert len(files) == 1
    # The in-memory bound holds: the parked entry is a stub, so total
    # accounted bytes stay far below the payload.
    assert box._total < 64 << 10
    got = box.claim("m1")
    assert got.data == {"status": "ok"}
    assert bytes(got.bufs["value"]) == bytes(1 << 20)
    assert os.listdir(tmp_path / "spill") == []   # claimed = deleted
    assert box.claim("m1") is None                # exactly once


def test_mailbox_peek_all_keeps_spilled_entries(tmp_path):
    from nbdistributed_tpu.resilience.dedup import ResultMailbox
    box = ResultMailbox(spill_dir=str(tmp_path / "s"),
                        spill_entry_bytes=1024)
    box.park("m1", big_reply(64 << 10))
    peeked = box.peek_all()
    assert bytes(peeked["m1"].bufs["value"]) == bytes(64 << 10)
    assert len(os.listdir(tmp_path / "s")) == 1   # still on disk
    assert box.claim("m1") is not None


def test_mailbox_too_large_verdict(tmp_path):
    from nbdistributed_tpu.resilience.dedup import ResultMailbox
    box = ResultMailbox(spill_dir=str(tmp_path / "s"),
                        spill_entry_bytes=1024,
                        max_spill_bytes=16 << 10)
    box.park("m1", big_reply(64 << 10))
    got = box.claim("m1")
    assert got.data["verdict"] == "too_large"
    assert "parked reply unavailable" in got.data["error"]
    assert got.data["orig_type"] == "response"
    assert box.counters()["spill_verdicts"] == 1


def test_mailbox_disk_full_verdict():
    from nbdistributed_tpu.resilience.dedup import ResultMailbox
    box = ResultMailbox(spill_dir="/proc/nope/definitely-unwritable",
                        spill_entry_bytes=1024)
    box.park("m1", big_reply(64 << 10))
    got = box.claim("m1")
    assert got.data["verdict"] == "disk_full"
    assert box.counters()["spill_verdicts"] == 1


# ----------------------------------------------------------------------
# chunk-level fault injection


def test_fault_plan_xfer_spec_roundtrip():
    from nbdistributed_tpu.resilience.faults import FaultPlan
    plan = FaultPlan(seed=9, xfer_drop=0.25, xfer_corrupt=0.1)
    spec = plan.spec()
    assert spec["xfer_drop"] == 0.25 and spec["xfer_corrupt"] == 0.1
    again = FaultPlan.from_spec(spec)
    assert again.spec() == spec


def test_fault_plan_drops_only_bulk_frames():
    from nbdistributed_tpu.resilience.faults import FaultPlan
    plan = FaultPlan(seed=1234, xfer_drop=0.3)
    chunk = b"z" * (128 << 10)
    sent: list = []
    n = 60
    for _ in range(n):
        plan.transmit(chunk, sent.append, kind="xfer_chunk")
    dropped = plan.counters["xfer_dropped"]
    assert 0 < dropped < n and len(sent) == n - dropped
    # Control frames are exempt from the chunk-fault stream entirely.
    small_sent: list = []
    for _ in range(n):
        plan.transmit(b"ok", small_sent.append, kind="xfer_begin")
    assert len(small_sent) == n
    # Determinism: an identical plan replays the identical decisions.
    replay = FaultPlan(seed=1234, xfer_drop=0.3)
    replay_sent: list = []
    for _ in range(n):
        replay.transmit(chunk, replay_sent.append, kind="xfer_chunk")
    assert replay.counters["xfer_dropped"] == dropped


def test_fault_plan_corruption_hits_payload_not_header():
    from nbdistributed_tpu.resilience.faults import FaultPlan
    plan = FaultPlan(seed=77, xfer_corrupt=1.0)
    frame = bytes(range(256)) * 1024           # 256 KiB
    out: list = []
    plan.transmit(frame, out.append, kind="xfer_chunk")
    assert plan.counters["xfer_corrupted"] == 1
    got = out[0]
    assert len(got) == len(frame)              # length-preserving
    assert got != frame
    half = len(frame) // 2
    assert got[:half] == frame[:half]          # JSON header half intact
    diff = [i for i in range(half, len(frame)) if got[i] != frame[i]]
    assert len(diff) == 1                      # exactly one flipped bit
    assert bin(got[diff[0]] ^ frame[diff[0]]).count("1") == 1
