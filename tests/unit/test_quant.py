"""Int8 weight-only quantization: reconstruction fidelity, forward
agreement, KV-cache generation, and tensor-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

from nbdistributed_tpu.models import (dequantize_weight, forward,
                                      generate, init_params,
                                      is_quantized, param_shardings,
                                      quantization_error,
                                      quantize_params, quantize_weight,
                                      quantized_shardings, tiny_config)
from nbdistributed_tpu.parallel.mesh import make_mesh

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    return cfg, params, tokens


def test_quantize_roundtrip_error_bounded():
    """Per-channel symmetric int8: reconstruction error <= s/2 per
    element, i.e. <= max|col| / 254."""
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 32)) * 3.0
    qw = quantize_weight(w)
    assert qw["q8"].dtype == jnp.int8
    back = dequantize_weight(qw)
    bound = np.max(np.abs(np.asarray(w)), axis=0, keepdims=True) / 254.0
    assert np.all(np.abs(np.asarray(back - w)) <= bound + 1e-7)


def test_scale_commutes_with_matmul():
    """x @ dequant(W) == (x @ q8) * s — the identity the fast path
    relies on."""
    w = jax.random.normal(jax.random.PRNGKey(3), (32, 16))
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 32))
    qw = quantize_weight(w)
    ref = x @ dequantize_weight(qw)
    fast = (x @ qw["q8"].astype(x.dtype)) * qw["s"][0]
    np.testing.assert_allclose(np.asarray(fast), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_quantized_forward_close_to_fp(setup):
    cfg, params, tokens = setup
    qparams = quantize_params(params)
    ref = np.asarray(forward(params, tokens, cfg))
    got = np.asarray(forward(qparams, tokens, cfg))
    # Weight-only int8 shifts logits slightly; the distribution must
    # stay essentially the same: tight normalized error + top-1
    # agreement on nearly all positions.
    nmse = float(np.mean((got - ref) ** 2) / np.mean(ref ** 2))
    assert nmse < 1e-3, nmse
    top1_match = np.mean(got.argmax(-1) == ref.argmax(-1))
    assert top1_match > 0.9, top1_match
    errs = quantization_error(params, qparams)
    assert set(errs) == {"wq", "wk", "wv", "wo", "w_gate", "w_up",
                         "w_down", "lm_head"}
    assert all(e < 0.02 for e in errs.values()), errs


def test_quantized_generation_runs_and_matches_its_forward(setup):
    """The KV-cache decode loop accepts quantized params and is
    consistent with the quantized full re-forward (same argmax chain)."""
    cfg, params, tokens = setup
    qparams = quantize_params(params)
    prompt = tokens[:, :5]
    got = generate(qparams, prompt, cfg, max_new_tokens=8)
    # Reference: greedy re-forward decoding with the same qparams.
    toks = prompt
    for _ in range(8):
        logits = forward(qparams, toks, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(toks))


def test_quantized_tensor_parallel_matches_unsharded(setup):
    cfg, params, tokens = setup
    qparams = quantize_params(params)
    ref = np.asarray(forward(qparams, tokens, cfg))
    mesh = make_mesh({"dp": 2, "tp": 4})
    rules = quantized_shardings(param_shardings(cfg))
    from jax.sharding import PartitionSpec as P
    q_s = jax.device_put(qparams, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), rules,
        is_leaf=lambda x: isinstance(x, P)))
    got = np.asarray(jax.jit(lambda p, t: forward(p, t, cfg))(q_s,
                                                              tokens))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_quantize_params_validates_targets(setup):
    cfg, params, _ = setup
    with pytest.raises(ValueError, match="unknown quantization target"):
        quantize_params(params, targets=("nope",))


def test_memory_halved(setup):
    cfg, params, _ = setup
    qparams = quantize_params(params)

    def nbytes(t):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(t))

    # Layer weights went fp32 -> int8 (+small scales): big shrink even
    # with embed/norms left fp.
    assert nbytes(qparams) < 0.45 * nbytes(params)


def test_quantized_moe_forward_and_ep_mesh():
    """MoE family: expert + attention weights int8, forward close to fp,
    and exact across an ep mesh vs the same quantized model unsharded."""
    from nbdistributed_tpu.models import (init_moe_model, moe_forward,
                                          moe_model_shardings,
                                          quantize_moe_params,
                                          quantized_moe_shardings,
                                          tiny_moe_config)

    mcfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    mp = init_moe_model(jax.random.PRNGKey(0), mcfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                mcfg.vocab_size)
    ref, _ = moe_forward(mp, tokens, mcfg)
    qp = quantize_moe_params(mp)
    got, _ = moe_forward(qp, tokens, mcfg)
    # Routing can flip for borderline tokens under weight quantization
    # (different experts -> genuinely different outputs for those few
    # tokens), so the MoE bound is looser than the dense family's.
    nmse = float(np.mean((np.asarray(got) - np.asarray(ref)) ** 2)
                 / np.mean(np.asarray(ref) ** 2))
    assert nmse < 1e-2, nmse

    mesh = make_mesh({"dp": 2, "ep": 4})
    rules = quantized_moe_shardings(
        moe_model_shardings(mcfg, tp_axis=None))
    from jax.sharding import PartitionSpec as P
    qp_s = jax.device_put(qp, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), rules,
        is_leaf=lambda x: isinstance(x, P)))
    got_s, _ = jax.jit(
        lambda p, t: moe_forward(p, t, mcfg, mesh=mesh))(qp_s, tokens)
    np.testing.assert_allclose(np.asarray(got_s), np.asarray(got),
                               atol=2e-4, rtol=2e-4)


# ------------------------------------------------------------------ int4
# Nibble-packed int4 with grouped scales (quant.py quantize_weight4).

def test_int4_pack_roundtrip_exact():
    """Every representable value survives pack -> unpack bit-exactly."""
    from nbdistributed_tpu.models.quant import (_pack_nibbles,
                                                _unpack_nibbles)
    q = jnp.arange(-7, 8, dtype=jnp.int32)
    q = jnp.tile(q, 4).reshape(12, 5)          # even rows, odd cols
    packed = _pack_nibbles(q)
    assert packed.dtype == jnp.uint8
    assert packed.shape == (6, 5)
    back = _unpack_nibbles(packed, jnp.int32)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(q))


def test_int4_roundtrip_error_bounded():
    """Grouped symmetric int4: error <= s/2 per element, i.e.
    <= group-max / 14."""
    from nbdistributed_tpu.models import (dequantize_weight4,
                                          quantize_weight4)
    w = jax.random.normal(jax.random.PRNGKey(5), (128, 32)) * 2.0
    qw = quantize_weight4(w, group=64)
    assert qw["q4"].shape == (64, 32)
    back = np.asarray(dequantize_weight4(qw))
    wg = np.asarray(w).reshape(2, 64, 32)
    bound = (np.abs(wg).max(axis=1, keepdims=True) / 14.0 + 1e-6)
    err = np.abs(back.reshape(2, 64, 32) - wg)
    assert np.all(err <= bound)


def test_int4_qlinear_matches_dequantized_matmul():
    """qlinear's grouped-einsum int4 path == x @ dequant(W4) up to
    fp32 reassociation."""
    from nbdistributed_tpu.models import dequantize_weight4, quantize_weight4
    from nbdistributed_tpu.models.transformer import qlinear
    w = jax.random.normal(jax.random.PRNGKey(6), (128, 48))
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 128))
    qw = quantize_weight4(w, group=64)
    ref = x @ dequantize_weight4(qw)
    got = qlinear(x, qw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_int4_forward_matches_dequantized_params(setup):
    """The whole model with int4 leaves == the same model with those
    leaves dequantized back to fp — isolates the packed-compute path
    from the quantization error itself."""
    from nbdistributed_tpu.models import (dequantize_weight4,
                                          is_quantized4,
                                          quantize_params4)
    cfg, params, tokens = setup
    q4 = quantize_params4(params)
    deq = jax.tree_util.tree_map(
        lambda leaf: (dequantize_weight4(leaf, cfg.dtype)
                      if is_quantized4(leaf) else leaf),
        q4, is_leaf=is_quantized4)
    ref = np.asarray(forward(deq, tokens, cfg))
    got = np.asarray(forward(q4, tokens, cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_int4_generation_runs(setup):
    from nbdistributed_tpu.models import quantize_params4
    cfg, params, _ = setup
    q4 = quantize_params4(params)
    prompt = jnp.ones((1, 4), jnp.int32)
    toks = generate(q4, prompt, cfg, max_new_tokens=6)
    assert toks.shape == (1, 10)


def test_int4_memory_below_half_of_int8(setup):
    """Packed uint8 bytes = half the int8 weight bytes; group scales
    add ~6%: the int4 tree must land well under int8's."""
    from nbdistributed_tpu.models import quantize_params4
    cfg, params, _ = setup

    def nbytes(t):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(t))

    b8 = nbytes(quantize_params(params))
    b4 = nbytes(quantize_params4(params))
    assert b4 < 0.75 * b8


def test_int4_shardings_structure_matches(setup):
    """quantized_shardings4 must mirror quantize_params4's pytree so
    device_put(tree_map(...)) works — a structure mismatch dies far
    from the mistake."""
    from nbdistributed_tpu.models import (quantize_params4,
                                          quantized_shardings4)
    cfg, params, _ = setup
    q4 = quantize_params4(params)
    rules = quantized_shardings4(param_shardings(cfg))
    jax.tree_util.tree_map(lambda a, b: None, q4, rules)  # must not raise


def test_int4_tensor_parallel_places_and_matches(setup):
    """quantized_shardings4 must PLACE on a real tp mesh (the grouped
    scales replicate over the contraction shard — G=2 here and 9 at
    smol scale need not divide tp) and the sharded forward must match
    the unsharded int4 forward."""
    from jax.sharding import PartitionSpec as P

    from nbdistributed_tpu.models import (quantize_params4,
                                          quantized_shardings4)
    cfg, params, tokens = setup
    q4 = quantize_params4(params)
    ref = np.asarray(forward(q4, tokens, cfg))
    mesh = make_mesh({"dp": 2, "tp": 4})
    rules = quantized_shardings4(param_shardings(cfg))
    q_s = jax.device_put(q4, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), rules,
        is_leaf=lambda x: isinstance(x, P)))
    got = np.asarray(jax.jit(lambda p, t: forward(p, t, cfg))(q_s,
                                                              tokens))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_int4_quantization_error_reports_all_targets(setup):
    from nbdistributed_tpu.models import (quantization_error,
                                          quantize_params4)
    cfg, params, _ = setup
    rep = quantization_error(params, quantize_params4(params))
    assert set(rep) >= {"wq", "wo", "w_gate", "lm_head"}
    # int4 group-64 lands in the few-percent band: real numbers, not
    # zeros, and better than 15 % everywhere at this scale.
    assert all(0.0 < v < 0.15 for v in rep.values())
