"""Unit tests for the TCP transport (coordinator listener + worker channel)."""

import threading
import time

import pytest

from nbdistributed_tpu.messaging.codec import Message
from nbdistributed_tpu.messaging.transport import (
    CoordinatorListener, TransportError, WorkerChannel)


@pytest.fixture
def listener():
    lst = CoordinatorListener()
    received = []
    connected = []
    disconnected = []
    lst.on_message = lambda r, m: received.append((r, m))
    lst.on_connect = connected.append
    lst.on_disconnect = disconnected.append
    lst.start()
    lst.received, lst.connected, lst.disconnected = (
        received, connected, disconnected)
    yield lst
    lst.close()


def wait_until(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


def test_hello_identifies_rank(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=7)
    assert wait_until(lambda: listener.connected == [7])
    assert listener.connected_ranks() == [7]
    ch.close()
    assert wait_until(lambda: listener.disconnected == [7])


def test_bidirectional_messages(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: 0 in listener.connected)
    ch.send(Message(msg_type="response", data={"out": "hi"}, rank=0))
    assert wait_until(lambda: len(listener.received) == 1)
    rank, msg = listener.received[0]
    assert rank == 0 and msg.data == {"out": "hi"}

    listener.send_to_rank(0, Message(msg_type="execute", data="1+1"))
    got = ch.recv(timeout=5)
    assert got.msg_type == "execute" and got.data == "1+1"
    ch.close()


def test_close_wakes_a_blocked_untimed_recv(listener):
    """ISSUE 15 lifecycle fix: close() must shutdown() the socket
    before closing the fd — closing an fd alone never wakes a thread
    blocked in an untimed recv() (the TenantClient reader-thread hang
    the live verify caught), while SHUT_RDWR delivers EOF at once."""
    ch = WorkerChannel("127.0.0.1", listener.port, rank=5)
    assert wait_until(lambda: listener.connected == [5])
    woke = threading.Event()

    def _reader():
        try:
            ch.recv()          # untimed: blocks in sock.recv
        except TransportError:
            woke.set()

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    time.sleep(0.2)            # let it reach the blocking recv
    ch.close()
    assert woke.wait(2.0), "blocked recv never woke after close()"
    t.join(timeout=2.0)
    assert not t.is_alive()


def test_send_to_unknown_rank_raises(listener):
    with pytest.raises(TransportError):
        listener.send_to_rank(99, Message(msg_type="x"))


def test_multiple_workers_routing(listener):
    chans = [WorkerChannel("127.0.0.1", listener.port, rank=r)
             for r in range(4)]
    assert wait_until(lambda: len(listener.connected) == 4)
    listener.send_to_ranks([1, 3], Message(msg_type="go"))
    assert chans[1].recv(timeout=5).msg_type == "go"
    assert chans[3].recv(timeout=5).msg_type == "go"
    # ranks 0 and 2 got nothing
    with pytest.raises(TimeoutError):
        chans[0].recv(timeout=0.2)
    for c in chans:
        c.close()


def test_large_frame(listener):
    import numpy as np
    ch = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: 0 in listener.connected)
    big = np.random.default_rng(0).standard_normal((512, 512)).astype("float32")
    ch.send(Message(msg_type="response", rank=0, bufs={"t": big}))
    assert wait_until(lambda: len(listener.received) == 1)
    _, msg = listener.received[0]
    np.testing.assert_array_equal(msg.bufs["t"], big)
    ch.close()


def test_concurrent_sends_no_interleave(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: 0 in listener.connected)
    n_threads, per = 8, 25
    def blast(tid):
        for i in range(per):
            ch.send(Message(msg_type="response", rank=0,
                            data={"tid": tid, "i": i}))
    threads = [threading.Thread(target=blast, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert wait_until(lambda: len(listener.received) == n_threads * per)
    seen = {(m.data["tid"], m.data["i"]) for _, m in listener.received}
    assert len(seen) == n_threads * per
    ch.close()


from nbdistributed_tpu.messaging import native as _native_mod

_AUTH_IMPLS = (["python", "native"] if _native_mod.available()
               else ["python"])


@pytest.fixture(params=_AUTH_IMPLS)
def auth_impl(request):
    return request.param


class TestAuthToken:
    """Shared-secret handshake for non-loopback binds: the control
    plane executes code, so nothing may reach dispatch — least of all
    the pickle decoder — before the preamble digest is verified.  Both
    listener implementations must enforce it identically."""

    def _listener(self, token, impl="python"):
        if impl == "native":
            lis = _native_mod.NativeCoordinatorListener(
                "127.0.0.1", 0, auth_token=token)
        else:
            from nbdistributed_tpu.messaging.transport import (
                CoordinatorListener)
            lis = CoordinatorListener("127.0.0.1", 0, auth_token=token)
        connected, messages = [], []
        lis.on_connect = connected.append
        lis.on_message = lambda r, m: messages.append((r, m))
        lis.start()
        return lis, connected, messages

    def test_correct_token_attaches_and_routes(self, auth_impl):
        from nbdistributed_tpu.messaging.transport import (Message,
                                                           WorkerChannel)
        lis, connected, messages = self._listener("sekrit", auth_impl)
        try:
            ch = WorkerChannel("127.0.0.1", lis.port, rank=0,
                               auth_token="sekrit")
            ch.send(Message(msg_type="hello", data={"x": 1}, rank=0))
            deadline = time.time() + 5
            while time.time() < deadline and not messages:
                time.sleep(0.01)
            assert connected == [0]
            assert messages and messages[0][1].msg_type == "hello"
            ch.close()
        finally:
            lis.close()

    @pytest.mark.parametrize("token", [None, "wrong"])
    def test_missing_or_wrong_token_never_attaches(self, token,
                                                    auth_impl):
        import socket as socket_mod

        from nbdistributed_tpu.messaging.transport import (Message,
                                                           WorkerChannel)
        lis, connected, messages = self._listener("sekrit", auth_impl)
        try:
            try:
                ch = WorkerChannel("127.0.0.1", lis.port, rank=0,
                                   auth_token=token)
                ch.send(Message(msg_type="execute", data="1+1", rank=0))
            except (OSError, socket_mod.error):
                pass  # coordinator may close the socket mid-send
            time.sleep(0.5)
            assert connected == []
            assert messages == []
        finally:
            lis.close()

    def test_pickle_never_deserialized_before_auth(self, tmp_path,
                                                    auth_impl):
        """A malicious peer sends a pickle-encoded frame as its first
        message; the payload's __reduce__ would create a file.  The
        pre-auth decode path must refuse pickle entirely."""
        import socket as socket_mod
        import struct

        from nbdistributed_tpu.messaging.transport import make_preamble

        marker = tmp_path / "pwned"

        class Evil:
            def __reduce__(self):
                return (open, (str(marker), "w"))

        import pickle

        evil = pickle.dumps(Evil())
        header = {
            "id": "x" * 32, "type": "auth", "rank": 0, "ts": 0.0,
            "enc": "pickle",
            "bufs": [{"name": "__pickle__", "kind": "bytes",
                      "dtype": "", "shape": [], "len": len(evil)}],
        }
        import json as json_mod
        hb = json_mod.dumps(header).encode()
        frame = (struct.pack("<4sIQ", b"NBD1", len(hb), len(evil))
                 + hb + evil)

        lis, connected, messages = self._listener("sekrit", auth_impl)
        try:
            s = socket_mod.create_connection(("127.0.0.1", lis.port),
                                             timeout=5)
            s.sendall(make_preamble(0) + frame)
            time.sleep(0.5)
            assert not marker.exists(), "pickle ran before auth!"
            assert connected == [] and messages == []
            s.close()
        finally:
            lis.close()


class TestAuthNonLoopback:
    """The NBDA preamble exercised the way multihost actually uses it:
    a NON-loopback-address bind (distinct 127.0.1.x addresses — the
    shared-filesystem/loopback assumptions off, no root needed), with
    both the accept and the wrong-secret reject paths (ISSUE 6
    satellite: until now auth was only ever tested on 127.0.0.1)."""

    BIND = "127.0.1.21"

    def _bindable(self):
        import socket as socket_mod
        try:
            s = socket_mod.socket()
            s.bind((self.BIND, 0))
            s.close()
            return True
        except OSError:
            return False

    def test_auth_accepts_and_rejects_on_non_loopback_bind(self):
        from nbdistributed_tpu.messaging.transport import (
            CoordinatorListener, Message, WorkerChannel)
        if not self._bindable():
            pytest.skip(f"cannot bind {self.BIND} on this host")
        lis = CoordinatorListener(self.BIND, 0, auth_token="sekrit")
        connected, messages = [], []
        lis.on_connect = connected.append
        lis.on_message = lambda r, m: messages.append((r, m))
        lis.start()
        try:
            assert lis.host == self.BIND
            # Wrong secret first: dropped before any frame decodes.
            try:
                bad = WorkerChannel(self.BIND, lis.port, rank=0,
                                    auth_token="not-the-secret")
                bad.send(Message(msg_type="execute", data="1", rank=0))
            except OSError:
                pass
            time.sleep(0.4)
            assert connected == [] and messages == []
            # Right secret: attaches and routes across the
            # non-loopback address.
            ch = WorkerChannel(self.BIND, lis.port, rank=3,
                               auth_token="sekrit")
            ch.send(Message(msg_type="hello", data={"ok": 1}, rank=3))
            deadline = time.time() + 5
            while time.time() < deadline and not messages:
                time.sleep(0.01)
            assert connected == [3]
            assert messages and messages[0][0] == 3
            ch.close()
        finally:
            lis.close()
