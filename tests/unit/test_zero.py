"""ZeRO-1 optimizer-state sharding: numerics match unsharded training,
and the state really is dp-sharded."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from nbdistributed_tpu.models import (init_params, loss_fn,
                                      param_shardings, tiny_config)
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel import tensor_parallel
from nbdistributed_tpu.parallel.zero import (_add_dp,
                                             make_zero1_train_step,
                                             zero1_state_shardings)

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def test_add_dp_first_free_divisible_axis():
    assert _add_dp(P(), (8, 6), "dp", 4) == P("dp", None)
    assert _add_dp(P(), (6, 8), "dp", 4) == P(None, "dp")
    assert _add_dp(P(None, "tp"), (8, 16), "dp", 4) == P("dp", "tp")
    assert _add_dp(P("tp"), (8, 16), "dp", 4) == P("tp", "dp")
    assert _add_dp(P(), (3, 5), "dp", 4) == P(None, None)  # replicated
    assert _add_dp(P(), (), "dp", 4) == P()                # scalar


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)
    return cfg, params, opt, {"tokens": tokens}


def test_zero1_matches_unsharded_training(setup):
    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    rules = jax.tree_util.tree_map(
        lambda spec: P(*[None for _ in spec]), param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, P))
    loss = lambda p, b: loss_fn(p, b, cfg)

    step, init = make_zero1_train_step(loss, opt, mesh, rules, params,
                                       donate=False)
    p_sharded = tensor_parallel.apply_shardings(params, mesh, rules)
    s = init(p_sharded)
    b = mesh_mod.shard_batch(dict(batch), mesh)

    ref_p, ref_s = params, opt.init(params)
    for _ in range(3):
        p_sharded, s, l = step(p_sharded, s, b)
        rl, rg = jax.value_and_grad(loss)(ref_p, batch)
        ru, ref_s = opt.update(rg, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, ru)
        np.testing.assert_allclose(float(l), float(rl), rtol=1e-5)
    # ZeRO-1 reduces grads via reduce_scatter (per-shard partial sums)
    # vs the reference's single full all-reduce: fp32 summation order
    # differs, and 3 adamw steps compound it (observed drift ~4e-5).
    for a, b_ in zip(jax.tree_util.tree_leaves(p_sharded),
                     jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4, rtol=1e-4)


def test_zero1_state_is_dp_sharded(setup):
    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    rules = jax.tree_util.tree_map(
        lambda spec: P(*[None for _ in spec]), param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, P))
    _, init = make_zero1_train_step(
        lambda p, b: loss_fn(p, b, cfg), opt, mesh, rules, params,
        donate=False)
    s = init(tensor_parallel.apply_shardings(params, mesh, rules))
    specs = {str(sh.spec) for sh in
             (leaf.sharding for leaf in jax.tree_util.tree_leaves(s)
              if hasattr(leaf, "sharding"))}
    assert any("dp" in sp for sp in specs), specs


def test_zero1_pure_ddp_rules_none(setup):
    """param_rules=None (the canonical ZeRO-1 use: pure data parallel,
    replicated params) must work like make_tp_train_step's None."""
    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    step, init = make_zero1_train_step(
        lambda p, b: loss_fn(p, b, cfg), opt, mesh, None, params,
        donate=False)
    p = jax.device_put(params, jax.sharding.NamedSharding(mesh, P()))
    s = init(p)
    b = mesh_mod.shard_batch(dict(batch), mesh)
    p, s, l = step(p, s, b)
    assert np.isfinite(float(l))
    assert any("dp" in str(leaf.sharding.spec)
               for leaf in jax.tree_util.tree_leaves(s)
               if hasattr(leaf, "sharding"))


def test_zero1_composes_with_tp(setup):
    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2},
                              devices=jax.devices()[:4])
    rules = param_shardings(cfg)
    loss = lambda p, b: loss_fn(p, b, cfg)
    step, init = make_zero1_train_step(loss, opt, mesh, rules, params,
                                       donate=False)
    p = tensor_parallel.apply_shardings(params, mesh, rules)
    s = init(p)
    b = mesh_mod.shard_batch(dict(batch), mesh)
    p, s, l = step(p, s, b)
    assert np.isfinite(float(l))
    # moments for a tp-sharded param carry BOTH axes
    mu_specs = {str(leaf.sharding.spec)
                for leaf in jax.tree_util.tree_leaves(s)
                if hasattr(leaf, "sharding") and leaf.ndim >= 2}
    assert any("dp" in sp and "tp" in sp for sp in mu_specs), mu_specs


def test_zero2_accum_matches_plain_accumulation(setup):
    """ZeRO-2 = ZeRO-1 + a dp-sharded fp32 gradient accumulator:
    numerics must match the unsharded-accumulator accumulation step,
    and the compiled program must actually pin the accumulator (a
    sharding constraint appears in the jaxpr)."""
    from nbdistributed_tpu.parallel.zero import (make_zero2_train_step,
                                                 zero2_accum_rules)

    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    rules = jax.tree_util.tree_map(
        lambda spec: P(*[None for _ in spec]), param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, P))
    loss = lambda p, b: loss_fn(p, b, cfg)

    z2step, z2init = make_zero2_train_step(loss, opt, mesh, rules,
                                           params, accum_steps=2,
                                           donate=False)
    ref_step = tensor_parallel.make_tp_train_step(
        loss, opt, mesh, rules, donate=False, accum_steps=2)

    p2 = tensor_parallel.apply_shardings(params, mesh, rules)
    s2 = z2init(p2)
    pr = tensor_parallel.apply_shardings(params, mesh, rules)
    sr = opt.init(pr)
    b = mesh_mod.shard_batch(dict(batch), mesh)
    for _ in range(2):
        p2, s2, l2 = z2step(p2, s2, b)
        pr, sr, lr = ref_step(pr, sr, b)
        np.testing.assert_allclose(float(l2), float(lr), rtol=1e-5)
    for a, b_ in zip(jax.tree_util.tree_leaves(p2),
                     jax.tree_util.tree_leaves(pr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4, rtol=1e-4)

    # The accumulator rules place dp on a real axis for the big
    # weights, and the step's jaxpr carries sharding constraints
    # (the pin is in the program, not just intent).
    acc = zero2_accum_rules(params, rules, mesh)
    flat = jax.tree_util.tree_leaves(
        acc, is_leaf=lambda x: isinstance(x, P))
    assert any("dp" in tuple(s) for s in flat)
    jaxpr = str(jax.make_jaxpr(
        lambda p, s, bt: z2step(p, s, bt))(p2, s2, b))
    assert "sharding_constraint" in jaxpr


def test_zero2_accum1_is_zero1(setup):
    """accum_steps=1 has no accumulator: ZeRO-2 must degrade to
    exactly the ZeRO-1 step (same loss trajectory)."""
    from nbdistributed_tpu.parallel.zero import make_zero2_train_step

    cfg, params, opt, batch = setup
    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    rules = jax.tree_util.tree_map(
        lambda spec: P(*[None for _ in spec]), param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, P))
    loss = lambda p, b: loss_fn(p, b, cfg)
    b = mesh_mod.shard_batch(dict(batch), mesh)

    s2, i2 = make_zero2_train_step(loss, opt, mesh, rules, params,
                                   accum_steps=1, donate=False)
    s1, i1 = make_zero1_train_step(loss, opt, mesh, rules, params,
                                   donate=False)
    pa = tensor_parallel.apply_shardings(params, mesh, rules)
    pb = tensor_parallel.apply_shardings(params, mesh, rules)
    oa, ob = i2(pa), i1(pb)
    for _ in range(2):
        pa, oa, la = s2(pa, oa, b)
        pb, ob, lb = s1(pb, ob, b)
        assert float(la) == float(lb)

    import pytest as _pytest
    with _pytest.raises(ValueError, match="accum_steps"):
        make_zero2_train_step(loss, opt, mesh, rules, params,
                              accum_steps=0)
