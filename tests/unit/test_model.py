"""Transformer model tests on the 8-device virtual CPU mesh: forward
shapes/determinism, DDP equivalence, tensor-parallel equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from nbdistributed_tpu.models import (forward, init_params, loss_fn,
                                      make_train_step, param_shardings,
                                      tiny_config)
from nbdistributed_tpu.parallel import data_parallel, mesh as mesh_mod
from nbdistributed_tpu.parallel import tensor_parallel

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]

CFG = tiny_config(dtype=jnp.float32, use_flash=False)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def batch():
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                CFG.vocab_size)
    return {"tokens": tokens}


def test_forward_shape_and_dtype(params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_count_formula(params):
    actual = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(params))
    assert actual == CFG.num_params()


def test_causality(params):
    """Changing token t must not affect logits before t."""
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    l1 = forward(params, t1, CFG)
    l2 = forward(params, t2, CFG)
    np.testing.assert_allclose(np.asarray(l1[0, :10]),
                               np.asarray(l2[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_loss_decreases_under_training(params, batch):
    opt = optax.adam(1e-2)
    step = make_train_step(CFG, opt)
    p = params
    state = opt.init(p)
    jstep = jax.jit(step)
    first = None
    for _ in range(5):
        p, state, loss = jstep(p, state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_ddp_matches_single_device(params, batch):
    """DDP over 8 virtual devices must be numerically equivalent to
    single-device training (same global batch)."""
    opt = optax.sgd(1e-2)
    loss = lambda p, b: loss_fn(p, b, CFG)

    # single device
    def single_step(p, s, b):
        lval, g = jax.value_and_grad(loss)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, lval

    p1, s1, l1 = jax.jit(single_step)(params, opt.init(params), batch)

    # DDP over the mesh
    m = mesh_mod.make_mesh({"dp": 8})
    step = data_parallel.make_ddp_step(loss, opt, m, donate=False)
    p_r, s_r = data_parallel.ddp_init(params, opt.init(params), m)
    b_r = mesh_mod.shard_batch(batch, m)
    p2, s2, l2 = step(p_r, s_r, b_r)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_tensor_parallel_matches_replicated(params, batch):
    """tp=4 sharded forward must equal the unsharded forward — XLA
    inserts the Megatron all-reduces from the sharding rules."""
    m = mesh_mod.make_mesh({"dp": 2, "tp": 4})
    rules = param_shardings(CFG)
    p_sharded = tensor_parallel.apply_shardings(params, m, rules)
    tokens = batch["tokens"]

    ref = forward(params, tokens, CFG)
    out = jax.jit(lambda p, t: forward(p, t, CFG))(p_sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_tp_train_step_runs_and_learns(params, batch):
    m = mesh_mod.make_mesh({"dp": 2, "tp": 4})
    rules = param_shardings(CFG)
    opt = optax.adam(1e-2)
    loss = lambda p, b: loss_fn(p, b, CFG)
    step = tensor_parallel.make_tp_train_step(loss, opt, m, rules,
                                              donate=False)
    p = tensor_parallel.apply_shardings(params, m, rules)
    s = opt.init(p)
    b = mesh_mod.shard_batch(batch, m)
    losses = []
    for _ in range(3):
        p, s, lval = step(p, s, b)
        losses.append(float(lval))
    assert losses[-1] < losses[0]


def test_mesh_builder_wildcard():
    m = mesh_mod.make_mesh({"dp": -1, "tp": 2})
    assert m.shape == {"dp": 4, "tp": 2}


def test_mesh_builder_rejects_bad_shapes():
    with pytest.raises(ValueError):
        mesh_mod.make_mesh({"dp": 3})
    with pytest.raises(ValueError):
        mesh_mod.make_mesh({"dp": -1, "tp": -1})


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=2 must give the same update as the full batch (mean
    loss over equal microbatches == full-batch mean)."""
    import optax
    from jax.sharding import PartitionSpec as P
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    mesh = mesh_mod.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    rules = jax.tree_util.tree_map(
        lambda spec: P(*[None for _ in spec]), param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, P))
    loss = lambda p, b: loss_fn(p, b, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)

    outs = {}
    for accum in (1, 2, 4):
        step = tensor_parallel.make_tp_train_step(
            loss, opt, mesh, rules, donate=False, accum_steps=accum)
        p = tensor_parallel.apply_shardings(params, mesh, rules)
        s = opt.init(p)
        b = mesh_mod.shard_batch({"tokens": tokens}, mesh)
        p, s, l = step(p, s, b)
        outs[accum] = (p, float(l))
    for accum in (2, 4):
        np.testing.assert_allclose(outs[accum][1], outs[1][1], rtol=1e-6)
        # fp32 summation order differs (microbatch accumulation vs one
        # batched reduction) and compounds through the adamw update;
        # observed drift ~4e-5 after the full-S logits-shift loss, so
        # the bound is 1e-4.
        for a, b_ in zip(jax.tree_util.tree_leaves(outs[accum][0]),
                         jax.tree_util.tree_leaves(outs[1][0])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-4, rtol=1e-4)


def test_gradient_accumulation_rejects_indivisible():
    import optax
    import pytest
    from jax.sharding import PartitionSpec as P
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = mesh_mod.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    step = tensor_parallel.make_tp_train_step(
        lambda p, b: loss_fn(p, b, cfg), optax.sgd(1e-3), mesh, None,
        donate=False, accum_steps=3)
    p = jax.device_put(params,
                       jax.sharding.NamedSharding(mesh, P()))
    s = optax.sgd(1e-3).init(p)
    tokens = jnp.zeros((8, 17), jnp.int32)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        step(p, s, mesh_mod.shard_batch({"tokens": tokens}, mesh))


def test_remat_matches_no_remat():
    """jax.checkpoint changes memory, never math: loss and grads must
    be bitwise-comparable between remat on/off (fp32, same inputs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.models import init_params, loss_fn, tiny_config

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    cfg_r = type(cfg)(**{**cfg.__dict__, "remat": True})
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens}

    l0, g0 = jax.value_and_grad(lambda p: loss_fn(p, batch, cfg))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss_fn(p, batch, cfg_r))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)

    # The "dots" policy (save matmul outputs, recompute only cheap
    # ops) is also math-neutral; an unknown policy must fail loudly.
    import pytest

    cfg_d = type(cfg)(**{**cfg.__dict__, "remat": True,
                         "remat_policy": "dots"})
    l2, g2 = jax.value_and_grad(lambda p: loss_fn(p, batch, cfg_d))(params)
    np.testing.assert_allclose(float(l0), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
    # Structured partial policies: checkpoint one sub-block, keep the
    # other's activations — still math-neutral.
    for pol in ("attn_only", "mlp_only"):
        cfg_p = type(cfg)(**{**cfg.__dict__, "remat": True,
                             "remat_policy": pol})
        lp, gp = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg_p))(params)
        np.testing.assert_allclose(float(l0), float(lp), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(gp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
    cfg_bad = type(cfg)(**{**cfg.__dict__, "remat": True,
                           "remat_policy": "everything"})
    with pytest.raises(ValueError, match="remat_policy"):
        loss_fn(params, batch, cfg_bad)
    # A policy without remat=True would be silently ignored — reject.
    cfg_off = type(cfg)(**{**cfg.__dict__, "remat": False,
                           "remat_policy": "dots"})
    with pytest.raises(ValueError, match="remat=False"):
        loss_fn(params, batch, cfg_off)


def test_sliding_window_model_paths_agree():
    """sliding_window through the full model: the flash and reference
    attention paths must produce identical logits, and generation with
    a window must match the windowed batch forward (greedy)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.models import (forward, generate, init_params,
                                          tiny_config)

    base = tiny_config(dtype=jnp.float32, use_flash=False)
    mk = lambda **kw: type(base)(**{**base.__dict__, **kw})
    cfg_ref = mk(sliding_window=24)
    cfg_flash = mk(sliding_window=24, use_flash=True)
    cfg_full = mk()  # no window
    params = init_params(jax.random.PRNGKey(0), cfg_ref)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                base.vocab_size)

    lr = forward(params, tokens, cfg_ref)
    lf = forward(params, tokens, cfg_flash)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lr),
                               atol=2e-4, rtol=2e-4)
    # The window must actually bite: a 24-token window over 64 tokens
    # differs from full causal attention.
    lfull = forward(params, tokens, cfg_full)
    assert float(jnp.max(jnp.abs(lfull - lr))) > 1e-3

    # Windowed KV-cache generation == argmax of the windowed forward.
    prompt = tokens[:, :40]
    gen = generate(params, prompt, cfg_ref, max_new_tokens=1)
    nxt = jnp.argmax(forward(params, prompt, cfg_ref)[:, -1], -1)
    np.testing.assert_array_equal(np.asarray(gen[:, -1]),
                                  np.asarray(nxt))


def _run_fsdp_case(mesh_axes, tp_axis, optimizer, key0, key1):
    """Shared harness: one train step under fsdp_param_shardings on
    ``mesh_axes`` must match replicated training, with the big weights
    genuinely sharded across all devices of the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbdistributed_tpu.models import (fsdp_param_shardings,
                                          make_train_step)

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(key0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(key1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens}
    step = make_train_step(cfg, optimizer)
    ref_p, _, ref_loss = jax.jit(step)(params, optimizer.init(params),
                                       batch)

    n_dev = int(np.prod(list(mesh_axes.values())))
    m = mesh_mod.make_mesh(mesh_axes, devices=jax.devices()[:n_dev])
    rules = fsdp_param_shardings(cfg, tp_axis=tp_axis)
    p_s = jax.device_put(params, jax.tree_util.tree_map(
        lambda sp: NamedSharding(m, sp), rules))
    wq = p_s["layers"]["wq"]
    assert wq.addressable_shards[0].data.size * n_dev == wq.size,         wq.sharding
    tok_s = jax.device_put(tokens, NamedSharding(m, P("dp")))
    got_p, _, got_loss = jax.jit(step)(p_s, optimizer.init(p_s),
                                       {"tokens": tok_s})
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(got_p),
                    jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_fsdp_sharding_matches_replicated():
    """FSDP/ZeRO-3-style weight sharding: exact vs replicated, weights
    genuinely dp-sharded."""
    _run_fsdp_case({"dp": 4}, None, optax.adamw(1e-3), 0, 1)


def test_hsdp_fsdp_plus_tp_matches_replicated():
    """2-D weight sharding (FSDP over dp x Megatron over tp)."""
    _run_fsdp_case({"dp": 2, "tp": 2}, "tp", optax.sgd(1e-2), 2, 3)


def test_packed_documents_match_separate_forwards():
    """The whole packed-training contract in one test: a window
    holding two packed documents (segment mask + per-document RoPE
    positions) must produce, at each document's positions, EXACTLY
    the logits of forwarding that document alone — and the packed
    loss must equal the token-weighted mix of the per-document
    losses."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.models import (forward, init_params, loss_fn,
                                          packed_positions, tiny_config)
    from nbdistributed_tpu.models.transformer import shifted_xent

    for use_flash in (False, True):
        cfg = tiny_config(dtype=jnp.float32, use_flash=use_flash)
        params = init_params(jax.random.PRNGKey(0), cfg)
        la, lb = 20, 12
        d0 = jax.random.randint(jax.random.PRNGKey(1), (1, la), 0,
                                cfg.vocab_size)
        d1 = jax.random.randint(jax.random.PRNGKey(2), (1, lb), 0,
                                cfg.vocab_size)
        packed = jnp.concatenate([d0, d1], axis=1)
        seg = jnp.concatenate([jnp.zeros((1, la), jnp.int32),
                               jnp.ones((1, lb), jnp.int32)], axis=1)
        pos = packed_positions(seg)
        np.testing.assert_array_equal(
            np.asarray(pos[0]),
            np.concatenate([np.arange(la), np.arange(lb)]))

        lp = forward(params, packed, cfg, pos, segment_ids=seg)
        l0 = forward(params, d0, cfg)
        l1 = forward(params, d1, cfg)
        np.testing.assert_allclose(np.asarray(lp[:, :la]),
                                   np.asarray(l0), atol=2e-5,
                                   rtol=2e-5,
                                   err_msg=f"doc0 flash={use_flash}")
        np.testing.assert_allclose(np.asarray(lp[:, la:]),
                                   np.asarray(l1), atol=2e-5,
                                   rtol=2e-5,
                                   err_msg=f"doc1 flash={use_flash}")

        # Packed loss == token-weighted mean of the per-doc losses
        # (the boundary target is excluded, so the target counts are
        # (la-1) and (lb-1)).
        packed_loss = float(loss_fn(params, {"tokens": packed,
                                             "segments": seg}, cfg))
        per0 = float(shifted_xent(l0, d0))
        per1 = float(shifted_xent(l1, d1))
        mix = (per0 * (la - 1) + per1 * (lb - 1)) / (la + lb - 2)
        np.testing.assert_allclose(packed_loss, mix, rtol=1e-5)


def test_pack_tokens_segments_roundtrip():
    from nbdistributed_tpu.utils.data import pack_tokens

    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    win, seg = pack_tokens(docs, 5, eos_id=0, return_segments=True)
    assert win.shape == seg.shape == (2, 5)
    np.testing.assert_array_equal(win[0], [1, 2, 3, 0, 4])
    np.testing.assert_array_equal(seg[0], [0, 0, 0, 0, 1])
    np.testing.assert_array_equal(win[1], [5, 0, 6, 7, 8])
    np.testing.assert_array_equal(seg[1], [1, 1, 2, 2, 2])
    # Padded trailing window inherits the final doc's segment.
    win2, seg2 = pack_tokens(docs, 4, eos_id=0, drop_remainder=False,
                             return_segments=True)
    assert win2.shape == seg2.shape == (3, 4)
    np.testing.assert_array_equal(seg2[-1], [2, 2, 2, 2])
