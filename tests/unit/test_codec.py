"""Unit tests for the wire codec (nbdistributed_tpu/messaging/codec.py)."""

import numpy as np
import pytest

from nbdistributed_tpu.messaging.codec import (
    CodecError, Message, decode, encode, frame_ready)


def roundtrip(msg, **kw):
    return decode(encode(msg, **kw), **kw)


def test_json_roundtrip():
    m = Message(msg_type="execute", data={"code": "x = 1"}, rank=-1)
    out = roundtrip(m)
    assert out.msg_type == "execute"
    assert out.data == {"code": "x = 1"}
    assert out.rank == -1
    assert out.msg_id == m.msg_id


def test_ndarray_buffer_roundtrip():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    m = Message(msg_type="var", data={"name": "w"}, bufs={"w": arr})
    out = roundtrip(m)
    np.testing.assert_array_equal(out.bufs["w"], arr)
    assert out.bufs["w"].dtype == np.float32


def test_bfloat16_buffer_roundtrip():
    import ml_dtypes
    arr = np.ones((4, 4), dtype=ml_dtypes.bfloat16)
    m = Message(msg_type="var", bufs={"w": arr})
    out = roundtrip(m)
    assert out.bufs["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        out.bufs["w"].astype(np.float32), arr.astype(np.float32))


def test_bytes_buffer_roundtrip():
    m = Message(msg_type="blob", bufs={"b": b"\x00\x01\xff"})
    assert roundtrip(m).bufs["b"] == b"\x00\x01\xff"


class Custom:
    def __eq__(self, other):
        return isinstance(other, Custom)

    def __hash__(self):
        return 0


def test_pickle_fallback_flagged():
    m = Message(msg_type="set_var", data={"name": "o", "value": Custom()})
    out = roundtrip(m, allow_pickle=True)
    assert out.data["value"] == Custom()


def test_pickle_disabled_raises_on_encode():
    m = Message(msg_type="set_var", data=object())
    with pytest.raises(CodecError):
        encode(m, allow_pickle=False)


def test_pickle_disabled_raises_on_decode():
    m = Message(msg_type="set_var", data=object())
    frame = encode(m, allow_pickle=True)
    with pytest.raises(CodecError):
        decode(frame, allow_pickle=False)


def test_reply_correlates_msg_id():
    req = Message(msg_type="execute", data="code")
    resp = req.reply(data={"status": "ok"}, rank=3)
    assert resp.msg_id == req.msg_id
    assert resp.msg_type == "response"
    assert resp.rank == 3


def test_frame_ready_incremental():
    m = Message(msg_type="x", data=[1, 2, 3])
    frame = encode(m)
    for cut in (0, 4, 10, len(frame) - 1):
        assert frame_ready(frame[:cut]) == 0
    assert frame_ready(frame) == len(frame)
    assert frame_ready(frame + b"extra") == len(frame)


def test_bad_magic_rejected():
    with pytest.raises(CodecError):
        frame_ready(b"EVIL" + b"\x00" * 20)
    with pytest.raises(CodecError):
        decode(b"EVIL" + b"\x00" * 20)


def test_multiple_buffers_order_preserved():
    a = np.zeros(3, np.int64)
    b = np.ones((2, 2), np.float64)
    out = roundtrip(Message(msg_type="vars", bufs={"a": a, "b": b, "c": b"z"}))
    np.testing.assert_array_equal(out.bufs["a"], a)
    np.testing.assert_array_equal(out.bufs["b"], b)
    assert out.bufs["c"] == b"z"
