"""Unit tests for the wire codec (nbdistributed_tpu/messaging/codec.py)."""

import numpy as np
import pytest

from nbdistributed_tpu.messaging.codec import (
    CodecError, Message, decode, encode, frame_ready)


def roundtrip(msg, **kw):
    return decode(encode(msg, **kw), **kw)


def test_json_roundtrip():
    m = Message(msg_type="execute", data={"code": "x = 1"}, rank=-1)
    out = roundtrip(m)
    assert out.msg_type == "execute"
    assert out.data == {"code": "x = 1"}
    assert out.rank == -1
    assert out.msg_id == m.msg_id


def test_ndarray_buffer_roundtrip():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    m = Message(msg_type="var", data={"name": "w"}, bufs={"w": arr})
    out = roundtrip(m)
    np.testing.assert_array_equal(out.bufs["w"], arr)
    assert out.bufs["w"].dtype == np.float32


def test_bfloat16_buffer_roundtrip():
    import ml_dtypes
    arr = np.ones((4, 4), dtype=ml_dtypes.bfloat16)
    m = Message(msg_type="var", bufs={"w": arr})
    out = roundtrip(m)
    assert out.bufs["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        out.bufs["w"].astype(np.float32), arr.astype(np.float32))


def test_bytes_buffer_roundtrip():
    m = Message(msg_type="blob", bufs={"b": b"\x00\x01\xff"})
    assert roundtrip(m).bufs["b"] == b"\x00\x01\xff"


class Custom:
    def __eq__(self, other):
        return isinstance(other, Custom)

    def __hash__(self):
        return 0


def test_pickle_fallback_flagged():
    m = Message(msg_type="set_var", data={"name": "o", "value": Custom()})
    out = roundtrip(m, allow_pickle=True)
    assert out.data["value"] == Custom()


def test_pickle_disabled_raises_on_encode():
    m = Message(msg_type="set_var", data=object())
    with pytest.raises(CodecError):
        encode(m, allow_pickle=False)


def test_pickle_disabled_raises_on_decode():
    m = Message(msg_type="set_var", data=object())
    frame = encode(m, allow_pickle=True)
    with pytest.raises(CodecError):
        decode(frame, allow_pickle=False)


def test_reply_correlates_msg_id():
    req = Message(msg_type="execute", data="code")
    resp = req.reply(data={"status": "ok"}, rank=3)
    assert resp.msg_id == req.msg_id
    assert resp.msg_type == "response"
    assert resp.rank == 3


def test_frame_ready_incremental():
    m = Message(msg_type="x", data=[1, 2, 3])
    frame = encode(m)
    for cut in (0, 4, 10, len(frame) - 1):
        assert frame_ready(frame[:cut]) == 0
    assert frame_ready(frame) == len(frame)
    assert frame_ready(frame + b"extra") == len(frame)


def test_bad_magic_rejected():
    with pytest.raises(CodecError):
        frame_ready(b"EVIL" + b"\x00" * 20)
    with pytest.raises(CodecError):
        decode(b"EVIL" + b"\x00" * 20)


def test_multiple_buffers_order_preserved():
    a = np.zeros(3, np.int64)
    b = np.ones((2, 2), np.float64)
    out = roundtrip(Message(msg_type="vars", bufs={"a": a, "b": b, "c": b"z"}))
    np.testing.assert_array_equal(out.bufs["a"], a)
    np.testing.assert_array_equal(out.bufs["b"], b)
    assert out.bufs["c"] == b"z"


# ---------------------------------------------------------------------
# pytree wire: treedef as JSON + leaves as buffers (no pickle)

def test_pytree_wire_roundtrip_structure_and_values():
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    tree = {"layers": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                       "b": np.zeros(3, np.float32)},
            "meta": ["adam", 3, 0.1, None, True],
            "pair": (np.int32(7), "x")}
    meta, bufs = flatten_pytree_wire(tree)
    got = unflatten_pytree_wire(meta, bufs)
    assert list(got) == ["layers", "meta", "pair"]   # insertion order
    np.testing.assert_array_equal(got["layers"]["w"],
                                  tree["layers"]["w"])
    assert got["meta"] == ["adam", 3, 0.1, None, True]
    assert isinstance(got["pair"], tuple)
    assert int(got["pair"][0]) == 7 and got["pair"][1] == "x"


def test_pytree_wire_survives_pickle_free_channel():
    """A params-like pytree rides a Message as JSON meta + buffers —
    encode/decode with allow_pickle=False must succeed bit-for-bit
    (the whole point: model state without pickle)."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    import ml_dtypes
    tree = {"w": np.arange(4, dtype=ml_dtypes.bfloat16),
            "opt": {"mu": np.ones((2, 2), np.float32), "step": 3}}
    meta, bufs = flatten_pytree_wire(tree)
    m = Message(msg_type="response", data={"pytree": meta}, bufs=bufs)
    out = decode(encode(m, allow_pickle=False), allow_pickle=False)
    got = unflatten_pytree_wire(out.data["pytree"], out.bufs)
    assert got["w"].dtype == tree["w"].dtype
    np.testing.assert_array_equal(np.asarray(got["w"], np.float32),
                                  np.asarray(tree["w"], np.float32))
    np.testing.assert_array_equal(got["opt"]["mu"], tree["opt"]["mu"])
    assert got["opt"]["step"] == 3


def test_pytree_wire_rejects_non_pytrees():
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    with pytest.raises(TypeError):
        flatten_pytree_wire({"fn": lambda: 1})       # unknown leaf
    with pytest.raises(TypeError):
        flatten_pytree_wire({1: np.zeros(2)})        # non-str keys
    with pytest.raises(TypeError):
        flatten_pytree_wire({"a": 1, "b": "x"})      # no array leaves


def test_pytree_wire_jax_leaves_flagged():
    import jax.numpy as jnp
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    tree = {"j": jnp.ones(3), "n": np.ones(3)}
    meta, bufs = flatten_pytree_wire(tree)
    flags = {k: sub["jax"] for k, sub in meta["items"]}
    assert flags == {"j": True, "n": False}
    got = unflatten_pytree_wire(
        meta, bufs, leaf_fn=lambda a, is_jax: jnp.asarray(a)
        if is_jax else a)
    assert isinstance(got["j"], jnp.ndarray)
    assert isinstance(got["n"], np.ndarray)


def test_pytree_wire_rejects_object_and_subclass_leaves():
    """Non-array numpy/jax objects (np.random.Generator, dtypes) and
    subclassed containers (NamedTuples like optax states, OrderedDict)
    must be rejected so callers fall back to the explicit-pickle path
    instead of shipping pointer bytes or flattening structure."""
    import collections
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire

    with pytest.raises(TypeError):
        flatten_pytree_wire({"rng": np.random.default_rng(),
                             "w": np.ones(3)})
    with pytest.raises(TypeError):
        flatten_pytree_wire({"o": np.asarray([object()], dtype=object)})
    Named = collections.namedtuple("Named", "mu nu")
    with pytest.raises(TypeError):
        flatten_pytree_wire(Named(np.ones(2), np.ones(2)))
    with pytest.raises(TypeError):
        flatten_pytree_wire(
            collections.OrderedDict(a=np.ones(2)))


def test_pytree_wire_pulled_leaves_are_writable():
    """Decoded buffers are read-only frombuffer views; the default
    reconstruction must copy so pulled trees are mutable."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    meta, bufs = flatten_pytree_wire({"w": np.ones(3, np.float32)})
    m = Message(msg_type="response", data={"pytree": meta}, bufs=bufs)
    out = decode(encode(m))
    got = unflatten_pytree_wire(out.data["pytree"], out.bufs)
    got["w"] += 1                      # must not raise read-only
    np.testing.assert_array_equal(got["w"], np.full(3, 2.0))


def test_pytree_wire_numpy_scalars_keep_type():
    """np.int64/np.float32 leaves round-trip as the SAME scalar type
    (never 0-d ndarrays — isinstance/hash/JSON behavior must not
    change after a pull/push round-trip)."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    import ml_dtypes
    bf16 = np.asarray([1.5], ml_dtypes.bfloat16)[0]
    tree = {"step": np.int64(3), "lr": np.float32(0.1),
            "lr64": np.float64(0.2),       # subclasses python float!
            "flag": np.bool_(True), "bf": bf16,
            "w": np.ones(2, np.float32)}
    meta, bufs = flatten_pytree_wire(tree)
    got = unflatten_pytree_wire(meta, bufs)
    assert type(got["step"]) is np.int64 and got["step"] == 3
    assert type(got["lr"]) is np.float32
    assert type(got["lr64"]) is np.float64
    assert type(got["flag"]) is np.bool_
    np.testing.assert_allclose(got["lr"], np.float32(0.1))
    np.testing.assert_allclose(got["lr64"], np.float64(0.2))
    if isinstance(bf16, np.generic):
        # ml_dtypes scalar: either exact-type npscalar (when it
        # registers as np.floating) or a 0-d buffer — both must
        # round-trip the VALUE without error.
        assert float(np.asarray(got["bf"], np.float32)) == 1.5
    # Non-JSON scalar kinds (complex) take the buffer path instead of
    # breaking the JSON header: value survives, type may become 0-d.
    meta2, bufs2 = flatten_pytree_wire(
        {"z": np.complex64(1 + 2j),
         "t": np.timedelta64(5, "s"),    # subclasses signedinteger!
         "d": np.datetime64("2026-08-01"),
         "w": np.ones(2)})
    got2 = unflatten_pytree_wire(meta2, bufs2)
    assert complex(got2["z"]) == 1 + 2j
    assert got2["t"] == np.timedelta64(5, "s")
    assert got2["d"] == np.datetime64("2026-08-01")
    m2 = Message(msg_type="response", data={"pytree": meta2},
                 bufs=bufs2)
    decode(encode(m2, allow_pickle=False), allow_pickle=False)
    # And the full frame still encodes with pickle disabled.
    m = Message(msg_type="response", data={"pytree": meta}, bufs=bufs)
    decode(encode(m, allow_pickle=False), allow_pickle=False)


def test_pytree_wire_rejects_ndarray_subclasses():
    """MaskedArray/np.matrix would silently lose subclass state under
    np.asarray — they must fall back to the explicit-pickle path."""
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    masked = np.ma.masked_invalid(np.array([1.0, np.nan]))
    with pytest.raises(TypeError, match="subclass"):
        flatten_pytree_wire({"m": masked, "w": np.ones(2)})
    with pytest.raises(TypeError, match="subclass"):
        flatten_pytree_wire({"m": np.matrix([[1.0]]), "w": np.ones(2)})


def test_pytree_wire_zero_d_and_empty_arrays():
    """0-d and 0-element leaves are legal buffers: shape survives
    exactly (a 0-d leaf must NOT come back as shape-(1,), an empty
    (0, 4) leaf must keep its trailing dims) — the bulk-transfer
    plane's layout descriptors depend on this."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    tree = {"zero_d": np.array(2.5, dtype=np.float16),
            "empty": np.empty((0, 4), dtype=np.float32),
            "empty1d": np.array([], dtype=np.int64),
            "w": np.ones(3, np.float32)}
    meta, bufs = flatten_pytree_wire(tree)
    m = Message(msg_type="response", data={"pytree": meta}, bufs=bufs)
    out = decode(encode(m, allow_pickle=False), allow_pickle=False)
    got = unflatten_pytree_wire(out.data["pytree"], out.bufs)
    assert got["zero_d"].shape == () and got["zero_d"].dtype == np.float16
    assert float(got["zero_d"]) == 2.5
    assert got["empty"].shape == (0, 4)
    assert got["empty"].dtype == np.float32
    assert got["empty1d"].shape == (0,) and got["empty1d"].dtype == np.int64


def test_pytree_wire_bare_array_top_level():
    """A bare ndarray (no container) is a valid tree — the single-leaf
    branch %dist_push relies on for plain-array pushes."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    arr = np.arange(10, dtype=np.float64).reshape(2, 5)
    meta, bufs = flatten_pytree_wire(arr)
    assert meta["k"] == "leaf" and len(bufs) == 1
    got = unflatten_pytree_wire(meta, bufs)
    assert isinstance(got, np.ndarray)
    np.testing.assert_array_equal(got, arr)
    # ...but a bare scalar with no array leaf anywhere still falls back
    with pytest.raises(TypeError):
        flatten_pytree_wire(3.14)


def test_pytree_wire_deeply_nested_treedef_roundtrip():
    """Mixed nesting depth (dict→list→tuple→dict) with duplicate leaf
    names at different paths: buffer naming must disambiguate and the
    treedef must reconstruct the exact container types per level."""
    from nbdistributed_tpu.messaging.codec import (flatten_pytree_wire,
                                                   unflatten_pytree_wire)
    tree = {"a": [({"w": np.ones(2, np.float32)},
                   [np.zeros(1, np.int32),
                    {"w": np.full(2, 7, np.float32)}]),
                  np.arange(3, dtype=np.int8)],
            "b": (np.array(1.0),)}
    meta, bufs = flatten_pytree_wire(tree)
    m = Message(msg_type="response", data={"pytree": meta}, bufs=bufs)
    out = decode(encode(m, allow_pickle=False), allow_pickle=False)
    got = unflatten_pytree_wire(out.data["pytree"], out.bufs)
    assert isinstance(got["a"], list) and isinstance(got["a"][0], tuple)
    assert isinstance(got["a"][0][1], list)
    assert isinstance(got["b"], tuple)
    np.testing.assert_array_equal(got["a"][0][0]["w"], np.ones(2))
    np.testing.assert_array_equal(got["a"][0][1][1]["w"],
                                  np.full(2, 7, np.float32))
    np.testing.assert_array_equal(got["a"][1],
                                  np.arange(3, dtype=np.int8))
    np.testing.assert_array_equal(got["b"][0], np.array(1.0))


def test_pytree_wire_typeerror_fallback_reports_path():
    """Every rejection is a TypeError (the XferFallback/legacy-path
    contract) even for exotic leaves buried deep in the tree."""
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    deep = {"ok": np.ones(2),
            "bad": [({"x": (set([1]),)},)]}      # set leaf, 4 deep
    with pytest.raises(TypeError):
        flatten_pytree_wire(deep)
    with pytest.raises(TypeError):
        flatten_pytree_wire([])                  # no array leaves
    with pytest.raises(TypeError):
        flatten_pytree_wire({"g": (x for x in [np.ones(1)])})
