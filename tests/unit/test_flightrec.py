"""Flight recorder, telemetry, and postmortem units (ISSUE 3).

The crash-recovery tests simulate what a SIGKILL leaves behind — a
ring file whose final record was cut mid-write — by truncating or
corrupting the bytes directly, and assert the reader recovers every
COMPLETE event and flags the torn tail.
"""

import json
import os
import struct

import pytest

from nbdistributed_tpu.observability import flightrec as fr
from nbdistributed_tpu.observability import postmortem as pm_mod
from nbdistributed_tpu.observability import telemetry as tel

pytestmark = [pytest.mark.unit, pytest.mark.obs, pytest.mark.postmortem]


def _ring(tmp_path, name="t.ring", size=1 << 16):
    return fr.FlightRecorder(str(tmp_path / name), ring_bytes=size)


def _last_record_pos(path):
    blob = open(path, "rb").read()
    idx = blob.find(fr.REC_MAGIC, 64)
    last = -1
    while idx != -1:
        last = idx
        idx = blob.find(fr.REC_MAGIC, idx + 1)
    assert last >= 0
    return last, blob


# ----------------------------------------------------------------------
# append / recover round-trip


class TestRoundTrip:
    def test_events_recovered_in_order(self, tmp_path):
        r = _ring(tmp_path)
        for i in range(20):
            r.record("dispatch", msg_id=f"m{i}", n=i)
        d = fr.read_ring(r.path)
        assert d["recovered"] == 20
        assert not d["torn_tail"]
        assert [e["n"] for e in d["events"]] == list(range(20))
        assert all(e["t"] == "dispatch" for e in d["events"])
        assert all(e["ts"] > 0 for e in d["events"])
        assert d["pid"] == os.getpid()

    def test_fast_encoder_matches_json_for_escapy_values(self, tmp_path):
        r = _ring(tmp_path)
        tricky = 'x = "quo\\ted"\nline2\ttab'
        r.record("cell_start", code=tricky, flag=True, none=None,
                 f=1.5, nested={"a": [1, 2]})
        ev = fr.read_ring(r.path)["events"][0]
        assert ev["code"] == tricky
        assert ev["flag"] is True and ev["none"] is None
        assert ev["f"] == 1.5 and ev["nested"] == {"a": [1, 2]}

    def test_wrap_drops_oldest_keeps_newest(self, tmp_path):
        r = _ring(tmp_path, size=4096)
        n = 400
        for i in range(n):
            r.record("ev", n=i, pad="x" * 40)
        d = fr.read_ring(r.path)
        assert d["events"][-1]["n"] == n - 1          # newest survives
        assert d["overwritten"] > 0                   # oldest gone
        assert d["recovered"] + d["overwritten"] == n
        # the survivors are a contiguous suffix, in order
        ns = [e["n"] for e in d["events"]]
        assert ns == list(range(n - d["recovered"], n))
        assert not d["torn_tail"]                     # clean writer

    def test_reopen_does_not_leak_previous_generation(self, tmp_path):
        """Opening an existing ring path (pid recycling, re-init) must
        zero the whole region: the old generation's CRC-valid records
        must not merge into the new writer's recovery output."""
        p = str(tmp_path / "reopen.ring")
        r1 = fr.FlightRecorder(p)
        for i in range(50):
            r1.record("gen1", n=i)
        r1.close()
        r2 = fr.FlightRecorder(p)
        r2.record("gen2", n=0)
        d = fr.read_ring(r2.path)
        assert [e["t"] for e in d["events"]] == ["gen2"]
        assert d["overwritten"] == 0

    def test_oversize_payload_does_not_corrupt_neighbors(self, tmp_path):
        r = _ring(tmp_path)
        r.record("before", n=1)
        r.record("big", blob="y" * (fr.MAX_PAYLOAD + 100))
        r.record("after", n=2)
        d = fr.read_ring(r.path)
        names = [e["t"] for e in d["events"]]
        assert "before" in names and "after" in names


# ----------------------------------------------------------------------
# crash recovery (simulated SIGKILL mid-write)


class TestTornTail:
    def _write(self, tmp_path, n=12):
        r = _ring(tmp_path, name="torn.ring")
        for i in range(n):
            r.record("ev", n=i)
        r.flush()
        r.close()
        return str(tmp_path / "torn.ring"), n

    def test_truncated_final_record_flagged(self, tmp_path):
        path, n = self._write(tmp_path)
        last, blob = _last_record_pos(path)
        # cut the file mid-payload of the final record
        open(path, "wb").write(blob[: last + fr.REC_HEADER_SIZE + 2])
        d = fr.read_ring(path)
        assert d["recovered"] == n - 1
        assert d["torn_tail"] is True
        assert [e["n"] for e in d["events"]] == list(range(n - 1))

    def test_corrupted_final_payload_flagged(self, tmp_path):
        path, n = self._write(tmp_path)
        last, blob = _last_record_pos(path)
        mangled = bytearray(blob)
        pos = last + fr.REC_HEADER_SIZE + 1
        mangled[pos] = mangled[pos] ^ 0xFF            # bit-flip, CRC fails
        open(path, "wb").write(bytes(mangled))
        d = fr.read_ring(path)
        assert d["recovered"] == n - 1
        assert d["torn_tail"] is True

    def test_corrupt_middle_record_not_reported_as_torn(self, tmp_path):
        path, n = self._write(tmp_path)
        blob = open(path, "rb").read()
        first = blob.find(fr.REC_MAGIC, 64)
        mangled = bytearray(blob)
        pos = first + fr.REC_HEADER_SIZE + 1
        mangled[pos] = mangled[pos] ^ 0xFF
        open(path, "wb").write(bytes(mangled))
        d = fr.read_ring(path)
        assert d["recovered"] == n - 1                # one casualty
        assert d["torn_tail"] is False                # but tail is whole

    def test_reader_ignores_header_hints(self, tmp_path):
        """Recovery must not trust the writer's header (a torn header
        is as likely as a torn record): zero the hint fields and the
        scan still finds everything."""
        path, n = self._write(tmp_path)
        blob = bytearray(open(path, "rb").read())
        blob[16:40] = b"\0" * 24                      # hint region
        open(path, "wb").write(bytes(blob))
        d = fr.read_ring(path)
        assert d["recovered"] == n


# ----------------------------------------------------------------------
# process wiring


class TestProcessWiring:
    def test_run_dir_minted_and_exported(self, tmp_path, monkeypatch):
        monkeypatch.delenv("NBD_RUN_DIR", raising=False)
        monkeypatch.setattr("tempfile.gettempdir",
                            lambda: str(tmp_path))
        d = fr.run_dir()
        assert os.path.isdir(d)
        assert os.environ["NBD_RUN_DIR"] == d
        assert fr.run_dir() == d                      # stable

    def test_init_and_module_record(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
        fr.reset_for_tests()
        try:
            r = fr.init("rank7")
            fr.record("dispatch", msg_id="abc")
            assert len(r) == 1
            d = fr.read_latest(str(tmp_path), "rank7")
            assert d["events"][0]["msg_id"] == "abc"
            assert fr.find_rings(str(tmp_path), "rank7")
        finally:
            fr.reset_for_tests()

    def test_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
        monkeypatch.setenv("NBD_FLIGHT", "0")
        fr.reset_for_tests()
        try:
            r = fr.init("rank8")
            r.record("ev")
            assert len(r) == 0
            assert fr.find_rings(str(tmp_path)) == []
        finally:
            fr.reset_for_tests()

    def test_unwritable_dir_degrades_to_noop(self, tmp_path,
                                             monkeypatch):
        # NBD_RUN_DIR "under" a regular file: makedirs/open must fail,
        # and the recorder must degrade to a no-op, never raise.
        blocker = tmp_path / "a_file"
        blocker.write_text("x")
        monkeypatch.setenv("NBD_RUN_DIR", str(blocker / "sub"))
        fr.reset_for_tests()
        try:
            r = fr.init("rank9")
            r.record("ev")                            # must not raise
            assert len(r) == 0
        finally:
            fr.reset_for_tests()

    def test_record_before_init_is_noop(self):
        fr.reset_for_tests()
        fr.record("ev", n=1)                          # must not raise
        assert len(fr.recorder()) == 0


# ----------------------------------------------------------------------
# telemetry


class TestTelemetry:
    def test_sampler_snapshot_shape(self):
        s = tel.TelemetrySampler(0, extra_fn=lambda: {"dedup": 3})
        snap = s.sample()
        assert snap["ts"] > 0
        assert snap["bufs"] >= 0                      # CPU backend: works
        assert snap["dedup"] == 3
        assert s.last is snap

    def test_sampler_paces_itself(self):
        s = tel.TelemetrySampler(0, min_interval_s=3600)
        assert s.maybe_sample() is not None
        assert s.maybe_sample() is None               # too soon

    def test_extra_fn_failure_is_soft(self):
        def boom():
            raise RuntimeError("x")
        snap = tel.TelemetrySampler(0, extra_fn=boom).sample()
        assert "ts" in snap

    def test_device_memory_none_on_cpu(self):
        import jax
        assert tel.device_memory(jax.devices()[0]) is None

    def test_device_status_still_reports(self):
        from nbdistributed_tpu.runtime import introspect
        st = introspect.device_status(0, 1)
        assert st["devices"]
        assert "memory_gb" in st["devices"][0]

    def test_peak_hbm_summary(self):
        snaps = [
            {"hbm": [{"id": 0, "in_use": 5, "peak": 10, "limit": 100}]},
            {"hbm": [{"id": 0, "in_use": 7, "peak": 30, "limit": 100}]},
            None,
        ]
        assert tel.peak_hbm(snaps) == {"0": 30}


# ----------------------------------------------------------------------
# postmortem bundles


class _FakeComm:
    """The minimal coordinator surface postmortem.capture touches."""

    def __init__(self, n):
        self.num_workers = n
        from nbdistributed_tpu.observability.clock import ClockEstimator
        from nbdistributed_tpu.observability.spans import Tracer
        self.tracer = Tracer()
        self.clock = ClockEstimator()

    def fault_plan(self):
        return None

    def telemetry_history(self, rank):
        return [{"ts": 5.0, "hbm": [{"id": 0, "in_use": 9,
                                     "peak": 11, "limit": 100}],
                 "bufs": 4}] if rank == 1 else []


class TestPostmortem:
    def _seed_rings(self, run_d, torn_rank=1):
        for r in (0, 1):
            rec = fr.FlightRecorder(
                fr.ring_path(str(run_d), f"rank{r}", pid=1000 + r))
            for i in range(5):
                rec.record("dispatch", msg_id=f"r{r}m{i}")
            rec.close()
        crec = fr.FlightRecorder(
            fr.ring_path(str(run_d), "coordinator", pid=999))
        crec.record("send", msg_id="r1m4", type="execute")
        crec.close()
        if torn_rank is not None:
            path = fr.ring_path(str(run_d), f"rank{torn_rank}",
                                pid=1000 + torn_rank)
            last, blob = _last_record_pos(path)
            open(path, "wb").write(
                blob[: last + fr.REC_HEADER_SIZE + 2])

    def test_capture_builds_full_bundle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
        self._seed_rings(tmp_path)
        manifest = pm_mod.capture(_FakeComm(2), [1], reason="test kill")
        assert manifest is not None
        d = manifest["dir"]
        assert manifest["dead_ranks"] == [1]
        assert manifest["rings"]["1"]["torn_tail"] is True
        # dead rank's recovered flight ring, with the torn tail cut off
        ring1 = json.load(open(os.path.join(d, "flight_rank1.json")))
        assert [e["msg_id"] for e in ring1["events"]] == \
            [f"r1m{i}" for i in range(4)]
        # merged chrome trace has every pid incl. the dead rank's
        trace = json.load(open(os.path.join(d, "trace.json")))
        flight = [e for e in trace["traceEvents"]
                  if e.get("cat") == "flight"]
        assert {e["pid"] for e in flight} == {-1, 0, 1}
        dead_evs = [e for e in flight if e["pid"] == 1]
        assert all(e["args"].get("ring_torn_tail") for e in dead_evs)
        # telemetry + human report
        telj = json.load(open(os.path.join(d, "telemetry.json")))
        assert telj["1"][0]["bufs"] == 4
        report = open(os.path.join(d, "report.txt")).read()
        assert "rank 1 [DEAD]" in report
        assert "TORN final record" in report
        assert "test kill" in report
        # bundle listing / --last plumbing
        assert pm_mod.list_bundles(str(tmp_path)) == [d]

    def test_capture_never_raises(self, tmp_path, monkeypatch):
        blocker = tmp_path / "a_file"
        blocker.write_text("x")
        monkeypatch.setenv("NBD_RUN_DIR", str(blocker / "sub"))
        assert pm_mod.capture(_FakeComm(2), [0]) is None

    def test_flight_to_trace_dump_empty(self):
        assert pm_mod.flight_to_trace_dump(None)["instants"] == []


# ----------------------------------------------------------------------
# format stability: a reader from another process must agree on layout


def test_record_header_layout_frozen():
    assert fr.REC_HEADER_SIZE == struct.calcsize("<4sHIQ") == 18
    assert fr.REC_MAGIC == b"\xf1\x1e\xc0\xde"
