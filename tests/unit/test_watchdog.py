"""Unit tests for the collective hang watchdog + stuck-cell doctor
(ISSUE 5): policy env parsing, skew/stall/deadline detection on
synthetic per-rank sequences (including the no-false-positive contract
for uniformly-slow cells), the escalation ladder's ordering and grace
timing against fake comm/pm, the guard's collective-progress stream,
the FaultPlan collective-freeze knob, and the attach-timeout
diagnostics satellite."""

import time

import pytest

from nbdistributed_tpu.manager.process_manager import (ProcessManager,
                                                       wait_until_ready)
from nbdistributed_tpu.resilience import FaultPlan
from nbdistributed_tpu.resilience.watchdog import (HangPolicy,
                                                   HangWatchdog,
                                                   SkewDetector,
                                                   hang_report,
                                                   parse_ladder)
from nbdistributed_tpu.runtime import collective_guard as cg

pytestmark = [pytest.mark.unit, pytest.mark.hang]


# ----------------------------------------------------------------------
# HangPolicy / ladder parsing

def test_policy_defaults_and_env():
    p = HangPolicy.from_env(env={})
    assert p.enabled and p.escalate == ("warn", "dump")
    p = HangPolicy.from_env(env={"NBD_HANG": "0"})
    assert not p.enabled
    p = HangPolicy.from_env(env={
        "NBD_HANG_SKEW_S": "5", "NBD_HANG_STALL_S": "9",
        "NBD_HANG_POLL_S": "0.2", "NBD_HANG_GRACE_S": "3",
        "NBD_HANG_ESCALATE": "warn,dump,interrupt,heal"})
    assert (p.skew_s, p.stall_s, p.poll_s, p.grace_s) == (5, 9, 0.2, 3)
    assert p.escalate == ("warn", "dump", "interrupt", "heal")
    # Malformed floats degrade to defaults, not crashes (%dist_init
    # must come up even with a typo'd knob).
    p = HangPolicy.from_env(env={"NBD_HANG_SKEW_S": "soon"})
    assert p.skew_s == HangPolicy.skew_s


def test_unknown_ladder_step_is_an_error():
    with pytest.raises(ValueError, match="unknown escalation"):
        parse_ladder("warn,dmup")
    with pytest.raises(ValueError, match="unknown escalation"):
        HangPolicy(escalate=("warn", "explode"))
    with pytest.raises(ValueError, match="unknown escalation"):
        HangPolicy.from_env(env={"NBD_HANG_ESCALATE": "wran"})
    # The lenient variant (status/doctor surfaces) degrades the typo'd
    # ladder to the default but still honors the numeric knobs.
    p = HangPolicy.from_env_lenient(env={"NBD_HANG_ESCALATE": "wran",
                                         "NBD_HANG_STALL_S": "33"})
    assert p.escalate == HangPolicy.escalate and p.stall_s == 33.0


def test_set_policy_preserves_ladder_state():
    """Reconfiguring a live watchdog must not zero active-hang ladder
    progress or counters (a replaced instance would re-run warn/dump
    from step 0 on the still-hung cell)."""
    pol = HangPolicy(skew_s=1, stall_s=60, grace_s=100,
                     escalate=("warn",))
    wd, clock = _watchdog(pol)
    comm, pm = FakeComm(2), FakePM([0, 1])
    wd._comm, wd._pm = comm, pm
    comm.pending["mZ"] = {"type": "execute", "expect": [0, 1],
                          "responded": [0], "sent_at": 999.0}
    comm.pings[1] = (clock["t"],
                     {"busy_type": "execute", "busy_s": 2.0,
                      "busy_id": "mZ",
                      "col": {"seq": 1, "op": "barrier", "in": True,
                              "age": 2.0, "cops": 1}})
    wd.poll_once()
    clock["t"] += 2.0
    wd.poll_once()
    assert wd.escalations == {"warn": 1} and wd.cells_flagged == 1
    wd.set_policy(HangPolicy(skew_s=1, stall_s=300, grace_s=100,
                             escalate=("warn",)))
    clock["t"] += 2.0
    wd.poll_once()
    assert wd.policy.stall_s == 300
    assert wd.escalations == {"warn": 1}     # no re-run from step 0
    assert wd.cells_flagged == 1             # same hang, not re-flagged
    assert wd.detector.policy.stall_s == 300


# ----------------------------------------------------------------------
# SkewDetector on synthetic sequences

POL = HangPolicy(skew_s=10.0, stall_s=60.0)


def _busy(mid, s, seq=None, op=None, in_=False, cops=None,
          deadline=None):
    v = {"busy_id": mid, "busy_type": "execute", "busy_s": s,
         "hb_age": 0.5}
    if seq is not None:
        v.update({"seq": seq, "op": op or "all_reduce", "in": in_,
                  "cops": seq if cops is None else cops})
    if deadline is not None:
        v["deadline"] = deadline
    return v


def test_uniformly_slow_cell_never_flags():
    """All ranks advancing through the same collective sequence
    together — slow, but NOT hung: zero verdicts, ever."""
    det = SkewDetector(POL)
    for step in range(8):
        now = step * 20.0  # each collective takes 20s > skew_s
        ranks = {r: _busy("m1", now + 5, seq=step + 1, in_=True)
                 for r in range(4)}
        assert det.observe(now, ranks, {}) == []


def test_uniform_inside_one_collective_is_stall_only_after_window():
    """Every rank stuck inside the SAME collective: no skew (equal
    positions), stall only once the policy window is blown."""
    det = SkewDetector(POL)
    ranks = {r: _busy("m1", 5.0, seq=3, in_=True) for r in range(4)}
    assert det.observe(0.0, ranks, {}) == []
    ranks = {r: _busy("m1", 45.0, seq=3, in_=True) for r in range(4)}
    assert det.observe(40.0, ranks, {}) == []  # under stall_s
    ranks = {r: _busy("m1", 70.0, seq=3, in_=True) for r in range(4)}
    (v,) = det.observe(65.0, ranks, {})
    assert v["kind"] == "stall" and v["ranks"] == [0, 1, 2, 3]


def test_cross_rank_skew_names_lagging_rank_and_divergence():
    """Ranks 0-2 entered all_reduce #7; rank 3 never did."""
    det = SkewDetector(POL)

    def views():
        r = {i: _busy("m1", 30.0, seq=7, op="all_reduce", in_=True)
             for i in range(3)}
        r[3] = _busy("m1", 30.0, seq=6, op="all_reduce", in_=False)
        return r

    assert det.observe(0.0, views(), {}) == []     # not yet persistent
    assert det.observe(5.0, views(), {}) == []
    (v,) = det.observe(11.0, views(), {})
    assert v["kind"] == "skew"
    assert v["ranks"] == [3] and v["seq"] == 7
    assert v["op"] == "all_reduce"
    assert "[3] never did" in v["detail"]
    # The laggard advances -> the verdict clears.
    healthy = views()
    healthy[3] = _busy("m1", 31.0, seq=7, op="all_reduce", in_=True)
    assert det.observe(12.0, healthy, {}) == []


def test_straggler_behind_responded_peers_is_skew():
    """Peers finished the cell; one rank is still inside a collective
    — skew (collective evidence), naming the straggler."""
    det = SkewDetector(POL)
    ranks = {1: _busy("m1", 30.0, seq=4, in_=True)}
    pending = {"m1": {"expect": [0, 1], "responded": [0],
                      "sent_at": 0.0}}
    det.observe(0.0, ranks, pending)
    (v,) = det.observe(11.0, ranks, pending)
    assert v["kind"] == "skew" and v["ranks"] == [1]
    assert v["peers"] == [0]
    assert "stuck inside" in v["detail"]


def test_post_collective_local_work_is_not_skew():
    """Healthy rank asymmetry: peers responded while a rank does long
    rank-LOCAL work AFTER its collectives (same cell position, not
    inside any collective) — never skew; only the stall window may
    eventually claim it."""
    det = SkewDetector(POL)
    ranks = {1: _busy("m1", 30.0, seq=4, in_=False, cops=2)}
    pending = {"m1": {"expect": [0, 1], "responded": [0],
                      "sent_at": 0.0}}
    det.observe(0.0, ranks, pending)
    assert det.observe(15.0, ranks, pending) == []     # > skew_s
    ranks = {1: _busy("m1", 95.0, seq=4, in_=False, cops=2)}
    (v,) = det.observe(65.0, ranks, pending)           # > stall_s
    assert v["kind"] == "stall"


def test_infinite_loop_without_collectives_is_stall():
    """Pure-Python infinite loop: zero collectives this cell, busy
    past the stall window -> stall, not skew."""
    det = SkewDetector(POL)
    ranks = {1: _busy("m1", 30.0, cops=0)}
    pending = {"m1": {"expect": [0, 1], "responded": [0],
                      "sent_at": 0.0}}
    det.observe(0.0, ranks, pending)
    assert det.observe(30.0, ranks, pending) == []  # under stall_s
    ranks = {1: _busy("m1", 95.0, cops=0)}
    (v,) = det.observe(65.0, ranks, pending)
    assert v["kind"] == "stall" and v["ranks"] == [1]
    assert "no collective progress" in v["detail"]


def test_divergent_lifetime_seqs_equal_cell_positions_not_skew():
    """Process-lifetime sequences diverge permanently and harmlessly
    (a hazard-raising subset collective advances only the caller; a
    broken hang leaves the laggard behind forever) — a later healthy
    cell where every rank is at the SAME cell-local position must
    never be flagged, no matter how stale, below the stall window."""
    det = SkewDetector(POL)
    ranks = {
        0: _busy("m2", 30.0, seq=9, op="all_reduce", in_=True, cops=2),
        1: _busy("m2", 30.0, seq=8, op="all_reduce", in_=True, cops=2),
        2: _busy("m2", 30.0, seq=8, op="all_reduce", in_=True, cops=2),
    }
    det.observe(0.0, ranks, {})
    assert det.observe(15.0, ranks, {}) == []   # > skew_s, no verdict
    # But a genuinely-behind CELL position still flags, and reports
    # the divergence at the ahead ranks' global seq.
    ranks[2] = _busy("m2", 30.0, seq=7, op="all_reduce", in_=False,
                     cops=1)
    det.observe(16.0, ranks, {})
    (v,) = det.observe(27.0, ranks, {})
    assert v["kind"] == "skew" and v["ranks"] == [2]
    assert v["seq"] == 9  # the ahead members' newest global seq


def test_one_poll_phantom_divergence_is_not_skew():
    """Heartbeats propagate positions with up to a ping-interval of
    lag: a lockstep cell with long inter-collective gaps shows a
    one-poll divergence (the faster rank's ping landed first) that
    clears on the next ping.  The divergence itself must persist for
    skew_s before a verdict — a phantom never does."""
    det = SkewDetector(POL)
    # Both ranks in step for a long compute gap (> skew_s, no
    # progress) — then rank 0's ping shows the next collective first.
    ranks = {0: _busy("m1", 25.0, seq=1, in_=False, cops=1),
             1: _busy("m1", 25.0, seq=1, in_=False, cops=1)}
    det.observe(0.0, ranks, {})
    ranks[0] = _busy("m1", 51.0, seq=2, in_=True, cops=2)
    # rank 1 entered ms later but its ping is still in flight: it
    # looks behind with a 26s-stale progress clock — NO verdict (the
    # divergence is 0s old).
    assert det.observe(26.0, ranks, {}) == []
    # Next poll the slow ping landed: back in step, clocks cleared.
    ranks[1] = _busy("m1", 53.0, seq=2, in_=True, cops=2)
    assert det.observe(28.0, ranks, {}) == []
    # GENUINE lag: rank 1 stays behind past skew_s -> verdict.
    det2 = SkewDetector(POL)
    ranks = {0: _busy("m1", 30.0, seq=2, in_=True, cops=2),
             1: _busy("m1", 30.0, seq=1, in_=False, cops=1)}
    det2.observe(0.0, ranks, {})
    assert det2.observe(6.0, ranks, {}) == []
    (v,) = det2.observe(11.0, ranks, {})
    assert v["kind"] == "skew" and v["ranks"] == [1]


def test_stale_pings_never_produce_verdicts():
    """A rank whose pings stopped right after a busy one must not be
    judged on that frozen data (it may long have finished): no busy
    view past the hb_stale_s cutoff, hence no stall/skew — silent
    ranks belong to the supervisor's degraded/dead machinery."""
    pol = HangPolicy(skew_s=1, stall_s=2, grace_s=0, escalate=())
    wd, clock = _watchdog(pol)
    comm, pm = FakeComm(2), FakePM([0, 1])
    wd._comm, wd._pm = comm, pm
    comm.pings[1] = (clock["t"],
                     {"busy_type": "execute", "busy_s": 1.0,
                      "busy_id": "mS"})
    wd.poll_once()
    clock["t"] += 60.0          # ping now 60s old: frozen data
    assert wd.poll_once() == []
    assert wd.rank_views().get(1, {}).get("busy_s") is None


def test_deadline_verdict_is_immediate():
    det = SkewDetector(POL)
    ranks = {0: _busy("m1", 12.0, deadline=10.0),
             1: _busy("m1", 12.0, deadline=10.0)}
    (v,) = det.observe(0.0, ranks, {})
    assert v["kind"] == "deadline" and v["ranks"] == [0, 1]
    assert "--deadline" in v["detail"]
    # Under budget: nothing.
    det2 = SkewDetector(POL)
    assert det2.observe(0.0, {0: _busy("m1", 5.0, deadline=10.0)},
                        {}) == []


# ----------------------------------------------------------------------
# HangWatchdog escalation ladder (fake comm/pm, fake clock)

class FakeComm:
    def __init__(self, n=2):
        self.num_workers = n
        self.pings = {}
        self.pending = {}

    def last_ping(self, rank):
        return self.pings.get(rank)

    def pending_snapshot(self):
        return dict(self.pending)


class FakePM:
    def __init__(self, ranks):
        self._ranks = list(ranks)
        self.dumped = []
        self.interrupted = []

    def alive_ranks(self):
        return list(self._ranks)

    def dump_stacks(self, ranks=None):
        self.dumped.append(ranks)
        return list(self._ranks)

    def interrupt(self, ranks=None):
        self.interrupted.append(ranks)
        return list(self._ranks)


def _watchdog(policy, heal=None):
    clock = {"t": 1000.0}
    wd = HangWatchdog(policy, heal=heal, clock=lambda: clock["t"])
    return wd, clock


def test_ladder_order_and_grace(capsys):
    pol = HangPolicy(skew_s=5, stall_s=60, grace_s=10,
                     escalate=("warn", "dump", "interrupt"))
    wd, clock = _watchdog(pol)
    comm, pm = FakeComm(2), FakePM([0, 1])
    # attach() would start the thread; bind directly and drive
    # poll_once with the fake clock instead.
    wd._comm, wd._pm = comm, pm
    comm.pending["m1"] = {"type": "execute", "expect": [0, 1],
                          "responded": [0], "sent_at": 990.0}
    busy = {"busy_type": "execute", "busy_s": 3.0, "busy_id": "m1",
            "col": {"seq": 2, "op": "all_reduce", "in": True,
                    "age": 3.0, "cops": 2}}
    comm.pings[1] = (clock["t"], busy)
    assert wd.poll_once() == []          # no persistence yet
    clock["t"] += 6.0                    # past skew_s
    comm.pings[1] = (clock["t"], busy)   # heartbeats keep arriving
    verdicts = wd.poll_once()
    assert verdicts and verdicts[0]["kind"] == "skew"
    assert wd.escalations == {"warn": 1}          # step 1 immediately
    assert "hang watchdog" in capsys.readouterr().out
    clock["t"] += 5.0                    # inside grace: no new step
    comm.pings[1] = (clock["t"], busy)
    wd.poll_once()
    assert wd.escalations == {"warn": 1} and pm.dumped == []
    clock["t"] += 6.0                    # grace elapsed -> dump
    comm.pings[1] = (clock["t"], busy)
    wd.poll_once()
    assert wd.escalations == {"warn": 1, "dump": 1}
    assert pm.dumped == [None]
    clock["t"] += 11.0                   # -> interrupt (ALL ranks)
    comm.pings[1] = (clock["t"], busy)
    wd.poll_once()
    assert wd.escalations["interrupt"] == 1
    assert pm.interrupted == [None]
    # The hang clears (rank went idle) -> resolved, gauge drops.
    comm.pings[1] = (clock["t"], {})
    del comm.pending["m1"]
    clock["t"] += 1.0
    assert wd.poll_once() == []
    st = wd.status()
    assert st["active"] == {} and st["cells_resolved"] == 1
    assert st["cells_flagged"] == 1


def test_heal_step_rebinds_to_fresh_world():
    comm2, pm2 = FakeComm(2), FakePM([0, 1])
    pol = HangPolicy(skew_s=1, stall_s=60, grace_s=0,
                     escalate=("heal",))
    wd, clock = _watchdog(pol, heal=lambda: (comm2, pm2))
    comm, pm = FakeComm(2), FakePM([0, 1])
    wd._comm, wd._pm = comm, pm
    comm.pending["m9"] = {"type": "execute", "expect": [0, 1],
                          "responded": [0], "sent_at": 999.0}
    comm.pings[1] = (clock["t"],
                     {"busy_type": "execute", "busy_s": 2.0,
                      "busy_id": "m9",
                      "col": {"seq": 1, "op": "barrier", "in": True,
                              "age": 2.0, "cops": 1}})
    wd.poll_once()
    clock["t"] += 2.0
    wd.poll_once()
    assert wd.escalations == {"heal": 1}
    assert wd._comm is comm2 and wd._pm is pm2
    assert wd.status()["active"] == {}   # state reset after rebind


def test_dead_ranks_are_not_hangs():
    """A dead process is the supervisor's domain: its stale ping must
    not produce a hang verdict."""
    pol = HangPolicy(skew_s=1, stall_s=2, grace_s=0, escalate=())
    wd, clock = _watchdog(pol)
    comm, pm = FakeComm(2), FakePM([0])   # rank 1 dead
    wd._comm, wd._pm = comm, pm
    comm.pings[1] = (clock["t"],
                     {"busy_type": "execute", "busy_s": 50.0,
                      "busy_id": "mX"})
    wd.poll_once()
    clock["t"] += 5.0
    assert wd.poll_once() == []


def test_hang_report_names_laggard_without_processes(tmp_path,
                                                     monkeypatch):
    """The doctor renders from coordinator state alone (no workers,
    no stack dump) and names the lagging rank + divergence point.
    The fake clock rides slightly AHEAD of wall time because
    hang_report itself reads time.time() for heartbeat ages (future
    arrivals clamp to age 0 = fresh)."""
    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
    pol = HangPolicy(skew_s=2, stall_s=60, grace_s=2,
                     escalate=("warn", "dump"))
    clock = {"t": time.time()}
    wd = HangWatchdog(pol, clock=lambda: clock["t"])
    comm = FakeComm(2)
    wd._comm = comm
    comm.pending["mA"] = {"type": "execute", "expect": [0, 1],
                          "responded": [], "sent_at": clock["t"] - 5}

    def _ping(seq, in_, cops):
        return {"busy_type": "execute", "busy_s": 20.0,
                "busy_id": "mA",
                "col": {"seq": seq, "op": "all_reduce", "in": in_,
                        "age": 18.0, "cops": cops}}

    comm.pings[0] = (clock["t"], _ping(7, True, 7))
    comm.pings[1] = (clock["t"], _ping(6, False, 6))
    wd.poll_once()
    clock["t"] += 3.0
    comm.pings[0] = (clock["t"], _ping(7, True, 7))
    comm.pings[1] = (clock["t"], _ping(6, False, 6))
    wd.poll_once()   # the doctor reads, never drives, detection
    clock["t"] += 3.0  # past grace: a POLL would run the dump step
    comm.pings[0] = (clock["t"], _ping(7, True, 7))
    comm.pings[1] = (clock["t"], _ping(6, False, 6))
    esc_before = dict(wd.escalations)
    report = hang_report(comm, None, wd, dump_stacks=False)
    # Read-only contract: consulting the doctor must never execute
    # ladder steps (it would interrupt/heal mid-capture otherwise).
    assert wd.escalations == esc_before == {"warn": 1}
    assert "lagging rank(s) [1]" in report
    assert "HUNG [skew]" in report
    assert "#7" in report
    assert "waiting on [0, 1]" in report


# ----------------------------------------------------------------------
# collective_guard progress stream

def test_guard_progress_stream_and_done():
    cg.reset_progress()
    cg.begin_cell([0, 1], world=2)
    try:
        assert cg.progress() is None
        cg.check("all_reduce")
        p = cg.progress()
        assert (p["seq"], p["op"], p["in"], p["cops"]) == \
            (1, "all_reduce", True, 1)
        cg.done("all_reduce")
        p = cg.progress()
        assert p["seq"] == 1 and p["in"] is False
        cg.check("barrier")
        assert cg.progress()["seq"] == 2
        cg.done("barrier")
    finally:
        cg.end_cell()
        # Sequence is monotonic ACROSS cells; cell op count resets.
        cg.begin_cell([0, 1], world=2)
        cg.check("all_reduce")
        p = cg.progress()
        assert p["seq"] == 3 and p["cops"] == 1
        cg.done("all_reduce")
        cg.end_cell()
        cg.reset_progress()


def test_guard_progress_nested_suppression():
    cg.reset_progress()
    cg.begin_cell(None, world=2)
    try:
        cg.check("scatter")
        with cg.nested():
            cg.check("broadcast")      # suppressed
            cg.done("broadcast")       # suppressed
        p = cg.progress()
        assert p["seq"] == 1 and p["op"] == "scatter" and p["in"]
        cg.done("scatter")
        assert cg.progress()["in"] is False
    finally:
        cg.end_cell()
        cg.reset_progress()


def test_guard_freeze_hook_runs_at_entry():
    cg.reset_progress()
    seen = []
    cg.set_freeze_hook(lambda op, seq: seen.append((op, seq)))
    cg.begin_cell(None, world=2)
    try:
        cg.check("all_reduce")
        cg.check("barrier")
        with cg.nested():
            cg.check("broadcast")      # nested: no hook
        assert seen == [("all_reduce", 1), ("barrier", 2)]
    finally:
        cg.end_cell()
        cg.reset_progress()


# ----------------------------------------------------------------------
# FaultPlan collective freeze

def test_fault_plan_freeze_spec_and_one_shot():
    p = FaultPlan(freeze_rank=1, freeze_at=3, freeze_s=42.0)
    q = FaultPlan.from_spec(p.spec())
    assert q.spec() == p.spec()
    assert p.should_freeze(0, 3) is None       # wrong rank
    assert p.should_freeze(1, 2) is None       # not yet
    assert p.should_freeze(1, 3) == 42.0       # fires
    assert p.counters["frozen"] == 1
    assert p.should_freeze(1, 4) is None       # one-shot
    with pytest.raises(ValueError, match="freeze_rank and freeze_at"):
        FaultPlan(freeze_rank=1)
    assert not FaultPlan().has_freeze() and p.has_freeze()


# ----------------------------------------------------------------------
# attach-timeout diagnostics (satellite)

class _DeadProc:
    pid = 4242

    def poll(self):
        return 17


class _LiveProc:
    pid = 4243

    def poll(self):
        return None


class _IO:
    def __init__(self, text):
        self._text = text

    def tail(self, n=8):
        return self._text


def test_startup_diagnostics_fold_exit_codes_and_stdio():
    pm = ProcessManager()
    pm.processes = {0: _LiveProc(), 1: _DeadProc()}
    pm.io = {0: _IO(""), 1: _IO("ImportError: no module named jax\n")}
    text = pm.startup_diagnostics([1])
    assert "rank 1: exited with code 17" in text
    assert "ImportError" in text
    text = pm.startup_diagnostics()
    assert "rank 0: still running (pid 4243" in text
    assert "(no output captured)" in text
    assert "rank 1: exited with code 17" in text


def test_wait_until_ready_timeout_carries_diagnostics():
    class _Comm:
        num_workers = 2

        def wait_for_workers(self, timeout):
            time.sleep(min(timeout, 0.01))
            raise TimeoutError("workers [1] did not attach")

        def connected_ranks(self):
            return [0]

    pm = ProcessManager()
    pm.processes = {0: _LiveProc(), 1: _DeadProc()}
    pm.io = {0: _IO(""), 1: _IO("Traceback: boom at startup\n")}
    # check_startup_failure would raise first for a dead child — that
    # path already carries stdio; bypass it to exercise the timeout
    # path's own diagnostics (rank alive-but-never-attached).
    pm.check_startup_failure = lambda: None
    with pytest.raises(TimeoutError) as err:
        wait_until_ready(_Comm(), pm, timeout_s=0.05, poll_s=0.02)
    msg = str(err.value)
    assert "did not attach" in msg and "budget" in msg
    assert "rank 1: exited with code 17" in msg
    assert "boom at startup" in msg


def test_hang_verdict_cites_preflight_lint_finding(tmp_path,
                                                   monkeypatch):
    """ISSUE 7 loop closure: when the hung cell was flagged by the
    pre-dispatch analyzer, the verdict, the doctor report, and the
    watchdog events all cite the pre-flight finding."""
    from nbdistributed_tpu.analysis import preflight, vet_cell

    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
    preflight.clear()
    hazardous = ("import jax.numpy as jnp\n"
                 "if rank == 1:\n"
                 "    b = all_reduce(jnp.ones(2))\n")
    res = vet_cell(hazardous)
    assert res.errors
    preflight.note("sha-hang", res.findings)

    pol = HangPolicy(skew_s=2, stall_s=60, grace_s=30,
                     escalate=("warn",))
    clock = {"t": time.time()}
    wd = HangWatchdog(pol, clock=lambda: clock["t"])
    comm = FakeComm(2)
    wd._comm = comm
    comm.pending["mH"] = {"type": "execute", "expect": [0, 1],
                          "responded": [], "sent_at": clock["t"] - 5,
                          "cell_sha1": "sha-hang"}

    def _ping(seq, in_):
        return {"busy_type": "execute", "busy_s": 20.0,
                "busy_id": "mH",
                "col": {"seq": seq, "op": "all_reduce", "in": in_,
                        "age": 18.0, "cops": seq}}

    for _ in range(2):
        comm.pings[0] = (clock["t"], _ping(2, True))
        comm.pings[1] = (clock["t"], _ping(1, False))
        wd.poll_once()
        clock["t"] += 3.0
    assert wd.cells_flagged == 1
    st = wd._hangs["mH"]
    assert "rank-conditional-collective" in st.get("preflight", "")
    assert any(e["event"] == "preflight" for e in wd.events)

    report = hang_report(comm, None, wd, dump_stacks=False)
    assert "pre-flight lint flagged this cell" in report
    assert "rank-conditional-collective" in report
    preflight.clear()


def test_hang_verdict_without_preflight_note_has_no_citation(
        tmp_path, monkeypatch):
    from nbdistributed_tpu.analysis import preflight

    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
    preflight.clear()
    pol = HangPolicy(skew_s=2, stall_s=60, grace_s=30,
                     escalate=("warn",))
    clock = {"t": time.time()}
    wd = HangWatchdog(pol, clock=lambda: clock["t"])
    comm = FakeComm(2)
    wd._comm = comm
    comm.pending["mN"] = {"type": "execute", "expect": [0, 1],
                          "responded": [], "sent_at": clock["t"] - 5,
                          "cell_sha1": "sha-unvetted"}

    def _ping(seq, in_):
        return {"busy_type": "execute", "busy_s": 20.0,
                "busy_id": "mN",
                "col": {"seq": seq, "op": "all_reduce", "in": in_,
                        "age": 18.0, "cops": seq}}

    for _ in range(2):
        comm.pings[0] = (clock["t"], _ping(2, True))
        comm.pings[1] = (clock["t"], _ping(1, False))
        wd.poll_once()
        clock["t"] += 3.0
    assert wd.cells_flagged == 1
    assert "preflight" not in wd._hangs["mN"]
    report = hang_report(comm, None, wd, dump_stacks=False)
    assert "pre-flight lint" not in report
