"""Timeline recording tests — measured, not estimated (SURVEY §5.1)."""

import json

from nbdistributed_tpu.magics.timeline import Timeline
from nbdistributed_tpu.messaging import Message


def fake_responses():
    return {
        0: Message(msg_type="response", rank=0,
                   data={"output": "1", "status": "success",
                         "duration_s": 0.25}),
        1: Message(msg_type="response", rank=1,
                   data={"error": "boom", "duration_s": 0.1}),
    }


def test_record_lifecycle():
    tl = Timeline()
    rec = tl.start("x = 1", [0, 1])
    tl.finish(rec, fake_responses())
    assert rec.wall_s >= 0
    assert rec.rank_duration_s == {0: 0.25, 1: 0.1}
    assert rec.rank_status == {0: "success", 1: "error"}


def test_summary_lists_cells():
    tl = Timeline()
    tl.finish(tl.start("first_cell()", [0]), None)
    tl.finish(tl.start("second_cell()", [0, 1]), fake_responses())
    s = tl.summary()
    assert "first_cell" in s and "second_cell" in s
    assert "error" in s


def test_save_roundtrip(tmp_path):
    tl = Timeline()
    tl.finish(tl.start("x", [0]), fake_responses())
    path = tmp_path / "tl.json"
    n = tl.save(str(path))
    assert n == 1
    loaded = json.loads(path.read_text())
    assert loaded["version"] == 1
    assert loaded["records"][0]["code"] == "x"
    assert loaded["records"][0]["rank_duration_s"]["0"] == 0.25


def test_clear():
    tl = Timeline()
    tl.start("x", [0])
    tl.clear()
    assert tl.records == []
    assert "no distributed cells" in tl.summary()


def test_record_local_and_debug_dump():
    tl = Timeline()
    tl.record_local("x = 1", started_at=123.0, wall_s=0.002)
    tl.record_local("boom()", started_at=124.0, wall_s=0.001, ok=False)
    assert [r.kind for r in tl.records] == ["local", "local"]
    assert tl.records[1].rank_status == {-1: "error"}
    dump = tl.debug_dump()
    assert "2 records" in dump and "boom()" in dump


def test_hooks_record_every_cell(capsys):
    """The IPython pre/post_run_cell hooks give the timeline full-
    session coverage: local cells get kind="local" records, cells that
    produced a distributed record are not double-counted, and the hooks
    unregister cleanly (reference: magic.py:123-130, 647-707)."""
    from IPython import get_ipython
    from IPython.testing.globalipapp import start_ipython

    from nbdistributed_tpu.magics.magic import DistributedMagics

    # start_ipython() only returns the shell on its *first* call in a
    # process; later callers (e.g. after the magics e2e suite) get None.
    shell = start_ipython() or get_ipython()
    shell.run_line_magic("load_ext", "nbdistributed_tpu")
    try:
        tl = DistributedMagics._timeline
        tl.clear()
        shell.run_cell("x_local = 41 + 1")
        assert [r.kind for r in tl.records] == ["local"]
        assert "x_local" in tl.records[0].code
        # A cell that created a distributed record must not also add a
        # local one (the distributed record is the richer of the two).
        shell.run_cell(
            "from nbdistributed_tpu.magics.magic import "
            "DistributedMagics as _D\n"
            "_r = _D._timeline.start('fake', [0])\n"
            "_D._timeline.finish(_r, None)")
        assert [r.kind for r in tl.records] == ["local", "distributed"]
        # Failed local cells record an error status.
        shell.run_cell("raise ValueError('nope')")
        assert tl.records[-1].kind == "local"
        assert tl.records[-1].rank_status == {-1: "error"}
        # %timeline_debug prints raw internals including local cells.
        capsys.readouterr()
        shell.run_line_magic("timeline_debug", "")
        out = capsys.readouterr().out
        assert "x_local" in out and '"kind": "local"' in out
    finally:
        DistributedMagics.unregister_cell_hooks()
    n = len(tl.records)
    shell.run_cell("y_after = 1")
    assert len(tl.records) == n, "hooks must be gone after unregister"
