"""Timeline recording tests — measured, not estimated (SURVEY §5.1)."""

import json

from nbdistributed_tpu.magics.timeline import Timeline
from nbdistributed_tpu.messaging import Message


def fake_responses():
    return {
        0: Message(msg_type="response", rank=0,
                   data={"output": "1", "status": "success",
                         "duration_s": 0.25}),
        1: Message(msg_type="response", rank=1,
                   data={"error": "boom", "duration_s": 0.1}),
    }


def test_record_lifecycle():
    tl = Timeline()
    rec = tl.start("x = 1", [0, 1])
    tl.finish(rec, fake_responses())
    assert rec.wall_s >= 0
    assert rec.rank_duration_s == {0: 0.25, 1: 0.1}
    assert rec.rank_status == {0: "success", 1: "error"}


def test_summary_lists_cells():
    tl = Timeline()
    tl.finish(tl.start("first_cell()", [0]), None)
    tl.finish(tl.start("second_cell()", [0, 1]), fake_responses())
    s = tl.summary()
    assert "first_cell" in s and "second_cell" in s
    assert "error" in s


def test_save_roundtrip(tmp_path):
    tl = Timeline()
    tl.finish(tl.start("x", [0]), fake_responses())
    path = tmp_path / "tl.json"
    n = tl.save(str(path))
    assert n == 1
    loaded = json.loads(path.read_text())
    assert loaded["version"] == 1
    assert loaded["records"][0]["code"] == "x"
    assert loaded["records"][0]["rank_duration_s"]["0"] == 0.25


def test_clear():
    tl = Timeline()
    tl.start("x", [0])
    tl.clear()
    assert tl.records == []
    assert "no distributed cells" in tl.summary()
