"""Ulysses all-to-all sequence parallelism vs full attention on the
8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel.ulysses import ulysses_attention

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_mod.make_mesh({"sp": 8})


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full_attention(sp_mesh, causal):
    B, S, H, D = 2, 64, 8, 16  # S shards 8-way; H splits 8-way
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out = ulysses_attention(q, k, v, sp_mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_flash_inner_matches(sp_mesh):
    """The head-parallel layout composes with the Pallas flash kernel
    (interpreter mode on CPU — same code path as TPU)."""
    B, S, H, D = 1, 64, 8, 16
    q, k, v = (rand((B, S, H, D), i + 3) for i in range(3))
    out = ulysses_attention(q, k, v, sp_mesh, causal=True,
                            use_flash=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_output_stays_sequence_sharded(sp_mesh):
    B, S, H, D = 1, 64, 8, 16
    q, k, v = (rand((B, S, H, D), i + 6) for i in range(3))
    out = ulysses_attention(q, k, v, sp_mesh)
    assert len(out.sharding.device_set) == 8


def test_ulysses_long_sequence(sp_mesh):
    B, S, H, D = 1, 512, 8, 32
    q, k, v = (rand((B, S, H, D), i + 9) for i in range(3))
    out = ulysses_attention(q, k, v, sp_mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    q, k, v = (rand((1, 64, 6, 16), i) for i in range(3))
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, sp_mesh)


def test_ulysses_rejects_kv_heads_not_divisible_by_axis(sp_mesh):
    """GQA is native, but Hkv must still split over the mesh axis."""
    q = rand((1, 64, 8, 16), 0)
    kv = rand((1, 64, 4, 16), 1)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, kv, kv, sp_mesh)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ulysses_gqa_native(sp_mesh, use_flash):
    """K/V stay at n_kv_heads through the all-to-alls — exact vs the
    full-attention oracle without any pre-expansion."""
    B, S, H, Hkv, D = 1, 64, 16, 8, 16
    q = rand((B, S, H, D), 20)
    k = rand((B, S, Hkv, D), 21)
    v = rand((B, S, Hkv, D), 22)
    out = ulysses_attention(q, k, v, sp_mesh, causal=True,
                            use_flash=use_flash)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_flash_gradients(sp_mesh):
    """Grads through the flash inner path (Pallas blockwise backward
    under the all-to-alls) match the reference."""
    B, S, H, Hkv, D = 1, 64, 16, 8, 16
    q = rand((B, S, H, D), 30)
    k = rand((B, S, Hkv, D), 31)
    v = rand((B, S, Hkv, D), 32)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, sp_mesh, causal=True,
                                         use_flash=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("use_flash", [False, True])
def test_ulysses_segments_match_reference(use_flash):
    """Packed-document segments through Ulysses (segment ids
    all-gathered over the sp axis): exact vs the masked reference,
    fwd and grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ulysses import ulysses_attention

    B, S, H, Hkv, D = 1, 64, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    seg = jnp.sort(jax.random.randint(ks[3], (B, S), 0, 3), axis=1)
    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    out = ulysses_attention(q, k, v, mesh, causal=True,
                            use_flash=use_flash, segment_ids=seg)
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    gu = jax.grad(lambda q_, k_, v_: jnp.sum(ulysses_attention(
        q_, k_, v_, mesh, causal=True, use_flash=use_flash,
        segment_ids=seg) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q_, k_, v_: jnp.sum(attention_reference(
        q_, k_, v_, causal=True, segment_ids=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gu, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


def test_model_sp_ulysses_packed_matches_plain_packed():
    """Ulysses packed path (all-gathered segment ids over the sp
    axis): sp-ulysses packed loss equals the single-device packed
    loss.  tiny_config has H=4, Hkv=2 -> sp=2 divides both."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nbdistributed_tpu.models import (SeqParallel, init_params,
                                          loss_fn, tiny_config)
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = mesh_mod.make_mesh({"sp": 2}, devices=jax.devices()[:2])
    S = 32
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0,
                             cfg.vocab_size)
    seg = jnp.sort(jax.random.randint(jax.random.PRNGKey(2),
                                      (2, S), 0, 3), axis=1)
    batch = {"tokens": tok, "segments": seg}
    ref = float(loss_fn(params, batch, cfg))
    sp = SeqParallel(mesh=mesh, axis="sp", method="ulysses",
                     use_flash=False)
    got = float(loss_fn(params, batch, cfg, sp=sp))
    np.testing.assert_allclose(got, ref, rtol=1e-5)
