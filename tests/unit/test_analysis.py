"""Static-analysis tests (ISSUES 7 + 9): the pre-dispatch SPMD cell
analyzer (rule-by-rule, plus the never-block-on-unparseable contract),
the IPython source-stripping helper, the preflight finding memory, the
env-knob registry accessors, the framework self-lint passes, and the
ISSUE 9 effect-inference engine (name/collective footprints, opacity,
the session dependency DAG) — including the acceptance gates: the
PR 5 frozen-rank hang cell is an error pre-dispatch AND carries a
non-empty ordered collective footprint, the analyzer has zero
error-severity false positives over the examples/ notebooks and the
selftest corpus, every one of those cells gets a parseable non-opaque
EffectReport, and ``run_self_lint`` is clean over this very checkout
(the CI ``static-analysis`` job as a test) — now covering the gateway
classes and the ``_locked`` helper convention."""

import ast
import json
import os

import pytest

from nbdistributed_tpu.analysis import (cellcheck, ipycompat, preflight,
                                        strip_ipython, vet_cell)
from nbdistributed_tpu.analysis.effects import (collective_class,
                                                infer_effects)
from nbdistributed_tpu.analysis.selfcheck import (_ThreadPass,
                                                  check_env_knobs,
                                                  run_self_lint)
from nbdistributed_tpu.utils import knobs

pytestmark = [pytest.mark.unit, pytest.mark.lint]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The exact cell shape tests/integration/test_hang_watchdog.py wedges:
# rank 1's in-branch all_reduce is collective #2 for rank 1 only.
HANG_CELL = """
import jax.numpy as jnp
a = all_reduce(jnp.ones(2))        # collective #1: both ranks join
if rank == 1:
    b = all_reduce(a)              # collective #2: frozen by the plan
'done-%d' % rank
"""


def rules(res, severity=None):
    return [f.rule for f in res.findings
            if severity is None or f.severity == severity]


# ----------------------------------------------------------------------
# rank-conditional collectives


def test_frozen_rank_hang_cell_is_an_error_pre_dispatch():
    res = vet_cell(HANG_CELL)
    assert res.parsed
    errs = res.errors
    assert [f.rule for f in errs] == ["rank-conditional-collective"]
    # The finding points at the in-branch collective, not the safe one.
    assert errs[0].line == 5
    assert "all_reduce" in errs[0].message


def test_process_index_branch_flagged():
    res = vet_cell("if jax.process_index() == 0:\n    barrier()")
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_while_on_rank_flagged():
    res = vet_cell("while rank < 1:\n    x = all_reduce(x)")
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_ternary_on_rank_flagged():
    res = vet_cell("x = all_reduce(y) if rank == 0 else y")
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_rank_conditional_collective_inside_def_body():
    # A def body runs when every rank calls it — the branch inside
    # still diverges, including through the return value expression.
    res = vet_cell("def step():\n"
                   "    if rank == 0:\n"
                   "        return all_reduce(x)")
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_match_on_rank_flagged():
    res = vet_cell("match rank:\n"
                   "    case 0:\n"
                   "        all_reduce(x)\n"
                   "    case _:\n"
                   "        pass\n")
    assert rules(res, "error") == ["rank-conditional-collective"]
    # A rank-dependent case GUARD diverges the same way.
    res = vet_cell("match mode:\n"
                   "    case 'a' if rank == 0:\n"
                   "        barrier()\n")
    assert rules(res, "error") == ["rank-conditional-collective"]
    # Uniform subject, uniform guards: clean.
    assert not vet_cell("match mode:\n"
                        "    case 'a':\n"
                        "        x = all_reduce(x)\n").findings


def test_match_on_rank_exit_desyncs():
    res = vet_cell("match rank:\n"
                   "    case 0:\n"
                   "        raise ValueError('x')\n"
                   "y = all_reduce(x)\n")
    assert rules(res, "error") == ["rank-conditional-exit"]


def test_uniform_condition_is_clean():
    assert not vet_cell(
        "if step % 10 == 0:\n    x = all_reduce(x)").findings


def test_collective_outside_branch_is_clean():
    assert not vet_cell(
        "x = all_reduce(x)\nif rank == 0:\n    print('saved')"
    ).errors


def test_rank_conditional_def_definition_is_not_a_collective():
    # Defining a helper under a rank branch executes no collective.
    res = vet_cell("if rank == 0:\n"
                   "    def helper():\n"
                   "        return all_reduce(x)")
    assert "rank-conditional-collective" not in rules(res, "error")


# ----------------------------------------------------------------------
# rank-conditional exits


def test_raise_before_collectives_desyncs():
    res = vet_cell("if rank == 0:\n"
                   "    raise ValueError('x')\n"
                   "y = all_reduce(x)")
    assert rules(res, "error") == ["rank-conditional-exit"]


def test_raise_after_last_collective_is_clean():
    assert not vet_cell("x = all_reduce(x)\n"
                        "if rank == 0:\n"
                        "    raise ValueError(str(x))").errors


def test_break_skipping_loop_collectives_desyncs():
    res = vet_cell("for i in range(5):\n"
                   "    if rank == 1:\n"
                   "        break\n"
                   "    x = all_reduce(x)")
    assert rules(res, "error") == ["rank-conditional-exit"]


def test_break_in_while_training_loop_desyncs():
    # The most common SPMD loop shape: collectives at the top of a
    # while body, rank-conditional break below — the break skips the
    # remaining ITERATIONS' collectives.
    res = vet_cell("while step < 10:\n"
                   "    g = all_reduce(g)\n"
                   "    if rank == 0:\n"
                   "        break")
    assert rules(res, "error") == ["rank-conditional-exit"]


def test_break_on_uniform_condition_is_clean():
    assert not vet_cell("for i in range(5):\n"
                        "    if done:\n"
                        "        break\n"
                        "    x = all_reduce(x)").errors


# ----------------------------------------------------------------------
# subset rankspec vs collectives


def test_subset_collective_call_is_an_error():
    res = vet_cell("y = all_reduce(x)", ranks=[0], world=4)
    assert rules(res, "error") == ["subset-collective"]


def test_subset_bare_reference_is_a_warning():
    res = vet_cell("alias = all_reduce", ranks=[0], world=4)
    assert rules(res) == ["subset-collective-ref"]
    assert not res.errors


def test_subset_collective_inside_def_is_a_warning():
    res = vet_cell("def f():\n    return all_reduce(x)",
                   ranks=[0, 2], world=4)
    assert "subset-collective" in rules(res, "warning")
    assert not res.errors


def test_full_world_collective_is_clean():
    assert not vet_cell("y = all_reduce(x)",
                        ranks=[0, 1, 2, 3], world=4).findings
    # Duplicate rank listings still cover the world.
    assert not vet_cell("y = all_reduce(x)",
                        ranks=[0, 0, 1], world=2).findings


# ----------------------------------------------------------------------
# host syncs in loops (perf lints stay warnings)


@pytest.mark.parametrize("cell", [
    "for i in range(10):\n    tot += loss.item()",
    "while True:\n    y = jax.device_get(x)",
    "for i in range(3):\n    print(loss)",
    "for i in range(3):\n    vals = x.tolist()",
])
def test_host_sync_in_loop_warns(cell):
    res = vet_cell(cell)
    assert rules(res) == ["host-sync-in-loop"]
    assert not res.errors


def test_host_sync_outside_loop_is_clean():
    assert not vet_cell("tot = loss.item()\nprint(loss)").findings


def test_constant_print_in_loop_is_clean():
    assert not vet_cell("for i in range(3):\n    print('step')"
                        ).findings


# ----------------------------------------------------------------------
# namespace hazards


@pytest.mark.parametrize("cell", [
    "rank = 5",
    "del all_reduce",
    "from mymod import rank",
    "def all_reduce():\n    pass",
    "for rank in range(3):\n    pass",
])
def test_framework_name_shadowing_warns(cell):
    res = vet_cell(cell)
    assert rules(res) == ["namespace-shadow"]
    assert not res.errors


def test_idiomatic_reimports_are_not_hazards():
    assert not vet_cell("import jax\n"
                        "import jax.numpy as jnp\n"
                        "import numpy as np").findings


def test_attribute_and_subscript_writes_are_not_shadowing():
    assert not vet_cell("cfg.rank = 3\nstate['rank'] = 4").findings


# ----------------------------------------------------------------------
# contracts: never block on unparseable, never raise, ordering


def test_unparseable_source_reports_parsed_false_and_no_findings():
    res = vet_cell("def f(:")
    assert not res.parsed and res.findings == []


def test_vet_never_raises_on_weird_input():
    for src in ("", "\x00", "  ", "\n\n", "ловлю = 1",
                "x = " + "(" * 200 + "1" + ")" * 200):
        vet_cell(src, ranks=[0], world=2)


def test_errors_sort_before_warnings_and_dedup():
    res = vet_cell("for i in range(4):\n"
                   "    print(loss)\n"
                   "if rank == 0:\n"
                   "    y = all_reduce(x)\n")
    sevs = [f.severity for f in res.findings]
    assert sevs == sorted(sevs, key=lambda s: 0 if s == "error" else 1)
    keys = [(f.rule, f.line, f.col) for f in res.findings]
    assert len(keys) == len(set(keys))


# ----------------------------------------------------------------------
# ipycompat: line-preserving IPython stripping


def test_strip_line_magic_and_shell_escape_keep_line_numbers():
    src = "%time x = 1\n!pip list\ny = all_reduce(x) if rank==0 else 2"
    cleaned = strip_ipython(src)
    assert cleaned.splitlines()[0] == "pass"
    assert cleaned.splitlines()[1] == "pass"
    res = vet_cell(src)
    assert res.errors and res.errors[0].line == 3


def test_strip_assignment_escape_and_help_suffix():
    cleaned = strip_ipython("files = !ls\nobj.method??\nx = 1")
    lines = cleaned.splitlines()
    assert lines[0] == "pass" and lines[1] == "pass"
    assert lines[2] == "x = 1"
    ast.parse(cleaned)


def test_strip_preserves_indentation():
    cleaned = strip_ipython("for i in range(2):\n    %time f(i)")
    assert cleaned.splitlines()[1] == "    pass"
    ast.parse(cleaned)


def test_modulo_continuation_line_survives():
    src = "y = (x\n% b)"
    assert strip_ipython(src) == src


def test_pure_python_returns_identity():
    src = "a = 1\nb = a % 2\n"
    assert strip_ipython(src) is src


def test_string_literals_are_not_ipython_syntax():
    # A shell-looking line INSIDE a triple-quoted string is data; the
    # cell parses as-is and must come back verbatim — corrupting the
    # string would turn the cell unparseable and blind the vetting.
    src = ('cmd = """\n'
           '!pip install foo\n'
           '"""\n'
           'if rank == 0:\n'
           '    all_reduce(x)\n')
    assert strip_ipython(src) == src
    res = vet_cell(src)
    assert res.parsed
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_mixed_magic_and_multiline_string():
    # A real magic line alongside a multi-line string whose interior
    # line starts with '!': only the magic line is rewritten.
    src = ('%time x = 1\n'
           'tmpl = """\n'
           '!do-not-touch\n'
           '"""\n'
           'if rank == 0:\n'
           '    all_reduce(x)\n')
    cleaned = strip_ipython(src)
    lines = cleaned.splitlines()
    assert lines[0] == "pass"
    assert lines[2] == "!do-not-touch"
    res = vet_cell(src)
    assert res.parsed
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_cell_magic_line_stripped():
    cleaned = strip_ipython("%%time\nx = 1")
    assert cleaned.splitlines()[0] == "pass"
    ast.parse(cleaned)


def test_is_ipython_line_classifier():
    assert ipycompat._is_ipython_line("%time f()")
    assert ipycompat._is_ipython_line("!ls")
    assert ipycompat._is_ipython_line("obj?")
    assert not ipycompat._is_ipython_line("x = y % z")
    assert not ipycompat._is_ipython_line("")


# ----------------------------------------------------------------------
# preflight memory (the "analyzer told you so" loop)


def test_preflight_note_and_lookup_roundtrip():
    preflight.clear()
    res = vet_cell(HANG_CELL)
    preflight.note("sha-abc", res.findings)
    entry = preflight.lookup("sha-abc")
    assert entry is not None
    assert entry["errors"] == 1
    assert "rank-conditional-collective" in entry["rules"]
    assert "rank-conditional-collective" in entry["summary"]
    assert preflight.lookup("sha-unknown") is None
    assert preflight.lookup(None) is None
    preflight.clear()
    assert preflight.lookup("sha-abc") is None


def test_preflight_empty_findings_not_noted():
    preflight.clear()
    preflight.note("sha-clean", [])
    assert preflight.lookup("sha-clean") is None


def test_preflight_is_bounded():
    preflight.clear()
    findings = vet_cell(HANG_CELL).findings
    for i in range(preflight._MAX + 10):
        preflight.note(f"sha-{i}", findings)
    assert preflight.lookup("sha-0") is None          # evicted
    assert preflight.lookup(f"sha-{preflight._MAX + 9}") is not None
    preflight.clear()


def test_summarize_puts_errors_first():
    res = vet_cell("for i in range(3):\n"
                   "    print(loss)\n"
                   "    if rank == 0:\n"
                   "        x = all_reduce(x)")
    s = preflight.summarize(res.findings)
    assert s.startswith("[rank-conditional-collective]")
    assert "more finding" in s


# ----------------------------------------------------------------------
# env-knob registry accessors


def test_undeclared_knob_read_fails_fast():
    with pytest.raises(KeyError, match="NBD_TOTALLY_BOGUS"):
        knobs.get_raw("NBD_TOTALLY_BOGUS")


def test_knob_accessor_semantics():
    env = {"NBD_HANG": "off", "NBD_HANG_SKEW_S": "2.5",
           "NBD_FLIGHT_RING_BYTES": "1024",
           "NBD_ORPHAN_TTL_S": "soon"}
    assert knobs.get_bool("NBD_HANG", True, env=env) is False
    assert knobs.get_bool("NBD_FLIGHT", True, env=env) is True
    assert knobs.get_float("NBD_HANG_SKEW_S", 20.0, env=env) == 2.5
    assert knobs.get_int("NBD_FLIGHT_RING_BYTES", 0, env=env) == 1024
    # Typo'd numeric knobs degrade to the default, never crash.
    assert knobs.get_float("NBD_ORPHAN_TTL_S", 600.0, env=env) == 600.0
    assert knobs.get_str("NBD_RUN_DIR", "-", env=env) == "-"


def test_knob_table_documents_every_knob():
    table = knobs.knob_table_markdown()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table


# ----------------------------------------------------------------------
# framework self-lint (the CI static-analysis gate, as a test)


def test_self_lint_clean_on_this_checkout():
    results = run_self_lint(REPO)
    # All TEN passes, none skippable: the four registry/discipline
    # passes, the three concur lock passes, and the three ISSUE 15
    # lifecycle passes.
    assert set(results) == {"env-knobs", "codec-headers",
                            "thread-shared-state",
                            "protocol-coverage", "lock-order",
                            "blocking-under-lock",
                            "callback-under-lock",
                            "resource-leak", "bracket-discipline",
                            "shutdown-completeness"}
    for name, findings in results.items():
        assert findings == [], (
            f"[{name}] " + "; ".join(f.render() for f in findings))


def test_cli_repo_root_resolution(tmp_path, monkeypatch):
    from nbdistributed_tpu.analysis.cli import _repo_root, main
    assert _repo_root("/explicit/x") == "/explicit/x"
    # This checkout: README.md sits next to the package dir.
    assert _repo_root(None) == REPO
    # No checkout anywhere (package parent is faked away, cwd bare):
    # --self must refuse with a clear exit code, not flag every knob
    # as undocumented against a missing README.
    monkeypatch.chdir(tmp_path)
    import nbdistributed_tpu
    monkeypatch.setattr(nbdistributed_tpu, "__file__",
                        str(tmp_path / "site-packages"
                            / "nbdistributed_tpu" / "__init__.py"))
    assert _repo_root(None) is None
    assert main(["--self"]) == 2


def test_env_knob_pass_catches_undeclared_knob(tmp_path):
    pkg = tmp_path / "nbdistributed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "mod.py").write_text(
        "import os\nX = os.environ.get('NBD_BOGUS_KNOB')\n")
    findings = check_env_knobs(str(tmp_path))
    assert any(f.rule == "env-knob" and "NBD_BOGUS_KNOB" in f.message
               for f in findings)


def _thread_findings(src, exempt=None):
    tree = ast.parse(src)
    cls = tree.body[0]
    fn = [n for n in cls.body if isinstance(n, ast.FunctionDef)
          and n.name != "__init__"][0]
    p = _ThreadPass("x.py", cls.name, {"counts"}, exempt or {})
    p.visit(fn)
    return p.findings


_THREAD_SRC = """
class C:
    def __init__(self):
        self._lock = None
        self.counts = dict()
    def bump(self):
        <BODY>
"""


def test_thread_pass_flags_unlocked_mutation():
    src = _THREAD_SRC.replace("<BODY>", "self.counts['a'] = 1")
    assert _thread_findings(src)
    src = _THREAD_SRC.replace("<BODY>", "self.n += 1")
    assert _thread_findings(src)


def test_thread_pass_accepts_locked_mutation_and_exemptions():
    src = _THREAD_SRC.replace(
        "<BODY>", "with self._lock:\n            self.counts['a'] = 1")
    assert not _thread_findings(src)
    src = _THREAD_SRC.replace("<BODY>", "self.n += 1")
    assert not _thread_findings(src, exempt={"C.n": "single writer"})


# ----------------------------------------------------------------------
# acceptance corpus: zero error-severity false positives


def _notebook_cells(path):
    with open(path, encoding="utf-8") as f:
        nb = json.load(f)
    for cell in nb.get("cells", []):
        if cell.get("cell_type") == "code":
            yield "".join(cell.get("source", []))


def _subset_context(src, world):
    """Mirror the magic layer: a leading ``%%rank [spec]`` arms the
    subset rule with the parsed ranks."""
    from nbdistributed_tpu.magics import rankspec
    first = src.splitlines()[0].strip() if src.strip() else ""
    if first.startswith("%%rank"):
        spec = first[len("%%rank"):].strip()
        try:
            return rankspec.parse_ranks(spec, world), world
        except rankspec.RankSpecError:
            return None, None
    return None, world


@pytest.mark.parametrize("nb", ["00_quickstart.ipynb",
                                "01_parallelism.ipynb",
                                "02_finetune.ipynb"])
def test_no_error_false_positives_in_example_notebooks(nb):
    path = os.path.join(REPO, "examples", nb)
    bad = []
    for i, src in enumerate(_notebook_cells(path)):
        ranks, world = _subset_context(src, world=2)
        res = vet_cell(src, ranks=ranks, world=world)
        for f in res.errors:
            bad.append(f"{nb} cell {i} L{f.line}: [{f.rule}] "
                       f"{f.snippet.strip()}")
    assert not bad, "\n".join(bad)


def _selftest_cells():
    """Every cell the selftest dispatches: the inline one-liners plus
    the big ``*_cell`` string assignments, extracted from the module
    source so the corpus cannot drift from the code."""
    path = os.path.join(REPO, "nbdistributed_tpu", "selftest.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    cells = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id.endswith("_cell")
                        for t in node.targets)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            cells.append(node.value.value)
        # Inline cells: string literals passed to send_to_all /
        # send_to_ranks "execute" calls.
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("send_to_all", "send_to_ranks")):
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and arg.value not in ("execute", "sync",
                                              "get_status",
                                              "checkpoint", "trace",
                                              "metrics"):
                    cells.append(arg.value)
    assert len(cells) >= 8
    return cells


def test_no_error_false_positives_in_selftest_corpus():
    bad = []
    for i, src in enumerate(_selftest_cells()):
        res = vet_cell(src, ranks=None, world=2)
        for f in res.errors:
            bad.append(f"selftest cell {i} L{f.line}: [{f.rule}] "
                       f"{f.snippet.strip()}")
    assert not bad, "\n".join(bad)


def test_integration_hang_cells_classified_correctly():
    # The deliberately-hazardous watchdog cell IS an error…
    assert vet_cell(HANG_CELL).errors
    # …while its companions (uniformly slow, rank-local infinite
    # loop, post-hang realignment) carry no error findings.
    clean = [
        "import time\ntime.sleep(0.5)\n'slow-%d' % rank",
        "if rank == 1:\n    while True:\n        pass\n'ok-%d' % rank",
        "float(all_reduce(jnp.ones(2))[0])",
    ]
    for src in clean:
        assert not vet_cell(src).errors, src


# ----------------------------------------------------------------------
# magic-layer wiring: _vet_cell gates dispatch


@pytest.fixture
def magic(monkeypatch, tmp_path):
    """A DistributedMagics instance with a fake 2-rank world and no
    IPython shell — enough surface for the pre-dispatch vet path."""
    from nbdistributed_tpu.magics.magic import DistributedMagics
    monkeypatch.setenv("NBD_FLIGHT", "0")
    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path))
    monkeypatch.setattr(DistributedMagics, "_world", 2)
    monkeypatch.setattr(DistributedMagics, "_lint_mode", "warn")
    preflight.clear()
    yield DistributedMagics.__new__(DistributedMagics)
    preflight.clear()


def test_magic_warn_mode_annotates_and_dispatches(magic, capsys):
    from nbdistributed_tpu.runtime.collective_guard import cell_hash
    assert magic._vet_cell(HANG_CELL, [0, 1]) is True
    out = capsys.readouterr().out
    assert "rank-conditional-collective" in out
    # Dispatched-despite-findings cells are remembered by hash so a
    # later hang verdict cites the pre-flight finding.
    note = preflight.lookup(cell_hash(HANG_CELL))
    assert note is not None and note["errors"] == 1


def test_magic_strict_mode_blocks_error_cells(magic, capsys):
    from nbdistributed_tpu.magics.magic import DistributedMagics
    DistributedMagics._lint_mode = "strict"
    assert magic._vet_cell(HANG_CELL, [0, 1]) is False
    assert "NOT dispatched" in capsys.readouterr().out
    # Warnings alone never block, even under strict.
    assert magic._vet_cell(
        "for i in range(3):\n    print(loss)", [0, 1]) is True


def test_magic_per_cell_strict_flag_blocks(magic):
    assert magic._vet_cell(HANG_CELL, [0, 1], strict=True) is False


def test_magic_off_mode_skips_analysis(magic, capsys):
    from nbdistributed_tpu.magics.magic import DistributedMagics
    DistributedMagics._lint_mode = "off"
    assert magic._vet_cell(HANG_CELL, [0, 1]) is True
    assert capsys.readouterr().out == ""


def test_magic_per_cell_strict_overrides_off_mode(magic, capsys):
    # An explicit `%%distributed --strict` must vet (and block) even
    # when the session mode is off — the flag is a per-cell request.
    from nbdistributed_tpu.magics.magic import DistributedMagics
    DistributedMagics._lint_mode = "off"
    assert magic._vet_cell(HANG_CELL, [0, 1], strict=True) is False
    assert "NOT dispatched" in capsys.readouterr().out


def test_magic_unparseable_never_blocks_even_strict(magic, capsys):
    from nbdistributed_tpu.magics.magic import DistributedMagics
    DistributedMagics._lint_mode = "strict"
    assert magic._vet_cell("def f(:", [0, 1]) is True
    # Unparseable subset cells degrade to the legacy regex warning.
    assert magic._vet_cell("def f(:\nall_reduce(x)", [0]) is True
    assert "deadlock" in capsys.readouterr().out.lower()


def test_magic_findings_counted_in_metrics(magic):
    from nbdistributed_tpu.observability import metrics as obs_metrics
    c = obs_metrics.registry().counter(
        "nbd_lint_findings_total",
        "pre-dispatch cell-vetting findings",
        {"rule": "rank-conditional-collective"})
    before = c.value
    magic._vet_cell(HANG_CELL, [0, 1])
    assert c.value == before + 1


def test_magic_lint_mode_resolution(magic, monkeypatch):
    from nbdistributed_tpu.magics.magic import DistributedMagics
    DistributedMagics._lint_mode = None
    monkeypatch.setenv("NBD_LINT", "strict")
    assert DistributedMagics._lint_mode_now() == "strict"
    monkeypatch.setenv("NBD_LINT", "bogus")
    assert DistributedMagics._lint_mode_now() == "warn"
    DistributedMagics._lint_mode = "off"       # %dist_lint pin wins
    assert DistributedMagics._lint_mode_now() == "off"


# ----------------------------------------------------------------------
# ISSUE 9: effect inference — name footprint


def test_name_footprint_binds_mutations_deletes():
    r = infer_effects("x = a + b\n"
                      "c.cfg = 2\n"
                      "d[k] = 3\n"
                      "lst.append(9)\n"
                      "e += 1\n"
                      "del f\n")
    assert r.parsed and not r.opaque
    assert {"a", "b", "c", "d", "e", "k", "lst"} <= r.reads
    assert r.writes == {"x", "e"}
    assert r.mutates == {"c", "d", "lst"}
    assert r.deletes == {"f"}
    # touched = the DAG's write side.
    assert r.touched == {"x", "e", "c", "d", "lst", "f"}


def test_footprint_free_reads_exclude_cell_local_bindings():
    r = infer_effects("x = 1\ny = x + z")
    assert "x" not in r.reads          # bound before the read
    assert "z" in r.reads
    # …but a deleted name read later is free again.
    r = infer_effects("x = 1\ndel x\ny = x")
    assert "x" in r.reads


def test_footprint_global_escape_and_augassign():
    r = infer_effects("def bump():\n"
                      "    global counter\n"
                      "    counter = counter + 1\n"
                      "bump()")
    assert "counter" in r.writes       # escapes the def
    assert "counter" in r.reads
    r = infer_effects("tot += loss")
    assert "tot" in r.writes and "tot" in r.reads


def test_footprint_imports_and_walrus_and_for_target():
    r = infer_effects("import numpy as np\n"
                      "from math import sqrt\n"
                      "for i in range(3):\n"
                      "    pass\n"
                      "n = (m := 7)\n")
    assert {"np", "sqrt", "i", "n", "m"} <= r.writes


def test_comprehension_scope_not_module_writes():
    r = infer_effects("ys = [w * xi for xi in xs]")
    assert "xi" not in r.writes
    assert {"w", "xs"} <= r.reads and "ys" in r.writes
    assert r.collective_verdict == "none"


@pytest.mark.parametrize("cell,why", [
    ("exec('x=1')", "exec"),
    ("y = eval(s)", "eval"),
    ("from jax.numpy import *", "star-import"),
    ("globals()['q'] = 7", "globals"),
    ("vars().update(d)", "vars"),
])
def test_dynamic_escapes_are_opaque(cell, why):
    r = infer_effects(cell)
    assert r.opaque, cell
    assert any(why in reason for reason in r.opaque_reasons)
    assert collective_class(r) == "unknown"


def test_unparseable_source_is_opaque_not_raised():
    r = infer_effects("def f(:")
    assert not r.parsed and r.opaque
    assert collective_class(r) == "unknown"


def test_reading_globals_is_not_opaque():
    r = infer_effects("names = sorted(globals())")
    assert not r.opaque


# ----------------------------------------------------------------------
# ISSUE 9: effect inference — collective footprint


def test_collective_footprint_ordered_sites():
    r = infer_effects(HANG_CELL)
    assert r.parsed and not r.opaque
    assert [s.op for s in r.collectives] == ["all_reduce",
                                             "all_reduce"]
    lines = [s.line for s in r.collectives]
    assert lines == sorted(lines) and len(set(lines)) == 2
    assert r.collectives[1].conditional
    assert r.collective_verdict == "exact"
    assert collective_class(r) == "bearing"


def test_proven_free_cell():
    r = infer_effects("import time\n"
                      "time.sleep(0.5)\n"
                      "zz = sorted([3, 1])\n"
                      "zz")
    assert r.collective_verdict == "none"
    assert collective_class(r) == "free"
    assert r.collective_free


def test_safe_roots_and_builtins_stay_free():
    r = infer_effects("import numpy as np\n"
                      "a = np.ones(3)\n"
                      "b = jnp.ones(3).sum()\n"
                      "c = math.sqrt(float(len(str(2))))\n"
                      "hist = []\nhist.append(c)")
    assert r.collective_verdict == "none", r.taints


def test_unvetted_calls_taint_to_unknown():
    r = infer_effects("y = train_step(x)")
    assert r.collective_verdict == "unknown"
    assert any("train_step" in t for t in r.taints)
    assert collective_class(r) == "unknown"
    # jax.* is NOT a safe root: jitted products can hide collectives.
    r = infer_effects("f = jax.jit(g)")
    assert r.collective_verdict == "unknown"


def test_same_cell_def_resolved_one_level():
    r = infer_effects("def step(x):\n"
                      "    return all_reduce(x) + 1\n"
                      "y = step(y0)")
    assert [s.op for s in r.collectives] == ["all_reduce"]
    assert r.collectives[0].via == "step"
    assert r.collective_verdict == "exact"


def test_nested_def_call_taints_and_recursion_terminates():
    r = infer_effects("def inner(x):\n"
                      "    return other(x)\n"
                      "def outer(x):\n"
                      "    return inner(x)\n"
                      "outer(1)")
    assert r.collective_verdict == "unknown"
    assert any("one level deep" in t for t in r.taints)
    # A recursive def must terminate with an honest unknown, not
    # recurse forever.
    r = infer_effects("def f(n):\n    return f(n - 1)\nf(3)")
    assert r.collective_verdict == "unknown"


def test_uncalled_def_with_collective_is_free():
    # Defining a helper runs nothing; only a CALL reaches the mesh.
    r = infer_effects("def helper(x):\n    return all_reduce(x)")
    assert r.collectives == ()
    assert r.collective_verdict == "none"


def test_def_escaping_as_argument_is_classified():
    """A def passed INTO a call escapes: the callee may invoke it, so
    its collectives run with no visible site — `list(map(step, data))`
    must not be falsely proven free."""
    r = infer_effects("def step(x):\n"
                      "    return psum(x)\n"
                      "list(map(step, data))")
    assert r.collective_verdict == "unknown"
    assert any("step" in t and "passed to a call" in t
               for t in r.taints)
    # Precision kept: a PROVABLY free body may escape anywhere.
    r = infer_effects("def key(x):\n"
                      "    return x + 1\n"
                      "zz = sorted(data, key=key)")
    assert r.collective_verdict == "none", r.taints
    # A def escaping before/outside its (conditional) statement has no
    # resolvable body — taint, never guess.
    r = infer_effects("if flag:\n"
                      "    def f(x):\n"
                      "        return all_reduce(x)\n"
                      "list(map(f, xs))")
    assert r.collective_verdict == "unknown"
    # Recursive escape terminates with an honest unknown.
    r = infer_effects("def f(x):\n"
                      "    return list(map(f, x))\n"
                      "f(q)")
    assert r.collective_verdict == "unknown"


def test_def_alias_and_shadowed_builtin_escapes():
    """`g = step` must carry step's classification (aliases escape
    the same way defs do), and a rebound builtin must stay rebound
    inside escape-checked bodies."""
    r = infer_effects("def step(x):\n"
                      "    return psum(x)\n"
                      "g = step\n"
                      "zz = sorted(xs, key=g)")
    assert r.collective_verdict == "unknown", r.taints
    assert infer_effects("def step(x):\n"
                         "    return -x\n"
                         "g = step\n"
                         "zz = sorted(xs, key=g)"
                         ).collective_verdict == "none"
    # Alias chains, and aliases CALLED directly, resolve the body.
    r = infer_effects("def step(x):\n"
                      "    return psum(x)\n"
                      "g = step\nh = g\nlist(map(h, xs))")
    assert r.collective_verdict == "unknown"
    r = infer_effects("def step(x):\n"
                      "    return psum(x)\n"
                      "g = step\ng(x0)")
    assert r.collective_verdict == "exact"
    # `float = bad_fn` earlier in the cell: the escaped body's
    # `float(x)` call is no longer a provably inert builtin.
    r = infer_effects("float = bad_fn\n"
                      "def step(x):\n"
                      "    return float(x)\n"
                      "list(map(step, xs))")
    assert r.collective_verdict == "unknown"


def test_class_decorator_application_is_classified():
    r = infer_effects("@my_decorator\nclass C:\n    pass")
    assert r.collective_verdict == "unknown"
    assert any("class decorator" in t for t in r.taints)
    # Safe-module class decorators introspect only — still provable,
    # in both bare and factory form.
    assert infer_effects("from dataclasses import dataclass\n"
                         "@dataclass\nclass C:\n    x: int = 0"
                         ).collective_verdict == "none"
    assert infer_effects("from dataclasses import dataclass\n"
                         "@dataclass(frozen=True)\n"
                         "class C:\n    x: int = 0"
                         ).collective_verdict == "none"


def test_lambda_escape_and_lambda_assignment():
    r = infer_effects("zz = sorted(xs, key=lambda a: all_reduce(a))")
    assert r.collective_verdict == "unknown"
    assert any("lambda" in t for t in r.taints)
    assert infer_effects("zz = sorted(xs, key=lambda a: a[0])"
                         ).collective_verdict == "none"
    # A lambda-assigned name is a same-cell function definition: it
    # resolves at calls and escape-checks as an argument.
    r = infer_effects("g = lambda x: all_reduce(x)\nlist(map(g, xs))")
    assert r.collective_verdict == "unknown"
    assert infer_effects("g = lambda x: x + 1\nlist(map(g, xs))"
                         ).collective_verdict == "none"
    r = infer_effects("g = lambda x: all_reduce(x)\ny = g(x0)")
    assert [s.op for s in r.collectives] == ["all_reduce"]
    # Annotated-assign and walrus lambda bindings are the same hole.
    assert infer_effects("g: object = lambda x: all_reduce(x)\n"
                         "list(map(g, xs))"
                         ).collective_verdict == "unknown"
    assert infer_effects("y = (g := (lambda x: all_reduce(x)))\n"
                         "list(map(g, xs))"
                         ).collective_verdict == "unknown"
    assert infer_effects("g: object = lambda x: -x\nlist(map(g, xs))"
                         ).collective_verdict == "none"


def test_decorator_application_is_classified():
    """`@dec` calls `dec(f)` at definition time — a call site, not an
    expression read (the `@my_decorator` false-free)."""
    r = infer_effects("@my_decorator\ndef g():\n    pass")
    assert r.collective_verdict == "unknown"
    assert any("my_decorator" in t for t in r.taints)
    # Safe-module decorator over a provably free body stays proven.
    r = infer_effects("import functools\n"
                      "@functools.cache\n"
                      "def f():\n    return 1\n"
                      "v = f()")
    assert r.collective_verdict == "none", r.taints
    # …but not over a collective-bearing body (the product calls it).
    r = infer_effects("import functools\n"
                      "@functools.cache\n"
                      "def f():\n    return all_reduce(x)")
    assert r.collective_verdict == "unknown"
    # Factory form: the product that wraps f is a dynamic callee.
    r = infer_effects("@retry(3)\ndef f():\n    pass")
    assert r.collective_verdict == "unknown"
    # A same-cell decorator may return ANYTHING: later calls to the
    # decorated name must not resolve the raw body.
    r = infer_effects("def deco(fn):\n"
                      "    return other_fn\n"
                      "@deco\ndef f():\n    pass\n"
                      "f()")
    assert r.collective_verdict == "unknown"
    # Descriptor builtins never invoke at application time: defining
    # a class with decorated methods stays proven free.
    r = infer_effects("class C:\n"
                      "    @staticmethod\n"
                      "    def m(x):\n"
                      "        return x + 1\n"
                      "    @property\n"
                      "    def v(self):\n"
                      "        return self._v")
    assert r.collective_verdict == "none", r.taints


def test_call_before_def_resolves_earlier_binding():
    """Resolution honors source order: `f = g; f(); def f(): pass`
    invokes g at runtime — the later (collective-free) body proves
    nothing about the call."""
    r = infer_effects("f = unvetted_fn\nf()\ndef f():\n    pass")
    assert r.collective_verdict == "unknown"
    assert any("f()" in t for t in r.taints)
    # The earlier binding CAN be provably safe on its own terms.
    r = infer_effects("from math import sqrt\n"
                      "v = sqrt(2)\n"
                      "def sqrt(x):\n    return all_reduce(x)")
    assert r.collective_verdict == "none", r.taints
    # After the def statement, the body resolves as before.
    r = infer_effects("def f():\n    pass\nf()")
    assert r.collective_verdict == "none"


def test_rebound_safe_root_and_rebound_def_lose_their_proofs():
    r = infer_effects("time = Trainer()\ntime.step()")
    assert r.collective_verdict == "unknown"
    r = infer_effects("def f():\n    pass\nf = trainer.step\nf()")
    assert r.collective_verdict == "unknown"


def test_cross_cell_safe_root_rebind_poisons_later_proofs():
    """A rebind in cell 1 must not let cell 2 be falsely PROVEN free:
    ambient_poison feeds the next cell's assume_unsafe."""
    from nbdistributed_tpu.analysis.effects import ambient_poison
    cell1 = infer_effects("np = weird_module")
    poison = ambient_poison(cell1)
    assert "np" in poison
    # Without the poison, cell 2 would be proven free — the hole.
    assert infer_effects("y = np.sum(x)").collective_verdict == "none"
    r = infer_effects("y = np.sum(x)", assume_unsafe=poison)
    assert r.collective_verdict == "unknown"
    # Builtins poison the same way (`float = my_fn` in cell 1).
    poison2 = ambient_poison(infer_effects("float = my_fn"))
    assert "float" in poison2
    assert infer_effects("z = float(x)",
                         assume_unsafe=poison2
                         ).collective_verdict == "unknown"


def test_reimport_rearms_instead_of_poisoning():
    from nbdistributed_tpu.analysis.effects import ambient_poison
    # `import numpy as np` RESTORES the assumption — no poison…
    assert "np" not in ambient_poison(
        infer_effects("import numpy as np\na = np.ones(2)"))
    # …and a poisoned root is re-armed within the importing cell.
    r = infer_effects("import numpy as np\na = np.ones(2)",
                      assume_unsafe=frozenset({"np"}))
    assert r.collective_verdict == "none"
    # But `import jax as np` both disarms in-cell and poisons onward.
    p = ambient_poison(infer_effects("import jax as np"))
    assert "np" in p


def test_opaque_cell_poisons_every_ambient_assumption():
    from nbdistributed_tpu.analysis.effects import (SAFE_CALL_ROOTS,
                                                    ambient_poison)
    p = ambient_poison(infer_effects("exec(payload)"))
    assert SAFE_CALL_ROOTS <= p and "float" in p


def test_host_sync_flags_and_taint():
    r = infer_effects("for i in range(5):\n    tot += loss.item()")
    assert r.host_sync and r.host_sync_in_loop
    assert r.collective_verdict == "unknown"   # may gather cross-host
    r = infer_effects("v = loss.item()")
    assert r.host_sync and not r.host_sync_in_loop
    r = infer_effects("for i in range(3):\n    print(loss)")
    assert r.host_sync_in_loop
    r = infer_effects("print('hello')")
    assert not r.host_sync


def test_pure_property():
    assert infer_effects("1 + 1").pure
    assert not infer_effects("x = 1").pure
    assert not infer_effects("y = all_reduce(x)").pure


def test_effects_report_as_dict_is_json_safe():
    d = infer_effects(HANG_CELL).as_dict()
    json.dumps(d)
    assert d["collective_verdict"] == "exact"
    assert [s["op"] for s in d["collectives"]] == ["all_reduce",
                                                   "all_reduce"]


def test_await_collective_counts():
    r = infer_effects("r = await all_reduce(jnp.ones(2))")
    assert r.parsed
    assert [s.op for s in r.collectives] == ["all_reduce"]
    assert collective_class(r) == "bearing"


# ----------------------------------------------------------------------
# ISSUE 9: preflight effect store + session dependency DAG


def test_note_effects_log_and_lookup():
    preflight.clear()
    preflight.note_effects("sha-a", infer_effects("x = 1"))
    preflight.note_effects("sha-b", infer_effects("y = x"))
    log = preflight.effects_log()
    assert [e["sha"] for e in log] == ["sha-a", "sha-b"]
    assert preflight.effects_for("sha-b")["reads"] == ["x"]
    assert preflight.effects_for("missing") is None
    preflight.clear()
    assert preflight.effects_log() == []


def test_deps_dag_write_read_edges():
    preflight.clear()
    for sha, src in [("s0", "x = 1\ny = 2"),
                     ("s1", "z = x + 1"),
                     ("s2", "import time\ntime.sleep(0)"),
                     ("s3", "cfg.lr = x"),   # mutation counts as write
                     ("s4", "v = cfg")]:
        preflight.note_effects(sha, infer_effects(src))
    dag = preflight.deps_dag()
    edges = {(e["src"], e["dst"]): e["names"] for e in dag["edges"]}
    assert edges[(0, 1)] == ["x"]
    assert edges[(3, 4)] == ["cfg"]
    assert (0, 2) not in edges and (1, 2) not in edges
    preflight.clear()


def test_deps_dag_war_and_waw_hazards():
    """No-edge must mean REORDERABLE: anti (read→write) and output
    (write→write) hazards get edges too, not just write→read."""
    preflight.clear()
    preflight.note_effects("i", infer_effects("y = x + 1"))
    preflight.note_effects("j", infer_effects("x = 5"))
    dag = preflight.deps_dag()
    edges = {(e["src"], e["dst"]): e["names"] for e in dag["edges"]}
    assert edges[(0, 1)] == ["x"]       # WAR: i reads x, j writes it
    preflight.clear()
    preflight.note_effects("i", infer_effects("x = 1"))
    preflight.note_effects("j", infer_effects("x = 2"))
    dag = preflight.deps_dag()
    edges = {(e["src"], e["dst"]): e["names"] for e in dag["edges"]}
    assert edges[(0, 1)] == ["x"]       # WAW: final value is ordered
    preflight.clear()


def test_deps_dag_opaque_poisons_both_directions():
    preflight.clear()
    for sha, src in [("s0", "a = 1"),
                     ("s1", "exec('b = 2')"),
                     ("s2", "c = 3")]:
        preflight.note_effects(sha, infer_effects(src))
    dag = preflight.deps_dag()
    edges = {(e["src"], e["dst"]): e["names"] for e in dag["edges"]}
    assert edges[(0, 1)] == ["*"]
    assert edges[(1, 2)] == ["*"]
    assert (0, 2) not in edges
    preflight.clear()


def test_effects_log_is_bounded():
    preflight.clear()
    rep = infer_effects("x = 1")
    for i in range(preflight._MAX_CELLS + 10):
        preflight.note_effects(f"s{i}", rep)
    log = preflight.effects_log()
    assert len(log) == preflight._MAX_CELLS
    assert log[0]["sha"] == "s10"      # oldest evicted
    preflight.clear()


# ----------------------------------------------------------------------
# ISSUE 9 satellite: cell magics other than %%distributed/%%rank


def test_nested_python_body_cell_magic_still_vets_remainder():
    for head in ("%%time", "%%time -n1", "%%capture out", "%%prun"):
        src = f"{head}\nif rank == 0:\n    all_reduce(x)\n"
        res = vet_cell(src)
        assert res.parsed, head
        assert rules(res, "error") == ["rank-conditional-collective"], \
            head


def test_non_python_cell_magic_masks_whole_cell():
    for src in ("%%bash\necho hi there\n",
                "%%writefile out.py\nthis is : not python\n",
                "%%sql\nselect * from t where x > 2\n"):
        res = vet_cell(src)
        assert res.parsed and res.findings == [], src
        rep = infer_effects(src)
        assert rep.parsed and not rep.opaque
        assert rep.collective_verdict == "none"
        # Masked payloads still have REAL host side effects (files,
        # subprocesses): never pure/reorderable, though mesh-silent.
        assert rep.host_sync and not rep.pure, src
    # Line count survives the masking (finding lines stay honest).
    assert len(strip_ipython("%%bash\necho hi\necho bye\n")
               .splitlines()) == 3


def test_bare_double_percent_line_is_stripped():
    res = vet_cell("%%\nif rank == 0:\n    all_reduce(x)\n")
    assert res.parsed
    assert rules(res, "error") == ["rank-conditional-collective"]


# ----------------------------------------------------------------------
# ISSUE 9 satellite: async cells — pin the rule semantics


def test_top_level_await_cell_is_vetted():
    # ast.parse accepts module-level await (the error is compile-
    # stage), so IPython's top-level-await cells are NOT unparseable.
    res = vet_cell("import asyncio\n"
                   "await asyncio.sleep(0)\n"
                   "if rank == 0:\n"
                   "    await all_reduce(x)\n")
    assert res.parsed
    assert rules(res, "error") == ["rank-conditional-collective"]


def test_async_for_break_desyncs_like_plain_for():
    res = vet_cell("async def main():\n"
                   "    async for b in stream:\n"
                   "        if rank == 1:\n"
                   "            break\n"
                   "        x = all_reduce(b)\n"
                   "await main()\n")
    assert "rank-conditional-exit" in rules(res, "error")


def test_async_for_host_sync_warns_like_plain_for():
    res = vet_cell("async def main():\n"
                   "    async for b in stream:\n"
                   "        print(loss)\n"
                   "await main()\n")
    assert rules(res) == ["host-sync-in-loop"]


def test_rank_exit_in_async_def_with_collectives_ahead():
    res = vet_cell("async def step():\n"
                   "    if rank == 0:\n"
                   "        return\n"
                   "    y = all_reduce(x)\n")
    assert "rank-conditional-exit" in rules(res, "error")


def test_uniform_async_cell_is_clean():
    assert not vet_cell("async def main():\n"
                        "    y = all_reduce(x)\n"
                        "    return y\n"
                        "await main()\n").errors


# ----------------------------------------------------------------------
# ISSUE 9: effect-engine acceptance corpora (the CI effects check)


@pytest.mark.parametrize("nb", ["00_quickstart.ipynb",
                                "01_parallelism.ipynb",
                                "02_finetune.ipynb"])
def test_example_notebook_cells_get_non_opaque_reports(nb):
    path = os.path.join(REPO, "examples", nb)
    bad = []
    for i, src in enumerate(_notebook_cells(path)):
        rep = infer_effects(src)
        if not rep.parsed or rep.opaque:
            bad.append(f"{nb} cell {i}: {rep.opaque_reasons}")
    assert not bad, "\n".join(bad)


def test_selftest_corpus_cells_get_non_opaque_reports():
    bad = []
    for i, src in enumerate(_selftest_cells()):
        rep = infer_effects(src)
        if not rep.parsed or rep.opaque:
            bad.append(f"selftest cell {i}: {rep.opaque_reasons}")
    assert not bad, "\n".join(bad)


def test_hang_cell_footprint_nonempty_and_ordered():
    rep = infer_effects(HANG_CELL)
    assert rep.collectives, "HANG_CELL must carry a collective " \
                            "footprint"
    lines = [s.line for s in rep.collectives]
    assert lines == sorted(lines)
    assert collective_class(rep) != "free"


# ----------------------------------------------------------------------
# ISSUE 9 satellite: thread pass — gateway coverage + _locked helpers


def test_thread_pass_covers_gateway_files():
    from nbdistributed_tpu.analysis.selfcheck import \
        _THREAD_CHECKED_FILES
    covered = {os.path.basename(f) for f in _THREAD_CHECKED_FILES}
    assert {"daemon.py", "tenancy.py", "scheduler.py"} <= covered


def _locked_findings(src, method_name):
    tree = ast.parse(src)
    cls = tree.body[0]
    fn = [n for n in cls.body if isinstance(n, ast.FunctionDef)
          and n.name == method_name][0]
    p = _ThreadPass("x.py", cls.name, {"counts"}, {},
                    method=method_name)
    p.visit(fn)
    return p.findings


_LOCKED_SRC = """
class C:
    def __init__(self):
        self._lock = None
        self.counts = dict()
    def _bump_locked(self):
        self.counts['a'] = 1
        self.n += 1
    def unlocked_caller(self):
        self._bump_locked()
    def locked_caller(self):
        with self._lock:
            self._bump_locked()
"""


def test_locked_suffix_body_is_treated_as_locked():
    assert not _locked_findings(_LOCKED_SRC, "_bump_locked")


def test_unlocked_call_to_locked_helper_is_flagged():
    found = _locked_findings(_LOCKED_SRC, "unlocked_caller")
    assert found and "lock-asserting" in found[0].message


def test_locked_call_to_locked_helper_is_clean():
    assert not _locked_findings(_LOCKED_SRC, "locked_caller")


# ----------------------------------------------------------------------
# ISSUE 9: magic wiring — dispatched cells record effect footprints


def test_vet_cell_records_effects_on_dispatch(magic):
    from nbdistributed_tpu.runtime.collective_guard import cell_hash
    src = "ana_x = 1\nana_y = ana_x + free_read"
    assert magic._vet_cell(src, [0, 1]) is True
    entry = preflight.effects_for(cell_hash(src))
    assert entry is not None
    assert "ana_x" in entry["writes"] and "free_read" in entry["reads"]


def test_vet_cell_strict_block_records_nothing(magic):
    from nbdistributed_tpu.runtime.collective_guard import cell_hash
    assert magic._vet_cell(HANG_CELL, [0, 1], strict=True) is False
    assert preflight.effects_for(cell_hash(HANG_CELL)) is None


def test_vet_cell_unparseable_records_opaque(magic):
    from nbdistributed_tpu.runtime.collective_guard import cell_hash
    src = "def broken(:\npass"
    assert magic._vet_cell(src, [0, 1]) is True
    entry = preflight.effects_for(cell_hash(src))
    assert entry is not None and entry["opaque"]


def test_dist_lint_deps_and_effects_render(magic, capsys):
    magic._vet_cell("dag_a = 1", [0, 1])
    magic._vet_cell("dag_b = dag_a + 1", [0, 1])
    magic.dist_lint("deps")
    out = capsys.readouterr().out
    assert "dependency DAG" in out and "dag_a" in out
    magic.dist_lint("effects")
    out = capsys.readouterr().out
    assert "effect footprints" in out and "writes dag_b" in out


# ----------------------------------------------------------------------
# codec registry sanity (the table both the codec and self-lint import)


def test_wire_extensions_registry_shape():
    from nbdistributed_tpu.messaging.codec import (BASE_HEADER_KEYS,
                                                   WIRE_EXTENSIONS)
    assert {"at", "tr", "ep"} <= {
        k for k, v in WIRE_EXTENSIONS.items() if v["plane"] == "header"}
    assert {"col", "busy_s", "tel"} <= {
        k for k, v in WIRE_EXTENSIONS.items() if v["plane"] == "ping"}
    assert not set(WIRE_EXTENSIONS) & set(BASE_HEADER_KEYS)


# ======================================================================
# ISSUE 10: concurrency self-analysis (analysis/concur.py)


def _concur_results(tmp_path, src):
    """Run the three concurrency passes over one synthetic module in
    a throwaway product tree."""
    from nbdistributed_tpu.analysis.concur import run_concur_lint
    pkg = tmp_path / "nbdistributed_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "mod.py").write_text(src)
    return run_concur_lint(str(tmp_path))


def _only(results, rule):
    """Assert exactly ``rule`` fired (the corpus contract: each
    synthetic violation must fire its rule and no other)."""
    for name, findings in results.items():
        if name == rule:
            assert findings, f"{rule} did not fire"
        else:
            assert findings == [], (
                f"[{name}] " + "; ".join(f.render() for f in findings))
    return results[rule]


_CYCLE_SRC = """
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self._other_lock = threading.Lock()
    def fwd(self):
        with self._lock:
            with self._other_lock:
                pass
    def rev(self):
        with self._other_lock:
            with self._lock:
                pass
"""


def test_lock_order_cycle_fires_exactly_its_rule(tmp_path):
    found = _only(_concur_results(tmp_path, _CYCLE_SRC), "lock-order")
    assert any("cycle" in f.message and "A._lock" in f.message
               and "A._other_lock" in f.message for f in found)


_BURIED_CYCLE_SRC = """
import threading

class A:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._c_lock = threading.Lock()
    def ab(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def ac(self):
        with self._a_lock:
            with self._c_lock:
                pass
    def fwd(self):
        with self._b_lock:
            with self._c_lock:
                pass
    def rev(self):
        with self._c_lock:
            with self._b_lock:
                pass
"""


def test_lock_order_cycle_not_through_start_node_is_found(tmp_path):
    """A b↔c inversion reachable only THROUGH a third lock must still
    be reported — the SCC enumeration regression pin (a pruned
    DFS-from-each-start missed exactly this shape)."""
    found = _only(_concur_results(tmp_path, _BURIED_CYCLE_SRC),
                  "lock-order")
    assert any("cycle" in f.message and "A._b_lock" in f.message
               and "A._c_lock" in f.message for f in found)
    # The acyclic a→b / a→c prefix edges are NOT part of any finding.
    assert all("A._a_lock" not in f.message for f in found)


_REACQUIRE_SRC = """
import threading

class B:
    def __init__(self):
        self._lock = threading.{LOCK}()
    def outer(self):
        with self._lock:
            self._inner()
    def _inner(self):
        with self._lock:
            pass
"""


def test_plain_lock_reacquire_via_helper_is_a_deadlock(tmp_path):
    src = _REACQUIRE_SRC.replace("{LOCK}", "Lock")
    found = _only(_concur_results(tmp_path, src), "lock-order")
    assert any("already held" in f.message for f in found)


def test_rlock_reacquire_is_reentrant_and_clean(tmp_path):
    src = _REACQUIRE_SRC.replace("{LOCK}", "RLock")
    res = _concur_results(tmp_path, src)
    assert all(v == [] for v in res.values())


_SENDALL_SRC = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None
    def flush(self, frame):
        with self._lock:
            self.sock.sendall(frame)
"""


def test_sendall_under_lock_fires_exactly_its_rule(tmp_path):
    found = _only(_concur_results(tmp_path, _SENDALL_SRC),
                  "blocking-under-lock")
    assert "sendall" in found[0].message
    assert "C._lock" in found[0].message


def test_blocking_ok_exemption_table_silences_the_site(tmp_path):
    src = ('_LINT_BLOCKING_OK = {"C.flush:sendall": "frame-write '
           'serializer"}\n') + _SENDALL_SRC
    res = _concur_results(tmp_path, src)
    assert all(v == [] for v in res.values())


_CALLBACK_SRC = """
import threading

class D:
    def __init__(self):
        self._lock = threading.Lock()
        self.on_done = None
    def fire_direct(self):
        with self._lock:
            self.on_done(1)
    def fire_alias(self):
        with self._lock:
            cb = self.on_done
            cb(2)
    def fire_outside(self):
        with self._lock:
            cb = self.on_done
        cb(3)
"""


def test_callback_under_lock_fires_exactly_its_rule(tmp_path):
    found = _only(_concur_results(tmp_path, _CALLBACK_SRC),
                  "callback-under-lock")
    # Direct invocation and the locked alias fire; the copy-then-
    # invoke-outside pattern (the documented fix) is clean.
    lines = sorted(f.line for f in found)
    assert len(found) == 2
    assert all("on_done" in f.message or "cb" in f.message
               for f in found)
    src_lines = _CALLBACK_SRC.splitlines()
    assert all("fire_outside" not in src_lines[ln - 2]
               for ln in lines)


def test_callback_ok_exemption_table_silences_the_site(tmp_path):
    src = ('_LINT_CALLBACK_OK = {"D.fire_direct:on_done": "reentry-'
           'safe by contract", "D.fire_alias:cb": "ditto"}\n'
           ) + _CALLBACK_SRC
    res = _concur_results(tmp_path, src)
    assert all(v == [] for v in res.values())


_LOCKED_HELPER_SRC = """
import threading
import time

class E:
    def __init__(self):
        self._lock = threading.Lock()
    def _flush_locked(self):
        time.sleep(1)
"""


def test_locked_suffix_asserts_entry_lockset(tmp_path):
    found = _only(_concur_results(tmp_path, _LOCKED_HELPER_SRC),
                  "blocking-under-lock")
    assert "time.sleep" in found[0].message
    assert "E._lock" in found[0].message


def test_locked_helper_defect_reported_once_not_per_caller(tmp_path):
    """One blocking op in a `_locked` helper with k locked callers is
    ONE defect: the helper self-reports via its entry lockset, and
    via-resolution must not re-flag it at every call site."""
    src = _LOCKED_HELPER_SRC + """
    def caller_one(self):
        with self._lock:
            self._flush_locked()
    def caller_two(self):
        with self._lock:
            self._flush_locked()
"""
    found = _only(_concur_results(tmp_path, src),
                  "blocking-under-lock")
    assert len(found) == 1
    assert found[0].message.startswith("E._flush_locked:")


_VIA_HELPER_SRC = """
import threading

class F:
    def __init__(self):
        self._lock = threading.Lock()
        self.ch = None
    def caller(self):
        with self._lock:
            self._emit()
    def _emit(self):
        self.ch.sendall(b"x")
"""


def test_one_level_resolution_flags_blocking_via_helper(tmp_path):
    found = _only(_concur_results(tmp_path, _VIA_HELPER_SRC),
                  "blocking-under-lock")
    assert "via F._emit" in found[0].message
    # The finding anchors at the locked CALL site, not inside the
    # (lock-free when called alone) helper.
    assert found[0].line == _VIA_HELPER_SRC.splitlines().index(
        "            self._emit()") + 1


_ACQUIRE_RELEASE_SRC = """
import threading
import time

class G:
    def __init__(self):
        self._lock = threading.Lock()
    def run(self):
        self._lock.acquire()
        time.sleep(1)
        self._lock.release()
        time.sleep(2)
"""


def test_acquire_release_pairs_scope_the_lockset(tmp_path):
    found = _only(_concur_results(tmp_path, _ACQUIRE_RELEASE_SRC),
                  "blocking-under-lock")
    assert len(found) == 1   # only the sleep between acquire/release
    assert found[0].line == _ACQUIRE_RELEASE_SRC.splitlines().index(
        "        time.sleep(1)") + 1


def test_module_level_lock_is_tracked(tmp_path):
    src = """
import threading
import time

_lock = threading.Lock()

def flush():
    with _lock:
        time.sleep(1)
"""
    found = _only(_concur_results(tmp_path, src),
                  "blocking-under-lock")
    assert "mod::_lock" in found[0].message


def test_non_lock_attrs_never_participate(tmp_path):
    # "block" in the name is not enough — only attributes proven to
    # be Lock()/RLock()/Condition() constructions count.
    src = """
import time

class H:
    def __init__(self):
        self.blocker = object()
    def run(self):
        with self.blocker:
            time.sleep(1)
"""
    res = _concur_results(tmp_path, src)
    assert all(v == [] for v in res.values())


def test_lock_graph_dot_contains_real_edges():
    from nbdistributed_tpu.analysis.concur import lock_graph_dot
    dot = lock_graph_dot(REPO)
    assert dot.startswith("digraph lock_order")
    # The daemon parks/claims mailbox results under its lock — the
    # cross-class edge the attr-type registry resolves.
    assert '"GatewayDaemon._lock" -> "ResultMailbox._mlock"' in dot
    # Reentrant self-edges (RLock helper convention) are drawn dashed,
    # documenting the re-entry rather than flagging it.
    assert "style=dashed" in dot


# ----------------------------------------------------------------------
# ISSUE 10 satellite: protocol handler coverage


def test_protocol_coverage_synthetic_both_directions():
    from nbdistributed_tpu.analysis.selfcheck import \
        check_protocol_coverage
    planes = [{"name": "x",
               "sent": {"a": ("f.py", 1), "b": ("f.py", 2)},
               "handled": {"a": ("g.py", 3), "c": ("g.py", 4)}}]
    found = check_protocol_coverage(REPO, planes=planes, external={})
    msgs = [f.message for f in found]
    assert len(found) == 2
    assert any("'b' is sent here but no receiver handles" in m
               for m in msgs)
    assert any("'c' is registered here but nothing" in m for m in msgs)
    # Exemptions silence both directions.
    assert check_protocol_coverage(
        REPO, planes=planes,
        external={"x:b": "why", "x:c": "why"}) == []


def test_protocol_planes_cover_the_real_wire():
    from nbdistributed_tpu.analysis.selfcheck import _protocol_planes
    planes = {p["name"]: p for p in _protocol_planes(REPO)}
    assert {"worker", "worker-notice", "tenant", "tenant-notice",
            "agent", "agent-notice"} <= set(planes)
    assert {"execute", "shutdown", "tenant_gc"} <= set(
        planes["worker"]["sent"])
    assert {"execute", "shutdown", "tenant_gc"} <= set(
        planes["worker"]["handled"])
    assert {"tenant_hello", "execute", "mailbox", "detach"} <= set(
        planes["tenant"]["sent"])
    assert {"queued", "parked_notice", "stream_output",
            # ISSUE 11: the serving plane's pushes (serving.py) are
            # tenant-plane notices too.
            "serve_tokens", "serve_done",
            # ISSUE 16: tenant_import reconstructs migrated parked
            # results as "response"-typed mailbox entries; they only
            # ever leave inside a mailbox drain (exempted in
            # _PROTOCOL_EXTERNAL).
            "response"} == set(
        planes["tenant-notice"]["sent"])
    assert {"serve_submit", "serve_result", "serve_stream",
            "serve_start", "serve_status", "serve_stop"} <= set(
        planes["tenant"]["sent"])
    assert {"serve_open", "serve_step", "serve_close"} <= set(
        planes["worker"]["handled"])
    assert {"spawn", "signal", "tail", "reap", "poll"} <= set(
        planes["agent"]["sent"])


# ----------------------------------------------------------------------
# ISSUE 10 satellite: CLI modes — dot exports, JSON format, exit codes


def test_cli_exit_codes_pinned(tmp_path, capsys):
    from nbdistributed_tpu.analysis.cli import main
    # 2: no mode selected (help), unreadable file, --deps-dot sans
    # files.
    assert main([]) == 2
    capsys.readouterr()
    assert main([str(tmp_path / "missing.py")]) == 2
    capsys.readouterr()
    assert main(["--deps-dot"]) == 2
    capsys.readouterr()
    # 0: clean checkout self-lint; clean file.
    assert main(["--self", "--root", REPO]) == 0
    capsys.readouterr()
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert main([str(ok)]) == 0
    capsys.readouterr()
    # 1: error-severity cell finding.
    bad = tmp_path / "bad.py"
    bad.write_text(HANG_CELL)
    assert main([str(bad)]) == 1
    capsys.readouterr()
    # Highest code wins regardless of argument order: unreadable (2)
    # beats findings (1) in both positions.
    missing = str(tmp_path / "missing.py")
    assert main([missing, str(bad)]) == 2
    capsys.readouterr()
    assert main([str(bad), missing]) == 2
    capsys.readouterr()
    # Unparseable: 0 by the never-block contract, 1 under --strict
    # (an uninspectable cell cannot be called clean there).
    broken = tmp_path / "broken.py"
    broken.write_text("def broken(:\n    pass\n")
    assert main([str(broken)]) == 0
    capsys.readouterr()
    assert main([str(broken), "--strict"]) == 1
    out = capsys.readouterr().out
    assert "FAILED under --strict" in out


def test_cli_json_format_self_and_files(tmp_path, capsys):
    from nbdistributed_tpu.analysis.cli import main
    assert main(["--self", "--root", REPO, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["mode"] == "self" and doc["total"] == 0
    assert doc["exit_code"] == 0
    assert set(doc["passes"]) >= {"lock-order", "blocking-under-lock",
                                  "callback-under-lock",
                                  "protocol-coverage"}
    bad = tmp_path / "bad.py"
    bad.write_text(HANG_CELL)
    assert main([str(bad), "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["mode"] == "files" and doc["exit_code"] == 1
    (entry,) = doc["files"].values()
    assert entry["parsed"] is True
    assert any(f["rule"] == "rank-conditional-collective"
               and f["severity"] == "error"
               for f in entry["findings"])


def test_cli_lock_graph_and_deps_dot(tmp_path, capsys):
    from nbdistributed_tpu.analysis.cli import main
    assert main(["--lock-graph", "--root", REPO]) == 0
    assert capsys.readouterr().out.startswith("digraph lock_order")
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = x + 1\n")
    assert main(["--deps-dot", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph cell_deps")
    assert '"c0" -> "c1"' in out and 'label="x"' in out


def test_dag_to_dot_marks_opaque_cells():
    from nbdistributed_tpu.analysis.effects import infer_effects
    from nbdistributed_tpu.analysis.preflight import (dag_from_entries,
                                                      dag_to_dot)
    entries = []
    for seq, src in enumerate(["a = 1", "exec('a = 2')", "b = a"]):
        e = {"seq": seq, "sha": f"s{seq}"}
        e.update(infer_effects(src).as_dict())
        entries.append(e)
    dag = dag_from_entries(entries)
    dot = dag_to_dot(dag)
    assert "fillcolor" in dot          # the opaque exec cell
    # Opaque cells gate everything: both neighbors connect to c1.
    assert '"c0" -> "c1"' in dot and '"c1" -> "c2"' in dot


def test_dist_lint_deps_dot_renders(magic, capsys):
    magic._vet_cell("dot_a = 1", [0, 1])
    magic._vet_cell("dot_b = dot_a + 1", [0, 1])
    magic.dist_lint("deps --dot")
    out = capsys.readouterr().out
    assert out.strip().startswith("digraph cell_deps")
    assert "->" in out


# ======================================================================
# ISSUE 15: lifecycle lint (analysis/lifecycle.py) — synthetic corpus
# (per rule: one sample firing exactly that rule, and a clean twin)


def _lifecycle_results(tmp_path, src):
    """Run the three lifecycle passes over one synthetic module in a
    throwaway product tree (the _concur_results analog)."""
    from nbdistributed_tpu.analysis.lifecycle import run_lifecycle_lint
    pkg = tmp_path / "nbdistributed_tpu"
    pkg.mkdir(parents=True)
    (tmp_path / "tools").mkdir()
    (pkg / "mod.py").write_text(src)
    return run_lifecycle_lint(str(tmp_path))


def _lifecycle_clean(tmp_path, src):
    res = _lifecycle_results(tmp_path, src)
    assert all(v == [] for v in res.values()), {
        k: [f.render() for f in v] for k, v in res.items() if v}


# -- resource-leak ------------------------------------------------------


def test_leak_socket_never_released_fires(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import socket

def probe(host):
    s = socket.create_connection((host, 80))
    s.sendall(b"x")
"""), "resource-leak")
    assert "never released" in found[0].message
    assert "socket" in found[0].message


def test_leak_release_only_on_fall_through_fires(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import socket

def probe(host):
    s = socket.create_connection((host, 80))
    s.sendall(b"x")
    s.close()
"""), "resource-leak")
    assert "fall-through" in found[0].message


def test_leak_clean_twins_with_block_and_finally(tmp_path):
    _lifecycle_clean(tmp_path, """
import socket

def probe_with(host):
    with socket.create_connection((host, 80)) as s:
        s.sendall(b"x")

def probe_finally(host):
    s = socket.create_connection((host, 80))
    try:
        s.sendall(b"x")
    finally:
        s.close()

def make_and_close():
    s = socket.socket()
    s.close()
""")


def test_leak_ownership_transfer_clean_twins(tmp_path):
    _lifecycle_clean(tmp_path, """
import socket

def returned():
    s = socket.socket()
    return s

def registered(registry):
    s = socket.socket()
    registry.register(s)

class Owner:
    def __init__(self):
        self.sock = None
    def arm(self, host):
        s = socket.create_connection((host, 80))
        self.sock = s
    def close(self):
        self.sock.close()
""")


def test_leak_nondaemon_thread_fires_daemon_clean(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import threading

def run(fn):
    t = threading.Thread(target=fn)
    t.start()
"""), "resource-leak")
    assert "thread" in found[0].message
    _lifecycle_clean(tmp_path / "d", """
import threading

def run(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()

def run_joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    try:
        pass
    finally:
        t.join()
""")


def test_leak_popen_and_write_open(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import subprocess

def launch(argv):
    p = subprocess.Popen(argv)
    p.poll()
"""), "resource-leak")
    assert "process" in found[0].message
    # Read-mode open is not in the acquire vocabulary; adjacent
    # wait() is a zero-raise-window release.
    _lifecycle_clean(tmp_path / "c", """
import subprocess

def launch(argv):
    p = subprocess.Popen(argv)
    p.wait()

def read(path):
    f = open(path)
    return f
""")


def test_leak_socketpair_each_end_needs_its_own_release(tmp_path):
    # Closing one end must not satisfy the check for the other.
    found = _only(_lifecycle_results(tmp_path, """
import socket

def pair():
    r, w = socket.socketpair()
    r.close()
"""), "resource-leak")
    assert len(found) == 1 and "'w'" in found[0].message
    _lifecycle_clean(tmp_path / "c", """
import socket

def pair():
    r, w = socket.socketpair()
    try:
        pass
    finally:
        r.close()
        w.close()
""")


def test_leak_exemption_table_silences_the_site(tmp_path):
    _lifecycle_clean(tmp_path, """
_LINT_LIFECYCLE_OK = {"probe:socket": "one-shot probe; the process "
                      "exits right after and the OS reclaims the fd"}
import socket

def probe(host):
    s = socket.create_connection((host, 80))
    s.sendall(b"x")
""")


# -- bracket-discipline -------------------------------------------------


_SERVE_BRACKET_HEAD = """
import threading

class G:
    def __init__(self):
        self._lock = threading.Lock()
        self._serving = {}
    def _serve_done(self, name):
        with self._lock:
            self._serving[name] = self._serving.get(name, 1) - 1
"""


def test_bracket_serve_slot_unprotected_fires(tmp_path):
    found = _only(_lifecycle_results(tmp_path, _SERVE_BRACKET_HEAD + """
    def submit(self, name):
        with self._lock:
            self._serving[name] = self._serving.get(name, 0) + 1
        self.do_work(name)
"""), "bracket-discipline")
    assert "serve-slot" in found[0].message


def test_bracket_serve_slot_thread_handoff_clean(tmp_path):
    _lifecycle_clean(tmp_path, _SERVE_BRACKET_HEAD + """
    def submit(self, name):
        with self._lock:
            self._serving[name] = self._serving.get(name, 0) + 1
        threading.Thread(target=self._serve, args=(name,),
                         daemon=True).start()
    def _serve(self, name):
        try:
            self.work(name)
        finally:
            self._serve_done(name)
    def submit_inline(self, name):
        with self._lock:
            self._serving[name] = self._serving.get(name, 0) + 1
        try:
            self.work(name)
        finally:
            self._serve_done(name)
""")


def test_bracket_mailbox_claim_fires_and_repark_twin_clean(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
class W:
    def drain(self, box, reply):
        claimed = box.claim_all()
        return reply(claimed)
"""), "bracket-discipline")
    assert "mailbox-claim" in found[0].message
    _lifecycle_clean(tmp_path / "c", """
class W:
    def drain(self, box, reply):
        claimed = box.claim_all()
        try:
            return reply(claimed)
        except Exception:
            for mid, r in claimed.items():
                box.park(mid, r)
            raise
""")


def test_bracket_gauge_updown_fires_only_with_dec_in_module(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
class M:
    def __init__(self):
        self.g = None
    def enter(self):
        self.g.inc()
        self.work()
    def leave(self):
        self.g.dec()
"""), "bracket-discipline")
    assert "gauge-updown" in found[0].message
    # Monotonic counters (inc with no dec anywhere in the module)
    # never arm the bracket…
    _lifecycle_clean(tmp_path / "mono", """
class M:
    def count(self, c):
        c.inc()
        self.work()
""")
    # …nor does a dec on a DIFFERENT receiver arm someone else's
    # counter inc (pairing is per dotted receiver).
    _lifecycle_clean(tmp_path / "other", """
class M:
    def __init__(self):
        self.g = None
        self.requests = None
    def count(self):
        self.requests.inc()
        self.work()
    def leave(self):
        self.g.dec()
""")
    # …and the finally twin is clean even with dec present.
    _lifecycle_clean(tmp_path / "c", """
class M:
    def __init__(self):
        self.g = None
    def enter(self):
        self.g.inc()
        try:
            self.work()
        finally:
            self.g.dec()
""")


def test_bracket_exemption_table_silences_the_site(tmp_path):
    _lifecycle_clean(tmp_path, """
_LINT_LIFECYCLE_OK = {"W.drain:mailbox-claim": "the completion "
                      "callback reparks on failure by contract"}

class W:
    def drain(self, box, reply):
        claimed = box.claim_all()
        return reply(claimed)
""")


# -- shutdown-completeness ----------------------------------------------


def test_shutdown_unreleased_socket_fires_release_twin_clean(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import socket

class S:
    def __init__(self):
        self._sock = socket.create_connection(("h", 1))
    def close(self):
        pass
"""), "shutdown-completeness")
    assert "_sock" in found[0].message
    _lifecycle_clean(tmp_path / "c", """
import socket

class S:
    def __init__(self):
        self._sock = socket.create_connection(("h", 1))
    def close(self):
        self._sock.close()
""")


def test_shutdown_no_surface_at_all_fires(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import socket

class S:
    def __init__(self):
        self._sock = socket.socket()
"""), "shutdown-completeness")
    assert "defines no close" in found[0].message


def test_shutdown_nondaemon_thread_must_be_joined(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import threading

class S:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()
    def _run(self):
        pass
    def close(self):
        pass
"""), "shutdown-completeness")
    assert "non-daemon thread" in found[0].message


def test_shutdown_daemon_thread_lock_hazard_and_join_twin(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
    def _run(self):
        with self._lock:
            pass
    def close(self):
        pass
"""), "shutdown-completeness")
    assert "interpreter teardown" in found[0].message
    _lifecycle_clean(tmp_path / "joined", """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
    def _run(self):
        with self._lock:
            pass
    def close(self):
        self._t.join(timeout=1.0)
""")
    # A daemon thread that touches no lock needs no surface at all.
    _lifecycle_clean(tmp_path / "harmless", """
import threading

class S:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
    def _run(self):
        pass
""")


def test_shutdown_owner_typed_attr_and_alias_release(tmp_path):
    found = _only(_lifecycle_results(tmp_path, """
import socket

class Inner:
    def __init__(self):
        self._sock = socket.socket()
    def close(self):
        self._sock.close()

class Outer:
    def __init__(self):
        self._inner = Inner()
    def close(self):
        pass
"""), "shutdown-completeness")
    assert "Inner" in found[0].message and "_inner" in found[0].message
    # The swap-then-close alias (`s, self._sock = self._sock, None`)
    # and the close-loop over a tuple of attrs both count as releases.
    _lifecycle_clean(tmp_path / "alias", """
import socket

class S:
    def __init__(self):
        self._sock = socket.socket()
        self._wake_r, self._wake_w = socket.socketpair()
    def close(self):
        s, self._sock = self._sock, None
        s.close()
        for w in (self._wake_r, self._wake_w):
            w.close()
""")


def test_shutdown_exemption_table_silences_the_attr(tmp_path):
    _lifecycle_clean(tmp_path, """
_LINT_LIFECYCLE_OK = {"S:_sock": "held for the process lifetime by "
                      "design (faulthandler-style registration)"}
import socket

class S:
    def __init__(self):
        self._sock = socket.socket()
""")


def test_shutdown_ledger_report_shape():
    from nbdistributed_tpu.analysis.lifecycle import shutdown_ledger
    ledger = shutdown_ledger(REPO)
    # Real owners with their release evidence…
    tc = ledger["TenantClient"]
    assert tc["file"] == "nbdistributed_tpu/gateway/client.py"
    reader = {r["attr"]: r for r in tc["resources"]}["_reader"]
    assert reader["daemon"] and "join" in reader["released_by"]
    # …and the worker's exemption-tabled faulthandler fd carries its
    # reason.
    w = ledger["DistributedWorker"]
    stack = {r["attr"]: r for r in w["resources"]}["_stack_file"]
    assert stack["exempt"] and "faulthandler" in stack["exempt"]
    json.dumps(ledger)   # CI artifact: must be JSON-serializable


# ----------------------------------------------------------------------
# ISSUE 15 satellite: SARIF output (one run, rule ids = pass names)


def test_cli_sarif_self_mode_validates(capsys):
    from nbdistributed_tpu.analysis.cli import main
    assert main(["--self", "--root", REPO, "--format", "sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert "sarif-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "nbd-lint"
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"resource-leak", "bracket-discipline",
            "shutdown-completeness", "lock-order", "env-knobs",
            "protocol-coverage"} <= ids
    assert run["results"] == []        # the clean-checkout pin again


def test_cli_sarif_file_mode_findings_and_exit_codes(tmp_path, capsys):
    from nbdistributed_tpu.analysis.cli import main
    bad = tmp_path / "bad.py"
    bad.write_text(HANG_CELL)
    assert main([str(bad), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "rank-conditional-collective"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 5      # stable location
    # Unparseable input: visible as a note, exit 0 by the
    # never-block contract — but a warning AND exit 1 under --strict.
    broken = tmp_path / "broken.py"
    broken.write_text("def broken(:\n")
    assert main([str(broken), "--format", "sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "not-analyzable" and res["level"] == "note"
    assert main([str(broken), "--format", "sarif", "--strict"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"][0]["level"] == "warning"


def test_cli_shutdown_ledger_mode(capsys):
    from nbdistributed_tpu.analysis.cli import main
    assert main(["--shutdown-ledger", "--root", REPO]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "CoordinatorListener" in doc
    attrs = {r["attr"] for r in doc["CoordinatorListener"]["resources"]}
    assert {"_server", "_wake_r", "_wake_w"} <= attrs


# ----------------------------------------------------------------------
# ISSUE 15 satellite: %dist_lint self parity with the CLI


def test_dist_lint_self_reports_all_pass_counts(magic, capsys):
    magic.dist_lint("self")
    out = capsys.readouterr().out
    for name in ("env-knobs", "codec-headers", "thread-shared-state",
                 "protocol-coverage", "lock-order",
                 "blocking-under-lock", "callback-under-lock",
                 "resource-leak", "bracket-discipline",
                 "shutdown-completeness"):
        assert f"{name}: clean" in out, name
    assert "all passes clean" in out
