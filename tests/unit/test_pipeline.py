"""Pipeline parallelism: exactness vs the sequential stage loop, grad
flow, and bubble accounting (stretch beyond the reference, which has no
PP at all — SURVEY §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.parallel import pipeline
from nbdistributed_tpu.parallel.mesh import make_mesh

pytestmark = [pytest.mark.unit]

N_STAGES = 4
D = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"pp": N_STAGES},
                     devices=jax.devices()[:N_STAGES])


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _params(key):
    kw, kb = jax.random.split(key)
    return {"w": jax.random.normal(kw, (N_STAGES, D, D)) * 0.3,
            "b": jax.random.normal(kb, (N_STAGES, D)) * 0.1}


def _sequential(params, x):
    for s in range(N_STAGES):
        x = _stage_fn(jax.tree.map(lambda a: a[s], params), x)
    return x


def test_pipeline_matches_sequential(mesh):
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    got = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


def test_pipeline_more_microbatches(mesh):
    params = _params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (16, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    got = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh,
                                    n_microbatches=8)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


def test_pipeline_batch_not_divisible(mesh):
    params = pipeline.shard_stage_params(_params(jax.random.PRNGKey(4)),
                                         mesh)
    x = jnp.zeros((6, D))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline.pipeline_forward(_stage_fn, params, x, mesh)


def test_pipeline_grads_match_sequential(mesh):
    params = _params(jax.random.PRNGKey(5))
    x = jax.random.normal(jax.random.PRNGKey(6), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(7), (8, D))

    def loss_pipe(p):
        sharded = pipeline.shard_stage_params(p, mesh)
        out = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh)
        return jnp.mean((out - y) ** 2)

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        g_pipe, g_seq)


def test_make_pipeline_loss_trains(mesh):
    params = _params(jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(10), (8, D))

    loss = pipeline.make_pipeline_loss(
        _stage_fn, lambda out, tgt: jnp.mean((out - tgt) ** 2), mesh)
    sharded = pipeline.shard_stage_params(params, mesh)
    l0 = loss(sharded, x, y)
    g = jax.grad(loss)(sharded, x, y)
    stepped = jax.tree.map(lambda p, gg: p - 0.1 * gg, sharded, g)
    l1 = loss(stepped, x, y)
    assert float(l1) < float(l0)


def _mse_tail(out, tgt):
    return jnp.mean((out - tgt) ** 2)


def test_1f1b_matches_sequential_loss_and_grads(mesh):
    """The 1F1B schedule's (loss, grads) must equal value_and_grad of
    the sequential per-microbatch mean loss — the same quantity the
    GPipe path optimizes for a mean-reduced loss."""
    params = _params(jax.random.PRNGKey(20))
    x = jax.random.normal(jax.random.PRNGKey(21), (16, D))
    y = jax.random.normal(jax.random.PRNGKey(22), (16, D))
    M = 8

    def loss_seq(p):
        xs = x.reshape(M, -1, D)
        ys = y.reshape(M, -1, D)
        return jnp.mean(jax.vmap(
            lambda xm, ym: _mse_tail(_sequential(p, xm), ym))(xs, ys))

    l_ref, g_ref = jax.value_and_grad(loss_seq)(params)

    fn = pipeline.make_pipeline_1f1b(_stage_fn, _mse_tail, mesh,
                                     n_microbatches=M)
    sharded = pipeline.shard_stage_params(params, mesh)
    l_got, g_got = fn(sharded, x, y)
    np.testing.assert_allclose(float(l_got), float(l_ref), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_got, g_ref)


def test_1f1b_matches_gpipe_grads(mesh):
    """Same gradients as autodiff through the GPipe forward (the two
    schedules compute the same math in different orders)."""
    params = _params(jax.random.PRNGKey(23))
    x = jax.random.normal(jax.random.PRNGKey(24), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(25), (8, D))
    sharded = pipeline.shard_stage_params(params, mesh)

    gpipe_loss = pipeline.make_pipeline_loss(_stage_fn, _mse_tail, mesh)
    l_ref, g_ref = jax.value_and_grad(gpipe_loss)(sharded, x, y)

    fn = pipeline.make_pipeline_1f1b(_stage_fn, _mse_tail, mesh)
    l_got, g_got = fn(sharded, x, y)
    np.testing.assert_allclose(float(l_got), float(l_ref), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_got, g_ref)


def test_1f1b_trains_and_memory_bound(mesh):
    """1F1B's point: the in-flight saved-activation buffer is O(S)
    (2S-1 microbatch inputs), independent of M — while autodiff-GPipe
    residuals grow with M.  Asserted structurally on the jaxpr's
    largest scan-carried buffer, plus a descent check."""
    params = _params(jax.random.PRNGKey(26))
    M = 16  # >> 2S-1 = 7
    x = jax.random.normal(jax.random.PRNGKey(27), (32, D))
    y = jax.random.normal(jax.random.PRNGKey(28), (32, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    fn = pipeline.make_pipeline_1f1b(_stage_fn, _mse_tail, mesh,
                                     n_microbatches=M)
    l0, g = fn(sharded, x, y)
    stepped = jax.tree.map(lambda p, gg: p - 0.1 * gg, sharded, g)
    l1, _ = fn(stepped, x, y)
    assert float(l1) < float(l0)
    # Structural memory bound: the buffer CARRIED through the schedule
    # scan holds 2S-1 = 7 microbatch inputs, not M = 16 — checked on
    # the scan equations' carry avals (the microbatch inputs enter as
    # scan consts, so only carries measure in-flight state).
    micro = 32 // M

    def scan_carry_shapes(closed):
        shapes = []

        def subjaxprs(v):
            vals = v if isinstance(v, (list, tuple)) else [v]
            for p in vals:
                if hasattr(p, "jaxpr") and hasattr(p.jaxpr, "eqns"):
                    yield p.jaxpr
                elif hasattr(p, "eqns"):
                    yield p

        def walk(jaxpr):
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "scan":
                    nc = eqn.params["num_consts"]
                    nk = eqn.params["num_carry"]
                    for var in eqn.invars[nc:nc + nk]:
                        shapes.append(tuple(var.aval.shape))
                for v in eqn.params.values():
                    for sj in subjaxprs(v):
                        walk(sj)

        walk(closed.jaxpr)
        return shapes

    carries = scan_carry_shapes(jax.make_jaxpr(
        lambda p, x_, y_: fn(p, x_, y_))(sharded, x, y))
    assert (2 * N_STAGES - 1, micro, D) in carries, carries
    assert all(s[0] != M for s in carries if len(s) == 3), carries


def test_gpipe_remat_matches_plain(mesh):
    """remat=True must change memory, not math: identical loss and
    gradients to the non-remat GPipe loss."""
    params = _params(jax.random.PRNGKey(32))
    x = jax.random.normal(jax.random.PRNGKey(33), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(34), (8, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    plain = pipeline.make_pipeline_loss(_stage_fn, _mse_tail, mesh)
    rem = pipeline.make_pipeline_loss(_stage_fn, _mse_tail, mesh,
                                      remat=True)
    l0, g0 = jax.value_and_grad(plain)(sharded, x, y)
    l1, g1 = jax.value_and_grad(rem)(sharded, x, y)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), g1, g0)


def test_1f1b_dp_composition():
    """DP x PP: the 1F1B schedule with microbatch rows sharded over a
    dp axis must equal the single-group run on the full batch (grads
    mean-reduced across groups, the DDP convention)."""
    params = _params(jax.random.PRNGKey(40))
    x = jax.random.normal(jax.random.PRNGKey(41), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(42), (8, D))

    pp_only = make_mesh({"pp": N_STAGES},
                        devices=jax.devices()[:N_STAGES])
    ref_fn = pipeline.make_pipeline_1f1b(_stage_fn, _mse_tail, pp_only,
                                         n_microbatches=4)
    l_ref, g_ref = ref_fn(pipeline.shard_stage_params(params, pp_only),
                          x, y)

    dp_pp = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    # 2 stages over pp -> re-chunk the 4 stage slices into 2 stages of
    # 2 applications each?  Simpler: use a 2-stage parameterization.
    p2 = jax.tree.map(lambda a: a.reshape(2, 2, *a.shape[1:]), params)
    stage2 = lambda pr, h: _stage_fn(
        jax.tree.map(lambda a: a[1], pr),
        _stage_fn(jax.tree.map(lambda a: a[0], pr), h))
    ref2_fn = pipeline.make_pipeline_1f1b(
        stage2, _mse_tail, make_mesh({"pp": 2},
                                     devices=jax.devices()[:2]),
        n_microbatches=4)
    l_ref2, g_ref2 = ref2_fn(
        pipeline.shard_stage_params(p2, make_mesh(
            {"pp": 2}, devices=jax.devices()[:2])), x, y)
    np.testing.assert_allclose(float(l_ref2), float(l_ref), rtol=1e-6)

    dp_fn = pipeline.make_pipeline_1f1b(stage2, _mse_tail, dp_pp,
                                        n_microbatches=4,
                                        batch_axis="dp")
    sh2 = pipeline.shard_stage_params(p2, dp_pp)
    l_dp, g_dp = dp_fn(sh2, x, y)
    np.testing.assert_allclose(float(l_dp), float(l_ref), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_dp, g_ref2)


def test_1f1b_single_stage():
    mesh1 = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    params = _params(jax.random.PRNGKey(29))
    one = jax.tree.map(lambda a: a[:1], params)
    x = jax.random.normal(jax.random.PRNGKey(30), (4, D))
    y = jax.random.normal(jax.random.PRNGKey(31), (4, D))
    fn = pipeline.make_pipeline_1f1b(_stage_fn, _mse_tail, mesh1,
                                     n_microbatches=2)

    def ref(p):
        xs, ys = x.reshape(2, 2, D), y.reshape(2, 2, D)
        f = lambda xm, ym: _mse_tail(
            _stage_fn(jax.tree.map(lambda a: a[0], p), xm), ym)
        return jnp.mean(jax.vmap(f)(xs, ys))

    l_ref, g_ref = jax.value_and_grad(ref)(one)
    l_got, g_got = fn(one, x, y)
    np.testing.assert_allclose(float(l_got), float(l_ref), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_got, g_ref)


def test_single_stage_mesh_degenerates():
    mesh1 = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    params = _params(jax.random.PRNGKey(11))
    one = jax.tree.map(lambda a: a[:1], params)
    x = jax.random.normal(jax.random.PRNGKey(12), (4, D))
    got = pipeline.pipeline_forward(_stage_fn, one, x, mesh1)
    want = _stage_fn(jax.tree.map(lambda a: a[0], params), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)
