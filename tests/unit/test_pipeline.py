"""Pipeline parallelism: exactness vs the sequential stage loop, grad
flow, and bubble accounting (stretch beyond the reference, which has no
PP at all — SURVEY §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.parallel import pipeline
from nbdistributed_tpu.parallel.mesh import make_mesh

pytestmark = [pytest.mark.unit]

N_STAGES = 4
D = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"pp": N_STAGES},
                     devices=jax.devices()[:N_STAGES])


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _params(key):
    kw, kb = jax.random.split(key)
    return {"w": jax.random.normal(kw, (N_STAGES, D, D)) * 0.3,
            "b": jax.random.normal(kb, (N_STAGES, D)) * 0.1}


def _sequential(params, x):
    for s in range(N_STAGES):
        x = _stage_fn(jax.tree.map(lambda a: a[s], params), x)
    return x


def test_pipeline_matches_sequential(mesh):
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    got = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


def test_pipeline_more_microbatches(mesh):
    params = _params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (16, D))
    sharded = pipeline.shard_stage_params(params, mesh)
    got = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh,
                                    n_microbatches=8)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


def test_pipeline_batch_not_divisible(mesh):
    params = pipeline.shard_stage_params(_params(jax.random.PRNGKey(4)),
                                         mesh)
    x = jnp.zeros((6, D))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline.pipeline_forward(_stage_fn, params, x, mesh)


def test_pipeline_grads_match_sequential(mesh):
    params = _params(jax.random.PRNGKey(5))
    x = jax.random.normal(jax.random.PRNGKey(6), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(7), (8, D))

    def loss_pipe(p):
        sharded = pipeline.shard_stage_params(p, mesh)
        out = pipeline.pipeline_forward(_stage_fn, sharded, x, mesh)
        return jnp.mean((out - y) ** 2)

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        g_pipe, g_seq)


def test_make_pipeline_loss_trains(mesh):
    params = _params(jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9), (8, D))
    y = jax.random.normal(jax.random.PRNGKey(10), (8, D))

    loss = pipeline.make_pipeline_loss(
        _stage_fn, lambda out, tgt: jnp.mean((out - tgt) ** 2), mesh)
    sharded = pipeline.shard_stage_params(params, mesh)
    l0 = loss(sharded, x, y)
    g = jax.grad(loss)(sharded, x, y)
    stepped = jax.tree.map(lambda p, gg: p - 0.1 * gg, sharded, g)
    l1 = loss(stepped, x, y)
    assert float(l1) < float(l0)


def test_single_stage_mesh_degenerates():
    mesh1 = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    params = _params(jax.random.PRNGKey(11))
    one = jax.tree.map(lambda a: a[:1], params)
    x = jax.random.normal(jax.random.PRNGKey(12), (4, D))
    got = pipeline.pipeline_forward(_stage_fn, one, x, mesh1)
    want = _stage_fn(jax.tree.map(lambda a: a[0], params), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)
