"""Unit tests for the resilience subsystem: retry/backoff schedule,
deterministic fault plans, the exec-dedup replay cache, the auto-heal
supervisor state machine, and the coordinator's redelivery path driven
end-to-end over the real transport with scripted workers."""

import threading
import time

import pytest

from nbdistributed_tpu.messaging import (CommunicationManager, Message,
                                         WorkerChannel, decode, encode)
from nbdistributed_tpu.resilience import (FaultPlan, ReplayCache,
                                          RetryPolicy, Supervisor,
                                          SupervisorPolicy)

pytestmark = [pytest.mark.unit, pytest.mark.faults]


# ----------------------------------------------------------------------
# RetryPolicy

def test_retry_disabled_by_default():
    assert not RetryPolicy().enabled()
    assert RetryPolicy(attempt_timeout_s=1.0, attempts=1).enabled() is False
    assert RetryPolicy(attempt_timeout_s=1.0).enabled()


def test_backoff_grows_exponentially_and_caps():
    p = RetryPolicy(attempt_timeout_s=1.0, backoff_base_s=0.25,
                    backoff_factor=2.0, backoff_max_s=1.0, jitter=0.0)
    waits = [p.backoff_s(i) for i in range(5)]
    assert waits == [0.25, 0.5, 1.0, 1.0, 1.0]  # capped at max


def test_jitter_bounds_and_determinism():
    p = RetryPolicy(attempt_timeout_s=2.0, backoff_base_s=1.0,
                    backoff_factor=1.0, jitter=0.25)
    lo, hi = p.backoff_s(0, u=0.0), p.backoff_s(0, u=1.0)
    assert lo == pytest.approx(0.75) and hi == pytest.approx(1.25)
    assert p.backoff_s(0, u=0.5) == pytest.approx(1.0)
    # attempt_wait = per-attempt timeout + backoff
    assert p.attempt_wait_s(0, u=0.5) == pytest.approx(3.0)
    # random draws stay inside the jitter envelope
    for _ in range(50):
        assert 0.75 <= p.backoff_s(0) <= 1.25


def test_retry_from_env():
    assert RetryPolicy.from_env(env={}) is None
    p = RetryPolicy.from_env(env={"NBD_RETRY_TIMEOUT_S": "2.5",
                                  "NBD_RETRY_ATTEMPTS": "6"})
    assert p.attempt_timeout_s == 2.5 and p.attempts == 6 and p.enabled()


# ----------------------------------------------------------------------
# FaultPlan

def test_fault_plan_deterministic_per_seed():
    a = FaultPlan(seed=11, drop=0.3, delay_p=0.2, duplicate=0.2)
    b = FaultPlan(seed=11, drop=0.3, delay_p=0.2, duplicate=0.2)
    assert [a.decide(i) for i in range(200)] == \
           [b.decide(i) for i in range(200)]
    c = FaultPlan(seed=12, drop=0.3, delay_p=0.2, duplicate=0.2)
    assert [a.decide(i) for i in range(200)] != \
           [c.decide(i) for i in range(200)]


def test_fault_plan_spec_roundtrip_and_unknown_keys():
    p = FaultPlan(seed=5, drop=0.1, duplicate=0.05, kill_rank=1,
                  kill_at=3, freeze_heartbeat=True)
    q = FaultPlan.from_spec(p.spec())
    assert q.spec() == p.spec()
    with pytest.raises(ValueError, match="unknown fault spec"):
        FaultPlan.from_spec({"dorp": 0.1})
    with pytest.raises(TypeError):
        FaultPlan.from_spec([1, 2])


def test_fault_plan_from_env(monkeypatch):
    assert FaultPlan.from_env() is None
    monkeypatch.setenv("NBD_FAULT_PLAN", '{"seed": 9, "drop": 0.5}')
    p = FaultPlan.from_env()
    assert p.seed == 9 and p.drop == 0.5


def test_transmit_effects_and_counters():
    sent = []

    class Scripted(FaultPlan):
        script = {0: ["drop"], 1: [], 2: ["duplicate"], 3: ["truncate"],
                  4: ["delay"]}

        def decide(self, index):
            return self.script.get(index, [])

    p = Scripted(delay_s=0.0)
    frame = b"x" * 10
    for _ in range(5):
        p.transmit(frame, sent.append)
    # drop: nothing; plain: 1; duplicate: 2; truncate: half; delay: 1
    assert sent == [frame, frame, frame, frame[:5], frame]
    assert p.counters["dropped"] == 1
    assert p.counters["duplicated"] == 1
    assert p.counters["truncated"] == 1
    assert p.counters["delayed"] == 1
    assert p.counters["sent"] == 4


def test_transmit_exempt_kinds_skip_plan_and_index():
    p = FaultPlan(seed=0, drop=1.0)  # drops EVERY planned frame
    sent = []
    p.transmit(b"hb", sent.append, kind="ping")  # exempt by default
    p.transmit(b"rq", sent.append, kind="execute")
    assert sent == [b"hb"]
    assert p.counters["exempt"] == 1 and p.counters["dropped"] == 1


def test_should_kill_is_at_or_after_index():
    p = FaultPlan(kill_rank=1, kill_at=3)
    assert not p.should_kill(0, 5)       # other rank never
    assert not p.should_kill(1, 2)
    assert p.should_kill(1, 3) and p.should_kill(1, 4)
    # half a kill spec is a rejected typo, not a silent no-op
    with pytest.raises(ValueError, match="kill_rank and kill_at"):
        FaultPlan(kill_rank=1)
    with pytest.raises(ValueError, match="kill_rank and kill_at"):
        FaultPlan(kill_at=5)


# ----------------------------------------------------------------------
# codec: the attempt field rides redeliveries only

def test_codec_attempt_roundtrip():
    first = Message(msg_type="execute", data="x")
    assert decode(encode(first)).attempt == 0
    first.attempt = 2
    again = decode(encode(first))
    assert again.attempt == 2 and again.msg_id == first.msg_id


# ----------------------------------------------------------------------
# ReplayCache

def _msg(t="execute", data=None):
    return Message(msg_type=t, data=data)


def test_replay_cache_hit_and_counters():
    c = ReplayCache()
    req = _msg()
    rep = req.reply(data={"output": "1"})
    assert c.get(req.msg_id) is None
    assert c.put(req, rep)
    assert c.get(req.msg_id) is rep
    assert c.hits == 1 and c.stores == 1


def test_replay_cache_lru_bound():
    c = ReplayCache(capacity=3)
    reqs = [_msg() for _ in range(5)]
    for r in reqs:
        c.put(r, r.reply(data={}))
    assert len(c) == 3
    assert c.get(reqs[0].msg_id) is None      # evicted
    assert c.get(reqs[-1].msg_id) is not None


def test_replay_cache_total_byte_budget_evicts_old_keeps_recent():
    """Mutating replies are always cached, but their accumulated size
    is capped: old entries evict down to the byte budget while the
    min_keep most recent (the only retry targets) always survive."""
    c = ReplayCache(capacity=100, max_total_bytes=10_000, min_keep=2)
    reqs = [_msg("execute", f"cell {i}") for i in range(6)]
    for r in reqs:
        assert c.put(r, r.reply(data={"output": "x" * 3000}))
    assert c.total_bytes <= 10_000 + 3000  # budget honored (±1 entry)
    assert len(c) >= 2
    assert c.get(reqs[-1].msg_id) is not None   # most recent kept
    assert c.get(reqs[0].msg_id) is None        # oldest evicted
    # min_keep floor: a tiny budget still keeps the recent tail
    c2 = ReplayCache(capacity=100, max_total_bytes=1, min_keep=2)
    r1, r2, r3 = (_msg("execute", str(i)) for i in range(3))
    for r in (r1, r2, r3):
        c2.put(r, r.reply(data={"output": "y" * 500}))
    assert len(c2) == 2
    assert c2.get(r3.msg_id) is not None


def test_replay_cache_oversized_readonly_not_pinned():
    import numpy as np
    c = ReplayCache(max_buf_bytes=100)
    big = _msg("get_var", "params")
    big_reply = big.reply(data={"array": True},
                          bufs={"value": np.zeros(1000, np.float32)})
    assert not c.put(big, big_reply)          # re-reading is safe
    assert c.get(big.msg_id) is None
    # mutating types are always cached, whatever their size
    ex = _msg("execute", "x = 1")
    ex_reply = ex.reply(data={},
                        bufs={"value": np.zeros(1000, np.float32)})
    assert c.put(ex, ex_reply)
    assert c.get(ex.msg_id) is ex_reply


# ----------------------------------------------------------------------
# Supervisor state machine (fake comm/pm — no processes)

class FakePM:
    def __init__(self):
        self.cbs = []

    def add_death_callback(self, cb):
        self.cbs.append(cb)

    def die(self, rank, rc=-9):
        for cb in self.cbs:
            cb(rank, rc)


class FakeComm:
    def __init__(self, n=2):
        self.num_workers = n
        self.pings = {}
        self.seen = {}

    def last_ping(self, rank):
        return self.pings.get(rank)

    def last_seen(self, rank):
        return self.seen.get(rank)


FAST = SupervisorPolicy(poll_s=0.02, degraded_after_s=0.3)


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_supervisor_heals_on_death_and_rebinds():
    healed = threading.Event()
    comm2, pm2 = FakeComm(), FakePM()

    def heal():
        healed.set()
        return comm2, pm2

    sup = Supervisor(FAST, heal=heal)
    comm, pm = FakeComm(), FakePM()
    try:
        sup.attach(comm, pm)
        now = time.time()
        comm.seen = {0: now, 1: now}
        pm.die(1)
        assert healed.wait(5), "heal was never invoked"
        assert _wait(sup.healthy)
        st = sup.status()
        assert st["heals_done"] == 1 and st["restarts_used"] == 1
        # rebound to the fresh pair: a death on the NEW pm is seen
        pm2.die(0)
        assert _wait(lambda: sup.status()["heals_done"] == 2)
        kinds = [(e["rank"], e["to"]) for e in sup.status()["events"]]
        assert (1, "dead") in kinds and (1, "healing") in kinds
    finally:
        sup.stop()


def test_supervisor_restart_budget_caps_crash_loops():
    calls = []
    sup = Supervisor(SupervisorPolicy(poll_s=0.02, max_restarts=1,
                                      restart_window_s=600.0),
                     heal=lambda: calls.append(1) or None)
    comm, pm = FakeComm(), FakePM()
    try:
        sup.attach(comm, pm)
        pm.die(0)
        assert _wait(lambda: len(calls) == 1)
        assert _wait(sup.healthy)
        pm.die(1)  # budget (1) exhausted: must NOT heal again
        assert _wait(lambda: any("budget exhausted" in e["detail"]
                                 for e in sup.status()["events"]))
        assert len(calls) == 1
        assert sup.status()["states"][1] == "dead"
    finally:
        sup.stop()


def test_supervisor_degraded_is_not_dead():
    """Stale heartbeats flag a rank degraded — and recover to alive
    when pings resume; heal never fires for staleness alone."""
    calls = []
    sup = Supervisor(FAST, heal=lambda: calls.append(1) or None)
    comm, pm = FakeComm(), FakePM()
    try:
        sup.attach(comm, pm)
        now = time.time()
        comm.seen = {0: now, 1: now - 10}     # rank 1 silent for 10s
        assert _wait(lambda: sup.status()["states"][1] == "degraded")
        assert sup.status()["states"][0] == "alive"
        comm.seen[1] = time.time()            # heartbeat resumes
        assert _wait(lambda: sup.status()["states"][1] == "alive")
        assert not calls
    finally:
        sup.stop()


def test_supervisor_failed_heal_retries_until_budget_exhausted():
    """A transient respawn failure re-arms the heal (bounded by the
    restart budget) instead of silently ending supervision; a world
    that keeps failing stops at 'budget exhausted'."""
    def heal():
        raise RuntimeError("respawn failed")

    sup = Supervisor(SupervisorPolicy(poll_s=0.02, max_restarts=2,
                                      restart_window_s=600.0),
                     heal=heal)
    comm, pm = FakeComm(), FakePM()
    try:
        sup.attach(comm, pm)
        pm.die(0)
        assert _wait(lambda: sup.status()["heals_failed"] == 2)
        assert _wait(lambda: any("heal failed" in e["detail"]
                                 for e in sup.status()["events"]))
        assert _wait(lambda: any("budget exhausted" in e["detail"]
                                 for e in sup.status()["events"]))
        time.sleep(0.2)  # must not keep retrying past the budget
        assert sup.status()["heals_failed"] == 2
        assert not sup.healthy()
    finally:
        sup.stop()


# ----------------------------------------------------------------------
# Coordinator redelivery over the real transport (scripted workers)

class ScriptedWorker:
    """Worker loop that answers via a handler(rank, msg) -> data|None."""

    def __init__(self, port, rank, handler):
        self.chan = WorkerChannel("127.0.0.1", port, rank=rank)
        self.rank = rank
        self.handler = handler
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while True:
            try:
                msg = self.chan.recv()
            except Exception:
                return
            out = self.handler(self.rank, msg)
            if out is not None:
                try:
                    self.chan.send(msg.reply(data=out, rank=self.rank))
                except Exception:
                    return

    def close(self):
        self.chan.close()


def test_redelivery_after_dropped_request_same_msg_id():
    """The listener drops the first delivery; the retry layer resends
    the SAME msg_id with a bumped attempt and the request completes
    well inside its total deadline."""
    mgr = CommunicationManager(
        num_workers=1, timeout=30,
        retry=RetryPolicy(attempts=3, attempt_timeout_s=0.3,
                          backoff_base_s=0.05, jitter=0.0))
    seen = []

    class DropFirst(FaultPlan):
        def decide(self, index):
            return ["drop"] if index == 0 else []

    mgr.set_fault_plan(DropFirst())
    w = ScriptedWorker(mgr.port, 0,
                       lambda r, m: seen.append((m.msg_id, m.attempt))
                       or {"ok": True})
    try:
        mgr.wait_for_workers(timeout=10)
        t0 = time.time()
        out = mgr.send_to_all("execute", "x")
        assert time.time() - t0 < 5
        assert out[0].data == {"ok": True}
        assert mgr.retries_sent >= 1
        # worker saw exactly one delivery (the redelivery), attempt 1
        assert len(seen) == 1 and seen[0][1] == 1
    finally:
        w.close()
        mgr.shutdown()


def test_redelivery_of_lost_reply_not_reexecuted_semantics():
    """A worker whose FIRST reply is eaten: redelivery arrives under
    the same msg_id; the (scripted) worker answers it again and the
    coordinator returns exactly one response object."""
    replies = {"n": 0}

    def handler(rank, msg):
        replies["n"] += 1
        return {"n": replies["n"], "attempt": msg.attempt}

    mgr = CommunicationManager(
        num_workers=1, timeout=30,
        retry=RetryPolicy(attempts=4, attempt_timeout_s=0.3,
                          backoff_base_s=0.05, jitter=0.0))
    w = ScriptedWorker(mgr.port, 0, handler)
    try:
        mgr.wait_for_workers(timeout=10)

        class DropFirstReply(FaultPlan):
            def decide(self, index):
                return ["drop"] if index == 0 else []

        w.chan.fault_plan = DropFirstReply()
        out = mgr.send_to_all("execute", "x")
        # first reply dropped -> redelivered request answered again
        assert out[0].data["n"] == 2 and out[0].data["attempt"] == 1
    finally:
        w.close()
        mgr.shutdown()


def test_no_retry_policy_single_attempt_times_out_unchanged():
    """Without a policy the old contract holds: one delivery, timeout
    names the missing ranks."""
    mgr = CommunicationManager(num_workers=1, timeout=0.3)
    deliveries = []
    w = ScriptedWorker(mgr.port, 0,
                       lambda r, m: deliveries.append(m.attempt) and None)
    try:
        mgr.wait_for_workers(timeout=10)
        with pytest.raises(TimeoutError, match=r"\[0\]"):
            mgr.send_to_all("execute", "x")
        assert deliveries == [0]
    finally:
        w.close()
        mgr.shutdown()
