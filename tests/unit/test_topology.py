"""Pre-spawn resource validation + bring-up timeout diagnostics.

The reference validates its GPU-id list against torch.cuda before any
spawn (reference: magic.py:454-488); these tests cover the TPU analog
(chip-count probe vs the requested topology) and the elapsed/budget
timeout message (a 240 s wait once reported "did not attach within 2s"
— the poll interval)."""

import pytest

from nbdistributed_tpu.manager import topology
from nbdistributed_tpu.manager.process_manager import wait_until_ready


def test_available_chips_from_axon_pool(monkeypatch):
    monkeypatch.setattr(
        "glob.glob", lambda pat: [])
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert topology.available_tpu_chips() == 1
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1,10.0.0.2, ")
    assert topology.available_tpu_chips() == 2


def test_available_chips_from_device_nodes(monkeypatch):
    monkeypatch.setattr(
        "glob.glob",
        lambda pat: [f"/dev/accel{i}" for i in range(4)]
        if "accel" in pat else [])
    assert topology.available_tpu_chips() == 4


def test_available_chips_unknown(monkeypatch):
    monkeypatch.setattr("glob.glob", lambda pat: [])
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    assert topology.available_tpu_chips() is None


def test_validate_rejects_oversubscription(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    with pytest.raises(ValueError) as e:
        topology.validate_tpu_request(8, 1)
    msg = str(e.value)
    assert "8" in msg and "has 1" in msg and "-n 1" in msg


def test_validate_accounts_chips_per_worker(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    with pytest.raises(ValueError, match="= 8 TPU chips"):
        topology.validate_tpu_request(2, 4)
    topology.validate_tpu_request(1, 4)  # fits: no raise


def test_validate_passes_when_unknown(monkeypatch):
    """No probe signal -> trust the user (workers will report)."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: None)
    topology.validate_tpu_request(8, 1)


def test_validate_rejects_unsupported_grid(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="unsupported"):
        topology.validate_tpu_request(3, 1)


def test_start_workers_tpu_fails_fast_before_spawn(monkeypatch):
    """%dist_init -n 8 on a 1-chip host must fail in <1s with an
    actionable message and zero children spawned."""
    from nbdistributed_tpu.manager import ProcessManager

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    pm = ProcessManager()
    with pytest.raises(ValueError, match="host has 1"):
        pm.start_workers(8, 55555, backend="tpu")
    assert not pm.processes


# ---------------------------------------------------------------------
# explicit chip pinning (--chips): the reference's --gpu-ids analog
# (reference: magic.py:454-488 validation, process_manager.py:107-112
# assignment/recycling)

def test_parse_chips():
    assert topology.parse_chips("2,3") == [2, 3]
    assert topology.parse_chips(" 0, 1 ,3") == [0, 1, 3]


def test_parse_chips_bad_format():
    with pytest.raises(ValueError, match="comma-separated integers"):
        topology.parse_chips("2,x")
    with pytest.raises(ValueError, match="comma-separated integers"):
        topology.parse_chips("2;3")
    with pytest.raises(ValueError, match=">= 0"):
        topology.parse_chips("0,-1")


def test_chip_pinning_env_non_contiguous(monkeypatch):
    """--chips 2,3 on a shared host: rank r pins chips[r], not r."""
    for rank, want in ((0, "2"), (1, "3")):
        env = topology.tpu_worker_env(rank, 2, chips=[2, 3], base={})
        assert env["TPU_VISIBLE_CHIPS"] == want
        assert env["TPU_PROCESS_BOUNDS"] == "1,2,1"
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"


def test_chip_pinning_env_multi_chip_worker():
    """chips_per_worker=2 with an explicit list: consecutive slices."""
    env0 = topology.tpu_worker_env(0, 2, chips_per_worker=2,
                                   chips=[4, 5, 6, 7], base={})
    env1 = topology.tpu_worker_env(1, 2, chips_per_worker=2,
                                   chips=[4, 5, 6, 7], base={})
    assert env0["TPU_VISIBLE_CHIPS"] == "4,5"
    assert env1["TPU_VISIBLE_CHIPS"] == "6,7"


def test_chip_pinning_env_short_list_raises():
    """A short chip list raises at env-construction time (never the
    reference's modulo recycling, process_manager.py:107-112 — TPU
    runtime processes cannot share a chip), so direct callers of
    tpu_worker_env that bypass validate_tpu_request still cannot pin
    two workers to one chip."""
    with pytest.raises(ValueError, match="never recycled"):
        topology.tpu_worker_env(1, 2, chips=[5], base={})
    with pytest.raises(ValueError, match="never recycled"):
        topology.tpu_worker_env(1, 2, chips_per_worker=2,
                                chips=[0, 1, 2], base={})
    # Duplicates in a long-enough list are equally chip-sharing.
    with pytest.raises(ValueError, match="duplicate ids"):
        topology.tpu_worker_env(0, 2, chips_per_worker=2,
                                chips=[0, 1, 0, 1], base={})


def test_grid_blocks_no_phantom_ids():
    """The consecutive-run fallback never emits ids past total_chips
    (partial trailing blocks are dropped, not padded)."""
    for total, cpw in ((8, 3), (4, 3), (8, 5)):
        for b in topology._grid_blocks(total, cpw):
            assert all(c < total for c in b), (total, cpw, b)
            assert len(b) == cpw


def test_validate_chips_non_v5e_host_skips_geometry(monkeypatch):
    """A probed count outside the v5e grid table (e.g. a 16-entry axon
    pool) must skip the subgrid checks — never re-anchor them to the
    request size."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 16)
    assert topology.validate_tpu_request(1, 2, chips=[2, 3]) == 16


def test_validate_chips_adjacency(monkeypatch):
    """chips_per_worker>1 requires each worker's slice to be an
    aligned physical subgrid block of the host grid (the TPU runtime
    carves a contiguous (cx,cy) subgrid per process)."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="physical subgrid"):
        topology.validate_tpu_request(2, 2, chips=[0, 2, 4, 6])
    with pytest.raises(ValueError, match="physical subgrid"):
        topology.validate_tpu_request(1, 2, chips=[1, 2])  # unaligned
    topology.validate_tpu_request(2, 2, chips=[0, 1, 2, 3])  # ok
    topology.validate_tpu_request(1, 2, chips=[2, 3])        # ok
    topology.validate_tpu_request(2, 2, chips=[2, 3, 0, 1])  # any order


def test_validate_chips_subgrid_blocks_cpw4(monkeypatch):
    """4 chips/worker on a (2,4) v5e-8: the physical 2x2 subgrids are
    {0,1,4,5} / {2,3,6,7} under the row-major id map — NOT consecutive
    id runs.  The validator and the default env derive from the same
    carve, so the blocks agree."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    topology.validate_tpu_request(2, 4, chips=[0, 1, 4, 5, 2, 3, 6, 7])
    with pytest.raises(ValueError, match="physical subgrid"):
        # A consecutive id run is a 1x4 strip, contradicting the
        # declared 2x2 TPU_CHIPS_PER_PROCESS_BOUNDS carve.
        topology.validate_tpu_request(2, 4, chips=list(range(8)))
    env0 = topology.tpu_worker_env(0, 2, chips_per_worker=4, base={})
    env1 = topology.tpu_worker_env(1, 2, chips_per_worker=4, base={})
    assert env0["TPU_VISIBLE_CHIPS"] == "0,1,4,5"
    assert env1["TPU_VISIBLE_CHIPS"] == "2,3,6,7"
    assert env0["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
    assert env0["TPU_PROCESS_BOUNDS"] == "1,2,1"


def test_multi_chip_default_carve_is_host_aware(monkeypatch):
    """A 4-chip worker on an 8-chip host must get a 2x2 block of the
    HOST's (2,4) grid — {0,1,4,5} — not the (2,2) grid's {0,1,2,3};
    the env carve and validate_tpu_request agree on the geometry."""
    env = topology.tpu_worker_env(0, 1, chips_per_worker=4,
                                  host_chips=8, base={})
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,4,5"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    topology.validate_tpu_request(1, 4, chips=[0, 1, 4, 5])   # ok
    with pytest.raises(ValueError, match="physical subgrid"):
        topology.validate_tpu_request(1, 4, chips=[0, 1, 2, 3])
    # Without host info the requested total is the grid (standalone
    # 4-chip host): a (2,2) grid is one block, consecutive ids.
    env = topology.tpu_worker_env(0, 1, chips_per_worker=4, base={})
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    # Explicit non-first blocks still span a coherent process grid:
    # workers on blocks {4,5} and {6,7} of the (2,4) host sit in one
    # grid row of blocks -> process bounds 1,2.
    env = topology.tpu_worker_env(0, 2, chips_per_worker=2,
                                  chips=[4, 5, 6, 7], host_chips=8,
                                  base={})
    assert env["TPU_PROCESS_BOUNDS"] == "1,2,1"


def test_validate_chips_rectangle_and_ordering(monkeypatch):
    """Diagonal block picks are rejected (the TPU process grid is a
    rectangle: 2 workers on blocks {0,1}+{6,7} of a (2,4) host would
    declare 4 process slots); out-of-range ids get the range error,
    not a misleading subgrid message."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="rectangle"):
        topology.validate_tpu_request(2, 2, chips=[0, 1, 6, 7])
    with pytest.raises(ValueError, match="rectangle"):
        topology.validate_tpu_request(2, 2, chips=[2, 3, 4, 5])
    topology.validate_tpu_request(2, 2, chips=[0, 1, 4, 5])  # a column
    with pytest.raises(ValueError, match="Invalid chip IDs: \\[8, 9\\]"):
        topology.validate_tpu_request(2, 2, chips=[0, 1, 8, 9])
    assert topology.validate_tpu_request(2, 2,
                                         chips=[0, 1, 2, 3]) == 8
    # tpu_worker_env falls back to the linear carve (never an
    # inconsistent rectangle) when handed a non-rectangular pick, and
    # raises (not IndexError) when the host has too few blocks.
    env = topology.tpu_worker_env(0, 2, chips_per_worker=2,
                                  chips=[0, 1, 6, 7], host_chips=8,
                                  base={})
    assert env["TPU_PROCESS_BOUNDS"] == "1,2,1"
    with pytest.raises(ValueError, match="subgrid block"):
        topology.tpu_worker_env(1, 2, chips_per_worker=4,
                                host_chips=4, base={})


def test_validate_chips_not_enough(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="Not enough chip IDs"):
        topology.validate_tpu_request(4, 1, chips=[2, 3])
    with pytest.raises(ValueError, match="Need 4"):
        topology.validate_tpu_request(2, 2, chips=[0, 1, 2])


def test_validate_chips_duplicates(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="duplicate chip IDs"):
        topology.validate_tpu_request(2, 1, chips=[3, 3])


def test_validate_chips_invalid_vs_available(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    with pytest.raises(ValueError) as e:
        topology.validate_tpu_request(2, 1, chips=[2, 9])
    msg = str(e.value)
    assert "Invalid chip IDs: [9]" in msg
    assert "[0, 1, 2, 3]" in msg


def test_validate_chips_ok(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    topology.validate_tpu_request(2, 1, chips=[2, 3])   # no raise
    # Extra ids beyond the need are allowed (first N used) and not
    # held against availability.
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 2)
    topology.validate_tpu_request(2, 1, chips=[0, 1, 9])


def test_validate_chips_unknown_count(monkeypatch):
    """No probe signal: format/count/dup checks still apply, the
    availability AND subgrid-geometry checks are skipped (a (1,2)
    block at ids [2,3] is legal on a real v5e-8 even though a
    2-chip grid alone wouldn't contain it)."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: None)
    topology.validate_tpu_request(2, 1, chips=[6, 7])
    assert topology.validate_tpu_request(1, 2, chips=[2, 3]) is None


def test_start_workers_rejects_bad_chip_request(monkeypatch):
    from nbdistributed_tpu.manager import ProcessManager

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    pm = ProcessManager()
    with pytest.raises(ValueError, match="Not enough chip IDs"):
        pm.start_workers(4, 55555, backend="tpu", chips=[1, 2])
    assert not pm.processes


class _FakeComm:
    num_workers = 4

    def connected_ranks(self):
        return [0, 2]

    def wait_for_workers(self, timeout):
        import time
        time.sleep(min(timeout, 0.01))
        raise TimeoutError(f"within {timeout:.0f}s")  # inner message


class _FakePM:
    def check_startup_failure(self):
        pass


def test_wait_until_ready_reports_elapsed_and_budget():
    with pytest.raises(TimeoutError) as e:
        wait_until_ready(_FakeComm(), _FakePM(), 0.05, poll_s=0.01)
    msg = str(e.value)
    assert "budget 0s" in msg or "budget" in msg
    assert "[1, 3]" in msg, f"should name missing ranks: {msg}"
    assert "within 0s" in msg  # elapsed, not the 0.01s poll interval
