"""Pre-spawn resource validation + bring-up timeout diagnostics.

The reference validates its GPU-id list against torch.cuda before any
spawn (reference: magic.py:454-488); these tests cover the TPU analog
(chip-count probe vs the requested topology) and the elapsed/budget
timeout message (a 240 s wait once reported "did not attach within 2s"
— the poll interval)."""

import pytest

from nbdistributed_tpu.manager import topology
from nbdistributed_tpu.manager.process_manager import wait_until_ready


def test_available_chips_from_axon_pool(monkeypatch):
    monkeypatch.setattr(
        "glob.glob", lambda pat: [])
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert topology.available_tpu_chips() == 1
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1,10.0.0.2, ")
    assert topology.available_tpu_chips() == 2


def test_available_chips_from_device_nodes(monkeypatch):
    monkeypatch.setattr(
        "glob.glob",
        lambda pat: [f"/dev/accel{i}" for i in range(4)]
        if "accel" in pat else [])
    assert topology.available_tpu_chips() == 4


def test_available_chips_unknown(monkeypatch):
    monkeypatch.setattr("glob.glob", lambda pat: [])
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    assert topology.available_tpu_chips() is None


def test_validate_rejects_oversubscription(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    with pytest.raises(ValueError) as e:
        topology.validate_tpu_request(8, 1)
    msg = str(e.value)
    assert "8" in msg and "has 1" in msg and "-n 1" in msg


def test_validate_accounts_chips_per_worker(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    with pytest.raises(ValueError, match="= 8 TPU chips"):
        topology.validate_tpu_request(2, 4)
    topology.validate_tpu_request(1, 4)  # fits: no raise


def test_validate_passes_when_unknown(monkeypatch):
    """No probe signal -> trust the user (workers will report)."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: None)
    topology.validate_tpu_request(8, 1)


def test_validate_rejects_unsupported_grid(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="unsupported"):
        topology.validate_tpu_request(3, 1)


def test_start_workers_tpu_fails_fast_before_spawn(monkeypatch):
    """%dist_init -n 8 on a 1-chip host must fail in <1s with an
    actionable message and zero children spawned."""
    from nbdistributed_tpu.manager import ProcessManager

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    pm = ProcessManager()
    with pytest.raises(ValueError, match="host has 1"):
        pm.start_workers(8, 55555, backend="tpu")
    assert not pm.processes


class _FakeComm:
    num_workers = 4

    def connected_ranks(self):
        return [0, 2]

    def wait_for_workers(self, timeout):
        import time
        time.sleep(min(timeout, 0.01))
        raise TimeoutError(f"within {timeout:.0f}s")  # inner message


class _FakePM:
    def check_startup_failure(self):
        pass


def test_wait_until_ready_reports_elapsed_and_budget():
    with pytest.raises(TimeoutError) as e:
        wait_until_ready(_FakeComm(), _FakePM(), 0.05, poll_s=0.01)
    msg = str(e.value)
    assert "budget 0s" in msg or "budget" in msg
    assert "[1, 3]" in msg, f"should name missing ranks: {msg}"
    assert "within 0s" in msg  # elapsed, not the 0.01s poll interval
