"""Pre-spawn resource validation + bring-up timeout diagnostics.

The reference validates its GPU-id list against torch.cuda before any
spawn (reference: magic.py:454-488); these tests cover the TPU analog
(chip-count probe vs the requested topology) and the elapsed/budget
timeout message (a 240 s wait once reported "did not attach within 2s"
— the poll interval)."""

import pytest

from nbdistributed_tpu.manager import topology
from nbdistributed_tpu.manager.process_manager import wait_until_ready


def test_available_chips_from_axon_pool(monkeypatch):
    monkeypatch.setattr(
        "glob.glob", lambda pat: [])
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert topology.available_tpu_chips() == 1
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1,10.0.0.2, ")
    assert topology.available_tpu_chips() == 2


def test_available_chips_from_device_nodes(monkeypatch):
    monkeypatch.setattr(
        "glob.glob",
        lambda pat: [f"/dev/accel{i}" for i in range(4)]
        if "accel" in pat else [])
    assert topology.available_tpu_chips() == 4


def test_available_chips_unknown(monkeypatch):
    monkeypatch.setattr("glob.glob", lambda pat: [])
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    assert topology.available_tpu_chips() is None


def test_validate_rejects_oversubscription(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    with pytest.raises(ValueError) as e:
        topology.validate_tpu_request(8, 1)
    msg = str(e.value)
    assert "8" in msg and "has 1" in msg and "-n 1" in msg


def test_validate_accounts_chips_per_worker(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    with pytest.raises(ValueError, match="= 8 TPU chips"):
        topology.validate_tpu_request(2, 4)
    topology.validate_tpu_request(1, 4)  # fits: no raise


def test_validate_passes_when_unknown(monkeypatch):
    """No probe signal -> trust the user (workers will report)."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: None)
    topology.validate_tpu_request(8, 1)


def test_validate_rejects_unsupported_grid(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="unsupported"):
        topology.validate_tpu_request(3, 1)


def test_start_workers_tpu_fails_fast_before_spawn(monkeypatch):
    """%dist_init -n 8 on a 1-chip host must fail in <1s with an
    actionable message and zero children spawned."""
    from nbdistributed_tpu.manager import ProcessManager

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 1)
    pm = ProcessManager()
    with pytest.raises(ValueError, match="host has 1"):
        pm.start_workers(8, 55555, backend="tpu")
    assert not pm.processes


# ---------------------------------------------------------------------
# explicit chip pinning (--chips): the reference's --gpu-ids analog
# (reference: magic.py:454-488 validation, process_manager.py:107-112
# assignment/recycling)

def test_parse_chips():
    assert topology.parse_chips("2,3") == [2, 3]
    assert topology.parse_chips(" 0, 1 ,3") == [0, 1, 3]


def test_parse_chips_bad_format():
    with pytest.raises(ValueError, match="comma-separated integers"):
        topology.parse_chips("2,x")
    with pytest.raises(ValueError, match="comma-separated integers"):
        topology.parse_chips("2;3")
    with pytest.raises(ValueError, match=">= 0"):
        topology.parse_chips("0,-1")


def test_chip_pinning_env_non_contiguous(monkeypatch):
    """--chips 2,3 on a shared host: rank r pins chips[r], not r."""
    for rank, want in ((0, "2"), (1, "3")):
        env = topology.tpu_worker_env(rank, 2, chips=[2, 3], base={})
        assert env["TPU_VISIBLE_CHIPS"] == want
        assert env["TPU_PROCESS_BOUNDS"] == "1,2,1"
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"


def test_chip_pinning_env_multi_chip_worker():
    """chips_per_worker=2 with an explicit list: consecutive slices."""
    env0 = topology.tpu_worker_env(0, 2, chips_per_worker=2,
                                   chips=[4, 5, 6, 7], base={})
    env1 = topology.tpu_worker_env(1, 2, chips_per_worker=2,
                                   chips=[4, 5, 6, 7], base={})
    assert env0["TPU_VISIBLE_CHIPS"] == "4,5"
    assert env1["TPU_VISIBLE_CHIPS"] == "6,7"


def test_chip_pinning_env_recycles_modulo():
    """API-layer parity with the reference's modulo fallback
    (process_manager.py:107-112); the validated path rejects short
    lists before this engages."""
    env = topology.tpu_worker_env(1, 2, chips=[5], base={})
    assert env["TPU_VISIBLE_CHIPS"] == "5"


def test_validate_chips_not_enough(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="Not enough chip IDs"):
        topology.validate_tpu_request(4, 1, chips=[2, 3])
    with pytest.raises(ValueError, match="Need 4"):
        topology.validate_tpu_request(2, 2, chips=[0, 1, 2])


def test_validate_chips_duplicates(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 8)
    with pytest.raises(ValueError, match="duplicate chip IDs"):
        topology.validate_tpu_request(2, 1, chips=[3, 3])


def test_validate_chips_invalid_vs_available(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    with pytest.raises(ValueError) as e:
        topology.validate_tpu_request(2, 1, chips=[2, 9])
    msg = str(e.value)
    assert "Invalid chip IDs: [9]" in msg
    assert "[0, 1, 2, 3]" in msg


def test_validate_chips_ok(monkeypatch):
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    topology.validate_tpu_request(2, 1, chips=[2, 3])   # no raise
    # Extra ids beyond the need are allowed (first N used) and not
    # held against availability.
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 2)
    topology.validate_tpu_request(2, 1, chips=[0, 1, 9])


def test_validate_chips_unknown_count(monkeypatch):
    """No probe signal: format/count/dup checks still apply, the
    availability check is skipped."""
    monkeypatch.setattr(topology, "available_tpu_chips", lambda: None)
    topology.validate_tpu_request(2, 1, chips=[6, 7])


def test_start_workers_rejects_bad_chip_request(monkeypatch):
    from nbdistributed_tpu.manager import ProcessManager

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    pm = ProcessManager()
    with pytest.raises(ValueError, match="Not enough chip IDs"):
        pm.start_workers(4, 55555, backend="tpu", chips=[1, 2])
    assert not pm.processes


class _FakeComm:
    num_workers = 4

    def connected_ranks(self):
        return [0, 2]

    def wait_for_workers(self, timeout):
        import time
        time.sleep(min(timeout, 0.01))
        raise TimeoutError(f"within {timeout:.0f}s")  # inner message


class _FakePM:
    def check_startup_failure(self):
        pass


def test_wait_until_ready_reports_elapsed_and_budget():
    with pytest.raises(TimeoutError) as e:
        wait_until_ready(_FakeComm(), _FakePM(), 0.05, poll_s=0.01)
    msg = str(e.value)
    assert "budget 0s" in msg or "budget" in msg
    assert "[1, 3]" in msg, f"should name missing ranks: {msg}"
    assert "within 0s" in msg  # elapsed, not the 0.01s poll interval
