"""The native C++ listener must be behaviorally identical to the Python
listener — same protocol, same callbacks, same routing."""

import threading
import time

import numpy as np
import pytest

from nbdistributed_tpu.messaging import native as native_mod
from nbdistributed_tpu.messaging.codec import Message
from nbdistributed_tpu.messaging.transport import (
    CoordinatorListener, TransportError, WorkerChannel)

IMPLS = ["python", "native"] if native_mod.available() else ["python"]


@pytest.fixture(params=IMPLS)
def listener(request):
    if request.param == "native":
        lst = native_mod.NativeCoordinatorListener()
    else:
        lst = CoordinatorListener()
    received, connected, disconnected = [], [], []
    lst.on_message = lambda r, m: received.append((r, m))
    lst.on_connect = connected.append
    lst.on_disconnect = disconnected.append
    lst.start()
    lst.received, lst.connected, lst.disconnected = (
        received, connected, disconnected)
    yield lst
    lst.close()


def wait_until(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


def test_native_lib_builds_and_loads():
    assert native_mod.available(), \
        "native transport must build in this environment (run native/build.sh)"


def test_preamble_identifies_rank(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=5)
    assert wait_until(lambda: listener.connected == [5])
    assert listener.connected_ranks() == [5]
    ch.close()
    assert wait_until(lambda: listener.disconnected == [5])


def test_roundtrip_and_routing(listener):
    chans = [WorkerChannel("127.0.0.1", listener.port, rank=r)
             for r in range(3)]
    assert wait_until(lambda: len(listener.connected) == 3)
    chans[2].send(Message(msg_type="response", rank=2, data={"v": 42}))
    assert wait_until(lambda: len(listener.received) == 1)
    r, msg = listener.received[0]
    assert r == 2 and msg.data == {"v": 42}

    listener.send_to_ranks([0, 2], Message(msg_type="go"))
    assert chans[0].recv(timeout=5).msg_type == "go"
    assert chans[2].recv(timeout=5).msg_type == "go"
    with pytest.raises(TimeoutError):
        chans[1].recv(timeout=0.2)
    for c in chans:
        c.close()


def test_send_to_missing_rank_raises(listener):
    with pytest.raises(TransportError):
        listener.send_to_rank(77, Message(msg_type="x"))


def test_large_binary_frame(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: 0 in listener.connected)
    big = np.random.default_rng(1).standard_normal((1024, 1024)) \
        .astype("float32")  # 4 MB
    ch.send(Message(msg_type="response", rank=0, bufs={"t": big}))
    assert wait_until(lambda: len(listener.received) == 1, timeout=15)
    np.testing.assert_array_equal(listener.received[0][1].bufs["t"], big)
    ch.close()


def test_concurrent_worker_sends(listener):
    ch = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: 0 in listener.connected)
    n_threads, per = 6, 30
    def blast(tid):
        for i in range(per):
            ch.send(Message(msg_type="response", rank=0,
                            data={"tid": tid, "i": i}))
    threads = [threading.Thread(target=blast, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert wait_until(lambda: len(listener.received) == n_threads * per)
    seen = {(m.data["tid"], m.data["i"]) for _, m in listener.received}
    assert len(seen) == n_threads * per
    ch.close()


def test_reconnect_same_rank_no_false_death(listener):
    ch1 = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: listener.connected.count(0) == 1)
    ch2 = WorkerChannel("127.0.0.1", listener.port, rank=0)
    assert wait_until(lambda: listener.connected.count(0) == 2)
    ch1.close()  # old connection dies AFTER replacement
    time.sleep(0.3)
    assert listener.disconnected == []  # rank is still live via ch2
    listener.send_to_rank(0, Message(msg_type="hi"))
    assert ch2.recv(timeout=5).msg_type == "hi"
    ch2.close()
