"""Continuous-batching decode server: staggered admission must be
bit-identical per request to standalone generate(), slots must recycle,
EOS must cut streams, and MoE configs must serve through row_mask."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import generate, init_params, tiny_config
from nbdistributed_tpu.models.serving import DecodeServer

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def solo(params, cfg, prompt, n):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None], cfg, n)
    return [int(t) for t in np.asarray(out)[0][len(prompt):]]


def test_staggered_admission_matches_solo_generate(setup):
    """Three requests of different lengths admitted at different times
    into a 2-slot pool: every request's greedy tokens must equal its
    standalone generate() run — occupancy and admission order must be
    invisible to the numerics."""
    cfg, params = setup
    reqs = [([5, 9, 2], 7), ([7, 1, 3, 11, 4], 5), ([2, 2], 6)]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)

    r0 = srv.submit(*reqs[0])
    srv.step()
    r1 = srv.submit(*reqs[1])          # fills the second slot
    srv.step()
    r2 = srv.submit(*reqs[2])          # queues until a slot frees
    srv.run_until_done(max_steps=100)

    for rid, (prompt, n) in zip((r0, r1, r2), reqs):
        assert srv.outputs[rid] == solo(params, cfg, prompt, n), rid


def test_slots_recycle_and_outputs_complete(setup):
    """More requests than slots: all finish, each with exactly its
    token budget (no EOS in play for random-init logits over a tiny
    vocab is not guaranteed — so disable EOS)."""
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4)
    rids = [srv.submit([i + 1, i + 2], 4) for i in range(5)]
    srv.run_until_done(max_steps=200)
    assert srv.done() and srv.n_active == 0
    for rid in rids:
        assert len(srv.outputs[rid]) == 4
    assert srv.finished == set(rids)


def test_eos_frees_slot_early(setup):
    """A request whose next greedy token IS the eos id must finish on
    that step with the eos included, freeing the slot."""
    cfg, params = setup
    prompt, n = [5, 9, 2], 8
    toks = solo(params, cfg, prompt, n)
    eos = toks[2]                       # force an early cut at step 3
    srv = DecodeServer(params, cfg, max_batch=1, max_len=64,
                       pad_to=4, eos_id=eos)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    got = srv.outputs[rid]
    assert got == toks[:got.index(eos) + 1]
    assert got[-1] == eos and len(got) <= n


def test_single_token_budget_finishes_at_admission(setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4)
    rid = srv.submit([3, 1, 4], 1)
    assert srv.done()
    assert srv.outputs[rid] == solo(params, cfg, [3, 1, 4], 1)


def test_validation_errors(setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=16, pad_to=4)
    with pytest.raises(ValueError, match="empty"):
        srv.submit([], 4)
    with pytest.raises(ValueError, match=">= 1"):
        srv.submit([1], 0)
    with pytest.raises(ValueError, match="exceeds max_len"):
        srv.submit([1] * 10, 10)


def test_sampled_mode_runs_and_respects_budget(setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4,
                       temperature=1.0, top_k=8,
                       key=jax.random.PRNGKey(7))
    rids = [srv.submit([4, 2], 5), srv.submit([9], 3)]
    srv.run_until_done(max_steps=50)
    assert [len(srv.outputs[r]) for r in rids] == [5, 3]
    for r in rids:
        assert all(0 <= t < cfg.vocab_size for t in srv.outputs[r])


def test_int8_cache_serving_matches_int8_generate(setup):
    """kv_quantized serving must equal kv_quantized generate per
    request (same quantized-cache numerics path)."""
    cfg, params = setup
    prompt, n = [5, 9, 2, 7], 6
    ref = generate(params, jnp.asarray(prompt, jnp.int32)[None], cfg,
                   n, kv_quantized=True)
    ref = [int(t) for t in np.asarray(ref)[0][len(prompt):]]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4,
                       kv_quantized=True)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[rid] == ref


def test_int4_params_serving_matches_int4_generate(setup):
    """Nibble-packed int4 weights serve through DecodeServer exactly
    as through standalone generate (the qlinear packed path under the
    server's slot-pooled cache)."""
    from nbdistributed_tpu.models import quantize_params4
    cfg, params = setup
    q4 = quantize_params4(params)
    prompt, n = [5, 9, 2, 7], 6
    ref = generate(q4, jnp.asarray(prompt, jnp.int32)[None], cfg,
                   n, kv_quantized=True)
    ref = [int(t) for t in np.asarray(ref)[0][len(prompt):]]
    srv = DecodeServer(q4, cfg, max_batch=2, max_len=32, pad_to=4,
                       kv_quantized=True)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[rid] == ref


def test_token_mask_keeps_pads_out_of_expert_capacity():
    """forward_with_cache's token_mask: right-pad tokens routed
    through a tight-capacity MoE flood an expert's segment and evict
    real tokens' second-choice slots — with the mask, the padded
    prefill's last-real-token logits equal the unpadded run's; without
    it (seed pair pinned by a scan) they provably differ."""
    from nbdistributed_tpu.models import init_moe_model, tiny_moe_config
    from nbdistributed_tpu.models.generate import (forward_with_cache,
                                                   init_kv_cache)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          capacity_factor=1.0)
    params = init_moe_model(jax.random.PRNGKey(4), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(100), (5,), 1,
                                cfg.vocab_size)
    L, s_pad = 5, 64
    padded = jnp.concatenate(
        [prompt, jnp.zeros((s_pad - L,), jnp.int32)])[None]
    mask = (jnp.arange(s_pad)[None] < L)
    idx = jnp.asarray([L - 1])

    ref, _ = forward_with_cache(params, prompt[None],
                                init_kv_cache(cfg, 1, 80), 0, cfg,
                                last_index=idx)
    masked, _ = forward_with_cache(params, padded,
                                   init_kv_cache(cfg, 1, 80), 0, cfg,
                                   token_mask=mask, last_index=idx)
    unmasked, _ = forward_with_cache(params, padded,
                                     init_kv_cache(cfg, 1, 80), 0, cfg,
                                     last_index=idx)
    # Masked pads change nothing vs the unpadded run (no real-token
    # drops at this size on either side)...
    np.testing.assert_allclose(np.asarray(masked), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # ...while unmasked pads provably perturb the real tokens.
    assert float(jnp.max(jnp.abs(unmasked - ref))) > 0.1


def test_moe_long_prompt_exact_length_admission():
    """MoE expert capacity is shape-derived, so bucket padding would
    inflate it past a solo generate() run's (20 real tokens: solo
    capacity 16 vs a 64-bucket's 32) and change which tokens drop.
    The server admits MoE prompts at exact length — a 20-token prompt
    must match solo generate even with pad_to=64 requested."""
    from nbdistributed_tpu.models import init_moe_model, tiny_moe_config
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          capacity_factor=1.0)
    params = init_moe_model(jax.random.PRNGKey(4), cfg)
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(101), (20,), 1, cfg.vocab_size)]
    n = 4
    ref = generate(params, jnp.asarray(prompt, jnp.int32)[None], cfg, n)
    ref = [int(t) for t in np.asarray(ref)[0][len(prompt):]]
    srv = DecodeServer(params, cfg, max_batch=1, max_len=80, pad_to=64)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[rid] == ref


def test_release_evicts_and_guards_in_flight(setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4)
    rid = srv.submit([3, 1], 3)
    with pytest.raises(ValueError, match="in flight"):
        srv.release(rid)
    srv.run_until_done(max_steps=20)
    toks = srv.release(rid)
    assert len(toks) == 3
    assert rid not in srv.outputs and rid not in srv.prompts
    assert rid not in srv.finished
    with pytest.raises(KeyError, match="already-released"):
        srv.release(rid)
    with pytest.raises(KeyError, match="unknown"):
        srv.release(9999)


def test_moe_family_serves():
    """The MoE family drives the same server (row_mask keeps empty
    slots out of expert capacity); tokens match MoE generate when the
    pool runs a single request (capacity pooling across live rows is
    batched-decode semantics, so only the solo case is exact)."""
    from nbdistributed_tpu.models import init_moe_model, tiny_moe_config
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          capacity_factor=2.0)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    prompt, n = [5, 1, 3], 5
    ref = generate(params, jnp.asarray(prompt, jnp.int32)[None], cfg, n)
    ref = [int(t) for t in np.asarray(ref)[0][len(prompt):]]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=32, pad_to=4)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    assert srv.outputs[rid] == ref


# ---------------------------------------------------------------------
# speculative serving

@pytest.fixture(scope="module")
def spec_setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    target = init_params(jax.random.PRNGKey(0), cfg)
    draft = init_params(jax.random.PRNGKey(42), cfg)  # a WORSE model
    return cfg, target, draft


def test_spec_serving_matches_solo_generate_staggered(spec_setup):
    """Greedy speculative serving must reproduce the TARGET's own
    greedy decode per request (the draft only affects speed), under
    staggered admission into a 2-slot pool."""
    cfg, target, draft = spec_setup
    reqs = [([5, 9, 2], 9), ([7, 1, 3, 11], 6), ([2, 2], 7)]
    srv = DecodeServer(target, cfg, max_batch=2, max_len=64, pad_to=4,
                       draft_params=draft, draft_cfg=cfg, gamma=3)
    r0 = srv.submit(*reqs[0])
    srv.step()
    r1 = srv.submit(*reqs[1])
    srv.step()
    r2 = srv.submit(*reqs[2])
    srv.run_until_done(max_steps=100)
    for rid, (prompt, n) in zip((r0, r1, r2), reqs):
        assert srv.outputs[rid] == solo(target, cfg, prompt, n), rid
        assert len(srv.outputs[rid]) == n


def test_spec_serving_emits_multiple_tokens_per_step(spec_setup):
    """A self-draft accepts everything: each round must emit
    gamma + 1 tokens for the slot (the mechanics of batched verify)."""
    cfg, target, _ = spec_setup
    srv = DecodeServer(target, cfg, max_batch=1, max_len=64, pad_to=4,
                       draft_params=target, draft_cfg=cfg, gamma=3)
    rid = srv.submit([5, 9, 2], 13)
    out = srv.step()
    assert out[rid] and len(out[rid]) == 4   # gamma + 1 accepted
    srv.run_until_done(max_steps=20)
    assert len(srv.outputs[rid]) == 13
    assert srv.outputs[rid] == solo(target, cfg, [5, 9, 2], 13)


def test_spec_serving_eos_cuts_mid_round(spec_setup):
    cfg, target, draft = spec_setup
    prompt, n = [5, 9, 2], 10
    toks = solo(target, cfg, prompt, n)
    eos = toks[4]
    srv = DecodeServer(target, cfg, max_batch=1, max_len=64, pad_to=4,
                       eos_id=eos, draft_params=draft, draft_cfg=cfg,
                       gamma=3)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=50)
    got = srv.outputs[rid]
    assert got[-1] == eos
    assert got == toks[: got.index(eos) + 1]


def test_spec_serving_top_k1_matches_solo_greedy(spec_setup):
    """Speculative serving with sampling + top_k=1 (deterministic
    truncation) must reproduce the target's greedy decode per request
    — the truncation-aware acceptance path through the server."""
    from nbdistributed_tpu.models import generate

    cfg, target, draft = spec_setup
    srv = DecodeServer(target, cfg, max_batch=2, max_len=64, pad_to=4,
                       temperature=0.8, top_k=1,
                       draft_params=draft, draft_cfg=cfg, gamma=3,
                       key=jax.random.PRNGKey(11))
    reqs = [([5, 9, 2], 8), ([7, 1, 3, 11], 6)]
    rids = [srv.submit(*r) for r in reqs]
    srv.run_until_done(max_steps=100)
    for rid, (prompt, n) in zip(rids, reqs):
        solo = generate(target, jnp.asarray([prompt], jnp.int32),
                        cfg, n)
        assert srv.outputs[rid] == [int(t) for t in
                                    solo[0, len(prompt):]]


def test_spec_serving_validation(spec_setup):
    cfg, target, draft = spec_setup
    with pytest.raises(ValueError, match="both draft_params"):
        DecodeServer(target, cfg, max_batch=1, max_len=32,
                     draft_params=draft)
    with pytest.raises(ValueError, match="top_k"):
        DecodeServer(target, cfg, max_batch=1, max_len=32, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        DecodeServer(target, cfg, max_batch=1, max_len=32, top_p=0.0)
    srv = DecodeServer(target, cfg, max_batch=1, max_len=16, pad_to=4,
                       draft_params=draft, draft_cfg=cfg, gamma=3)
    with pytest.raises(ValueError, match="speculative headroom"):
        srv.submit([1, 2, 3, 4], 9)   # 4 + 9 + 4 > 16


def test_step_many_matches_single_steps(setup):
    """step_many(n) must emit exactly what n successive step() calls
    emit (greedy), amortizing the host sync without changing tokens."""
    cfg, params = setup
    reqs = [([5, 9, 2], 9), ([7, 1, 3, 11], 7)]
    a = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)
    b = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)
    ra = [a.submit(*r) for r in reqs]
    rb = [b.submit(*r) for r in reqs]
    for _ in range(8):
        a.step()
    b.step_many(4)
    b.step_many(4)
    for x, y in zip(ra, rb):
        assert a.outputs[x] == b.outputs[y]
    a.run_until_done(max_steps=20)
    b.run_until_done(max_steps=20)
    for x, y, (prompt, n) in zip(ra, rb, reqs):
        assert b.outputs[y] == solo(params, cfg, prompt, n)


def test_step_many_truncates_budget_and_eos(setup):
    cfg, params = setup
    prompt, n = [5, 9, 2], 6
    toks = solo(params, cfg, prompt, n)
    # Budget cut mid-scan: ask for 6, scan 8 past the end.
    srv = DecodeServer(params, cfg, max_batch=1, max_len=64, pad_to=4)
    rid = srv.submit(prompt, n)
    out = srv.step_many(8)
    assert out[rid] == toks[1:]          # seed emitted at admission
    assert srv.done() and len(srv.outputs[rid]) == n
    # EOS cut mid-scan.
    eos = toks[3]
    srv = DecodeServer(params, cfg, max_batch=1, max_len=64, pad_to=4,
                       eos_id=eos)
    rid = srv.submit(prompt, 8)
    srv.step_many(8)
    got = srv.outputs[rid]
    assert got[-1] == eos and got == toks[: got.index(eos) + 1]


def test_step_many_admits_at_boundaries(setup):
    """A request queued while a scan runs is admitted at the next
    boundary and still matches its solo decode."""
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=64, pad_to=4)
    r0 = srv.submit([5, 9, 2], 5)
    r1 = srv.submit([7, 1], 4)           # queued: one slot
    srv.step_many(4)                     # finishes r0, admits r1
    srv.run_until_done(max_steps=20)
    assert srv.outputs[r0] == solo(params, cfg, [5, 9, 2], 5)
    assert srv.outputs[r1] == solo(params, cfg, [7, 1], 4)


def test_step_many_validation(setup, spec_setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4)
    with pytest.raises(ValueError, match=">= 1"):
        srv.step_many(0)
    _, target, draft = spec_setup
    ssrv = DecodeServer(target, cfg, max_batch=1, max_len=32, pad_to=4,
                        draft_params=draft, draft_cfg=cfg)
    with pytest.raises(ValueError, match="plain serving"):
        ssrv.step_many(2)


# ---------------------------------------------------------------------
# chunked prefill admission

@pytest.mark.parametrize("L", [7, 12, 13])
def test_chunked_prefill_matches_solo(setup, L):
    """Chunked admission (chunk=4: exact-multiple, tail, and
    shorter-than-chunk prompts) must be invisible to the numerics —
    outputs equal solo generate and bucketed admission."""
    cfg, params = setup
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(40 + L), (L,), 1, cfg.vocab_size)]
    n = 5
    ref = solo(params, cfg, prompt, n)
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4,
                       prefill_chunk=4)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=30)
    assert srv.outputs[rid] == ref


def test_chunked_prefill_single_compile_shape(setup):
    """Every chunk segment shares one (1, chunk) program: admitting
    prompts of different lengths > chunk adds ONE prefill executable,
    where bucketed admission would mint one per bucket."""
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4,
                       prefill_chunk=4)
    if not hasattr(srv._prefill_fn, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    r0 = srv.submit([int(t) for t in range(1, 10)], 2)    # L=9
    r1 = srv.submit([int(t) for t in range(1, 14)], 2)    # L=13
    srv.run_until_done(max_steps=20)
    assert srv._prefill_fn._cache_size() == 1
    assert len(srv.outputs[r0]) == 2 and len(srv.outputs[r1]) == 2


def test_chunked_prefill_speculative(spec_setup):
    """Chunked admission composes with speculative serving: both
    caches prefill chunk-wise; greedy output equals the target's."""
    from nbdistributed_tpu.models import generate

    cfg, target, draft = spec_setup
    prompt = [5, 9, 2, 7, 1, 3, 11, 4, 6]                 # L=9
    n = 6
    srv = DecodeServer(target, cfg, max_batch=1, max_len=64, pad_to=4,
                       draft_params=draft, draft_cfg=cfg, gamma=3,
                       prefill_chunk=4)
    rid = srv.submit(prompt, n)
    srv.run_until_done(max_steps=30)
    solo_toks = generate(target, jnp.asarray([prompt], jnp.int32),
                         cfg, n)
    assert srv.outputs[rid] == [int(t) for t in
                                solo_toks[0, len(prompt):]]


def test_chunked_prefill_rejected_for_moe():
    from nbdistributed_tpu.models import init_moe_model, tiny_moe_config
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(4), cfg)
    with pytest.raises(ValueError, match="dense-family"):
        DecodeServer(params, cfg, max_batch=1, max_len=32,
                     prefill_chunk=8)
    with pytest.raises(ValueError, match="prefill_chunk"):
        DecodeServer(params, tiny_config(dtype=jnp.float32,
                                         use_flash=False),
                     max_batch=1, max_len=32, prefill_chunk=0)


# ---------------------------------------------------------------------
# spec_step_many: device-side multi-round speculation

def test_spec_step_many_matches_single_steps(spec_setup):
    """spec_step_many(n) must emit exactly what n successive step()
    calls emit (greedy speculative), and both must equal solo
    generate."""
    cfg, target, draft = spec_setup
    reqs = [([5, 9, 2], 9), ([7, 1, 3, 11], 7)]
    mk = lambda: DecodeServer(target, cfg, max_batch=2, max_len=64,
                              pad_to=4, draft_params=draft,
                              draft_cfg=cfg, gamma=3)
    a, b = mk(), mk()
    ra = [a.submit(*r) for r in reqs]
    rb = [b.submit(*r) for r in reqs]
    for _ in range(4):
        a.step()
    b.spec_step_many(2)
    b.spec_step_many(2)
    for x, y in zip(ra, rb):
        assert a.outputs[x] == b.outputs[y]
    while not b.done():
        b.spec_step_many(2)
    for y, (prompt, n) in zip(rb, reqs):
        assert b.outputs[y] == solo(target, cfg, prompt, n)


def test_spec_step_many_freezes_at_max_len(spec_setup):
    """A stream at the tightest legal max_len (prompt + budget +
    gamma + 1): surplus rounds self-freeze device-side instead of
    overflowing the cache, and the output is exactly the budget."""
    cfg, target, draft = spec_setup
    prompt, n, gamma = [5, 9, 2], 6, 3
    T = len(prompt) + n + gamma + 1                  # == 13
    srv = DecodeServer(target, cfg, max_batch=1, max_len=T, pad_to=4,
                       draft_params=draft, draft_cfg=cfg, gamma=gamma)
    rid = srv.submit(prompt, n)
    while not srv.done():
        srv.spec_step_many(4)                        # overshoots freely
    assert srv.outputs[rid] == solo(target, cfg, prompt, n)


def test_spec_step_many_eos_cut(spec_setup):
    """EOS discovered mid-scan truncates host-side exactly like the
    single-round path."""
    cfg, target, draft = spec_setup
    prompt, n = [5, 9, 2], 8
    toks = solo(target, cfg, prompt, n)
    eos = toks[3]
    srv = DecodeServer(target, cfg, max_batch=1, max_len=64, pad_to=4,
                       draft_params=draft, draft_cfg=cfg, gamma=3,
                       eos_id=eos)
    rid = srv.submit(prompt, n)
    while not srv.done():
        srv.spec_step_many(3)
    got = srv.outputs[rid]
    assert got == toks[: toks.index(eos) + 1]


def test_spec_step_many_validation(setup, spec_setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4)
    with pytest.raises(ValueError, match="speculative server"):
        srv.spec_step_many(2)
    _, target, draft = spec_setup
    ssrv = DecodeServer(target, cfg, max_batch=1, max_len=32, pad_to=4,
                        draft_params=draft, draft_cfg=cfg)
    with pytest.raises(ValueError, match=">= 1"):
        ssrv.spec_step_many(0)


# ---------------------------------------------------------------------
# prefix caching (cache_prefix / drop_prefix): shared system prompts
# admit by copying a prefilled KV block + suffix-only prefill

def test_prefix_cache_matches_solo_generate(setup):
    """N requests sharing a system prefix, admitted via cache_prefix:
    every request's greedy tokens must equal its standalone generate()
    run — the copied KV rows are bit-identical to a full prefill's
    (causal attention + absolute RoPE), so solo-equality survives."""
    cfg, params = setup
    sys_prefix = [3, 1, 4, 1, 5, 9, 2, 6]
    suffixes = [[5, 3], [8, 8, 8], [1], [9, 7, 9, 7]]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)
    pid = srv.cache_prefix(sys_prefix)
    assert pid == 0
    rids = [srv.submit(sys_prefix + s, 5) for s in suffixes]
    srv.run_until_done(max_steps=200)
    for rid, s in zip(rids, suffixes):
        assert srv.outputs[rid] == solo(params, cfg, sys_prefix + s, 5), \
            (rid, s)


def test_prefix_cache_whole_prompt_hit(setup):
    """A prompt EQUAL to the cached prefix admits with zero prefill
    forwards (the stored last-token logits seed the stream)."""
    cfg, params = setup
    prefix = [2, 7, 1, 8, 2, 8]
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4)
    srv.cache_prefix(prefix)
    calls = []
    orig = srv._prefill_fn
    srv._prefill_fn = (lambda *a, **k: calls.append(1) or orig(*a, **k))
    rid = srv.submit(prefix, 4)
    srv.run_until_done(max_steps=50)
    assert calls == []          # no prefill forward ran at admission
    assert srv.outputs[rid] == solo(params, cfg, prefix, 4)


def test_prefix_cache_longest_match_and_miss(setup):
    """Longest registered prefix wins; non-matching prompts take the
    plain path; drop_prefix frees and unmatches."""
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)
    p_short = srv.cache_prefix([4, 2])
    p_long = srv.cache_prefix([4, 2, 6, 1])
    assert srv._match_prefix([4, 2, 6, 1, 9]) == p_long
    assert srv._match_prefix([4, 2, 9]) == p_short
    assert srv._match_prefix([9, 9]) is None
    # Both matched and unmatched prompts produce solo-exact streams.
    reqs = [([4, 2, 6, 1, 9], 5), ([9, 9, 3], 5)]
    rids = [srv.submit(p, n) for p, n in reqs]
    srv.run_until_done(max_steps=100)
    for rid, (p, n) in zip(rids, reqs):
        assert srv.outputs[rid] == solo(params, cfg, p, n)
    srv.drop_prefix(p_long)
    assert srv._match_prefix([4, 2, 6, 1, 9]) == p_short
    with pytest.raises(KeyError):
        srv.drop_prefix(p_long)


def test_prefix_cache_saves_prefill_tokens(setup):
    """The admission-cost win: with a cached 16-token prefix, each
    admission's prefill forward sees only the suffix bucket, not the
    whole prompt — count the token positions fed through prefill."""
    cfg, params = setup
    prefix = list(range(1, 17))              # 16 tokens
    suffix = [7, 3]
    fed = {"with": 0, "without": 0}

    def counting(srv, key):
        orig = srv._prefill_fn

        def wrapper(p, cache, prompt, slot, start, length):
            fed[key] += prompt.shape[1]
            return orig(p, cache, prompt, slot, start, length)

        srv._prefill_fn = wrapper

    srv_a = DecodeServer(params, cfg, max_batch=1, max_len=64, pad_to=4)
    pid = srv_a.cache_prefix(prefix)         # one-time prefix prefill
    counting(srv_a, "with")
    srv_b = DecodeServer(params, cfg, max_batch=1, max_len=64, pad_to=4)
    counting(srv_b, "without")
    for srv, key in ((srv_a, "with"), (srv_b, "without")):
        for _ in range(3):
            srv.submit(prefix + suffix, 3)
        srv.run_until_done(max_steps=100)
    assert fed["with"] == 3 * 4              # 3 suffix buckets (pad 4)
    assert fed["without"] == 3 * 20          # 3 whole-prompt buckets
    assert list(srv_a.outputs.values()) == list(srv_b.outputs.values())


def test_prefix_cache_speculative(spec_setup):
    """Prefix admission composes with speculative serving: target AND
    draft caches absorb the prefix block; greedy streams match the
    plain server's."""
    cfg, params, dparams = spec_setup
    prefix = [5, 1, 5, 1, 5, 1]
    reqs = [(prefix + [2, 6], 6), (prefix + [9], 6)]
    srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4,
                       draft_params=dparams, draft_cfg=cfg, gamma=2)
    srv.cache_prefix(prefix)
    rids = [srv.submit(p, n) for p, n in reqs]
    srv.run_until_done(max_steps=100)
    for rid, (p, n) in zip(rids, reqs):
        assert srv.outputs[rid] == solo(params, cfg, p, n)


def test_prefix_cache_chunked_prefill_compose(setup):
    """A long prefix built through chunked prefill + chunked suffix
    admission still reproduces solo generate()."""
    cfg, params = setup
    prefix = [(i * 7) % 50 + 1 for i in range(37)]   # > chunk
    suffix = [3, 3, 9, 27, 5]
    srv = DecodeServer(params, cfg, max_batch=1, max_len=128, pad_to=4,
                       prefill_chunk=16)
    srv.cache_prefix(prefix)
    rid = srv.submit(prefix + suffix, 6)
    srv.run_until_done(max_steps=100)
    assert srv.outputs[rid] == solo(params, cfg, prefix + suffix, 6)


def test_prefix_cache_int8_kv(setup):
    """Prefix blocks copy through the quantized cache's int8+scale
    leaves; streams match the int8 solo run."""
    cfg, params = setup
    prefix = [6, 2, 8, 4]
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32, pad_to=4,
                       kv_quantized=True)
    srv.cache_prefix(prefix)
    rid = srv.submit(prefix + [1, 3], 4)
    srv.run_until_done(max_steps=50)
    out = generate(params,
                   jnp.asarray(prefix + [1, 3], jnp.int32)[None], cfg,
                   4, kv_quantized=True)
    want = [int(t) for t in np.asarray(out)[0][6:]]
    assert srv.outputs[rid] == want


def test_prefix_cache_rejected_for_moe():
    from nbdistributed_tpu.models import (init_moe_model,
                                          tiny_moe_config)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    srv = DecodeServer(params, cfg, max_batch=1, max_len=32)
    with pytest.raises(ValueError, match="dense-family"):
        srv.cache_prefix([1, 2, 3])


def test_prefix_cache_validation(setup):
    cfg, params = setup
    srv = DecodeServer(params, cfg, max_batch=1, max_len=16)
    with pytest.raises(ValueError, match="empty"):
        srv.cache_prefix([])
    with pytest.raises(ValueError, match="max_len"):
        srv.cache_prefix(list(range(16)))


def test_prefix_cache_on_mesh(setup):
    """Prefix admission over a dp×tp mesh: the prefix buffer is
    tp-sharded like the pool (batch/token replicated — a 1-slot
    buffer can't split over dp), the absorb copy preserves the pool's
    layout through donation, and streams stay solo-exact."""
    from nbdistributed_tpu.models import param_shardings
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.tensor_parallel import \
        apply_shardings
    cfg, params = setup
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2},
                              devices=jax.devices()[:4])
    ps = apply_shardings(params, mesh, param_shardings(cfg))
    prefix = [3, 1, 4, 1, 5, 9]
    reqs = [(prefix + [2, 6], 5), (prefix + [8], 5), ([9, 9], 5)]
    srv = DecodeServer(ps, cfg, max_batch=2, max_len=32, pad_to=4,
                       mesh=mesh)
    srv.cache_prefix(prefix)
    rids = [srv.submit(p, n) for p, n in reqs]
    srv.run_until_done(max_steps=100)
    for rid, (p, n) in zip(rids, reqs):
        assert srv.outputs[rid] == solo(params, cfg, p, n), (rid, p)
