"""Chunked-vocab cross-entropy (ops/xent.py) must equal the naive
full-logits loss — value AND gradients — to fp32 reassociation.  The
whole point of the chunked tail is that it is a pure memory
optimization: any numerical drift would silently change training."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import init_params, loss_fn, tiny_config
from nbdistributed_tpu.models.transformer import shifted_xent
from nbdistributed_tpu.ops.xent import (chunked_softmax_xent,
                                        shifted_chunked_xent)

pytestmark = pytest.mark.unit


def _tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


def test_chunked_xent_matches_naive_logsumexp():
    k = jax.random.PRNGKey(0)
    N, D, V = 24, 16, 130
    x = jax.random.normal(k, (N, D), jnp.float32)
    W = jax.random.normal(jax.random.PRNGKey(1), (D, V), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    naive = -jnp.take_along_axis(
        jax.nn.log_softmax((x @ W).astype(jnp.float32), axis=-1),
        t[:, None], axis=-1).mean()
    # chunk=32 does not divide V=130: exercises the ragged pad mask.
    got = chunked_softmax_xent(x, W, t, chunk=32)
    np.testing.assert_allclose(float(got), float(naive), rtol=1e-6)


def test_chunked_xent_valid_mask():
    k = jax.random.PRNGKey(3)
    N, D, V = 12, 8, 64
    x = jax.random.normal(k, (N, D), jnp.float32)
    W = jax.random.normal(jax.random.PRNGKey(4), (D, V), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(5), (N,), 0, V)
    valid = jnp.arange(N) % 3 != 0
    nll = -jnp.take_along_axis(
        jax.nn.log_softmax(x @ W, axis=-1), t[:, None], axis=-1)[:, 0]
    naive = (nll * valid).sum() / valid.sum()
    got = chunked_softmax_xent(x, W, t, valid=valid, chunk=16)
    np.testing.assert_allclose(float(got), float(naive), rtol=1e-6)


def test_loss_fn_chunked_matches_standard_value_and_grads():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    cfg_c = dataclasses.replace(cfg, ce_chunk=100)   # ragged vs V=512
    p = init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    f_std = jax.jit(jax.value_and_grad(
        lambda p_, t: loss_fn(p_, {"tokens": t}, cfg)))
    f_chk = jax.jit(jax.value_and_grad(
        lambda p_, t: loss_fn(p_, {"tokens": t}, cfg_c)))
    l0, g0 = f_std(p, tok)
    l1, g1 = f_chk(p, tok)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    _tree_allclose(g0, g1, rtol=2e-4, atol=2e-5)


def test_loss_fn_chunked_with_packed_segments():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    cfg_c = dataclasses.replace(cfg, ce_chunk=128)
    p = init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0,
                             cfg.vocab_size)
    seg = jnp.concatenate([jnp.zeros((2, 10), jnp.int32),
                           jnp.ones((2, 14), jnp.int32)], axis=1)
    batch = {"tokens": tok, "segments": seg}
    l0 = loss_fn(p, batch, cfg)
    l1 = loss_fn(p, batch, cfg_c)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)


@pytest.mark.slow
def test_loss_fn_chunked_composes_with_sp():
    """ce_chunk under ring sequence parallelism: the chunked tail is
    row-wise math over S-sharded hidden states and replicated head
    chunks, so GSPMD must partition it to the same value the plain
    single-device loss produces."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nbdistributed_tpu.models import (SeqParallel, init_params,
                                          param_shardings)
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    cfg_c = dataclasses.replace(cfg, ce_chunk=128)
    p = init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    ref = loss_fn(p, {"tokens": tok}, cfg)
    mesh = mesh_mod.make_mesh({"sp": 4, "tp": 1},
                              devices=jax.devices()[:4])
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=False)
    tok_s = jax.device_put(tok, NamedSharding(mesh, P(None, "sp")))
    p_s = jax.device_put(p, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_shardings(cfg)))
    got = jax.jit(
        lambda p_, t: loss_fn(p_, {"tokens": t}, cfg_c, sp=sp))(
            p_s, tok_s)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)


def test_moe_loss_fn_chunked_matches_standard():
    from nbdistributed_tpu.models import (init_moe_model, moe_loss_fn,
                                          tiny_moe_config)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    cfg_c = dataclasses.replace(cfg, ce_chunk=100)   # ragged chunk
    p = init_moe_model(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    l0, g0 = jax.value_and_grad(
        lambda p_: moe_loss_fn(p_, {"tokens": tok}, cfg))(p)
    l1, g1 = jax.value_and_grad(
        lambda p_: moe_loss_fn(p_, {"tokens": tok}, cfg_c))(p)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    _tree_allclose(g0, g1, rtol=2e-4, atol=2e-5)


def test_shifted_chunked_matches_shifted_xent_directly():
    k = jax.random.PRNGKey(7)
    B, S, D, V = 2, 16, 8, 96
    hidden = jax.random.normal(k, (B, S, D), jnp.float32)
    W = jax.random.normal(jax.random.PRNGKey(8), (D, V), jnp.float32)
    tok = jax.random.randint(jax.random.PRNGKey(9), (B, S), 0, V)
    logits = (hidden @ W).astype(jnp.float32)
    naive = shifted_xent(logits, tok)
    got = shifted_chunked_xent(hidden, W, tok, chunk=40)
    np.testing.assert_allclose(float(got), float(naive), rtol=1e-6)
