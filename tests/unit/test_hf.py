"""HF interop: converted Llama-family weights must reproduce the torch
forward's logits (fp32, no-flash reference path — exactness is the
point; the flash path's own parity is covered in test_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from nbdistributed_tpu.models import (config_from_hf, forward, generate,
                                      params_from_hf)

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def tiny_hf_llama(tie=False, n_kv=2):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=160, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=n_kv,
                      max_position_embeddings=256, rms_norm_eps=1e-5,
                      rope_theta=10000.0, tie_word_embeddings=tie,
                      attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("tie", [False, True])
def test_logits_match_torch_forward(tie):
    model = tiny_hf_llama(tie=tie)
    tokens = np.array([[3, 17, 94, 5, 62, 11], [88, 2, 45, 127, 0, 9]],
                      np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()

    params, cfg = params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gqa_head_grouping_matches():
    """Hkv < H exercises the head-ordering assumption in the transpose."""
    model = tiny_hf_llama(n_kv=1)
    tokens = np.array([[7, 1, 3, 99]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    params, cfg = params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_generate_matches_torch_greedy():
    """Greedy continuations through our KV-cache loop must equal HF's
    ``generate`` on the same weights."""
    model = tiny_hf_llama()
    prompt = np.array([[5, 9, 2, 44]], np.int64)
    with torch.no_grad():
        ref = model.generate(torch.from_numpy(prompt), max_new_tokens=8,
                             do_sample=False).numpy()
    params, cfg = params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    got = np.asarray(generate(params, jnp.asarray(prompt, jnp.int32),
                              cfg, max_new_tokens=8))
    np.testing.assert_array_equal(got, ref)


def test_config_mapping_and_guards():
    model = tiny_hf_llama()
    cfg = config_from_hf(model.config)
    assert (cfg.d_model, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads) == \
        (64, 2, 4, 2)
    model.config.rope_scaling = {"rope_type": "linear", "factor": 2.0}
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(model.config)


def test_hf_checkpoint_quantizes_and_generates():
    """The realistic inference path end-to-end: HF torch checkpoint ->
    framework pytree -> int8 weights + int8 KV cache -> greedy decode.
    Fidelity: quantized logits stay close; the decode loop is
    self-consistent vs the quantized re-forward."""
    import numpy as np
    from nbdistributed_tpu.models import (forward, generate,
                                          quantization_error,
                                          quantize_params)
    from nbdistributed_tpu.models.hf import params_from_hf

    model = tiny_hf_llama()
    params, cfg = params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    qparams = quantize_params(params)
    errs = quantization_error(params, qparams)
    assert all(e < 0.02 for e in errs.values()), errs

    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    ref = np.asarray(forward(params, prompt, cfg))
    got = np.asarray(forward(qparams, prompt, cfg))
    nmse = float(np.mean((got - ref) ** 2) / np.mean(ref ** 2))
    assert nmse < 1e-3, nmse

    toks = generate(qparams, prompt, cfg, max_new_tokens=8,
                    kv_quantized=True)
    assert toks.shape == (1, 13)
    # Self-consistency: int8-weight full re-forward greedy chain.
    ref_toks = prompt
    for _ in range(8):
        lg = forward(qparams, ref_toks, cfg)
        nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
        ref_toks = jnp.concatenate([ref_toks, nxt[:, None]], axis=1)
    # int8 KV adds small noise on top of int8 weights; demand strong
    # (not necessarily perfect) agreement of the greedy chains.
    agree = float(jnp.mean((toks[:, 5:] == ref_toks[:, 5:])
                           .astype(jnp.float32)))
    assert agree >= 0.75, agree


def tiny_hf_mixtral():
    from transformers import MixtralConfig, MixtralForCausalLM
    torch.manual_seed(1)
    cfg = MixtralConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=96, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        num_local_experts=4, num_experts_per_tok=2,
                        max_position_embeddings=128,
                        sliding_window=None, rope_theta=10000.0)
    model = MixtralForCausalLM(cfg)
    model.eval()
    return model


def test_mixtral_logits_match_torch_forward():
    """MoE conversion: logits parity with the HF Mixtral forward at
    lossless capacity (the default — no token dropped, identical
    routing math: softmax -> top-k -> renormalize)."""
    import numpy as np
    from nbdistributed_tpu.models import moe_forward
    from nbdistributed_tpu.models.hf import moe_params_from_hf

    model = tiny_hf_mixtral()
    tokens = np.array([[7, 3, 99, 12, 0, 64, 2]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    params, cfg = moe_params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    got, _aux = moe_forward(params, jnp.asarray(tokens, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4,
                               rtol=2e-4)


def test_mixtral_via_generate_and_autodispatch():
    """The shared KV-cache generate loop serves the converted Mixtral,
    and load-style dispatch picks the MoE converter."""
    import numpy as np
    from nbdistributed_tpu.models import generate
    from nbdistributed_tpu.models.hf import moe_params_from_hf

    model = tiny_hf_mixtral()
    prompt = np.array([[5, 9, 2, 44]], np.int64)
    with torch.no_grad():
        ref = model.generate(torch.from_numpy(prompt), max_new_tokens=6,
                             do_sample=False).numpy()
    params, cfg = moe_params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    got = np.asarray(generate(params, jnp.asarray(prompt, jnp.int32),
                              cfg, max_new_tokens=6))
    np.testing.assert_array_equal(got, ref)


def test_load_hf_pretrained_autodispatch(tmp_path):
    """load_hf_pretrained picks the MoE converter for Mixtral
    checkpoints and the dense converter for Llama ones (round-tripped
    through save_pretrained — the real from_pretrained path)."""
    from nbdistributed_tpu.models.hf import load_hf_pretrained

    mix = tiny_hf_mixtral()
    mix.save_pretrained(tmp_path / "mix")
    params, cfg = load_hf_pretrained(str(tmp_path / "mix"),
                                     dtype=jnp.float32)
    assert "moe" in params["layers"] and hasattr(cfg, "n_experts")

    dense = tiny_hf_llama()
    dense.save_pretrained(tmp_path / "dense")
    params, cfg = load_hf_pretrained(str(tmp_path / "dense"),
                                     dtype=jnp.float32)
    assert "w_gate" in params["layers"] and not hasattr(cfg, "n_experts")


def test_mixtral_quantizes():
    """The converted Mixtral pytree goes through the MoE int8 path
    (quantize_moe_params — the dense quantize_params rejects the MoE
    layout by design) and still forwards close to fp."""
    import numpy as np
    from nbdistributed_tpu.models import (moe_forward,
                                          quantization_error,
                                          quantize_moe_params)
    from nbdistributed_tpu.models.hf import moe_params_from_hf

    model = tiny_hf_mixtral()
    params, cfg = moe_params_from_hf(model, dtype=jnp.float32)
    cfg = type(cfg)(**{**cfg.__dict__, "use_flash": False})
    qparams = quantize_moe_params(params)
    errs = quantization_error(params, qparams)
    assert {"moe.w_gate", "moe.w_up", "moe.w_down"} <= set(errs), errs
    tokens = jnp.asarray([[7, 3, 99, 12]], jnp.int32)
    ref, _ = moe_forward(params, tokens, cfg)
    got, _ = moe_forward(qparams, tokens, cfg)
    nmse = float(jnp.mean((got - ref) ** 2) / jnp.mean(ref ** 2))
    assert nmse < 1e-2, nmse
