"""InterruptGate: the Python-level SIGINT discipline.

These tests deterministically reproduce the round-2 interrupt-storm
tail race (a SIGINT delivered to a lazily-spawned, mask-unblocked side
thread defeating a main-thread pthread mask) and prove the gate closes
it: outside a window a signal can only ever become *pending*, no matter
which OS thread the kernel delivered it to.
"""

import os
import signal
import threading
import time

import pytest

from nbdistributed_tpu.runtime.interrupt import InterruptGate

pytestmark = [pytest.mark.unit]


@pytest.fixture
def gate():
    old = signal.getsignal(signal.SIGINT)
    g = InterruptGate().install()
    yield g
    signal.signal(signal.SIGINT, old)


def sigint_self():
    os.kill(os.getpid(), signal.SIGINT)


def settle():
    """Give CPython a few bytecode boundaries to run a tripped handler."""
    for _ in range(100):
        time.sleep(0.001)


def test_closed_gate_defers_to_pending(gate):
    sigint_self()
    settle()  # handler must run and must NOT raise
    assert gate.pending


def test_pending_delivered_at_window_entry(gate):
    sigint_self()
    settle()
    with pytest.raises(KeyboardInterrupt):
        with gate.window():
            pytest.fail("window body must not run with a pending interrupt")
    assert not gate.pending


def test_sigint_inside_window_raises(gate):
    with pytest.raises(KeyboardInterrupt):
        with gate.window():
            sigint_self()
            settle()
            pytest.fail("KI should have raised during settle()")


def test_window_closes_after_exit(gate):
    with gate.window():
        pass
    sigint_self()
    settle()
    assert gate.pending  # closed again: deferred, not raised


def test_shielded_defers_then_raises_at_exit(gate):
    hit = []
    with pytest.raises(KeyboardInterrupt):
        with gate.window():
            with gate.shielded():
                sigint_self()
                settle()  # handler runs here but must not raise
                hit.append("send completed")
            pytest.fail("KI must raise at shield exit, before this")
    assert hit == ["send completed"]
    assert not gate.pending


def test_shielded_outside_window_stays_pending(gate):
    with gate.shielded():
        sigint_self()
        settle()
    assert gate.pending  # no surrounding window: defer to the next one


def test_unblocked_side_thread_cannot_defeat_closed_gate(gate):
    """The root cause, reproduced: a side thread with SIGINT unblocked
    (as XLA/gloo pools spawned during user code are) receives the
    process-directed signal while the main thread has it pthread-
    blocked.  Under the old pthread-mask discipline the main thread
    raised KeyboardInterrupt anyway (CPython's flag is process-global);
    under the gate it must become pending."""
    # Main thread pthread-blocks SIGINT, like the old masked region.
    signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGINT})
    try:
        # Spawn the "XLA pool" thread with SIGINT unblocked.
        def spawn():
            signal.pthread_sigmask(signal.SIG_UNBLOCK, {signal.SIGINT})
            t = threading.Thread(target=lambda: time.sleep(5),
                                 daemon=True)
            t.start()
            signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGINT})
            return t

        spawn()
        sigint_self()  # kernel delivers to the unblocked side thread
        settle()       # handler runs on the MAIN thread — gate closed
        assert gate.pending, \
            "signal via side thread was not recorded as pending"
        # ... and it surfaces only at the next window, as designed.
        with pytest.raises(KeyboardInterrupt):
            with gate.window():
                pass
    finally:
        signal.pthread_sigmask(signal.SIG_UNBLOCK, {signal.SIGINT})


def test_worker_channel_recv_scopes_gate_to_select(gate):
    """A pending interrupt aborts the idle recv wait (no bytes
    consumed); bytes already buffered are returned before the gate
    opens, so an interrupt can never cost a received frame."""
    import socket

    from nbdistributed_tpu.messaging.codec import Message, encode
    from nbdistributed_tpu.messaging.transport import WorkerChannel

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    ch = WorkerChannel("127.0.0.1", port, rank=0)
    peer, _ = srv.accept()
    try:
        # Pending interrupt + a complete frame already buffered: the
        # frame wins (returned without opening the gate's window).
        peer.sendall(encode(Message(msg_type="x", data=1)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                ch._sock.settimeout(0.05)
                ch._rbuf.extend(ch._sock.recv(1 << 16))
                break
            except TimeoutError:
                continue
            finally:
                ch._sock.settimeout(None)
        sigint_self()
        settle()
        assert gate.pending
        msg = ch.recv(timeout=5, gate=gate)
        assert msg.msg_type == "x"
        # Buffer drained, nothing to read: the pending interrupt now
        # aborts the select wait instead of timing out.
        with pytest.raises(KeyboardInterrupt):
            ch.recv(timeout=5, gate=gate)
        assert not gate.pending
    finally:
        ch.close()
        peer.close()
        srv.close()
