"""Notebook-metadata timeline persistence: the server-side
pre_save_hook folds the kernel-written sidecar into the .ipynb's
metadata at save — the frontend-agnostic replacement for the
reference's classic-notebook-only injected JS (reference:
magic.py:196-233)."""

import json

import pytest

from nbdistributed_tpu import jupyter_hooks as jh
from nbdistributed_tpu.magics.timeline import Timeline

pytestmark = [pytest.mark.unit]


def _model():
    return {"type": "notebook",
            "content": {"metadata": {"kernelspec": {"name": "py"}},
                        "cells": []}}


def _write_sidecar(tmp_path, payload):
    nb = tmp_path / "nb.ipynb"
    nb.write_text("{}")
    sc = jh.sidecar_path(str(nb))
    with open(sc, "w") as f:
        json.dump(payload, f)
    return str(nb)


def test_hook_injects_sidecar_into_metadata(tmp_path):
    tl = Timeline()
    rec = tl.start("x = 1", [0, 1])
    tl.finish(rec, None)
    nb = _write_sidecar(tmp_path, tl.payload())
    model = _model()
    jh.pre_save_hook(model=model, path=nb)
    got = model["content"]["metadata"][jh.METADATA_KEY]
    assert got["version"] == 1
    assert got["records"][0]["code"] == "x = 1"
    assert got["records"][0]["target_ranks"] == [0, 1]
    # Pre-existing metadata keys survive.
    assert model["content"]["metadata"]["kernelspec"] == {"name": "py"}


def test_hook_noop_without_sidecar(tmp_path):
    nb = tmp_path / "plain.ipynb"
    nb.write_text("{}")
    model = _model()
    jh.pre_save_hook(model=model, path=str(nb))
    assert jh.METADATA_KEY not in model["content"]["metadata"]


def test_hook_fail_open(tmp_path):
    """Malformed sidecar, wrong model type, missing content: saving
    must proceed untouched, never raise."""
    nb = tmp_path / "nb.ipynb"
    nb.write_text("{}")
    with open(jh.sidecar_path(str(nb)), "w") as f:
        f.write("{not json")
    model = _model()
    jh.pre_save_hook(model=model, path=str(nb))
    assert jh.METADATA_KEY not in model["content"]["metadata"]
    with open(jh.sidecar_path(str(nb)), "w") as f:
        f.write('["a list, not a payload"]')
    jh.pre_save_hook(model=model, path=str(nb))
    assert jh.METADATA_KEY not in model["content"]["metadata"]
    jh.pre_save_hook(model={"type": "file"}, path=str(nb))
    jh.pre_save_hook(model=None, path=str(nb))
    jh.pre_save_hook()                      # no args at all


def test_hook_resolves_contents_manager_os_path(tmp_path):
    """Jupyter passes API paths; the hook resolves them through the
    contents manager's _get_os_path."""
    tl = Timeline()
    tl.start("y = 2", [0])
    nb = _write_sidecar(tmp_path, tl.payload())

    class _CM:
        def _get_os_path(self, api_path):
            assert api_path == "nb.ipynb"
            return nb

    model = _model()
    jh.pre_save_hook(model=model, path="nb.ipynb", contents_manager=_CM())
    assert model["content"]["metadata"][jh.METADATA_KEY]["records"]
