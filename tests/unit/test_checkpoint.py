"""Unit tests for the checkpoint subsystem (SURVEY §5.4 upgrade).

Pure-logic tier: save/restore round-trips on a local namespace dict,
no worker processes involved.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from nbdistributed_tpu.runtime import checkpoint


def roundtrip(tmp_path, ns, names, restore_names=None):
    checkpoint.save(str(tmp_path / "ck"), ns, names, rank=0, world_size=1)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out, restore_names, rank=0)
    return out


def test_array_roundtrip_exact(tmp_path):
    ns = {"x": jnp.arange(12.0).reshape(3, 4)}
    out = roundtrip(tmp_path, ns, ["x"])
    assert isinstance(out["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out["x"]),
                                  np.asarray(ns["x"]))


def test_bfloat16_dtype_survives(tmp_path):
    ns = {"w": jnp.asarray([1.5, -2.0, 3.25], jnp.bfloat16)}
    out = roundtrip(tmp_path, ns, ["w"])
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["w"], np.float32),
                                  np.asarray(ns["w"], np.float32))


def test_numpy_stays_numpy_jax_stays_jax(tmp_path):
    ns = {"a": np.arange(3, dtype=np.int64), "b": jnp.ones(2)}
    out = roundtrip(tmp_path, ns, ["a", "b"])
    assert type(out["a"]) is np.ndarray and out["a"].dtype == np.int64
    assert isinstance(out["b"], jax.Array)


def test_pytree_with_optax_state(tmp_path):
    params = {"dense": {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}}
    opt = optax.adamw(1e-3)
    state = opt.init(params)
    ns = {"params": params, "opt_state": state, "step": 17,
          "note": "hello"}
    out = roundtrip(tmp_path, ns, ["params", "opt_state", "step", "note"])
    assert out["step"] == 17 and out["note"] == "hello"
    # NamedTuple structure (ScaleByAdamState etc.) must reconstruct.
    assert type(out["opt_state"]) is type(state)
    leaves_in = jax.tree_util.tree_leaves(state)
    leaves_out = jax.tree_util.tree_leaves(out["opt_state"])
    for a, b in zip(leaves_in, leaves_out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_object_dtype_ndarray_roundtrips_via_pickle_path(tmp_path):
    ns = {"o": np.array([{"a": 1}, None, "s"], dtype=object)}
    out = roundtrip(tmp_path, ns, ["o"])
    assert out["o"].dtype == object
    assert list(out["o"]) == [{"a": 1}, None, "s"]


def test_restored_numpy_array_is_writable(tmp_path):
    ns = {"a": np.arange(4.0)}
    out = roundtrip(tmp_path, ns, ["a"])
    out["a"][0] = 99.0
    assert out["a"][0] == 99.0


def test_non_contiguous_array_saves_correctly(tmp_path):
    base = np.arange(12.0).reshape(3, 4)
    ns = {"t": base.T}  # strided view
    out = roundtrip(tmp_path, ns, ["t"])
    np.testing.assert_array_equal(out["t"], base.T)


def test_restore_subset_of_names(tmp_path):
    ns = {"x": jnp.ones(2), "y": jnp.zeros(2)}
    out = roundtrip(tmp_path, ns, ["x", "y"], restore_names=["y"])
    assert set(out) == {"y"}


def test_missing_name_on_save_raises(tmp_path):
    with pytest.raises(KeyError, match="nope"):
        checkpoint.save(str(tmp_path / "ck"), {"x": 1}, ["nope"], rank=0)


def test_missing_name_on_restore_raises(tmp_path):
    ns = {"x": 1}
    checkpoint.save(str(tmp_path / "ck"), ns, ["x"], rank=0)
    with pytest.raises(KeyError, match="ghost"):
        checkpoint.restore(str(tmp_path / "ck"), {}, ["ghost"], rank=0)


def test_missing_rank_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        checkpoint.restore(str(tmp_path / "ck"), {}, rank=3)


def test_per_rank_dirs_are_independent(tmp_path):
    for r in range(2):
        checkpoint.save(str(tmp_path / "ck"), {"v": jnp.full(2, r)},
                        ["v"], rank=r, world_size=2)
    out0, out1 = {}, {}
    checkpoint.restore(str(tmp_path / "ck"), out0, rank=0)
    checkpoint.restore(str(tmp_path / "ck"), out1, rank=1)
    assert float(out0["v"][0]) == 0.0 and float(out1["v"][0]) == 1.0


def test_resave_overwrites_cleanly(tmp_path):
    ns1 = {"x": jnp.ones(2), "extra": jnp.zeros(3)}
    checkpoint.save(str(tmp_path / "ck"), ns1, ["x", "extra"], rank=0)
    checkpoint.save(str(tmp_path / "ck"), {"x": jnp.full(2, 7.0)},
                    ["x"], rank=0)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out, rank=0)
    # Second save fully replaces the dir: no stale 'extra' entry.
    assert set(out) == {"x"}
    assert float(out["x"][0]) == 7.0


def test_failed_save_preserves_previous_checkpoint(tmp_path):
    checkpoint.save(str(tmp_path / "ck"), {"x": jnp.ones(2)}, ["x"],
                    rank=0)
    with pytest.raises(Exception):
        # Lambdas don't pickle → the staged tmp dir fails mid-write.
        checkpoint.save(str(tmp_path / "ck"), {"x": lambda: None},
                        ["x"], rank=0)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out, rank=0)
    assert float(out["x"][0]) == 1.0


def test_jax_leaf_manifest_records_sharding(tmp_path):
    import json
    checkpoint.save(str(tmp_path / "ck"), {"x": jnp.ones(2)}, ["x"],
                    rank=0)
    with open(tmp_path / "ck" / "rank_0" / "manifest.json") as f:
        manifest = json.load(f)
    leaf = manifest["entries"]["x"]["leaves"][0]
    assert leaf["kind"] == "jax" and "sharding" in leaf


def test_structured_dtype_roundtrips_via_pickle_path(tmp_path):
    rec = np.zeros(3, dtype=[("a", "<i4"), ("b", "<f8")])
    rec["a"] = [1, 2, 3]
    out = roundtrip(tmp_path, {"rec": rec}, ["rec"])
    assert out["rec"].dtype == rec.dtype
    np.testing.assert_array_equal(out["rec"]["a"], rec["a"])


def test_info_skips_staging_dirs(tmp_path):
    checkpoint.save(str(tmp_path / "ck"), {"x": jnp.ones(1)}, ["x"],
                    rank=0)
    # Simulate an interrupted save's leftovers.
    import shutil
    shutil.copytree(tmp_path / "ck" / "rank_0",
                    tmp_path / "ck" / "rank_0.tmp")
    shutil.copytree(tmp_path / "ck" / "rank_0",
                    tmp_path / "ck" / "rank_1.old")
    meta = checkpoint.info(str(tmp_path / "ck"))
    assert sorted(meta["ranks"]) == [0]


def test_info_lists_ranks_and_names(tmp_path):
    for r in range(2):
        checkpoint.save(str(tmp_path / "ck"), {"p": jnp.ones(1), "s": 2},
                        ["p", "s"], rank=r, world_size=2)
    meta = checkpoint.info(str(tmp_path / "ck"))
    assert sorted(meta["ranks"]) == [0, 1]
    assert meta["ranks"][0]["names"] == ["p", "s"]
    assert meta["ranks"][0]["world_size"] == 2


def test_save_async_roundtrip_and_done(tmp_path):
    """Background save: handle transitions to done, wait() returns the
    summary, and the restored values equal the saved ones."""
    ns = {"w": jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4),
          "step": 7}
    h = checkpoint.save_async(str(tmp_path / "ck"), ns, ["w", "step"])
    summary = h.wait(30)
    assert h.done()
    assert summary["w"]["bytes"] == 24
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out)
    np.testing.assert_array_equal(np.asarray(out["w"], np.float32),
                                  np.asarray(ns["w"], np.float32))
    assert out["step"] == 7


def test_save_async_snapshots_mutable_leaves(tmp_path):
    """Plain-Python leaves are frozen at call time: mutating them
    after save_async returns must not change what lands on disk."""
    cfg = {"lr": [1, 2, 3]}
    ns = {"cfg": cfg}
    h = checkpoint.save_async(str(tmp_path / "ck"), ns, ["cfg"])
    cfg["lr"].append(999)        # mutate while (possibly) writing
    h.wait(30)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out)
    assert out["cfg"] == {"lr": [1, 2, 3]}


def test_save_async_missing_name_raises_synchronously(tmp_path):
    with pytest.raises(KeyError, match="nope"):
        checkpoint.save_async(str(tmp_path / "ck"), {"a": 1}, ["nope"])


def test_save_async_error_surfaces_at_wait(tmp_path):
    """A failure inside the thread (unwritable path) re-raises from
    wait(), not silently."""
    target = tmp_path / "blocked"
    target.write_text("a file where the checkpoint dir must go")
    ns = {"x": jnp.ones(3)}
    h = checkpoint.save_async(str(target), ns, ["x"])
    with pytest.raises(Exception):
        h.wait(30)


def test_save_async_survives_buffer_donation(tmp_path):
    """This repo's own train steps donate params/opt buffers, deleting
    them on the next step.  save_async's device-side defensive copy
    must keep the checkpoint intact even when the original buffer is
    deleted immediately after the call (delete() is exactly what
    donation does to the old buffer)."""
    x = jnp.arange(8.0)
    h = checkpoint.save_async(str(tmp_path / "ck"), {"x": x}, ["x"])
    x.delete()
    h.wait(30)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out)
    np.testing.assert_array_equal(np.asarray(out["x"]),
                                  np.arange(8.0, dtype=np.float32))


def test_save_async_freezes_numpy_leaves(tmp_path):
    """In-place mutation of a host numpy leaf after save_async must
    not tear the snapshot (leaves are copy()-ed at call time)."""
    buf = np.arange(6, dtype=np.int32)
    h = checkpoint.save_async(str(tmp_path / "ck"), {"buf": buf},
                              ["buf"])
    buf[:] = -1
    h.wait(30)
    out: dict = {}
    checkpoint.restore(str(tmp_path / "ck"), out)
    np.testing.assert_array_equal(out["buf"],
                                  np.arange(6, dtype=np.int32))
