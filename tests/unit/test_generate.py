"""KV-cache generation: exactness vs full re-forward decoding, sampling
determinism, and tensor-parallel cache sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.models import (forward, forward_with_cache,
                                      generate, init_kv_cache,
                                      init_params, kv_cache_shardings,
                                      make_generate_fn, param_shardings,
                                      tiny_config)

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def full_forward_greedy(params, prompt, cfg, n_new):
    """Reference decoder: re-run the whole sequence each step, no cache."""
    toks = prompt
    for _ in range(n_new):
        logits = forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_prefill_logits_match_forward(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 11), 0,
                                cfg.vocab_size)
    cache = init_kv_cache(cfg, 2, 32)
    logits, _ = forward_with_cache(params, prompt, cache, 0, cfg)
    ref = forward(params, prompt, cfg)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_cached_greedy_matches_full_reforward(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 7), 0,
                                cfg.vocab_size)
    got = generate(params, prompt, cfg, max_new_tokens=12)
    ref = full_forward_greedy(params, prompt, cfg, 12)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_single_new_token(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0,
                                cfg.vocab_size)
    got = generate(params, prompt, cfg, max_new_tokens=1)
    ref = full_forward_greedy(params, prompt, cfg, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_sampling_deterministic_per_key_and_in_vocab(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0,
                                cfg.vocab_size)
    key = jax.random.PRNGKey(7)
    a = generate(params, prompt, cfg, 8, temperature=0.8, key=key)
    b = generate(params, prompt, cfg, 8, temperature=0.8, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jnp.max(a)) < cfg.vocab_size and int(jnp.min(a)) >= 0


def test_sampling_requires_key(setup):
    cfg, params = setup
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, prompt, cfg, 2, temperature=0.5)


def test_max_len_too_small_raises(setup):
    cfg, params = setup
    prompt = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(params, prompt, cfg, 8, max_len=10)


def test_jitted_generate_fn(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0,
                                cfg.vocab_size)
    fn = make_generate_fn(cfg, 5)
    got = fn(params, prompt)
    ref = full_forward_greedy(params, prompt, cfg, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_tensor_parallel_generate_matches(setup):
    """Greedy decode with params + cache sharded over a tp mesh equals
    the unsharded decode."""
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel import tensor_parallel

    cfg, params = setup  # tiny: n_heads=4, n_kv_heads=2 -> tp=2 fits
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2},
                              devices=jax.devices()[:4])
    rules = param_shardings(cfg)
    p = tensor_parallel.apply_shardings(params, mesh, rules)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 5), 0,
                                cfg.vocab_size)
    ref = generate(params, prompt, cfg, 6)
    # mesh= also shards the KV cache (batch over dp, KV heads over tp).
    got = generate(p, prompt, cfg, 6, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_sharded_cache_layout_is_applied(setup):
    from jax.sharding import PartitionSpec as P
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    cfg, _ = setup
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2},
                              devices=jax.devices()[:4])
    cache = init_kv_cache(cfg, 2, 16, mesh=mesh)
    assert cache["k"].sharding.spec == P(None, "dp", "tp", None, None)
    assert len(cache["k"].sharding.device_set) == 4


def test_zero_new_tokens_returns_prompt(setup):
    cfg, params = setup
    prompt = jnp.ones((2, 5), jnp.int32)
    out = generate(params, prompt, cfg, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))
    with pytest.raises(ValueError, match=">= 0"):
        generate(params, prompt, cfg, -1)


def test_moe_cached_greedy_matches_full_reforward():
    """The MoE family decodes through the same cached forward; lossless
    capacity (factor 2 >= n_experts/top_k) makes batched prefill and
    step-wise decode route identically, so tokens must match exactly."""
    from nbdistributed_tpu.models import (init_moe_model, moe_forward,
                                          tiny_moe_config)

    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False,
                          capacity_factor=2.0)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0,
                                cfg.vocab_size)
    got = generate(params, prompt, cfg, max_new_tokens=8)
    toks = prompt
    for _ in range(8):
        logits, _aux = moe_forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(toks))


def test_cache_sharding_spec_shape(setup):
    cfg, _ = setup
    spec = kv_cache_shardings()
    cache = init_kv_cache(cfg, 2, 16)
    assert len(spec["k"]) == cache["k"].ndim

def test_top_k_restricts_support(setup):
    """With top_k=1, sampling at any temperature must equal greedy."""
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 5), 0,
                                cfg.vocab_size)
    greedy = generate(params, prompt, cfg, 8)
    sampled = generate(params, prompt, cfg, 8, temperature=1.5,
                       top_k=1, key=jax.random.PRNGKey(9))
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_top_k_unit_sampler_support():
    """Directly check _sample only ever emits tokens inside the top-k
    set of each row."""
    from nbdistributed_tpu.models.generate import _sample
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    topk_sets = np.argsort(np.asarray(logits), axis=-1)[:, -8:]
    for seed in range(5):
        tok = _sample(logits, 1.0, jax.random.PRNGKey(seed), 8, None)
        for b in range(4):
            assert int(tok[b]) in topk_sets[b]


def test_top_p_keeps_top_token_and_restricts():
    """Nucleus sampling with a tiny top_p degenerates to greedy; with
    top_p=1.0 it must match unfiltered categorical exactly."""
    from nbdistributed_tpu.models.generate import _sample
    logits = jax.random.normal(jax.random.PRNGKey(1), (4, 64)) * 3
    key = jax.random.PRNGKey(2)
    # Tiny nucleus -> only the argmax survives.
    tok = _sample(logits, 1.0, key, None, 1e-6)
    np.testing.assert_array_equal(np.asarray(tok),
                                  np.argmax(np.asarray(logits), axis=-1))
    # Full nucleus -> identical distribution (same key) as no filter.
    a = _sample(logits, 0.7, key, None, 1.0)
    b = _sample(logits, 0.7, key, None, None)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_top_p_excludes_tail():
    """A spiked distribution with two dominant tokens: top_p=0.9 must
    never sample outside those two."""
    from nbdistributed_tpu.models.generate import _sample
    logits = np.full((1, 32), -10.0, np.float32)
    logits[0, 3] = 5.0
    logits[0, 17] = 4.5
    logits = jnp.asarray(logits)
    for seed in range(20):
        tok = _sample(logits, 1.0, jax.random.PRNGKey(seed), None, 0.9)
        assert int(tok[0]) in (3, 17)


def test_generate_validates_sampler_args(setup):
    cfg, params = setup
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="top_k"):
        generate(params, prompt, cfg, 2, temperature=1.0, top_k=0,
                 key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(params, prompt, cfg, 2, temperature=1.0, top_p=0.0,
                 key=jax.random.PRNGKey(0))
    # top_k above the vocabulary must fail at the argument, not as an
    # opaque lax.top_k trace error (ADVICE r2).
    with pytest.raises(ValueError, match="vocab_size"):
        generate(params, prompt, cfg, 2, temperature=1.0,
                 top_k=cfg.vocab_size + 1, key=jax.random.PRNGKey(0))


def test_empty_prompt_prefill_raises(setup):
    """prefill_chunked(S=0) must not silently return the zero init
    logits (which would seed decode with token 0) — ADVICE r2."""
    from nbdistributed_tpu.models import init_kv_cache, prefill_chunked
    cfg, params = setup
    cache = init_kv_cache(cfg, 1, 8)
    with pytest.raises(ValueError, match="empty prompt"):
        prefill_chunked(params, jnp.zeros((1, 0), jnp.int32), cache,
                        cfg, chunk=4)
    with pytest.raises(ValueError, match="empty prompt"):
        generate(params, jnp.zeros((1, 0), jnp.int32), cfg, 3)


def test_quantized_cache_with_stale_rules_raises(setup):
    """A caller-supplied rules dict that predates quantization (only
    k/v specs) must fail with a named error, not a KeyError — ADVICE
    r2."""
    from nbdistributed_tpu.parallel.mesh import make_mesh
    cfg, _ = setup
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    stale = kv_cache_shardings(dp_axis=None, tp_axis="tp",
                               quantized=False)
    with pytest.raises(ValueError, match="k_s"):
        init_kv_cache(cfg, 2, 16, mesh=mesh, rules=stale,
                      quantized=True)


def test_jitted_top_k_top_p(setup):
    """The truncated sampler must scan/jit (static shapes)."""
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 4), 0,
                                cfg.vocab_size)
    fn = make_generate_fn(cfg, 6, temperature=0.9, top_k=10, top_p=0.95)
    out = fn(params, prompt, jax.random.PRNGKey(11))
    assert out.shape == (2, 10)
    assert int(jnp.max(out)) < cfg.vocab_size and int(jnp.min(out)) >= 0


def test_kv_quantized_generation_close_to_fp(setup):
    """Int8-cache generation: single-step logits close to the fp cache
    path, full generation runs, and both caches agree on the argmax
    chain for a short horizon."""
    from nbdistributed_tpu.models import forward_with_cache, init_kv_cache
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(20), (2, 9), 0,
                                cfg.vocab_size)
    # Prefill logits: quantized cache vs fp cache.
    c_fp = init_kv_cache(cfg, 2, 32)
    c_q8 = init_kv_cache(cfg, 2, 32, quantized=True)
    assert c_q8["k"].dtype == jnp.int8 and "k_s" in c_q8
    lf, _ = forward_with_cache(params, prompt, c_fp, 0, cfg)
    lq, cq = forward_with_cache(params, prompt, c_q8, 0, cfg)
    nmse = float(jnp.mean((lq - lf) ** 2) / jnp.mean(lf ** 2))
    assert nmse < 1e-3, nmse
    # One decode step off the quantized cache.
    nxt = jnp.argmax(lq[:, -1:], axis=-1).astype(jnp.int32)
    l2, _ = forward_with_cache(params, nxt, cq, 9, cfg)
    assert l2.shape == (2, 1, cfg.vocab_size)
    # Full generation with the quantized cache.
    got = generate(params, prompt, cfg, max_new_tokens=8,
                   kv_quantized=True)
    ref = generate(params, prompt, cfg, max_new_tokens=8)
    assert got.shape == ref.shape
    agree = float(jnp.mean((got[:, 9:] == ref[:, 9:]).astype(jnp.float32)))
    assert agree > 0.7, agree


def test_kv_quantized_on_tp_mesh(setup):
    """Quantized cache + tp-sharded params through the mesh decode path."""
    from nbdistributed_tpu.models import param_shardings
    from nbdistributed_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding
    cfg, params = setup
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    p_s = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_shardings(cfg)))
    prompt = jax.random.randint(jax.random.PRNGKey(21), (2, 6), 0,
                                cfg.vocab_size)
    got = generate(p_s, prompt, cfg, max_new_tokens=6, mesh=mesh,
                   kv_quantized=True)
    ref = generate(params, prompt, cfg, max_new_tokens=6,
                   kv_quantized=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_chunked_prefill_matches_single_shot(setup):
    """Chunked prefill must fill the cache identically to one-shot
    prefill and produce the same last-position logits — for fp and
    int8 caches."""
    from nbdistributed_tpu.models import (forward_with_cache,
                                          init_kv_cache,
                                          prefill_chunked)
    cfg, params = setup
    prompt = jax.random.randint(jax.random.PRNGKey(30), (2, 12), 0,
                                cfg.vocab_size)
    for quantized in (False, True):
        c1 = init_kv_cache(cfg, 2, 24, quantized=quantized)
        ref_logits, ref_cache = forward_with_cache(
            params, prompt, c1, 0, cfg, last_only=True)
        c2 = init_kv_cache(cfg, 2, 24, quantized=quantized)
        got_logits, got_cache = jax.jit(
            lambda p, t, c: prefill_chunked(p, t, c, cfg, chunk=4)
        )(params, prompt, c2)
        np.testing.assert_allclose(np.asarray(got_logits),
                                   np.asarray(ref_logits),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"quantized={quantized}")
        for k in ref_cache:
            np.testing.assert_allclose(
                np.asarray(got_cache[k]).astype(np.float32),
                np.asarray(ref_cache[k]).astype(np.float32),
                atol=1e-5, rtol=1e-5, err_msg=f"{k} q={quantized}")
    with pytest.raises(ValueError, match="divisible"):
        prefill_chunked(params, prompt,
                        init_kv_cache(cfg, 2, 24), cfg, chunk=5)
