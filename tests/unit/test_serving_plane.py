"""Serving-plane units (ISSUE 11): the journal, the offset-dedup
merge, and the ServingManager's admission/failover/delivery machinery
driven against a fake comm — no pool, no jax, no sleeps beyond the
driver's own polling.

The fake workers decode a DETERMINISTIC position-weighted stream
(next token is a function of the whole sequence so far), which mirrors
the property the real greedy decoder has: re-prefilling from
``prompt + emitted-prefix`` continues the stream bit-identically.
That is exactly what makes journal-replay failover exact.
"""

from __future__ import annotations

import threading
import time
import types

import pytest

from nbdistributed_tpu.gateway.serving import (ServeJournal,
                                               ServingManager,
                                               journal_path,
                                               merge_emission)
from nbdistributed_tpu.messaging.coordinator import WorkerDied
from nbdistributed_tpu.observability.metrics import MetricsRegistry

pytestmark = [pytest.mark.unit, pytest.mark.serve, pytest.mark.gateway]


def next_tok(seq: list[int]) -> int:
    """Deterministic 'model': the continuation depends on the WHOLE
    sequence, so prompt+prefix re-admission must reproduce it."""
    return (sum((i + 1) * t for i, t in enumerate(seq)) + 7) % 50


def expected_stream(prompt: list[int], n: int) -> list[int]:
    seq = list(prompt)
    out = []
    for _ in range(n):
        t = next_tok(seq)
        out.append(t)
        seq.append(t)
    return out


# ----------------------------------------------------------------------
# journal + merge


def test_merge_emission_dedup_and_gap():
    # Fresh emission.
    assert merge_emission(0, 0, 0, [1, 2]) == ([1, 2], 0)
    # Append at the cursor.
    assert merge_emission(2, 0, 2, [3, 4]) == ([3, 4], 0)
    # Replayed overlap: the first 2 are already delivered.
    assert merge_emission(2, 0, 0, [1, 2, 3]) == ([3], 2)
    # Fully duplicated emission.
    assert merge_emission(3, 0, 0, [1, 2, 3]) == ([], 3)
    # Re-admission base: worker offset 0 maps to global offset 4.
    assert merge_emission(4, 4, 0, [9]) == ([9], 0)
    # Gap: refused, not silently journaled around.
    new, dup = merge_emission(1, 0, 3, [8])
    assert new is None and dup == 0


def test_journal_roundtrip_and_torn_tail(tmp_path):
    path = journal_path(str(tmp_path), "serve")
    j = ServeJournal(path)
    j.accept("r0", "t1", [5, 9], 4, 2)
    j.emit("r0", 0, [11, 12])
    j.accept("r1", "t2", [7], 3, 0)
    j.emit("r1", 0, [13])
    j.done("r1", "completed")
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"e": "emit", "rid": "r0", "o"')  # torn tail
    state = ServeJournal.load(path)
    assert state["r0"]["tokens"] == [11, 12]
    assert state["r0"]["done"] is None
    assert state["r1"] == {"tenant": "t2", "prompt": [7], "max_new": 3,
                           "prio": 0, "tokens": [13],
                           "done": "completed"}
    plan = ServeJournal.unfinished(state)
    assert plan == [{"rid": "r0", "tenant": "t1",
                     "prompt": [5, 9, 11, 12], "max_new": 2,
                     "base": 2, "prio": 2}]


def test_journal_load_dedups_replayed_emissions(tmp_path):
    path = journal_path(str(tmp_path), "serve")
    j = ServeJournal(path)
    j.accept("r0", "t", [1], 4, 0)
    j.emit("r0", 0, [10, 11])
    j.emit("r0", 0, [10, 11, 12])   # replayed + one new token
    j.emit("r0", 3, [13])
    j.close()
    state = ServeJournal.load(path)
    assert state["r0"]["tokens"] == [10, 11, 12, 13]


# ----------------------------------------------------------------------
# fake pool


class FakeComm:
    """A fake CommunicationManager speaking the serve_* protocol with
    per-rank in-memory 'workers' running the deterministic stream
    above.  Per-tick emission is capped so requests stay mid-decode
    long enough to be killed."""

    def __init__(self, num_workers: int = 2, per_tick: int = 2,
                 tick_delay: float = 0.0):
        self.num_workers = num_workers
        self.per_tick = per_tick
        self.tick_delay = tick_delay  # slow decode so tests can
        #                               interleave mid-stream faults
        self._dead: set[int] = set()
        self._lock = threading.Lock()
        self.open_fail_ranks: set[int] = set()  # serve_open errors
        # rank -> {rid: {"seq": [...], "emitted": n, "max": n}}
        self._srv: dict[int, dict] = {}
        self._replay: dict[str, dict] = {}
        self.overlap_next_reply = 0   # test hook: re-send n tokens
        self.fail_next = 0            # test hook: raise TimeoutError
        self.steps_seen: list[dict] = []

    # --- the surface ServingManager uses ------------------------------

    def dead_ranks(self):
        return set(self._dead)

    def kill(self, rank: int):
        with self._lock:
            self._dead.add(rank)
            self._srv.pop(rank, None)

    def post(self, ranks, msg_type, data=None):
        pass

    def send_to_ranks(self, ranks, msg_type, data=None, *, tenant=None,
                      priority=0, msg_id=None, timeout=None,
                      on_verdict=None, collective="unknown",
                      bufs=None):
        [rank] = ranks
        if rank in self._dead:
            raise WorkerDied(f"workers [{rank}] are dead")
        if msg_type == "execute":
            return {rank: types.SimpleNamespace(data={"output": "ok"})}
        if msg_type == "serve_open":
            if rank in self.open_fail_ranks:
                return {rank: types.SimpleNamespace(
                    data={"error": "injected serve_open failure"})}
            self._srv[rank] = {}
            return {rank: types.SimpleNamespace(
                data={"status": "open"})}
        if msg_type == "serve_close":
            self._srv.pop(rank, None)
            return {rank: types.SimpleNamespace(data={"status": "ok"})}
        assert msg_type == "serve_step"
        if self.tick_delay:
            time.sleep(self.tick_delay)
            if [r for r in ranks if r in self._dead]:
                # Killed while this tick was in flight: the reply is
                # lost with the rank, like a real SIGKILL mid-step.
                raise WorkerDied(f"workers {ranks} are dead")
        if self.fail_next > 0:
            self.fail_next -= 1
            raise TimeoutError("injected step timeout")
        if msg_id in self._replay:   # redelivery: cached reply
            return {rank: types.SimpleNamespace(
                data=self._replay[msg_id])}
        srv = self._srv.setdefault(rank, {})
        self.steps_seen.append(dict(data))
        for a in data.get("admit") or ():
            srv[a["rid"]] = {"seq": list(a["prompt"]), "emitted": 0,
                             "base_len": len(a["prompt"]),
                             "max": a["max_new"]}
        for rid in data.get("release") or ():
            srv.pop(rid, None)
        emitted, finished = {}, []
        for rid, st in srv.items():
            if st["emitted"] >= st["max"]:
                finished.append(rid)
                continue
            o = st["emitted"]
            new = []
            for _ in range(min(self.per_tick,
                               st["max"] - st["emitted"])):
                t = next_tok(st["seq"])
                st["seq"].append(t)
                new.append(t)
            st["emitted"] += len(new)
            back = min(self.overlap_next_reply, o)
            if back:
                # Test hook: pretend this reply re-sends `back`
                # already-reported tokens (a replayed emission).
                new = st["seq"][st["base_len"] + o - back:
                               st["base_len"] + st["emitted"]]
                o -= back
                self.overlap_next_reply = 0
            emitted[rid] = {"o": o, "t": list(new)}
            if st["emitted"] >= st["max"]:
                finished.append(rid)
        reply = {"status": "ok", "emitted": emitted,
                 "finished": finished, "errors": {},
                 "active": len(srv), "slots": 8, "pending": 0}
        if msg_id is not None:
            self._replay[msg_id] = reply
        return {rank: types.SimpleNamespace(data=reply)}


def make_mgr(tmp_path, comm, **kw):
    delivered: list = []
    notices: list = []
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("steps", 2)
    kw.setdefault("step_timeout", 5.0)
    kw.setdefault("queue_depth", 8)
    kw.setdefault("inflight", 8)
    mgr = ServingManager(
        comm, str(tmp_path), world_size=comm.num_workers,
        deliver=lambda t, m: delivered.append((t, m)),
        notify=lambda t, m: notices.append((t, m)), **kw)
    return mgr, delivered, notices


def wait_done(mgr, rids, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(mgr.result(r)["done"] for r in rids):
            return
        time.sleep(0.02)
    raise AssertionError(
        f"requests not done: "
        f"{({r: mgr.result(r) for r in rids})}; {mgr.describe()}")


# ----------------------------------------------------------------------
# manager behavior


def test_manager_serves_exact_streams_and_delivers_once(tmp_path):
    comm = FakeComm()
    mgr, delivered, notices = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        prompts = [[5, 9, 2], [7, 1], [3, 4, 8]]
        rids = [mgr.submit("t1", p, 5)["rid"] for p in prompts]
        wait_done(mgr, rids)
        for rid, p in zip(rids, prompts):
            r = mgr.result(rid)
            assert r["status"] == "completed"
            assert r["tokens"] == expected_stream(p, 5)
        # Terminal delivery exactly once per request, via serve_done.
        done_rids = [m.data["rid"] for _t, m in delivered
                     if m.msg_type == "serve_done"]
        assert sorted(done_rids) == sorted(rids)
        # Incremental notices carry contiguous offsets per rid.
        for rid in rids:
            offs = [(m.data["o"], len(m.data["t"]))
                    for _t, m in notices
                    if m.msg_type == "serve_tokens"
                    and m.data["rid"] == rid]
            pos = 0
            for o, n in offs:
                assert o == pos
                pos += n
        d = mgr.describe()
        assert d["completed"] == 3 and d["dup_dropped"] == 0
        assert d["failovers"] == 0
        # The journal replays to the exact streams.
        state = ServeJournal.load(journal_path(str(tmp_path),
                                               "serve"))
        for rid, p in zip(rids, prompts):
            assert state[rid]["tokens"] == expected_stream(p, 5)
            assert state[rid]["done"] == "completed"
    finally:
        mgr.stop()


def test_admission_verdicts_rejected_and_shed(tmp_path):
    comm = FakeComm()
    # 1 KV slot, queue depth 1, per-tenant cap 2: the third same-
    # tenant submit must be REJECTED at the cap; a low-priority
    # pending request must be SHED by a higher-priority burst.
    mgr, delivered, _ = make_mgr(tmp_path, comm, max_batch=1,
                                 queue_depth=1, inflight=2)
    # Driver NOT started: requests stay pending, so verdicts are
    # deterministic.
    v0 = mgr.submit("t1", [1], 4, priority=0)
    assert v0["status"] == "accepted" and not v0["queued"]
    v1 = mgr.submit("t1", [2], 4, priority=0)
    assert v1["status"] == "accepted" and v1["queued"]
    v2 = mgr.submit("t1", [3], 4)
    assert v2["status"] == "rejected"
    assert "in-flight" in v2["error"]
    # Higher-priority tenant floods: t1's queued request is the
    # lowest-priority pending one and sheds with a delivered verdict.
    v3 = mgr.submit("t2", [4], 4, priority=5)
    assert v3["status"] == "accepted"
    shed = [m for _t, m in delivered
            if m.data.get("status") == "shed"]
    assert len(shed) == 1 and shed[0].data["rid"] == v1["rid"]
    assert mgr.result(v1["rid"])["status"] == "shed"
    # Too-long requests are refused with a named verdict.
    v4 = mgr.submit("t2", [1] * 60, 10)
    assert v4["status"] == "rejected" and v4["reason"] == "too-long"
    mgr.stop()


def test_failover_readmits_from_journal_exactly(tmp_path):
    comm = FakeComm(num_workers=3, per_tick=1, tick_delay=0.05)
    mgr, delivered, _ = make_mgr(tmp_path, comm, steps=1)
    mgr.start()
    try:
        prompt = [5, 9, 2]
        rid = mgr.submit("t1", prompt, 8)["rid"]
        # Decode places on the HIGHEST live rank (2); let it emit a
        # few tokens, then SIGKILL that rank.
        deadline = time.monotonic() + 10
        while len(mgr.result(rid)["tokens"]) < 3:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert mgr.describe()["decode_rank"] == 2
        comm.kill(2)
        wait_done(mgr, [rid])
        r = mgr.result(rid)
        assert r["status"] == "completed"
        assert r["tokens"] == expected_stream(prompt, 8)
        d = mgr.describe()
        assert d["failovers"] >= 1
        assert d["replayed"] >= 1
        assert d["dup_dropped"] == 0
        assert d["decode_rank"] == 1
        # The re-admission carried prompt + emitted prefix and the
        # REMAINING budget (the journal-replay contract).
        readmits = [a for s in comm.steps_seen
                    for a in (s.get("admit") or ())
                    if a["rid"] == rid and len(a["prompt"]) >
                    len(prompt)]
        assert readmits, "no journal re-admission seen"
        ra = readmits[0]
        k = len(ra["prompt"]) - len(prompt)
        assert ra["prompt"] == prompt + expected_stream(prompt, k)
        assert ra["max_new"] == 8 - k
    finally:
        mgr.stop()


def test_replayed_emission_overlap_is_dropped(tmp_path):
    comm = FakeComm(per_tick=1, tick_delay=0.05)
    mgr, _d, _n = make_mgr(tmp_path, comm, steps=1)
    mgr.start()
    try:
        rid = mgr.submit("t1", [7, 1], 6)["rid"]
        deadline = time.monotonic() + 10
        while len(mgr.result(rid)["tokens"]) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        comm.overlap_next_reply = 2   # next reply re-sends 2 tokens
        wait_done(mgr, [rid])
        r = mgr.result(rid)
        assert r["tokens"] == expected_stream([7, 1], 6)
        assert mgr.describe()["dup_dropped"] >= 2
    finally:
        mgr.stop()


def test_step_timeout_redelivers_same_msg_id(tmp_path):
    comm = FakeComm()
    mgr, _d, _n = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        comm.fail_next = 1   # first tick times out, retry succeeds
        rid = mgr.submit("t1", [9], 4)["rid"]
        wait_done(mgr, [rid])
        assert mgr.result(rid)["tokens"] == expected_stream([9], 4)
        d = mgr.describe()
        assert d["step_retries"] >= 1 and d["dup_dropped"] == 0
    finally:
        mgr.stop()


def test_stream_resume_from_acked_offset(tmp_path):
    comm = FakeComm()
    mgr, _d, _n = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        prompt = [3, 4]
        rid = mgr.submit("t1", prompt, 6)["rid"]
        wait_done(mgr, [rid])
        full = expected_stream(prompt, 6)
        s = mgr.stream(rid, 4)
        assert s["tokens"] == full[4:] and s["offset"] == 4
        assert s["done"] is True
        assert mgr.describe()["resumed"] == 1
        assert mgr.stream(rid, 0)["tokens"] == full
    finally:
        mgr.stop()


def test_successor_plane_recovers_journal(tmp_path):
    """Gateway-death durability: a NEW ServingManager over the same
    run dir + tenant re-enters every journaled-but-unfinished request
    and completes it exactly — 'accepted' survives the daemon too."""
    comm_a = FakeComm(per_tick=1, tick_delay=0.05)
    mgr_a, _d, _n = make_mgr(tmp_path, comm_a, steps=1)
    mgr_a.start()
    prompt = [5, 9, 2]
    rid = mgr_a.submit("t1", prompt, 8)["rid"]
    deadline = time.monotonic() + 10
    while len(mgr_a.result(rid)["tokens"]) < 3:
        assert time.monotonic() < deadline
        time.sleep(0.02)
    mgr_a.stop(close_workers=False)   # daemon dies mid-stream
    prefix = mgr_a.result(rid)["tokens"]
    assert 0 < len(prefix) < 8

    comm_b = FakeComm()
    mgr_b, delivered, _ = make_mgr(tmp_path, comm_b)
    mgr_b.start()
    try:
        wait_done(mgr_b, [rid])
        r = mgr_b.result(rid)
        assert r["status"] == "completed"
        assert r["tokens"] == expected_stream(prompt, 8)
        d = mgr_b.describe()
        assert d["replayed"] >= 1 and d["dup_dropped"] == 0
        # The terminal result still reaches the submitter's mailbox.
        assert [m.data["rid"] for _t, m in delivered
                if m.msg_type == "serve_done"] == [rid]
        # Fresh submissions never reuse a journaled rid.
        rid2 = mgr_b.submit("t1", [1], 2)["rid"]
        assert rid2 != rid
        assert int(rid2.lstrip("r")) > int(rid.lstrip("r"))
        wait_done(mgr_b, [rid2])
    finally:
        mgr_b.stop()


def test_open_failure_backs_off_to_lower_rank(tmp_path):
    """A rank whose serve_open fails (lost namespace, OOM) is backed
    off so the plane fails over to a lower live rank instead of
    wedging on retries."""
    comm = FakeComm(num_workers=2)
    comm.open_fail_ranks.add(1)   # the preferred (highest) rank
    mgr, _d, _n = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        rid = mgr.submit("t1", [7, 1], 4)["rid"]
        wait_done(mgr, [rid])
        r = mgr.result(rid)
        assert r["status"] == "completed"
        assert r["tokens"] == expected_stream([7, 1], 4)
        assert mgr.describe()["decode_rank"] == 0
    finally:
        mgr.stop()


# ----------------------------------------------------------------------
# metrics satellite


def test_metrics_remove_label_series():
    reg = MetricsRegistry()
    reg.counter("nbd_x_total", "x", {"tenant": "a"}).inc()
    reg.counter("nbd_x_total", "x", {"tenant": "b"}).inc(2)
    reg.gauge("nbd_y", "y", {"tenant": "a", "kind": "k"}).set(1)
    reg.counter("nbd_z_total", "z").inc()
    assert reg.remove_label_series("tenant", "a") == 2
    j = reg.to_json()
    assert 'nbd_x_total{tenant="a"}' not in j["counters"]
    assert j["counters"]['nbd_x_total{tenant="b"}'] == 2
    assert j["gauges"] == {}
    assert j["counters"]["nbd_z_total"] == 1
    # Removing again is a no-op; the metric NAME stays registered
    # with its kind (a later re-create cannot flip kinds).
    assert reg.remove_label_series("tenant", "a") == 0
    with pytest.raises(ValueError):
        reg.gauge("nbd_x_total")


# ----------------------------------------------------------------------
# serving SLO histograms (ISSUE 13)


def test_slo_histograms_per_tenant_and_eviction(tmp_path):
    """Completed requests observe TTFT / TPOT / queue-wait / e2e into
    per-SUBMITTING-tenant histograms; describe() carries the p50/p99
    block split per tenant; tenant eviction's remove_label_series
    really retires the series."""
    from nbdistributed_tpu.observability import metrics as obs_metrics
    comm = FakeComm()
    mgr, _d, _n = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        rids = [mgr.submit("nb1", [5, 9, 2], 5)["rid"],
                mgr.submit("nb2", [7, 1], 4)["rid"]]
        wait_done(mgr, rids)
    finally:
        mgr.stop()
    text = obs_metrics.registry().prometheus_text()
    for name in ("nbd_serve_ttft_seconds",
                 "nbd_serve_queue_wait_seconds",
                 "nbd_serve_e2e_seconds"):
        assert f'{name}_count{{tenant="nb1"}} 1' in text
        assert f'{name}_count{{tenant="nb2"}} 1' in text
    # 5 tokens at 2/tick = 3 emissions: 2 inter-emission gaps observe
    # the per-token rate (the first batch is TTFT, never TPOT)
    assert 'nbd_serve_tpot_seconds_count{tenant="nb1"} 2' in text

    slo = mgr.describe()["slo"]
    assert slo["e2e_ms"]["n"] == 2
    assert slo["ttft_ms"]["p99"] >= slo["ttft_ms"]["p50"] >= 0
    assert set(slo["tenants"]) == {"nb1", "nb2"}
    assert slo["tenants"]["nb1"]["e2e_ms"]["n"] == 1

    # eviction hygiene: dropping nb1 removes ITS series, keeps nb2's
    assert obs_metrics.registry().remove_label_series(
        "tenant", "nb1") >= 4
    text = obs_metrics.registry().prometheus_text()
    assert 'nbd_serve_ttft_seconds_count{tenant="nb1"}' not in text
    assert 'nbd_serve_ttft_seconds_count{tenant="nb2"} 1' in text


def test_slo_queue_wait_counts_first_placement_only(tmp_path):
    """A failover re-admission is a heal, not queue wait: the queue
    histogram observes once per request even when the decode rank dies
    mid-stream and the request is re-placed."""
    from nbdistributed_tpu.observability import metrics as obs_metrics
    reg = obs_metrics.registry()

    def qcount():
        j = reg.to_json()["histograms"]
        e = j.get('nbd_serve_queue_wait_seconds{tenant="qw1"}')
        return e["count"] if e else 0

    base = qcount()
    comm = FakeComm(per_tick=1, tick_delay=0.05)
    mgr, _d, _n = make_mgr(tmp_path, comm)
    mgr.start()
    try:
        rid = mgr.submit("qw1", [5, 9, 2], 6)["rid"]
        deadline = time.monotonic() + 10
        while mgr.result(rid)["tokens"] == [] \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        comm.kill(1)          # decode rank dies mid-stream
        wait_done(mgr, [rid])
        assert mgr.describe()["failovers"] >= 1
    finally:
        mgr.stop()
    assert qcount() - base == 1
