"""Unit tests for ISSUE 6: per-link fault shaping, the partition
sentry, supervisor/watchdog host domains, per-class retry budgets, and
the reply-epoch fence."""

import threading
import time

import pytest

from nbdistributed_tpu.messaging.codec import Message
from nbdistributed_tpu.messaging.coordinator import (CommunicationManager,
                                                     _Pending)
from nbdistributed_tpu.messaging.transport import (CoordinatorListener,
                                                   TransportError,
                                                   WorkerChannel)
from nbdistributed_tpu.resilience.faults import FaultPlan, LinkSpec
from nbdistributed_tpu.resilience.partition import PartitionSentry
from nbdistributed_tpu.resilience.retry import (BULK_TYPES, RetryPolicy,
                                                class_of)
from nbdistributed_tpu.resilience.supervisor import (SUSPECT, Supervisor,
                                                     SupervisorPolicy)

pytestmark = pytest.mark.faults


# ----------------------------------------------------------------------
# LinkSpec / FaultPlan link shaping


def test_link_spec_validation():
    with pytest.raises(ValueError):
        LinkSpec(hosts=["a"])                 # not a pair
    with pytest.raises(ValueError):
        LinkSpec(hosts=["a", "a"])            # self-partition
    with pytest.raises(ValueError):
        LinkSpec.from_spec({"hosts": ["a", "b"], "nope": 1})
    l = LinkSpec.from_spec({"hosts": ["a", "b"], "after_s": 2,
                            "for_s": 5})
    assert l.matches("a", "b") and l.matches("b", "a")
    assert not l.matches("a", "c")


def test_link_spec_partition_window():
    l = LinkSpec(hosts=["a", "b"], after_s=2.0, for_s=5.0)
    assert not l.partition_active(1.9)
    assert l.partition_active(2.0)
    assert l.partition_active(6.9)
    assert not l.partition_active(7.0)
    # for_s=0 with after_s set: partitioned from after_s onward.
    forever = LinkSpec(hosts=["a", "b"], after_s=1.0)
    assert not forever.partition_active(0.5)
    assert forever.partition_active(100.0)
    # An EXPLICIT for_s=0 (the "%dist_chaos --partition-for 0" form)
    # means "until cleared", even with after_s 0 — not a no-op.
    now = LinkSpec.from_spec({"hosts": ["a", "b"], "after_s": 0,
                              "for_s": 0})
    assert now.has_partition and now.partition_active(0.0)
    assert now.partition_active(1e6)
    # No window declared at all: never partitioned — and the spec
    # roundtrip preserves that (0.0 defaults must not re-declare one).
    shaped = LinkSpec(hosts=["a", "b"], latency_s=0.01)
    assert not shaped.has_partition
    assert not LinkSpec.from_spec(shaped.spec()).has_partition
    assert LinkSpec.from_spec(now.spec()).has_partition


def test_link_spec_wildcard():
    l = LinkSpec(hosts=["*", "b"])
    assert l.matches("anything", "b") and l.matches("b", "x")
    assert not l.matches("x", "y")


def test_fault_plan_links_spec_roundtrip():
    p = FaultPlan.from_spec({"seed": 3, "links": [
        {"hosts": ["local", "hostB"], "after_s": 1, "for_s": 2},
        {"hosts": ["local", "hostC"], "latency_s": 0.05, "loss": 0.1},
    ]})
    assert p.has_links()
    p2 = FaultPlan.from_spec(p.spec())
    assert [l.spec() for l in p2.links] == [l.spec() for l in p.links]


def test_link_blocked_window_timing():
    p = FaultPlan.from_spec({"links": [
        {"hosts": ["local", "hostB"], "after_s": 5.0, "for_s": 10.0}]})
    # Window not yet open.
    assert not p.link_blocked("hostB", "local")
    # Rewind the install clock so 7 s have "elapsed": window open.
    p._t0 = time.monotonic() - 7.0
    assert p.link_blocked("hostB", "local")
    assert p.link_blocked("local", "hostB")
    assert not p.link_blocked("local", "hostC")
    # Same-host traffic never crosses a link.
    assert not p.link_blocked("hostB", "hostB")
    # Window closed again after after_s + for_s.
    p._t0 = time.monotonic() - 16.0
    assert not p.link_blocked("hostB", "local")


def test_link_transmit_partition_drops_silently():
    p = FaultPlan.from_spec({"links": [
        {"hosts": ["local", "hostB"], "after_s": 0.0, "for_s": 60.0}]})
    sent = []
    p.link_transmit("local", "hostB", b"x" * 10, sent.append,
                    kind="execute")
    assert sent == []
    assert p.counters["link_dropped"] == 1
    # Frames on an unmatched pair pass through untouched.
    p.link_transmit("local", "hostC", b"y", sent.append, kind="execute")
    assert sent == [b"y"]


def test_link_transmit_loss_is_seeded():
    def drops(seed):
        p = FaultPlan.from_spec({"seed": seed, "links": [
            {"hosts": ["a", "b"], "loss": 0.5}]})
        out = []
        for i in range(40):
            got = []
            p.link_transmit("a", "b", b"f", got.append, kind="k")
            out.append(bool(got))
        return out

    assert drops(7) == drops(7)          # deterministic per seed
    assert drops(7) != drops(8)          # seed actually matters
    assert 0 < sum(drops(7)) < 40        # some pass, some drop


def test_link_transmit_latency_composes_with_frame_faults():
    p = FaultPlan.from_spec({"drop": 1.0, "links": [
        {"hosts": ["a", "b"], "latency_s": 0.0}]})
    sent = []
    # Link passes the frame, the per-frame fault layer then drops it.
    p.link_transmit("a", "b", b"f", sent.append, kind="k")
    assert sent == []
    assert p.counters["dropped"] == 1


def test_worker_channel_severs_on_partition():
    """A blocked link makes send() raise AND tears the socket so the
    recv side surfaces TransportError — the orphan-entry path."""
    lst = CoordinatorListener()
    lst.start()
    try:
        ch = WorkerChannel("127.0.0.1", lst.port, rank=0)
        ch.local_host, ch.peer_host = "hostB", "local"
        ch.fault_plan = FaultPlan.from_spec({"links": [
            {"hosts": ["local", "hostB"], "after_s": 0.0,
             "for_s": 60.0}]})
        with pytest.raises(TransportError):
            ch.send(Message(msg_type="ping", rank=0))
        with pytest.raises(TransportError):
            ch.recv(timeout=1.0)
    finally:
        lst.close()


def test_listener_drops_frames_to_partitioned_host():
    lst = CoordinatorListener()
    lst.local_host = "local"
    lst.host_of_rank = {0: "hostB", 1: "hostC"}
    lst.start()
    try:
        ch0 = WorkerChannel("127.0.0.1", lst.port, rank=0)
        ch1 = WorkerChannel("127.0.0.1", lst.port, rank=1)
        # Identify both connections (preamble consumed on first recv).
        ch0.send(Message(msg_type="ping", rank=0))
        ch1.send(Message(msg_type="ping", rank=1))
        deadline = time.time() + 5
        while len(lst.connected_ranks()) < 2 and time.time() < deadline:
            time.sleep(0.01)
        lst.fault_plan = FaultPlan.from_spec({"links": [
            {"hosts": ["local", "hostB"], "after_s": 0.0,
             "for_s": 60.0}]})
        msg = Message(msg_type="execute", data="x")
        lst.send_to_ranks([0, 1], msg)

        def rx(ch, bucket):
            try:
                bucket.append(ch.recv(timeout=2.0))
            except TimeoutError:
                bucket.append(None)

        b0, b1 = [], []
        threading.Thread(target=rx, args=(ch1, b1), daemon=True).start()
        threading.Thread(target=rx, args=(ch0, b0), daemon=True).start()
        time.sleep(2.5)
        assert b1 and b1[0] is not None, "hostC frame should arrive"
        assert not b0 or b0[0] is None, "hostB frame crossed a " \
                                        "partitioned link"
        assert lst.fault_plan.counters["link_dropped"] >= 1
        ch0.close()
        ch1.close()
    finally:
        lst.close()


# ----------------------------------------------------------------------
# PartitionSentry


def _sentry(grace=10.0, clock=None):
    return PartitionSentry({0: "local", 1: "hostB", 2: "hostB",
                            3: "hostC"},
                           local_host="local", grace_s=grace,
                           source="test",
                           clock=clock or (lambda: 0.0))


def test_sentry_whole_host_silence_is_suspected():
    s = _sentry()
    # Partial silence: no suspicion.
    assert s.observe({1}, set(), {0, 2, 3}, now=1.0) == []
    # Whole host B silent, witnesses elsewhere fresh: suspected.
    evs = s.observe({1, 2}, set(), {0, 3}, now=2.0)
    assert [e["event"] for e in evs] == ["suspected"]
    assert evs[0]["host"] == "hostB" and evs[0]["ranks"] == [1, 2]
    assert s.suspected_ranks() == {1, 2}
    # Steady state: no repeat events.
    assert s.observe({1, 2}, set(), {0, 3}, now=3.0) == []


def test_sentry_needs_a_fresh_witness():
    s = _sentry()
    # EVERYTHING silent — that's a dead coordinator-side network or a
    # stopped world, not a partition of one host.
    assert s.observe({1, 2, 3}, set(), set(), now=1.0) == []


def test_sentry_heals_on_any_rank_returning():
    s = _sentry()
    s.observe({1, 2}, set(), {0, 3}, now=1.0)
    evs = s.observe({2}, set(), {0, 1, 3}, now=2.0)
    assert [e["event"] for e in evs] == ["healed"]
    assert s.suspected_ranks() == set()


def test_sentry_grace_expiry():
    s = _sentry(grace=10.0)
    s.observe({1, 2}, set(), {0, 3}, now=1.0)
    assert s.observe({1, 2}, set(), {0, 3}, now=9.0) == []
    evs = s.observe({1, 2}, set(), {0, 3}, now=12.0)
    assert [e["event"] for e in evs] == ["expired"]
    assert s.expired_hosts() == ["hostB"]
    assert s.suspected_ranks() == set()
    # A late return still heals an expired host.
    evs = s.observe(set(), set(), {0, 1, 2, 3}, now=13.0)
    assert [e["event"] for e in evs] == ["healed"]


def test_sentry_counts_process_death_as_gone():
    s = _sentry()
    evs = s.observe({1}, {2}, {0, 3}, now=1.0)
    assert [e["event"] for e in evs] == ["suspected"]


def test_sentry_local_host_exempt_and_single_host_inert():
    s = _sentry()
    # rank 0 is on the coordinator's host: its silence alone never
    # makes a suspicion (not even with witnesses).
    assert s.observe({0}, set(), {1, 2, 3}, now=1.0) == []
    single = PartitionSentry({0: "local", 1: "local"},
                             local_host="local", grace_s=5.0)
    assert not single.active
    assert single.observe({0, 1}, set(), set()) == []


# ----------------------------------------------------------------------
# Supervisor host domains (fake comm/pm, fake clock)


class FakePM:
    def __init__(self, hosts):
        self.hosts = dict(hosts)
        self.cbs = []

    def add_death_callback(self, cb):
        self.cbs.append(cb)

    def remove_death_callback(self, cb):
        if cb in self.cbs:
            self.cbs.remove(cb)

    def die(self, rank, rc=-9):
        for cb in self.cbs:
            cb(rank, rc)


class FakeComm:
    def __init__(self, n=3):
        self.num_workers = n
        self.local_host = "local"
        self.pings = {}
        self.seen = {}

    def last_ping(self, rank):
        return self.pings.get(rank)

    def last_seen(self, rank):
        return self.seen.get(rank)


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


POLICY = SupervisorPolicy(poll_s=0.02, degraded_after_s=1.0,
                          postmortem=False, partition_grace_s=30.0)


def test_supervisor_defers_heal_during_partition_grace_then_heals():
    clock = Clock()
    healed = threading.Event()

    def heal():
        healed.set()
        return None

    sup = Supervisor(POLICY, heal=heal, clock=clock)
    comm = FakeComm(3)
    pm = FakePM({0: "local", 1: "hostB", 2: "hostB"})
    try:
        sup.attach(comm, pm)
        comm.seen = {0: clock.t, 1: clock.t, 2: clock.t}
        # Host B falls silent while rank 0 stays fresh.
        clock.t += 10.0
        comm.seen[0] = clock.t
        assert _wait(lambda: SUSPECT in sup.status()["states"].values())
        assert "hostB" in sup.status()["suspected_hosts"]
        # Inside the grace window: no heal, ever.
        time.sleep(0.2)
        assert not healed.is_set()
        # Grace expires with the host still gone: now it heals.
        clock.t += 31.0
        comm.seen[0] = clock.t
        assert healed.wait(5), "heal never ran after grace expiry"
        kinds = [(e["rank"], e["to"]) for e in sup.status()["events"]]
        assert (1, SUSPECT) in kinds and (1, "dead") in kinds
    finally:
        sup.stop()


def test_supervisor_partition_heal_restores_alive_without_respawn():
    clock = Clock()
    healed = threading.Event()
    sup = Supervisor(POLICY, heal=lambda: healed.set(), clock=clock)
    comm = FakeComm(3)
    pm = FakePM({0: "local", 1: "hostB", 2: "hostB"})
    try:
        sup.attach(comm, pm)
        comm.seen = {0: clock.t, 1: clock.t, 2: clock.t}
        clock.t += 10.0
        comm.seen[0] = clock.t
        assert _wait(lambda: SUSPECT in sup.status()["states"].values())
        # The link comes back inside the grace window.
        clock.t += 5.0
        comm.seen = {0: clock.t, 1: clock.t, 2: clock.t}
        assert _wait(sup.healthy), "world did not return to ALIVE"
        time.sleep(0.2)
        assert not healed.is_set(), "partition heal must not respawn"
    finally:
        sup.stop()


def test_supervisor_whole_host_death_defers_but_partial_heals():
    """All ranks of one host dying together rides the partition grace;
    a single rank dying on a multi-rank host heals immediately."""
    clock = Clock()
    healed = threading.Event()
    sup = Supervisor(POLICY, heal=lambda: healed.set(), clock=clock)
    comm = FakeComm(3)
    pm = FakePM({0: "local", 1: "hostB", 2: "hostB"})
    try:
        sup.attach(comm, pm)
        comm.seen = {0: clock.t, 1: clock.t, 2: clock.t}
        # Only rank 1 dies; rank 2 (same host) keeps heartbeating.
        clock.t += 2.0
        comm.seen = {0: clock.t, 1: clock.t - 2, 2: clock.t}
        pm.die(1)
        assert healed.wait(5), "partial-host death must heal promptly"
    finally:
        sup.stop()

    # Whole host dies at once → deferred while the sentry suspects.
    clock2 = Clock()
    healed2 = threading.Event()
    sup2 = Supervisor(POLICY, heal=lambda: healed2.set(), clock=clock2)
    comm2 = FakeComm(3)
    pm2 = FakePM({0: "local", 1: "hostB", 2: "hostB"})
    try:
        sup2.attach(comm2, pm2)
        clock2.t += 2.0
        comm2.seen = {0: clock2.t, 1: clock2.t - 2, 2: clock2.t - 2}
        pm2.die(1)
        pm2.die(2)
        assert _wait(
            lambda: "hostB" in sup2.status()["suspected_hosts"])
        time.sleep(0.2)
        assert not healed2.is_set(), \
            "whole-host death healed inside partition grace"
        # The link "heals": rank 2 is heard from again, but rank 1's
        # process is KNOWN dead — a sibling's ping must not resurrect
        # it, and with the suspicion cleared the deferred heal fires.
        clock2.t += 5.0
        comm2.seen = {0: clock2.t, 2: clock2.t}
        assert healed2.wait(5), (
            "dead rank never healed after the partition cleared")
        assert sup2.status()["states"][1] == "dead" or healed2.is_set()
    finally:
        sup2.stop()


# ----------------------------------------------------------------------
# Per-class retry budgets


def test_class_of_mapping():
    assert class_of("get_var") == "bulk"
    assert class_of("set_var") == "bulk"
    assert class_of("checkpoint") == "bulk"
    for t in ("execute", "get_status", "hello", "mailbox", "chaos"):
        assert class_of(t) == "control"
    # The bulk-transfer plane's frames (ISSUE 20) ride the bulk
    # budget: a chunk redelivery is payload movement, not control.
    assert BULK_TYPES == {"get_var", "set_var", "checkpoint",
                          "xfer_begin", "xfer_chunk", "xfer_commit",
                          "xfer_pull_begin", "xfer_read",
                          "xfer_pull_end"}


def test_retry_classes_from_env():
    base = RetryPolicy(attempts=4, attempt_timeout_s=5.0)
    out = RetryPolicy.classes_from_env(base, env={})
    assert out == {}
    out = RetryPolicy.classes_from_env(base, env={
        "NBD_RETRY_CLASS_BULK_TIMEOUT_S": "60",
        "NBD_RETRY_CLASS_BULK_ATTEMPTS": "2",
        "NBD_RETRY_CLASS_CONTROL_TIMEOUT_S": "1.5",
    })
    assert out["bulk"].attempt_timeout_s == 60.0
    assert out["bulk"].attempts == 2
    assert out["control"].attempt_timeout_s == 1.5
    assert out["control"].attempts == 4          # inherited
    # Backoff shape is inherited from the base policy.
    assert out["bulk"].backoff_base_s == base.backoff_base_s
    # Malformed values are ignored knob-wise.
    out = RetryPolicy.classes_from_env(base, env={
        "NBD_RETRY_CLASS_BULK_TIMEOUT_S": "lots"})
    assert out == {}


def test_coordinator_retry_for_uses_class_override(monkeypatch):
    monkeypatch.setenv("NBD_RETRY_TIMEOUT_S", "2")
    monkeypatch.setenv("NBD_RETRY_CLASS_BULK_TIMEOUT_S", "90")
    comm = CommunicationManager(num_workers=1)
    try:
        assert comm.retry_for("execute").attempt_timeout_s == 2.0
        assert comm.retry_for("get_var").attempt_timeout_s == 90.0
        assert comm.retry_for("get_var").enabled()
    finally:
        comm.shutdown()


# ----------------------------------------------------------------------
# Reply-epoch fence (coordinator side)


def test_coordinator_rejects_stale_epoch_reply():
    comm = CommunicationManager(num_workers=1, session_token="t",
                                session_epoch=3)
    try:
        req = Message(msg_type="execute", data="x")
        pending = _Pending({0}, "execute")
        with comm._lock:
            comm._pending[req.msg_id] = pending
        stale = Message(msg_type="response", msg_id=req.msg_id,
                        rank=0, epoch=2)
        comm._on_message(0, stale)
        assert pending.responses == {}, "stale-epoch reply was applied"
        current = Message(msg_type="response", msg_id=req.msg_id,
                          rank=0, epoch=3)
        comm._on_message(0, current)
        assert 0 in pending.responses
        # Unstamped replies (pre-epoch workers) are never rejected.
        pending2 = _Pending({0}, "execute")
        req2 = Message(msg_type="execute")
        with comm._lock:
            comm._pending[req2.msg_id] = pending2
        comm._on_message(0, Message(msg_type="response",
                                    msg_id=req2.msg_id, rank=0))
        assert 0 in pending2.responses
    finally:
        comm.shutdown()
