"""Unit tests for the training integrity guard (ISSUE 19).

Pure-logic tier, single process: device fingerprints, the audit
majority vote, corrupt-spec plumbing, the TrainGuard skip/rollback
state machine (driven by a fake step fn so every verdict is scripted),
one real jitted guarded step proving the bitwise-unchanged skip, and
the checkpoint integrity manifest.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nbdistributed_tpu.resilience import faults  # noqa: E402
from nbdistributed_tpu.resilience import trainguard as tg  # noqa: E402

pytestmark = [pytest.mark.unit, pytest.mark.guard]


# ----------------------------------------------------------------------
# fingerprints

def _flip_bit(arr: np.ndarray, bitpos: int) -> np.ndarray:
    out = arr.copy()
    view = out.view(np.uint8).reshape(-1)
    view[bitpos // 8] ^= np.uint8(1 << (bitpos % 8))
    return out


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "float16",
                                   "int32", "uint8", "bool"])
def test_leaf_fingerprint_changes_on_any_single_bit(dtype):
    x = jnp.asarray(np.arange(96) % 7, jnp.dtype(dtype))
    base = tuple(int(v) for v in np.asarray(tg.leaf_fingerprint(x)))
    host = np.asarray(x)
    nbits = host.size * host.dtype.itemsize * 8
    # every byte gets one probed bit; exhaustive would be slow
    for bitpos in range(0, nbits, 8):
        flipped = jnp.asarray(_flip_bit(host, bitpos))
        got = tuple(int(v)
                    for v in np.asarray(tg.leaf_fingerprint(flipped)))
        assert got != base, f"bit {bitpos} flip not detected ({dtype})"


def test_leaf_fingerprint_deterministic():
    x = jnp.asarray(np.random.default_rng(0).normal(size=1000),
                    jnp.float32)
    a = np.asarray(tg.leaf_fingerprint(x))
    b = np.asarray(tg.leaf_fingerprint(jnp.asarray(np.asarray(x))))
    assert (a == b).all()


def test_tree_fingerprint_sees_leaf_order():
    a = jnp.ones((4, 4), jnp.float32)
    b = jnp.zeros((4, 4), jnp.float32)
    assert (tg.tree_fingerprint({"p": a, "q": b})
            != tg.tree_fingerprint({"p": b, "q": a}))


def test_tree_fingerprint_empty_tree():
    assert tg.tree_fingerprint({}) == (0, 0)


def test_tree_fingerprint_stable_across_calls():
    t = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
         "b": jnp.ones((8,), jnp.bfloat16)}
    assert tg.tree_fingerprint(t) == tg.tree_fingerprint(t)


# ----------------------------------------------------------------------
# majority vote

def test_vote_unanimous_ok():
    v = tg.vote([(1, 2)] * 4)
    assert v.ok and v.majority_rank is None and v.minority == ()


def test_vote_majority_names_minority():
    v = tg.vote([(1, 2), (9, 9), (1, 2)])
    assert not v.ok
    assert v.majority_rank == 0          # lowest rank in the majority
    assert v.minority == (1,)


def test_vote_two_rank_split_has_no_majority():
    v = tg.vote([(1, 2), (9, 9)])
    assert not v.ok and v.majority_rank is None
    assert set(v.minority) == {0, 1}


def test_vote_three_way_tie_has_no_majority():
    v = tg.vote([(1, 1), (2, 2), (3, 3)])
    assert not v.ok and v.majority_rank is None


# ----------------------------------------------------------------------
# corrupt specs

def test_corrupt_spec_roundtrip():
    c = faults.CorruptSpec(rank=1, step=7, name="w1", mode="scale",
                           bits=3, scale=0.5, count=4)
    assert faults.CorruptSpec.from_spec(c.spec()).spec() == c.spec()


def test_corrupt_spec_validation():
    with pytest.raises(ValueError):
        faults.CorruptSpec(rank=-1, step=0)
    with pytest.raises(ValueError):
        faults.CorruptSpec(rank=0, step=0, mode="nope")
    with pytest.raises(ValueError):
        faults.CorruptSpec.from_spec({"rank": 0})  # needs step too


def test_corrupt_due_is_one_shot_with_ge_step():
    plan = faults.FaultPlan(seed=3, corrupt=[
        {"rank": 1, "step": 5, "name": "*"}])
    assert plan.has_corrupt()
    assert plan.corrupt_due(0, 99) == []          # wrong rank
    assert plan.corrupt_due(1, 4) == []           # too early
    due = plan.corrupt_due(1, 8)                  # fired late (>=)
    assert len(due) == 1
    assert plan.corrupt_due(1, 9) == []           # one-shot


def test_corrupt_plan_spec_roundtrip():
    plan = faults.FaultPlan(seed=3, corrupt=[
        {"rank": 0, "step": 2, "mode": "bitflip", "bits": 2}])
    again = faults.FaultPlan.from_spec(plan.spec())
    assert [c.spec() for c in again.corrupt] \
        == [c.spec() for c in plan.corrupt]


def test_apply_corrupt_bitflip_deterministic_and_localized():
    tree = {"w1": jnp.zeros((8, 8), jnp.float32),
            "w2": jnp.zeros((8,), jnp.float32)}
    spec = faults.CorruptSpec(rank=0, step=1, name="w2")
    t1, leaf1 = tg.apply_corrupt(tree, spec, seed=11)
    t2, leaf2 = tg.apply_corrupt(tree, spec, seed=11)
    assert leaf1 == leaf2 and "w2" in leaf1
    np.testing.assert_array_equal(np.asarray(t1["w2"]),
                                  np.asarray(t2["w2"]))
    # the named leaf changed, the other leaf did not
    assert (np.asarray(t1["w2"]) != np.asarray(tree["w2"])).any()
    np.testing.assert_array_equal(np.asarray(t1["w1"]),
                                  np.asarray(tree["w1"]))
    # a different seed flips a different bit
    t3, _ = tg.apply_corrupt(tree, spec, seed=12)
    assert (np.asarray(t3["w2"]).view(np.uint32)
            != np.asarray(t1["w2"]).view(np.uint32)).any()


def test_apply_corrupt_scale_mode():
    tree = {"w": jnp.ones((16,), jnp.float32)}
    spec = faults.CorruptSpec(rank=0, step=1, name="w", mode="scale",
                              scale=4.0, count=3)
    out, _ = tg.apply_corrupt(tree, spec, seed=5)
    host = np.asarray(out["w"])
    assert (host == 4.0).sum() == 3 and (host == 1.0).sum() == 13


def test_apply_corrupt_unknown_leaf_raises():
    with pytest.raises(ValueError, match="no param leaf"):
        tg.apply_corrupt({"w": jnp.zeros(3)},
                         faults.CorruptSpec(rank=0, step=1,
                                            name="nope"))


# ----------------------------------------------------------------------
# TrainGuard state machine (scripted verdicts via a fake step fn)

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


def _fake_guard(script, **kw):
    """TrainGuard over a fake step fn whose per-call verdicts come
    from ``script`` (list of (ok, loss) tuples, repeated last).  The
    fake returns dict-aux ``{"ok", "gnorm"}`` — the documented
    fallback lane for hand-built steps."""
    params = {"w": jnp.arange(4.0)}
    opt = {"m": jnp.zeros(4)}
    calls = {"n": 0}

    def fake_fn(p, o, batch):
        ok, loss = script[min(calls["n"], len(script) - 1)]
        calls["n"] += 1
        newp = {"w": p["w"] + 1.0}
        newo = {"m": o["m"] + 1.0}
        if ok:
            return newp, newo, jnp.float32(loss), \
                {"ok": jnp.asarray(True), "gnorm": jnp.float32(1.0)}
        # a real guarded step skips on-device: state passes through
        return p, o, jnp.float32(loss), \
            {"ok": jnp.asarray(False), "gnorm": jnp.float32(np.inf)}

    kw.setdefault("audit_every", 0)
    kw.setdefault("snapshot_every", 4)
    kw.setdefault("skip_budget", 2)
    g = tg.TrainGuard(fake_fn, params, opt, rank=0,
                      clock=_FakeClock(), **kw)
    g._lag = 0  # resolve every verdict immediately
    return g


def test_guard_counts_skips_and_preserves_state():
    g = _fake_guard([(True, 1.0)] * 3 + [(False, 1.0)] + [(True, 1.0)])
    for _ in range(3):
        g.step(None)
    w3 = np.asarray(g.params["w"]).copy()
    g.step(None)                       # the scripted skip
    d = g.describe()
    assert d["skips"] == 1 and d["skip_streak"] == 1
    np.testing.assert_array_equal(np.asarray(g.params["w"]), w3)
    g.step(None)                       # healthy step clears the streak
    assert g.describe()["skip_streak"] == 0
    assert g.describe()["rollbacks"] == 0


def test_guard_blown_skip_budget_rolls_back():
    # 4 good steps (snapshot at 4), then skips forever: budget 2 blows
    # on the third consecutive skip and restores the step-4 snapshot.
    g = _fake_guard([(True, 1.0)] * 5 + [(False, 1.0)])
    for _ in range(5):
        g.step(None)
    w_snap = np.asarray(g.params["w"]).copy() - 1.0  # params at step 4
    for _ in range(3):
        g.step(None)
    d = g.describe()
    assert d["rollbacks"] == 1 and d["skips"] == 3
    assert d["skip_streak"] == 0       # rollback resets the streak
    np.testing.assert_array_equal(np.asarray(g.params["w"]), w_snap)
    assert "rollback" in [e["kind"] for e in d["events"]]


def test_guard_speculative_snapshot_dropped_on_late_skip():
    # lag deep enough that the step-4 snapshot happens while the bad
    # step-2 verdict is still pending — the resolve must then drop it.
    g = _fake_guard([(True, 1.0), (True, 1.0), (False, 1.0),
                     (True, 1.0)], skip_budget=10)
    g._lag = 50
    for _ in range(6):
        g.step(None)
    g.finish()
    steps = [s[0] for s in g._snapshots]
    assert steps == [0], steps         # the step-4 snapshot is gone
    assert "snapshot_dropped" in [e["kind"] for e in g._events]


def test_guard_disabled_passthrough():
    g = _fake_guard([(False, 1.0)])    # every step would skip
    tg.set_enabled(False)
    try:
        for _ in range(3):
            g.step(None)
        # host machinery bypassed: no verdicts resolved, no skips
        assert g.describe()["skips"] == 0
        assert g.step_index == 3
    finally:
        tg.set_enabled(True)


def test_guard_finish_drains_pending():
    g = _fake_guard([(False, 1.0)], skip_budget=0)
    g._lag = 50                        # nothing resolves in-loop
    for _ in range(4):
        g.step(None)
    assert g.describe()["skips"] == 0  # still pending
    d = g.finish()
    assert d["skips"] == 4


# ----------------------------------------------------------------------
# spike detector

def test_spike_detector_confirms_after_streak():
    sd = tg.SpikeDetector(window=8, nmad=3.0, confirm=2,
                          min_history=8)
    for x in [1.0, 1.1, 0.9, 1.0, 1.05, 0.95, 1.0, 1.0]:
        assert sd.observe(x) == "ok"   # warmup fills the window
    assert sd.observe(50.0) == "suspect"
    assert sd.observe(50.0) == "confirmed"


def test_spike_detector_suspects_stay_out_of_history():
    sd = tg.SpikeDetector(window=8, nmad=3.0, confirm=3,
                          min_history=8)
    for _ in range(8):
        sd.observe(1.0)
    for _ in range(2):
        assert sd.observe(50.0) in ("suspect", "confirmed")
    # healthy loss resets the streak; baseline still ~1.0 because the
    # suspect losses never entered the rolling history
    assert sd.observe(1.0) == "ok"
    assert sd.observe(50.0) == "suspect"


def test_guard_confirmed_spike_rolls_back():
    # SpikeDetector's min_history default is 16: 17 healthy losses
    # fill the baseline, then two spikes confirm and roll back.
    script = [(True, 1.0)] * 17 + [(True, 99.0), (True, 99.0)]
    g = _fake_guard(script, skip_budget=0, snapshot_every=4,
                    spike_window=16, spike_nmad=3.0, spike_confirm=2)
    for _ in range(19):
        g.step(None)
    d = g.describe()
    assert d["spikes"] >= 1
    assert d["rollbacks"] == 1


# ----------------------------------------------------------------------
# one real jitted guarded step

def _real_guarded():
    import optax

    from nbdistributed_tpu.parallel import data_parallel
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    m = mesh_mod.make_mesh({"dp": 1}, devices=jax.devices()[:1])

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    params = {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 4)), jnp.float32)}
    opt = optax.adam(1e-2)
    p, _ = data_parallel.ddp_init(
        jax.tree_util.tree_map(jnp.copy, params), None, m)
    s = jax.jit(opt.init)(p)
    step = data_parallel.make_ddp_step(loss_fn, opt, m, guard=True)
    return step, p, s


def test_real_guarded_step_skips_bitwise():
    step, p, s = _real_guarded()
    good = (jnp.ones((4, 8)), jnp.zeros((4, 4)))
    bad = (jnp.full((4, 8), jnp.nan), jnp.zeros((4, 4)))
    p, s, loss, aux = step(p, s, good)
    v = np.asarray(aux["v"])
    assert v.shape == (3,) and v[0] == 1.0          # ok lane
    assert np.isclose(v[1], float(loss))            # loss lane
    before = {k: np.asarray(x).copy()
              for k, x in jax.tree_util.tree_leaves_with_path(
                  {"p": p, "s": s})}
    p2, s2, loss2, aux2 = step(p, s, bad)
    assert np.asarray(aux2["v"])[0] == 0.0          # skip verdict
    after = {k: np.asarray(x)
             for k, x in jax.tree_util.tree_leaves_with_path(
                 {"p": p2, "s": s2})}
    for k in before:
        assert (before[k].reshape(-1).view(np.uint8)
                == after[k].reshape(-1).view(np.uint8)).all(), \
            f"{k} changed"


def test_real_guard_metrics_and_unguarded_api():
    import optax

    from nbdistributed_tpu.observability import metrics as obs_metrics
    from nbdistributed_tpu.parallel import data_parallel
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    step, p, s = _real_guarded()
    g = tg.TrainGuard(step, p, s, rank=0, audit_every=0,
                      snapshot_every=0, skip_budget=0)
    g._lag = 0
    skips = obs_metrics.registry().counter("nbd_guard_skips_total")
    base = skips.value
    g.step((jnp.full((4, 8), jnp.nan), jnp.zeros((4, 4))))
    g.finish()
    assert skips.value == base + 1
    # guard=False keeps the legacy 3-tuple contract
    m = mesh_mod.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step3 = data_parallel.make_ddp_step(
        lambda prm, b: jnp.mean((b[0] @ prm["w"] - b[1]) ** 2),
        optax.sgd(1e-2), m, guard=False)
    out = step3(g.params, jax.jit(optax.sgd(1e-2).init)(g.params),
                (jnp.ones((4, 8)), jnp.zeros((4, 4))))
    assert len(out) == 3


def test_trainguard_rejects_unguarded_step():
    def bare(p, o, b):
        return p, o, jnp.float32(0.0)

    g = tg.TrainGuard(bare, {"w": jnp.zeros(2)}, {"m": jnp.zeros(2)},
                      rank=0, audit_every=0, snapshot_every=0)
    with pytest.raises(TypeError, match="guard=True"):
        g.step(None)


# ----------------------------------------------------------------------
# checkpoint integrity manifest

def test_checkpoint_manifest_verifies_and_refuses(tmp_path):
    import json
    import os
    import zipfile

    from nbdistributed_tpu.runtime import checkpoint

    ns = {"params": {"w": jnp.arange(16.0).reshape(4, 4)}}
    path = str(tmp_path / "ck")
    checkpoint.save(path, ns, ["params"], rank=0, world_size=1)
    assert checkpoint.verify_rank(path, 0) == []

    # flip one payload byte inside arrays.npz: verify must name it and
    # restore must refuse
    d = os.path.join(path, "rank_0")
    zpath = os.path.join(d, "arrays.npz")
    with zipfile.ZipFile(zpath) as z:
        names = z.namelist()
        blobs = {n: bytearray(z.read(n)) for n in names}
    victim = [n for n in names if n.startswith("params")][0]
    blobs[victim][-1] ^= 0xFF
    with zipfile.ZipFile(zpath, "w") as z:
        for n in names:
            z.writestr(n, bytes(blobs[n]))
    problems = checkpoint.verify_rank(path, 0)
    assert problems and any("crc32" in p for p in problems)
    with pytest.raises(ValueError, match="integrity"):
        checkpoint.restore(path, {}, ["params"], rank=0)

    # back-compat: a pre-crc32 manifest is reported unverifiable, not
    # silently clean
    mpath = os.path.join(d, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    for entry in manifest["entries"].values():
        for meta in entry["leaves"]:
            meta.pop("crc32", None)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    problems = checkpoint.verify_rank(path, 0)
    assert problems and any("no crc32" in p for p in problems)
