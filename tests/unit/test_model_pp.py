"""Model-level pipeline parallelism: the pipelined transformer train
step must match the plain train step numerically."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from nbdistributed_tpu.models import (init_params, loss_fn,
                                      make_pp_train_step,
                                      make_train_step,
                                      pp_apply_shardings, pp_loss_fn,
                                      pp_stage_params,
                                      pp_unstage_params, tiny_config)
from nbdistributed_tpu.parallel import mesh as mesh_mod

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    # 4 layers so they chunk into 4 (or 2) pipeline stages.
    cfg = dataclasses.replace(tiny_config(dtype=jnp.float32,
                                          use_flash=False), n_layers=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    return cfg, params, tokens


def test_stage_roundtrip(setup):
    cfg, params, _ = setup
    pp = pp_stage_params(params, 2)
    assert pp["layers_pp"]["wq"].shape[:2] == (2, 2)
    back = pp_unstage_params(pp)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        back, params)
    with pytest.raises(ValueError, match="divisible"):
        pp_stage_params(params, 3)


def test_pp_loss_matches_plain(setup):
    cfg, params, tokens = setup
    batch = {"tokens": tokens}
    ref = float(loss_fn(params, batch, cfg))
    mesh = mesh_mod.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    pp = pp_apply_shardings(pp_stage_params(params, 4), mesh)
    got = float(jax.jit(
        lambda p, b: pp_loss_fn(p, b, cfg, mesh))(pp, batch))
    assert np.isclose(got, ref, atol=1e-5), (got, ref)


def test_pp_train_step_matches_plain(setup):
    cfg, params, tokens = setup
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_p, _, ref_loss = jax.jit(make_train_step(cfg, opt))(
        params, opt.init(params), batch)

    mesh = mesh_mod.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    pp = pp_apply_shardings(pp_stage_params(params, 4), mesh)
    step = jax.jit(make_pp_train_step(cfg, opt, mesh))
    got_pp, _, got_loss = step(pp, opt.init(pp), batch)
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
        pp_unstage_params(got_pp), ref_p)


def test_pp_1f1b_train_step_matches_gpipe(setup):
    """The model-level 1F1B step (embedding + layer stack + tail all
    trained) must produce the same loss and updated params as the
    autodiff-GPipe step — and as the plain, unpipelined step."""
    from nbdistributed_tpu.models import make_pp_1f1b_train_step

    cfg, params, tokens = setup
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_p, _, ref_loss = jax.jit(make_train_step(cfg, opt))(
        params, opt.init(params), batch)

    mesh = mesh_mod.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    pp = pp_apply_shardings(pp_stage_params(params, 4), mesh)
    step = jax.jit(make_pp_1f1b_train_step(cfg, opt, mesh))
    got_pp, _, got_loss = step(pp, opt.init(pp), batch)
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
        pp_unstage_params(got_pp), ref_p)


def test_pp_1f1b_more_microbatches(setup):
    """M > stages (the memory-win regime): still exact vs plain."""
    from nbdistributed_tpu.models import make_pp_1f1b_train_step

    cfg, params, tokens = setup
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_p, _, ref_loss = jax.jit(make_train_step(cfg, opt))(
        params, opt.init(params), batch)
    mesh = mesh_mod.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    pp = pp_apply_shardings(pp_stage_params(params, 2), mesh)
    step = jax.jit(make_pp_1f1b_train_step(cfg, opt, mesh,
                                           n_microbatches=4))
    got_pp, _, got_loss = step(pp, opt.init(pp), batch)
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
        pp_unstage_params(got_pp), ref_p)


def test_pp_1f1b_dp_composition(setup):
    """Model-level DP x PP: batch rows sharded over dp, layer stack
    pipelined over pp, full parameter tree trained — loss and updated
    params match the plain unpipelined step."""
    from nbdistributed_tpu.models import make_pp_1f1b_train_step

    cfg, params, tokens = setup
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_p, _, ref_loss = jax.jit(make_train_step(cfg, opt))(
        params, opt.init(params), batch)

    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 2},
                              devices=jax.devices()[:4])
    pp = pp_apply_shardings(pp_stage_params(params, 2), mesh)
    step = jax.jit(make_pp_1f1b_train_step(cfg, opt, mesh,
                                           batch_axis="dp"))
    got_pp, _, got_loss = step(pp, opt.init(pp), batch)
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
        pp_unstage_params(got_pp), ref_p)


def test_pp_more_microbatches(setup):
    """More microbatches than stages (smaller bubble) stays exact."""
    cfg, params, tokens = setup
    batch = {"tokens": tokens}
    ref = float(loss_fn(params, batch, cfg))
    mesh = mesh_mod.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    pp = pp_apply_shardings(pp_stage_params(params, 2), mesh)
    got = float(jax.jit(lambda p, b: pp_loss_fn(
        p, b, cfg, mesh, n_microbatches=4))(pp, batch))
    assert np.isclose(got, ref, atol=1e-5), (got, ref)


def test_pp_batch_divisibility(setup):
    cfg, params, _ = setup
    mesh = mesh_mod.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    pp = pp_stage_params(params, 4)
    bad = {"tokens": jnp.zeros((3, 16), jnp.int32)}
    with pytest.raises(ValueError, match="microbatches"):
        pp_loss_fn(pp, bad, cfg, mesh)


def test_pp_losses_reject_packed_segments():
    """The pipelined losses do not plumb segment ids; they must fail
    loudly rather than silently leak attention across documents."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax
    import pytest

    from nbdistributed_tpu.models import (init_params,
                                          make_pp_1f1b_train_step,
                                          pp_apply_shardings, pp_loss_fn,
                                          pp_stage_params, tiny_config)
    from nbdistributed_tpu.parallel import mesh as mesh_mod

    cfg = dataclasses.replace(tiny_config(dtype=jnp.float32,
                                          use_flash=False), n_layers=2)
    mesh = mesh_mod.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    p = pp_apply_shardings(
        pp_stage_params(init_params(jax.random.PRNGKey(0), cfg), 2),
        mesh)
    tok = jnp.zeros((2, 16), jnp.int32)
    batch = {"tokens": tok, "segments": jnp.zeros_like(tok)}
    with pytest.raises(ValueError, match="segments"):
        pp_loss_fn(p, batch, cfg, mesh)
    opt = optax.sgd(1e-2)
    step = make_pp_1f1b_train_step(cfg, opt, mesh)
    with pytest.raises(ValueError, match="segments"):
        step(p, opt.init(p), batch)
