"""Ring-overlapped collective matmuls: exact vs the monolithic
collective + matmul, differentiable, and structurally a ring (the
jaxpr carries exactly t-1 ppermutes per decomposed collective)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel.overlap import (allgather_matmul,
                                                matmul_reducescatter,
                                                megatron_sp_block)
from nbdistributed_tpu.utils.compat import shard_map

T = 4


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh({"tp": T}, devices=jax.devices()[:T])


def test_allgather_matmul_exact(mesh):
    S, D, F = 16, 12, 24
    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(ks[0], (S, D), jnp.float32)
    w = jax.random.normal(ks[1], (D, F), jnp.float32)

    got = jax.jit(shard_map(
        lambda xs, ws: allgather_matmul(xs, ws, "tp"),
        mesh=mesh, in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp")))(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               atol=1e-5, rtol=1e-5)


def test_matmul_reducescatter_exact(mesh):
    S, F, D = 16, 24, 12
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    h = jax.random.normal(ks[0], (S, F), jnp.float32)
    w = jax.random.normal(ks[1], (F, D), jnp.float32)

    got = jax.jit(shard_map(
        lambda hs, ws: matmul_reducescatter(hs, ws, "tp"),
        mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))(h, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(h @ w),
                               atol=1e-4, rtol=1e-4)


def test_megatron_sp_block_exact_and_grads(mesh):
    """Full SP->TP->SP MLP: forward exact vs the replicated block, and
    grads of a scalar loss match for every operand."""
    S, D, F = 16, 8, 32
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    x = jax.random.normal(ks[0], (S, D), jnp.float32)
    wu = jax.random.normal(ks[1], (D, F), jnp.float32) / np.sqrt(D)
    wd = jax.random.normal(ks[2], (F, D), jnp.float32) / np.sqrt(F)

    def sharded(x, wu, wd):
        return shard_map(
            lambda a, b, c: megatron_sp_block(a, b, c, "tp"),
            mesh=mesh,
            in_specs=(P("tp", None), P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None))(x, wu, wd)

    ref = jax.nn.gelu(x @ wu) @ wd
    np.testing.assert_allclose(np.asarray(jax.jit(sharded)(x, wu, wd)),
                               np.asarray(ref), atol=1e-4, rtol=1e-4)

    loss_s = lambda *a: jnp.sum(sharded(*a) ** 2)
    loss_r = lambda x, wu, wd: jnp.sum((jax.nn.gelu(x @ wu) @ wd) ** 2)
    gs = jax.jit(jax.grad(loss_s, argnums=(0, 1, 2)))(x, wu, wd)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, wu, wd)
    for a, b, name in zip(gs, gr, ("x", "w_up", "w_down")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3, err_msg=name)


def test_ring_structure(mesh):
    """The decomposition is structural: each collective lowers to
    exactly t-1 ppermutes (not one all_gather / psum_scatter), which is
    what makes the overlap guaranteed dataflow rather than a scheduler
    choice."""
    S, D, F = 8, 4, 8
    x = jnp.ones((S, D))
    w = jnp.ones((D, F))
    jaxpr = str(jax.make_jaxpr(shard_map(
        lambda xs, ws: allgather_matmul(xs, ws, "tp"),
        mesh=mesh, in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp")))(x, w))
    assert jaxpr.count("ppermute") == T - 1, jaxpr
    assert "all_gather" not in jaxpr

    h = jnp.ones((S, F))
    wd = jnp.ones((F, D))
    jaxpr = str(jax.make_jaxpr(shard_map(
        lambda hs, ws: matmul_reducescatter(hs, ws, "tp"),
        mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))(h, wd))
    assert jaxpr.count("ppermute") == T - 1, jaxpr
    assert "psum_scatter" not in jaxpr


def test_reducescatter_rejects_indivisible(mesh):
    with pytest.raises(ValueError, match="not divisible"):
        shard_map(
            lambda hs, ws: matmul_reducescatter(hs, ws, "tp"),
            mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None))(jnp.ones((6, 8)), jnp.ones((8, 4)))
