"""Per-rank data sharding: partition-exactness, determinism across
ranks, static shapes, epoch reshuffling."""

import numpy as np
import pytest

from nbdistributed_tpu.utils.data import (batch_iterator,
                                          interleave_shards, rank_slice,
                                          shard_arrays)


def test_rank_slices_tile_exactly():
    for n in (0, 1, 7, 8, 9, 100):
        for ws in (1, 2, 3, 8):
            covered = []
            for r in range(ws):
                sl = rank_slice(n, r, ws)
                covered.extend(range(n)[sl])
            assert covered == list(range(n)), (n, ws)


def test_rank_slice_rejects_bad_rank():
    with pytest.raises(ValueError):
        rank_slice(10, 3, 2)


def test_shard_arrays():
    batch = {"x": np.arange(10), "y": np.arange(10) * 2}
    parts = [shard_arrays(batch, r, 3) for r in range(3)]
    assert [len(p["x"]) for p in parts] == [4, 3, 3]
    np.testing.assert_array_equal(
        np.concatenate([p["x"] for p in parts]), batch["x"])


def test_batch_iterator_partitions_each_global_batch():
    """Ranks constructed with the same seed must take disjoint,
    jointly-exhaustive rows of each shuffled global batch."""
    n, ws, bs = 64, 4, 4
    data = {"x": np.arange(n), "y": np.arange(n) + 1000}
    streams = [list(batch_iterator(data, batch_size=bs, rank=r,
                                   world_size=ws, seed=7))
               for r in range(ws)]
    n_steps = n // (ws * bs)
    assert all(len(s) == n_steps for s in streams)
    seen = []
    for step in range(n_steps):
        glob = interleave_shards([streams[r][step] for r in range(ws)])
        assert glob["x"].shape == (ws * bs,)
        np.testing.assert_array_equal(glob["y"], glob["x"] + 1000)
        seen.extend(glob["x"].tolist())
    assert sorted(seen) == list(range(n))  # one epoch, every example once


def test_batch_iterator_static_shapes_drop_remainder():
    data = {"x": np.arange(70)}
    batches = list(batch_iterator(data, batch_size=4, rank=0,
                                  world_size=4, seed=0))
    assert all(b["x"].shape == (4,) for b in batches)
    assert len(batches) == 70 // 16


def test_batch_iterator_reshuffles_across_epochs():
    data = {"x": np.arange(32)}
    twice = list(batch_iterator(data, batch_size=4, rank=0,
                                world_size=2, seed=3, epochs=2))
    ep1 = np.concatenate([b["x"] for b in twice[:4]])
    ep2 = np.concatenate([b["x"] for b in twice[4:]])
    assert not np.array_equal(ep1, ep2)  # different permutations


def test_batch_iterator_no_shuffle_is_sequential():
    data = {"x": np.arange(16)}
    got = list(batch_iterator(data, batch_size=2, rank=1, world_size=2,
                              seed=None))
    np.testing.assert_array_equal(got[0]["x"], [2, 3])
    np.testing.assert_array_equal(got[1]["x"], [6, 7])


def test_batch_iterator_rejects_mismatched_leading_axes():
    with pytest.raises(ValueError, match="mismatch"):
        next(batch_iterator({"a": np.zeros(8), "b": np.zeros(7)},
                            batch_size=2, rank=0, world_size=2))


def test_batch_iterator_rejects_tiny_dataset():
    with pytest.raises(ValueError, match="global batch"):
        next(batch_iterator({"a": np.zeros(3)}, batch_size=2, rank=0,
                            world_size=2))


def test_batch_iterator_rejects_bad_rank_eagerly():
    with pytest.raises(ValueError, match="outside world"):
        batch_iterator({"x": np.arange(64)}, batch_size=8, rank=8,
                       world_size=8, epochs=None)


def test_batch_iterator_validation_is_eager():
    """Errors must surface at the construction cell, not at the first
    next() in some later training-loop cell."""
    with pytest.raises(ValueError, match="global batch"):
        batch_iterator({"a": np.zeros(3)}, batch_size=2, rank=0,
                       world_size=2)


def test_no_drop_remainder_equal_batch_counts():
    """drop_remainder=False must yield the SAME number of batches on
    every rank (a rank-dependent count deadlocks DDP collectives); the
    trailing global batch is split near-equally."""
    n, ws, bs = 70, 4, 4
    data = {"x": np.arange(n)}
    streams = [list(batch_iterator(data, batch_size=bs, rank=r,
                                   world_size=ws, seed=1,
                                   drop_remainder=False))
               for r in range(ws)]
    counts = [len(s) for s in streams]
    assert len(set(counts)) == 1, counts
    # every example appears exactly once across ranks and steps
    seen = sorted(int(x) for s in streams for b in s
                  for x in b["x"])
    assert seen == list(range(n))


def test_no_drop_remainder_tiny_tail_dropped_everywhere():
    """A tail smaller than world_size cannot be split to all ranks —
    it is dropped on EVERY rank (again: equal counts)."""
    n, ws, bs = 18, 4, 4  # tail of 2 < 4 ranks
    streams = [list(batch_iterator({"x": np.arange(n)}, batch_size=bs,
                                   rank=r, world_size=ws, seed=1,
                                   drop_remainder=False))
               for r in range(ws)]
    assert [len(s) for s in streams] == [1] * ws


def test_shard_arrays_rejects_misaligned():
    with pytest.raises(ValueError, match="mismatch"):
        shard_arrays({"x": np.arange(10), "y": np.arange(8)}, 0, 2)


def test_pack_tokens_basic():
    from nbdistributed_tpu.utils.data import pack_tokens
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    out = pack_tokens(docs, 4, eos_id=0)
    # stream: 1 2 3 0 4 5 0 6 7 8 9 0 -> windows of 4
    assert out.shape == (3, 4)
    assert out.tolist() == [[1, 2, 3, 0], [4, 5, 0, 6], [7, 8, 9, 0]]


def test_pack_tokens_padding_and_validation():
    import numpy as np
    import pytest
    from nbdistributed_tpu.utils.data import pack_tokens
    out = pack_tokens([[1, 2, 3, 4, 5]], 4, eos_id=9,
                      drop_remainder=False)
    assert out.tolist() == [[1, 2, 3, 4], [5, 9, 9, 9]]
    out = pack_tokens([[1, 2, 3, 4, 5]], 4)     # tail dropped
    assert out.tolist() == [[1, 2, 3, 4]]
    with pytest.raises(ValueError, match="seq_len"):
        pack_tokens([[1]], 1)
    with pytest.raises(ValueError, match="eos_id"):
        pack_tokens([[1, 2, 3]], 2, drop_remainder=False)
    assert pack_tokens([], 4).shape == (0, 4)


def test_prefetch_to_device_order_and_placement():
    """Prefetch preserves order and places leaves per the sharding;
    works for short iterators, empty iterators, and size=1."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.utils.data import prefetch_to_device

    batches = [{"x": np.full((4, 3), i, np.float32)} for i in range(5)]
    got = list(prefetch_to_device(iter(batches), size=2))
    assert len(got) == 5
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["x"]),
                                      batches[i]["x"])

    mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P("dp"))
    got = list(prefetch_to_device(iter(batches), size=3, sharding=sh))
    assert all(b["x"].sharding == sh for b in got)
    # Sharded batches feed a jitted mean without resharding.
    assert float(jax.jit(lambda b: jnp.mean(b["x"]))(got[2])) == 2.0

    assert list(prefetch_to_device(iter([]), size=2)) == []
    assert len(list(prefetch_to_device(iter(batches), size=1))) == 5
    with pytest.raises(ValueError, match="size"):
        list(prefetch_to_device(iter(batches), size=0))
