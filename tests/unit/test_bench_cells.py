"""The bench worker cells must at least EXECUTE — a syntax error or
API drift in a TPU-only cell would otherwise surface only during a
live tunnel window (which may be hours away).  Each cell is exec'd
here at toy scale via config/size substitution; numbers are not
asserted, only successful execution and JSON-parseable output."""

import json

import pytest

import bench

# Heavy (exec real model cells at toy scale): excluded from the fast
# product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def run_cell(src: str) -> dict:
    """exec a bench cell and parse its trailing json.dumps expression
    the way the worker REPL would (evaluate the last expression)."""
    import ast

    tree = ast.parse(src)
    last = tree.body.pop()
    assert isinstance(last, ast.Expr), "bench cells end in json.dumps"
    ns: dict = {}
    exec(compile(tree, "<cell>", "exec"), ns)
    out = eval(compile(ast.Expression(last.value), "<cell>", "eval"), ns)
    return json.loads(out)


def test_mfu_cell_executes():
    cell = bench.MFU_CELL.format(peak=1e30, shape="(1, 64, 2)",
                                 reps="(2, 2)", tr_start="2 * _B",
                                 extra_cfg=", max_seq_len=128",
                                 cfg_name="tiny_config")
    res = run_cell(cell)
    assert res["fwd_tokens_per_s"] > 0 and res["train_tokens_per_s"] > 0


def test_spec_cell_executes_batched():
    cell = bench.SPEC_CELL.replace("smol_135m_config", "tiny_config")
    cell = cell.replace("_N1, _N2, _G, _B = 16, 64, 4, 4",
                        "_N1, _N2, _G, _B = 4, 8, 2, 2")
    cell = cell.replace("use_flash=True", "use_flash=False")
    res = run_cell(cell)
    # tok_per_s rows are None when measurement noise wins (tiny CPU
    # deltas); execution + sample bookkeeping is what's asserted.
    for name in ("plain", "spec_selfdraft", "plain_b4",
                 "spec_selfdraft_b4", "spec_int4draft_b4"):
        assert res[name + "_tok_per_s"] is None \
            or res[name + "_tok_per_s"] > 0
        lo, hi = res[name + "_lo_hi_s"]
        assert lo > 0 and hi > 0
    assert res["batch"] == 2
    assert 0 <= res["mean_accepted"] <= 2
    assert 0 <= res["int4draft_mean_accepted"] <= 2


def test_decode7b_cell_executes_at_toy_scale():
    cell = bench.DECODE7B_CELL.replace("llama2_7b_config", "tiny_config")
    cell = cell.replace("_N1, _N2, _CL = 8, 32, 2048",
                        "_N1, _N2, _CL = 2, 4, 64")
    cell = cell.replace("use_flash=True", "use_flash=False")
    res = run_cell(cell)
    for name in ("int8", "int4"):
        v = res[name + "_tok_per_s"]
        assert v is None or v > 0
        lo, hi = res[name + "_lo_hi_s"]
        assert lo > 0 and hi > 0
        assert res[name + "_weight_gb"] >= 0  # rounds to 0 at toy scale
        r = res[name + "_roofline_pct_v5e"]
        assert r is None or r >= 0
    # The int4 tree must stream fewer bytes than the int8 one — compare
    # the unrounded weight trees (the _gb keys round to 0.0 at toy
    # scale, which would make the assertion vacuous).
    import jax
    import jax.numpy as jnp

    from nbdistributed_tpu.models import (init_params, quantize_params,
                                          quantize_params4, tiny_config)

    def nbytes(t):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(t))

    p = init_params(jax.random.PRNGKey(0),
                    tiny_config(dtype=jnp.float32, use_flash=False))
    assert nbytes(quantize_params4(p)) < nbytes(quantize_params(p))


def test_decode_cell_executes():
    cell = bench.DECODE_CELL.replace("smol_135m_config", "tiny_config")
    cell = cell.replace("_N1, _N2, _ML = 32, 256, 512",
                        "_N1, _N2, _ML = 2, 6, 64")
    cell = cell.replace("use_flash=True", "use_flash=False")
    res = run_cell(cell)
    for k in ("bf16", "int8", "int8_kv8"):
        # tok_per_s is None when noise wins the tiny CPU delta; the
        # sample bookkeeping must always be present and positive.
        assert res[k + "_tok_per_s"] is None or res[k + "_tok_per_s"] > 0
        lo, hi = res[k + "_lo_hi_s"]
        assert lo > 0 and hi > 0
        assert res[k + "_bytes_per_tok_mb"] > 0
    # int8 weights + int8 KV must stream fewer bytes than bf16, and
    # nibble-packed int4 fewer again (the packed uint8 array is
    # exactly half the int8 weight bytes plus group scales).
    assert (res["int8_kv8_bytes_per_tok_mb"]
            < res["bf16_bytes_per_tok_mb"])
    assert (res["int4_kv8_bytes_per_tok_mb"]
            < res["int8_kv8_bytes_per_tok_mb"])


def test_serve_cell_executes():
    cell = bench.SERVE_CELL.replace("smol_135m_config", "tiny_config")
    cell = cell.replace("_N, _B, _L = 48, 4, 16",
                        "_N, _B, _L = 6, 2, 4")
    cell = cell.replace("_PL, _SL = 128, 8", "_PL, _SL = 12, 4")
    cell = cell.replace("use_flash=True", "use_flash=False")
    res = run_cell(cell)
    assert res["server_tok_per_s"] > 0
    assert res["sequential_tok_per_s"] > 0
    assert res["batch"] == 2 and res["new_tokens"] == 6
    assert res["admit_ms_plain"] > 0
    assert res["admit_ms_prefix_cached"] > 0


def test_run_families_bails_after_consecutive_spawn_failures():
    """Two consecutive SPAWN_FAILED results (tunnel gone) must stop
    the family sweep instead of paying the attach timeout per
    remaining family."""
    calls = []

    def fake_measure(backend, name, cell, timeout):
        calls.append(name)
        return bench.SPAWN_FAILED

    extra: dict = {}
    fams = [(n, "cell", 1) for n in ("a", "b", "c", "d")]
    bench.run_families("tpu", fams, extra, measure=fake_measure)
    assert calls == ["a", "b"]
    assert extra == {}


def test_run_families_single_spawn_failure_continues():
    """A lone spawn failure (transient flap) must not end the sweep,
    and a later success resets the failure counter."""
    results = {"a": bench.SPAWN_FAILED, "b": {"x": 1},
               "c": bench.SPAWN_FAILED, "d": {"y": 2}}
    calls = []

    def fake_measure(backend, name, cell, timeout):
        calls.append(name)
        return results[name]

    extra: dict = {}
    fams = [(n, "cell", 1) for n in ("a", "b", "c", "d")]
    bench.run_families("tpu", fams, extra, measure=fake_measure)
    assert calls == ["a", "b", "c", "d"]
    assert extra == {"b": {"x": 1}, "d": {"y": 2}}


def test_run_families_on_family_fires_per_success():
    """The incremental-persist hook fires after every successful
    family (not for failures), and a hook crash never kills the
    sweep."""
    results = {"a": {"x": 1}, "b": None, "c": {"y": 2}}
    seen = []

    def fake_measure(backend, name, cell, timeout):
        return results[name]

    def hook(name):
        seen.append(name)
        if name == "a":
            raise RuntimeError("persist hiccup")   # must be survived

    extra: dict = {}
    fams = [(n, "cell", 1) for n in ("a", "b", "c")]
    bench.run_families("tpu", fams, extra, measure=fake_measure,
                       on_family=hook)
    assert seen == ["a", "c"]
    assert extra == {"a": {"x": 1}, "c": {"y": 2}}


def test_run_families_budget_skips_remaining(monkeypatch):
    """Once the family-stage budget is exhausted, remaining families
    are skipped loudly instead of risking the driver's outer deadline
    (the one JSON line must always print)."""
    import time

    monkeypatch.setenv("NBD_BENCH_FAMILY_BUDGET_S", "0.05")
    calls = []

    def slow_measure(backend, name, cell, timeout):
        calls.append(name)
        time.sleep(0.06)
        return {"v": 1}

    extra: dict = {}
    fams = [(n, "cell", 1) for n in ("a", "b", "c")]
    bench.run_families("tpu", fams, extra, measure=slow_measure)
    assert calls == ["a"]          # budget spent during 'a'
    assert extra == {"a": {"v": 1}}


def test_run_families_cell_failure_is_not_spawn_failure():
    """None (cell failed, world healthy) never trips the bail-out."""
    calls = []

    def fake_measure(backend, name, cell, timeout):
        calls.append(name)
        return None

    extra: dict = {}
    fams = [(n, "cell", 1) for n in ("a", "b", "c")]
    bench.run_families("tpu", fams, extra, measure=fake_measure)
    assert calls == ["a", "b", "c"]
    assert extra == {}


def test_chained_delta_ms_measures_positive_time():
    """The shared chained-scan protocol (ops/timing.py — used by the
    bench flash cell, tune_flash, and the preflight probe) must
    produce a positive per-call time with honest host timing."""
    import jax.numpy as jnp

    from nbdistributed_tpu.ops.timing import chained_delta_ms

    x = jnp.full((256, 256), 0.5, jnp.float32)
    ms, samples = chained_delta_ms(lambda c: (c @ c) * 1e-3, x,
                                   n1=2, n2=10, reps=3)
    assert len(samples["lo_s"]) == 3 and len(samples["hi_s"]) == 3
    assert all(t > 0 for t in samples["lo_s"] + samples["hi_s"])
    assert ms > 0


def test_persist_tpu_snapshot_carries_unmeasured_families(tmp_path):
    """A partial window's snapshot must carry forward families the
    tunnel died before re-measuring, with their original timestamps —
    never erase a fuller earlier capture."""
    path = str(tmp_path / "BENCH_TPU_LAST.json")
    bench.persist_tpu_snapshot(
        path, {"metric": "m", "extra": {}},
        {"flash_attn": {"speedup": 1.5}, "decode": {"tok": 100}})
    first = json.load(open(path))
    assert first["carried_from_previous"] == []
    ts_flash = first["family_measured_at"]["flash_attn"]

    # Second (partial) run re-measures only decode.
    bench.persist_tpu_snapshot(
        path, {"metric": "m", "extra": {}}, {"decode": {"tok": 120}})
    snap = json.load(open(path))
    assert snap["result"]["extra"]["decode"] == {"tok": 120}
    assert snap["result"]["extra"]["flash_attn"] == {"speedup": 1.5}
    assert snap["carried_from_previous"] == ["flash_attn"]
    assert snap["family_measured_at"]["flash_attn"] == ts_flash


def test_persist_tpu_snapshot_stamp_is_per_family(tmp_path,
                                                  monkeypatch):
    """The incremental persist stamps ONLY the family that just
    finished: families measured hours earlier keep their real
    measurement times across later persists of the same run."""
    path = str(tmp_path / "BENCH_TPU_LAST.json")
    times = iter(["T1", "T2", "T3"])
    monkeypatch.setattr(bench.time, "strftime",
                        lambda *_a, **_k: next(times))
    extra = {"smol135m": {"mfu": 0.4}}
    result = {"metric": "m", "extra": extra}
    bench.persist_tpu_snapshot(path, result, extra,
                               stamp=["smol135m"])       # at T1
    extra["tinyllama_1b"] = {"mfu": 0.38}
    bench.persist_tpu_snapshot(path, result, extra,
                               stamp=["tinyllama_1b"])   # at T2
    extra["allreduce"] = {"rows": []}
    bench.persist_tpu_snapshot(path, result, extra,
                               stamp=[])                 # final, T3
    snap = json.load(open(path))
    assert snap["family_measured_at"]["smol135m"] == "T1"
    assert snap["family_measured_at"]["tinyllama_1b"] == "T2"
    assert snap["family_measured_at"]["allreduce"] == "T3"


def test_moe_dispatch_cell_executes():
    cell = bench.MOE_CELL.replace(
        "_DM, _DF, _NL, _B, _S, _steps = 1024, 2048, 8, 8, 1024, 3",
        "_DM, _DF, _NL, _B, _S, _steps = 64, 128, 2, 2, 32, 1")
    cell = cell.replace("use_flash=True", "use_flash=False")
    cell = cell.replace("n_heads=16, n_kv_heads=4", "n_heads=4, n_kv_heads=2")
    res = run_cell(cell)
    # Rows are None when measurement noise wins the tiny CPU delta
    # ("noise won: say so" — same contract as the decode cells).
    for mode in ("dense", "sparse", "dropless"):
        v = res["small_" + mode + "_tok_per_s"]
        assert v is None or v > 0
    for mode in ("sparse", "dropless"):
        v = res["big_" + mode + "_tok_per_s"]
        assert v is None or v > 0
    assert res["big_tokens"] == 64
