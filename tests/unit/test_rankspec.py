"""Rank-spec grammar tests (reference grammar: magic.py:1679-1715)."""

import pytest

from nbdistributed_tpu.magics.rankspec import RankSpecError, parse_ranks


def test_simple_list():
    assert parse_ranks("[0,1]", 4) == [0, 1]


def test_range():
    assert parse_ranks("[0-2]", 4) == [0, 1, 2]


def test_mixed_and_spaces():
    assert parse_ranks("[0, 2-3, 1]", 8) == [0, 1, 2, 3]


def test_duplicates_collapse():
    assert parse_ranks("[1,1,1-2]", 4) == [1, 2]


def test_single():
    assert parse_ranks("[3]", 4) == [3]


def test_out_of_range_is_error_not_silent():
    # The reference silently filtered these (magic.py:1697-1715); we
    # surface the typo instead.
    with pytest.raises(RankSpecError, match=r"\[5\]"):
        parse_ranks("[1,5]", 4)


def test_descending_range_rejected():
    with pytest.raises(RankSpecError):
        parse_ranks("[3-1]", 8)


@pytest.mark.parametrize("bad", ["", "0,1", "[", "[]", "[a]", "[1;2]",
                                 "[-1]", "[1.5]"])
def test_malformed_specs_rejected(bad):
    with pytest.raises(RankSpecError):
        parse_ranks(bad, 8)
