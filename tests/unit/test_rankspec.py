"""Rank-spec grammar tests (reference grammar: magic.py:1679-1715).

Carries the ``lint`` marker: the static analyzer's subset-collective
rule (analysis/cellcheck.py) trusts this parser for its "does the
rankspec cover the world?" decision, so its edge cases are part of
the static-analysis CI job."""

import pytest

from nbdistributed_tpu.magics.rankspec import RankSpecError, parse_ranks

pytestmark = [pytest.mark.unit, pytest.mark.lint]


def test_simple_list():
    assert parse_ranks("[0,1]", 4) == [0, 1]


def test_range():
    assert parse_ranks("[0-2]", 4) == [0, 1, 2]


def test_mixed_and_spaces():
    assert parse_ranks("[0, 2-3, 1]", 8) == [0, 1, 2, 3]


def test_duplicates_collapse():
    assert parse_ranks("[1,1,1-2]", 4) == [1, 2]


def test_single():
    assert parse_ranks("[3]", 4) == [3]


def test_out_of_range_is_error_not_silent():
    # The reference silently filtered these (magic.py:1697-1715); we
    # surface the typo instead.
    with pytest.raises(RankSpecError, match=r"\[5\]"):
        parse_ranks("[1,5]", 4)


def test_descending_range_rejected():
    with pytest.raises(RankSpecError):
        parse_ranks("[3-1]", 8)


@pytest.mark.parametrize("bad", ["", "0,1", "[", "[]", "[a]", "[1;2]",
                                 "[-1]", "[1.5]"])
def test_malformed_specs_rejected(bad):
    with pytest.raises(RankSpecError):
        parse_ranks(bad, 8)


# -- edge cases the subset-collective lint rule leans on ---------------


@pytest.mark.parametrize("bad", ["[ ]", "[\t]", "[0,]", "[,1]",
                                 "[0,,1]", "[0 1]", "[1-]", "[-2]"])
def test_empty_and_ragged_specs_rejected(bad):
    with pytest.raises(RankSpecError):
        parse_ranks(bad, 8)


def test_overlapping_ranges_collapse_to_unique_sorted():
    assert parse_ranks("[0-2, 1-3]", 8) == [0, 1, 2, 3]
    assert parse_ranks("[2, 0-2, 2-2]", 8) == [0, 1, 2]


def test_degenerate_single_element_range():
    assert parse_ranks("[1-1]", 4) == [1]


def test_exact_world_coverage_is_not_a_subset():
    # The analyzer arms the subset-collective rule only when the
    # parsed set is a STRICT subset — full coverage must parse to
    # exactly the world.
    assert parse_ranks("[0-3]", 4) == [0, 1, 2, 3]


def test_range_straddling_world_bound_names_the_bad_ranks():
    with pytest.raises(RankSpecError, match=r"\[4, 5\]"):
        parse_ranks("[2-5]", 4)


def test_boundary_rank_equal_to_world_size_rejected():
    with pytest.raises(RankSpecError):
        parse_ranks("[4]", 4)
    assert parse_ranks("[3]", 4) == [3]


def test_leading_zeros_parse_as_ints():
    assert parse_ranks("[00, 01]", 4) == [0, 1]


def test_internal_whitespace_in_ranges():
    assert parse_ranks("[ 0 - 2 ]", 8) == [0, 1, 2]
