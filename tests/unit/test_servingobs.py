"""Unit tests for the serving observatory + perf-regression sentinel
(ISSUE 18): the telescoping stage decomposition (sum == e2e and TTFT
== admit+queue+kv_alloc+prefill EXACTLY, by construction), the
clock-corrected TPOT clamp, the KV fragmentation scan, the utilization
ring/gauges, the {tenant,rank} series-retirement pin, the autoscaler
audit record shape, and perfbase's band scoring."""

import math

import pytest

from nbdistributed_tpu.observability import metrics as obs_metrics
from nbdistributed_tpu.observability import perfbase
from nbdistributed_tpu.observability.servingobs import (
    SERVE_STAGES, ServingObservatory, format_serve_stage_table,
    format_serve_waterfall, largest_free_run)

pytestmark = [pytest.mark.unit, pytest.mark.obs, pytest.mark.serve]


class FakeClock:
    """Deterministic ``now()`` the tests advance by hand."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeOffsets:
    """Stand-in for ``ClockEstimator``: fixed per-rank offsets."""

    def __init__(self, offsets):
        self._off = offsets

    def offset(self, rank):
        return self._off.get(rank, 0.0)


def _drive_one(obs, clk, rid="r-1", tenant="tn", rank=0):
    """One full lifecycle with known stage widths; returns the
    completion record."""
    obs.begin(rid, tenant, t_submit=clk.t)
    clk.advance(0.010)                       # admit
    obs.note_admit(rid, t=clk.t)
    clk.advance(0.050)                       # queue
    obs.note_placed(rid, rank, kv_alloc_s=0.004, need_blocks=3,
                    pf_total=2, t=clk.t)
    clk.advance(0.030)                       # kv_alloc+prefill tail
    obs.note_emission(rid, rank, 1, t_recv=clk.t, emit_s=0.001)
    obs.note_decode(rid, 0.008)
    clk.advance(0.020)
    obs.note_emission(rid, rank, 2, t_recv=clk.t, emit_s=0.001)
    obs.note_decode(rid, 0.008)
    clk.advance(0.005)                       # deliver
    return obs.complete(rid, "completed", t_finish=clk.t)


def test_stage_sum_is_exactly_e2e():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    rec = _drive_one(obs, clk)
    assert rec is not None and rec["status"] == "completed"
    total = sum(rec["stages"][s] for s in SERVE_STAGES)
    # Telescoping gateway anchors: exact up to the record rounding
    # (6 decimal places), not a tolerance band.
    assert math.isclose(total, rec["e2e_s"], abs_tol=1e-5), \
        (total, rec["e2e_s"], rec["stages"])
    assert all(rec["stages"][s] >= 0.0 for s in SERVE_STAGES)


def test_ttft_identity_and_kv_alloc_cap():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    rec = _drive_one(obs, clk)
    st = rec["stages"]
    assert math.isclose(
        rec["ttft_s"],
        st["admit"] + st["queue"] + st["kv_alloc"] + st["prefill"],
        abs_tol=1e-9)
    # The TTFT tail [placed, first_tok] was 30ms: measured alloc 4ms
    # fits, prefill is the remainder.
    assert math.isclose(st["admit"], 0.010, abs_tol=1e-6)
    assert math.isclose(st["queue"], 0.050, abs_tol=1e-6)
    assert math.isclose(st["kv_alloc"], 0.004, abs_tol=1e-6)
    assert math.isclose(st["prefill"], 0.026, abs_tol=1e-6)
    # An alloc measurement LARGER than the tail is capped, never
    # negative-prefill.
    obs2 = ServingObservatory(now=clk)
    obs2.begin("r-2", "tn", t_submit=clk.t)
    obs2.note_admit("r-2", t=clk.t)
    obs2.note_placed("r-2", 0, kv_alloc_s=5.0, t=clk.t)
    clk.advance(0.010)
    obs2.note_emission("r-2", 0, 1, t_recv=clk.t)
    rec2 = obs2.complete("r-2", "completed", t_finish=clk.t)
    assert math.isclose(rec2["stages"]["kv_alloc"], 0.010,
                        abs_tol=1e-6)
    assert rec2["stages"]["prefill"] == 0.0


def test_decode_emit_split_capped_to_span():
    """Worker durations only SPLIT the [first, last] span: inflated
    decode/emit attributions cap out and decode_wait stays >= 0."""
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    obs.begin("r-3", "tn", t_submit=clk.t)
    obs.note_admit("r-3", t=clk.t)
    obs.note_placed("r-3", 1, t=clk.t)
    obs.note_emission("r-3", 1, 1, t_recv=clk.t)
    clk.advance(0.020)                       # span = 20ms
    obs.note_emission("r-3", 1, 1, t_recv=clk.t, emit_s=9.0)
    obs.note_decode("r-3", 9.0)              # wildly over-attributed
    rec = obs.complete("r-3", "completed", t_finish=clk.t)
    st = rec["stages"]
    assert math.isclose(st["decode"], 0.020, abs_tol=1e-6)
    assert st["emit"] == 0.0 and st["decode_wait"] == 0.0
    total = sum(st[s] for s in SERVE_STAGES)
    assert math.isclose(total, rec["e2e_s"], abs_tol=1e-5)


def test_tpot_prefers_corrected_worker_stamps():
    """Worker stamps skewed +5s are corrected by the per-rank offset
    before the inter-token mean — gateway arrival jitter never enters
    when stamps are present."""
    clk = FakeClock()
    obs = ServingObservatory(clock=FakeOffsets({1: 5.0}), now=clk)
    obs.begin("r-4", "tn", t_submit=clk.t)
    obs.note_admit("r-4", t=clk.t)
    obs.note_placed("r-4", 1, t=clk.t)
    t0 = clk.t
    obs.note_emission("r-4", 1, 1, t_recv=clk.t, t_worker=t0 + 5.0)
    clk.advance(0.500)                       # noisy gateway arrival
    obs.note_emission("r-4", 1, 3, t_recv=clk.t,
                      t_worker=t0 + 5.0 + 0.120)
    rec = obs.complete("r-4", "completed", t_finish=clk.t)
    # 120ms worker span over 3 inter-token gaps = 40ms, NOT the
    # 500/3 ms the gateway clock would give.
    assert math.isclose(rec["tpot_s"], 0.040, abs_tol=1e-6)


def test_tpot_clamped_nonnegative_on_offset_error():
    clk = FakeClock()
    obs = ServingObservatory(clock=FakeOffsets({1: 10.0}), now=clk)
    obs.begin("r-5", "tn", t_submit=clk.t)
    obs.note_placed("r-5", 1, t=clk.t)
    t0 = clk.t
    # A bad offset estimate makes corrected stamps run BACKWARD.
    obs.note_emission("r-5", 1, 1, t_recv=clk.t, t_worker=t0 + 10.0)
    clk.advance(0.050)
    obs.note_emission("r-5", 1, 2, t_recv=clk.t, t_worker=t0 + 9.5)
    rec = obs.complete("r-5", "completed", t_finish=clk.t)
    assert rec["tpot_s"] == 0.0


def test_tpot_gateway_fallback_without_stamps():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    obs.begin("r-6", "tn", t_submit=clk.t)
    obs.note_placed("r-6", 0, t=clk.t)
    obs.note_emission("r-6", 0, 1, t_recv=clk.t)
    clk.advance(0.100)
    obs.note_emission("r-6", 0, 2, t_recv=clk.t)
    rec = obs.complete("r-6", "completed", t_finish=clk.t)
    assert math.isclose(rec["tpot_s"], 0.050, abs_tol=1e-6)


def test_drop_and_unknown_rids_are_safe():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    obs.begin("r-7", "tn")
    obs.drop("r-7")
    assert obs.dropped == 1
    assert obs.complete("r-7", "completed") is None
    # note_* on never-begun rids must not create ghosts.
    obs.note_admit("ghost")
    obs.note_emission("ghost", 0, 1)
    obs.note_decode("ghost", 0.1)
    assert obs.records() == [] and obs.completed == 0


def test_summary_and_renderers():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    for i in range(4):
        _drive_one(obs, clk, rid=f"r-{i}")
    s = obs.summary()
    assert s["count"] == 4
    assert set(s["stages"]) == set(SERVE_STAGES)
    # Stage shares are fractions of mean e2e and roughly total 1.
    assert 0.95 < sum(v["share"] for v in s["stages"].values()) < 1.05
    table = format_serve_stage_table(s)
    assert "decode" in table and "ttft" in table
    wf = format_serve_waterfall(obs.records(2))
    assert "tok" in wf and "r-3" in wf
    blk = obs.status_block(records=2)
    assert blk["enabled"] and len(blk["records"]) == 2


# ---------------------------------------------------------------------
# fragmentation scan + utilization telemetry


def test_largest_free_run():
    assert largest_free_run([]) == 0
    assert largest_free_run([7]) == 1
    assert largest_free_run([3, 1, 2, 9]) == 3
    assert largest_free_run([5, 5, 6]) == 2          # dupes collapse
    assert largest_free_run(range(10)) == 10


def test_util_ring_summary_and_gauges():
    clk = FakeClock()
    obs = ServingObservatory(now=clk)
    for placed in (1, 2):
        obs.note_util(
            ranks={0: {"placed": placed, "slots": 2, "kv_used": 4,
                       "kv_free": 12, "frag": 7, "pending": 1}},
            prefill_toks=8, decode_toks=2, backlog=3,
            tenant="util-tn", t=clk.advance(0.1))
    u = obs.util_summary()
    assert u["count"] == 2
    assert math.isclose(u["fill_mean"], 0.75, abs_tol=1e-9)
    assert u["fill_max"] == 1.0
    assert math.isclose(u["prefill_share"], 16 / 20, abs_tol=1e-9)
    assert u["ranks"]["0"]["frag"] == 7
    j = obs_metrics.registry().to_json()["gauges"]
    assert j['nbd_serve_batch_fill_ratio{tenant="util-tn"}'] == 1.0
    assert j['nbd_kv_frag_largest_run{rank="0",tenant="util-tn"}'] \
        == 7.0
    assert j['nbd_serve_defer_depth{rank="0",tenant="util-tn"}'] == 1.0
    obs_metrics.registry().remove_label_series("tenant", "util-tn")


def test_tenant_eviction_retires_rank_labeled_series():
    """Satellite 1 pin: the per-rank KV gauges carry {tenant, rank}
    labels, so tenant eviction's ``remove_label_series('tenant', ...)``
    retires EVERY rank's series for that tenant — nothing accumulates
    for the daemon's lifetime."""
    reg = obs_metrics.registry()
    for rank in ("0", "1", "all"):
        reg.gauge("nbd_kv_blocks_used", "t",
                  {"tenant": "evict-me", "rank": rank}).set(3)
        reg.gauge("nbd_kv_blocks_free", "t",
                  {"tenant": "evict-me", "rank": rank}).set(5)
    reg.histogram("nbd_serve_stage_seconds", "t",
                  {"stage": "decode", "tenant": "evict-me"}).observe(.1)
    assert reg.remove_label_series("tenant", "evict-me") == 7
    text = reg.prometheus_text()
    assert "evict-me" not in text


# ---------------------------------------------------------------------
# perfbase: the regression-scoring contract


REPORT = {
    "offered": 20, "completed": 18, "shed_rate": 0.1,
    "tokens_per_s": 10.0,
    "client": {"ttft_ms": {"p50": 100.0, "p99": 300.0},
               "tpot_ms": {"p50": 20.0, "p99": 50.0},
               "e2e_ms": {"p50": 400.0, "p99": 900.0}},
}
STAGES = {"stages": {"decode": {"p95": 30.0}, "queue": {"p95": 80.0}}}


def _baseline():
    return perfbase.make_baseline(
        perfbase.extract_metrics(REPORT, STAGES), source="test")


def test_extract_and_seed_roundtrip(tmp_path):
    m = perfbase.extract_metrics(REPORT, STAGES)
    assert m["tokens_per_s"] == 10.0
    assert m["stage_queue_ms_p95"] == 80.0
    doc = {"baselines": {"serving_smoke": _baseline()}}
    path = str(tmp_path / "b.json")
    perfbase.save_baselines(path, doc)
    back = perfbase.load_baselines(path)
    assert back["schema"] == perfbase.BASELINE_SCHEMA_VERSION
    entry = back["baselines"]["serving_smoke"]
    assert entry["metrics"]["tokens_per_s"]["direction"] == "higher"


def test_score_clean_run_passes():
    res = perfbase.score(_baseline(),
                         perfbase.extract_metrics(REPORT, STAGES))
    assert res["pass"] and res["regressions"] == []


def test_score_catches_the_acceptance_regressions():
    """The ISSUE 18 pins: tokens/s -30% and p99 TTFT +3x must trip."""
    import copy
    bad = copy.deepcopy(REPORT)
    bad["tokens_per_s"] = 7.0                      # -30%
    bad["client"]["ttft_ms"]["p99"] = 900.0        # 3x
    res = perfbase.score(_baseline(),
                         perfbase.extract_metrics(bad, STAGES))
    assert not res["pass"]
    assert set(res["regressions"]) == {"tokens_per_s", "ttft_ms_p99"}
    assert res["metrics"]["tokens_per_s"]["verdict"] == "regressed"
    # Improvements in the good direction never fail.
    good = copy.deepcopy(REPORT)
    good["tokens_per_s"] = 30.0
    good["client"]["ttft_ms"]["p99"] = 10.0
    res = perfbase.score(_baseline(),
                         perfbase.extract_metrics(good, STAGES))
    assert res["pass"]
    assert res["metrics"]["tokens_per_s"]["verdict"] == "improved"


def test_score_missing_metric_fails():
    m = perfbase.extract_metrics(REPORT, STAGES)
    del m["tokens_per_s"]
    res = perfbase.score(_baseline(), m)
    assert not res["pass"]
    assert res["metrics"]["tokens_per_s"]["verdict"] == "missing"


def test_band_scale_widens_uniformly():
    import copy
    bad = copy.deepcopy(REPORT)
    bad["tokens_per_s"] = 7.0                      # -30%, band 25%
    m = perfbase.extract_metrics(bad, STAGES)
    assert not perfbase.score(_baseline(), m)["pass"]
    assert perfbase.score(_baseline(), m, band_scale=2.0)["pass"]


def test_shed_rate_band_is_absolute():
    import copy
    bad = copy.deepcopy(REPORT)
    bad["shed_rate"] = 0.35                        # +0.25 absolute
    res = perfbase.score(_baseline(),
                         perfbase.extract_metrics(bad, STAGES))
    assert "shed_rate" in res["regressions"]
    ok = copy.deepcopy(REPORT)
    ok["shed_rate"] = 0.15                         # +0.05 < 0.10 band
    assert perfbase.score(
        _baseline(), perfbase.extract_metrics(ok, STAGES))["pass"]


def test_format_diff_names_regressions():
    import copy
    bad = copy.deepcopy(REPORT)
    bad["tokens_per_s"] = 1.0
    res = perfbase.score(_baseline(),
                         perfbase.extract_metrics(bad, STAGES))
    txt = perfbase.format_diff(res)
    assert "REGRESSION" in txt and "tokens_per_s" in txt
    assert "PASS" in perfbase.format_diff(
        perfbase.score(_baseline(),
                       perfbase.extract_metrics(REPORT, STAGES)))
