"""Collectives on the in-process 8-device virtual CPU mesh (conftest
forces --xla_force_host_platform_device_count=8; the multi-process path
is covered by the integration tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.parallel import collectives


@pytest.fixture(autouse=True)
def fresh_mesh():
    collectives.clear_mesh_cache()
    yield
    collectives.clear_mesh_cache()


def test_world_is_eight_devices():
    assert jax.device_count() == 8
    assert collectives.device_world() == 8


def test_all_reduce_sum_rank_semantics():
    """One process = identity result, but the XLA collective path must
    actually run (8 local devices -> mesh path, then de-duplication)."""
    out = collectives.all_reduce(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), np.ones((4,)))
    assert collectives._reduce_fn.cache_info().currsize >= 1


def test_all_reduce_integer_sum_exact():
    out = collectives.all_reduce(jnp.arange(4, dtype=jnp.int32))
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))


def test_all_reduce_ops():
    x = jnp.arange(4.0)
    np.testing.assert_allclose(
        np.asarray(collectives.all_reduce(x, "mean")), np.arange(4.0))
    np.testing.assert_allclose(
        np.asarray(collectives.all_reduce(x, "max")), np.arange(4.0))


def test_all_reduce_bad_op():
    with pytest.raises(ValueError):
        collectives.all_reduce(jnp.ones(2), "median")


def test_all_gather_one_row_per_rank():
    out = collectives.all_gather(jnp.arange(3.0))
    assert out.shape == (1, 3)  # one process -> one row
    np.testing.assert_allclose(np.asarray(out)[0], np.arange(3.0))
    assert collectives._gather_fn.cache_info().currsize >= 1


def test_broadcast_single_process_identity():
    x = jnp.arange(5.0)
    np.testing.assert_allclose(np.asarray(collectives.broadcast(x)),
                               np.asarray(x))


def test_barrier_single_process_noop():
    collectives.barrier()  # must not raise or hang


def test_reduce_scatter_single_process_identity():
    x = jnp.arange(8.0)
    np.testing.assert_allclose(
        np.asarray(collectives.reduce_scatter(x)), np.asarray(x))


def test_dist_namespace_facade():
    d = collectives.DistNamespace()
    assert d.get_rank() == 0
    assert d.get_world_size() == 1
    assert "rank 0" in repr(d)
    out = d.all_reduce(jnp.ones(2))
    np.testing.assert_allclose(np.asarray(out), np.ones((2,)))


def test_all_reduce_matmul_sized():
    x = jnp.ones((100, 100))
    out = collectives.all_reduce(x)
    np.testing.assert_allclose(np.asarray(out), np.ones((100, 100)))


def test_repeated_calls_hit_jit_cache():
    collectives.all_reduce(jnp.ones(4))
    before = collectives._reduce_fn.cache_info()
    collectives.all_reduce(jnp.ones(4))
    after = collectives._reduce_fn.cache_info()
    assert after.currsize == before.currsize  # no new traced function
    assert after.hits > before.hits


def test_collectives_reject_jit_tracing():
    import jax

    @jax.jit
    def bad(x):
        return collectives.all_reduce(x)

    with pytest.raises(TypeError, match="eager collective"):
        bad(jnp.ones(4))
    with pytest.raises(TypeError, match="shard_map"):
        jax.jit(lambda x: collectives.all_gather(x))(jnp.ones(2))
    # broadcast in a 1-process world is an identity and must still
    # trace fine (single-chip notebooks jit through collectives).
    out = jax.jit(lambda x: collectives.broadcast(x))(jnp.ones(2))
    np.testing.assert_allclose(np.asarray(out), np.ones(2))


def test_all_reduce_quantized_close_to_exact():
    """int8 blockwise quantization: result within ~1/127 relative of
    the exact all-reduce (8 duplicate local devices here -> identity
    modulo quantization error)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 1000)) * 5.0
    exact = collectives.all_reduce(x)
    approx = collectives.all_reduce_quantized(x)
    assert approx.shape == x.shape and approx.dtype == x.dtype
    err = np.abs(np.asarray(approx) - np.asarray(exact))
    tol = np.abs(np.asarray(exact)).max() / 100
    assert err.max() < tol, err.max()


def test_all_reduce_quantized_mean_and_zero():
    z = collectives.all_reduce_quantized(jnp.zeros((7,)), op="mean")
    np.testing.assert_array_equal(np.asarray(z), np.zeros((7,)))


def test_all_reduce_quantized_bad_op():
    with pytest.raises(ValueError, match="sum|mean"):
        collectives.all_reduce_quantized(jnp.ones(4), op="max")


def test_reduce_scatter_single_process_is_identity():
    """n==1 early return (the psum_scatter fast path and the
    all_reduce+slice fallback are multi-process paths, covered by the
    integration tier's cluster tests)."""
    x = jnp.arange(16.0).reshape(16)
    out = collectives.reduce_scatter(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_all_reduce_quantized_integer_rounds():
    x = jnp.full((300,), 3, jnp.int32)
    out = collectives.all_reduce_quantized(x)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_scatter_gather_reduce_single_process():
    """torch.distributed-parity one-sided ops: single-process
    identities + the root/None contract."""
    x = jnp.arange(6.0).reshape(1, 6)          # (world=1, ...)
    np.testing.assert_allclose(np.asarray(collectives.scatter(x)),
                               np.arange(6.0))
    with pytest.raises(ValueError, match="stacked"):
        collectives.scatter(jnp.arange(6.0))   # not (world, ...)
    g = collectives.gather(jnp.arange(3.0), root=0)
    assert g is not None and g.shape == (1, 3)
    r = collectives.reduce(jnp.ones(2), root=0)
    np.testing.assert_allclose(np.asarray(r), np.ones(2))
    d = collectives.DistNamespace()
    assert d.scatter is collectives.scatter
    assert d.gather is collectives.gather
    assert d.reduce is collectives.reduce
