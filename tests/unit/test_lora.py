"""LoRA adapters: identity at init, frozen base, loss descent, and
tensor-parallel sharding exactness."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding

from nbdistributed_tpu.models import (ALL_TARGETS, forward, init_params,
                                      lora_init, lora_merge,
                                      lora_num_params, lora_shardings,
                                      loss_fn, make_lora_train_step,
                                      param_shardings, tiny_config)
from nbdistributed_tpu.parallel.mesh import make_mesh

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32, use_flash=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    return cfg, params, tokens


def test_zero_init_is_identity(setup):
    """b = 0 at init, so the merged model equals the base exactly."""
    cfg, params, tokens = setup
    lora = lora_init(jax.random.PRNGKey(2), cfg, rank=4)
    merged = lora_merge(params, lora)
    np.testing.assert_array_equal(
        np.asarray(forward(merged, tokens, cfg)),
        np.asarray(forward(params, tokens, cfg)))


def test_merge_applies_scaled_delta(setup):
    """Merged weight must be base + a@b * alpha/r for each target."""
    cfg, params, _ = setup
    lora = lora_init(jax.random.PRNGKey(3), cfg, rank=2,
                     targets=("wq", "w_down"))
    lora["layers"]["wq"]["b"] = jax.random.normal(
        jax.random.PRNGKey(4), lora["layers"]["wq"]["b"].shape)
    merged = lora_merge(params, lora, alpha=8.0)
    ab = lora["layers"]["wq"]
    want = params["layers"]["wq"] + jnp.einsum(
        "lir,lro->lio", ab["a"], ab["b"]) * (8.0 / 2)
    np.testing.assert_allclose(np.asarray(merged["layers"]["wq"]),
                               np.asarray(want), atol=1e-5, rtol=1e-5)
    # Untouched weights are the same objects, not copies.
    assert merged["layers"]["wk"] is params["layers"]["wk"]
    assert merged["lm_head"] is params["lm_head"]


def test_train_step_descends_and_freezes_base(setup):
    cfg, params, tokens = setup
    lora = lora_init(jax.random.PRNGKey(5), cfg, rank=4,
                     targets=ALL_TARGETS)
    opt = optax.adamw(1e-2)
    step = jax.jit(make_lora_train_step(cfg, opt))
    state = opt.init(lora)
    batch = {"tokens": tokens}
    base_before = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
    l0 = loss_fn(lora_merge(params, lora), batch, cfg)
    for _ in range(10):
        lora, state, loss = step(params, lora, state, batch)
    l1 = loss_fn(lora_merge(params, lora), batch, cfg)
    assert float(l1) < float(l0), (float(l0), float(l1))
    # The base pytree is untouched (passed in, never updated).
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        params, base_before)
    # b must have moved away from zero.
    assert float(jnp.abs(lora["layers"]["wq"]["b"]).max()) > 0


def test_adapter_is_small(setup):
    cfg, params, _ = setup
    lora = lora_init(jax.random.PRNGKey(6), cfg, rank=2)
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert lora_num_params(lora) < n_base * 0.1


def test_bad_args(setup):
    cfg, _, _ = setup
    with pytest.raises(ValueError, match="rank"):
        lora_init(jax.random.PRNGKey(0), cfg, rank=0)
    with pytest.raises(ValueError, match="unknown LoRA targets"):
        lora_init(jax.random.PRNGKey(0), cfg, rank=2,
                  targets=("wq", "nope"))
    with pytest.raises(ValueError, match="unknown LoRA targets"):
        lora_shardings(cfg, ("nope",))


def test_tensor_parallel_lora_matches_replicated(setup):
    """One LoRA train step on a 4-way tp mesh must match the
    unsharded step bit-for-bit up to reduction order."""
    cfg, params, tokens = setup
    lora = lora_init(jax.random.PRNGKey(7), cfg, rank=4,
                     targets=ALL_TARGETS)
    opt = optax.sgd(1e-2)
    step = make_lora_train_step(cfg, opt)
    batch = {"tokens": tokens}

    state = opt.init(lora)
    ref_lora, _, ref_loss = jax.jit(step)(params, lora, state, batch)

    mesh = make_mesh({"dp": 2, "tp": 4})
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_shardings(cfg))
    lshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), lora_shardings(cfg, lora))
    params_s = jax.device_put(params, pshard)
    lora_s = jax.device_put(lora, lshard)
    state_s = opt.init(lora_s)
    got_lora, _, got_loss = jax.jit(step)(params_s, lora_s, state_s,
                                          batch)
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4),
        got_lora, ref_lora)


def test_lora_composes_with_seq_parallel(setup):
    """LoRA train step with ring-attention SP must match the plain
    LoRA step (the merge happens before the forward, so SP sees an
    ordinary parameter pytree)."""
    from jax.sharding import PartitionSpec as P
    from nbdistributed_tpu.models import SeqParallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, params, tokens = setup
    lora = lora_init(jax.random.PRNGKey(8), cfg, rank=4,
                     targets=ALL_TARGETS)
    opt = optax.sgd(1e-2)
    batch = {"tokens": tokens}
    ref_lora, _, ref_loss = jax.jit(make_lora_train_step(cfg, opt))(
        params, lora, opt.init(lora), batch)

    mesh = make_mesh({"sp": 4, "tp": 2})
    sp = SeqParallel(mesh=mesh, method="ring", use_flash=False)
    step = jax.jit(make_lora_train_step(cfg, opt, sp=sp))
    tok_s = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    got_lora, _, got_loss = step(params, lora, opt.init(lora),
                                 {"tokens": tok_s})
    assert np.isclose(float(got_loss), float(ref_loss), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4),
        got_lora, ref_lora)


# ---------------------------------------------------------------------
# MoE family: attention-target LoRA on a Mixtral-style model

def test_moe_lora_zero_init_is_identity():
    from nbdistributed_tpu.models import (init_moe_model, moe_loss_fn,
                                          tiny_moe_config)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    lora = lora_init(jax.random.PRNGKey(2), cfg, rank=4)
    np.testing.assert_allclose(
        float(moe_loss_fn(lora_merge(params, lora),
                          {"tokens": tokens}, cfg)),
        float(moe_loss_fn(params, {"tokens": tokens}, cfg)),
        rtol=1e-6)


def test_moe_lora_descends_and_freezes_base():
    from nbdistributed_tpu.models import (init_moe_model, moe_loss_fn,
                                          tiny_moe_config)
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    lora = lora_init(jax.random.PRNGKey(2), cfg, rank=4)
    opt = optax.adamw(1e-2)
    step = jax.jit(make_lora_train_step(cfg, opt))
    st = opt.init(lora)
    before = float(moe_loss_fn(params, {"tokens": tokens}, cfg))
    base_snapshot = jax.tree_util.tree_map(np.asarray, params)
    for _ in range(5):
        lora, st, loss = step(params, lora, st, {"tokens": tokens})
    after = float(moe_loss_fn(lora_merge(params, lora),
                              {"tokens": tokens}, cfg))
    assert after < before, (after, before)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        params, base_snapshot)


def test_moe_lora_on_ep_mesh():
    """Adapter step over a dp×ep mesh: loss matches the unsharded
    step at every iteration (expert all-to-alls routed by mesh)."""
    from nbdistributed_tpu.models import (init_moe_model,
                                          moe_model_shardings,
                                          tiny_moe_config)
    from nbdistributed_tpu.parallel.tensor_parallel import \
        apply_shardings
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    params = init_moe_model(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    lora = lora_init(jax.random.PRNGKey(2), cfg, rank=4)
    opt = optax.sgd(1e-2)

    ref_step = jax.jit(make_lora_train_step(cfg, opt))
    lr, sr = lora, opt.init(lora)
    for _ in range(3):
        lr, sr, loss_ref = ref_step(params, lr, sr,
                                    {"tokens": tokens})

    mesh = make_mesh({"dp": 2, "ep": 2}, devices=jax.devices()[:4])
    ps = apply_shardings(params, mesh,
                         moe_model_shardings(cfg, tp_axis=None))
    mesh_step = jax.jit(make_lora_train_step(cfg, opt, mesh=mesh))
    lm, sm = lora, opt.init(lora)
    for _ in range(3):
        lm, sm, loss_mesh = mesh_step(ps, lm, sm, {"tokens": tokens})
    np.testing.assert_allclose(float(loss_mesh), float(loss_ref),
                               rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5),
        lm, lr)


def test_moe_lora_rejects_expert_targets():
    from nbdistributed_tpu.models import tiny_moe_config
    cfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
    with pytest.raises(ValueError, match="expert weights"):
        lora_init(jax.random.PRNGKey(0), cfg, rank=4,
                  targets=("wq", "w_up"))
