"""Ring attention vs full attention on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel.ring import ring_attention


def rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_mod.make_mesh({"sp": 8})


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full_attention(sp_mesh, causal):
    B, S, H, D = 2, 64, 2, 16  # S shards into 8 chunks of 8
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_output_stays_sequence_sharded(sp_mesh):
    B, S, H, D = 1, 64, 2, 16
    q, k, v = (rand((B, S, H, D), i + 3) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh)
    assert len(out.sharding.device_set) == 8


def test_ring_long_sequence(sp_mesh):
    """Longer-than-VMEM-friendly sequence: the point of the exercise."""
    B, S, H, D = 1, 512, 2, 32
    q, k, v = (rand((B, S, H, D), i + 7) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
