"""Ring attention vs full attention on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel.ring import ring_attention

# Heavy interpret-mode kernel/model tests: excluded from the
# fast product-path tier (`pytest -m "not slow"`).
pytestmark = [pytest.mark.unit, pytest.mark.slow]


def rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_mod.make_mesh({"sp": 8})


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full_attention(sp_mesh, causal):
    B, S, H, D = 2, 64, 2, 16  # S shards into 8 chunks of 8
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_output_stays_sequence_sharded(sp_mesh):
    B, S, H, D = 1, 64, 2, 16
    q, k, v = (rand((B, S, H, D), i + 3) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh)
    assert len(out.sharding.device_set) == 8


def test_ring_long_sequence(sp_mesh):
    """Longer-than-VMEM-friendly sequence: the point of the exercise."""
    B, S, H, D = 1, 512, 2, 32
    q, k, v = (rand((B, S, H, D), i + 7) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gqa_native(sp_mesh, causal, use_flash):
    """K/V circulate the ring at n_kv_heads (no pre-expansion) — exact
    vs the full-attention oracle, einsum and Pallas inner paths."""
    B, S, H, Hkv, D = 1, 64, 8, 2, 16
    q = rand((B, S, H, D), 20)
    k = rand((B, S, Hkv, D), 21)
    v = rand((B, S, Hkv, D), 22)
    out = ring_attention(q, k, v, sp_mesh, causal=causal,
                         use_flash=use_flash)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches(sp_mesh, causal):
    """MHA through the Pallas hop kernel (chunk-offset causal mask)."""
    B, S, H, D = 2, 64, 2, 16
    q, k, v = (rand((B, S, H, D), i + 30) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=causal, use_flash=True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gradients_match_reference(sp_mesh, use_flash):
    """Both inner paths must differentiate exactly: einsum via plain
    autodiff, flash via the ring custom-VJP over the blockwise Pallas
    backward (dk/dv accumulators ride the ring home)."""
    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    q = rand((B, S, H, D), 40)
    k = rand((B, S, Hkv, D), 41)
    v = rand((B, S, Hkv, D), 42)

    def loss_r(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, causal=True,
                                      use_flash=use_flash) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gr_ring = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gr_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr_ring, gr_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch "
                                           f"(use_flash={use_flash})")


def test_zigzag_order_is_permutation():
    from nbdistributed_tpu.parallel.ring import zigzag_order
    order = zigzag_order(64, 8)
    assert sorted(order.tolist()) == list(range(64))
    # device 0's shard = first 8 entries = chunks 0 and 15
    assert order[:8].tolist() == [0, 1, 2, 3, 60, 61, 62, 63]


def test_zigzag_shard_roundtrip():
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    x = jnp.arange(2 * 64 * 3).reshape(2, 64, 3)
    np.testing.assert_array_equal(
        np.asarray(zigzag_unshard(zigzag_shard(x, 8), 8)), np.asarray(x))


@pytest.mark.parametrize("H,Hkv", [(2, 2), (4, 2)])
def test_zigzag_matches_full_attention(sp_mesh, H, Hkv):
    """Zigzag-scheduled causal ring == full attention after undoing the
    zigzag ordering (the load-balanced schedule must stay exact)."""
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    B, S, D, n = 1, 64, 16, 8
    q = rand((B, S, H, D), 50)
    k = rand((B, S, Hkv, D), 51)
    v = rand((B, S, Hkv, D), 52)
    out_zz = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                            zigzag_shard(v, n), sp_mesh, causal=True,
                            use_flash=True, schedule="zigzag")
    out = zigzag_unshard(out_zz, n)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_zigzag_gradients_match_reference(sp_mesh):
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    B, S, H, Hkv, D, n = 1, 64, 4, 2, 16, 8
    q = rand((B, S, H, D), 60)
    k = rand((B, S, Hkv, D), 61)
    v = rand((B, S, Hkv, D), 62)

    def loss_zz(q, k, v):
        out = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                             zigzag_shard(v, n), sp_mesh, causal=True,
                             use_flash=True, schedule="zigzag")
        return jnp.sum(zigzag_unshard(out, n) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_zigzag_rejects_bad_configs(sp_mesh):
    q = rand((1, 64, 2, 16), 0)
    with pytest.raises(ValueError, match="use_flash"):
        ring_attention(q, q, q, sp_mesh, causal=True, use_flash=False,
                       schedule="zigzag")
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, sp_mesh, causal=False, use_flash=True,
                       schedule="zigzag")
    q65 = rand((1, 40, 2, 16), 0)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q65, q65, q65, sp_mesh, causal=True,
                       use_flash=True, schedule="zigzag")


def test_ring_sliding_window_exact_and_grads():
    """Windowed ring attention (einsum and Pallas paths) vs the
    windowed reference, forward and gradients."""
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import ring_attention

    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    B, S, H, Hkv, D, W = 1, 32, 4, 2, 16, 9
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True, window=W)
    for use_flash in (False, True):
        got = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                             use_flash=use_flash, window=W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"flash={use_flash}")
    # Full-argnum grads for BOTH inner paths: dK/dV exercise the
    # windowed backward accumulation riding the pruned hop plan (flash
    # custom-VJP and autodiff-through-unrolled-einsum alike).
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(attention_reference(
        q_, k_, v_, causal=True, window=W) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for use_flash in (False, True):
        g = jax.grad(lambda q_, k_, v_: jnp.sum(ring_attention(
            q_, k_, v_, mesh, axis="sp", causal=True,
            use_flash=use_flash, window=W) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, nm in zip(g, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"{nm} flash={use_flash}")


def test_ring_window_cross_length_exact():
    """Sq != Sk (queries sharded shorter than keys): the hop plan must
    size Q and K intervals independently — a plan computed from the
    K-chunk size alone would skip contributing hops for the later
    query chunks.  Parameters chosen so the correct cross-length plan
    both PRUNES (exercising the unrolled jump path and its backward)
    and DIFFERS from the k-size-only plan (the regression)."""
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import hop_plan

    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    B, Sq, Sk, H, Hkv, D, W = 1, 16, 32, 4, 2, 16, 3
    assert hop_plan(4, Sq // 4, W, sk_local=Sk // 4) == (0, 1, 2)
    assert hop_plan(4, Sk // 4, W) == (0, 1)  # the regression's plan
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, Sq, H, D))
    k = jax.random.normal(ks[1], (B, Sk, Hkv, D))
    v = jax.random.normal(ks[2], (B, Sk, Hkv, D))
    ref = attention_reference(q, k, v, causal=True, window=W)
    for use_flash in (False, True):
        got = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                             use_flash=use_flash, window=W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"flash={use_flash}")
    # Cross-length backward through the pruned plan (incl. the dk/dv
    # homing jump).
    g = jax.grad(lambda q_, k_, v_: jnp.sum(ring_attention(
        q_, k_, v_, mesh, axis="sp", causal=True, use_flash=True,
        window=W) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(attention_reference(
        q_, k_, v_, causal=True, window=W) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=nm)


def test_ring_zigzag_sliding_window_exact():
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import (ring_attention,
                                                 zigzag_shard,
                                                 zigzag_unshard)

    n = 4
    mesh = mesh_mod.make_mesh({"sp": n}, devices=jax.devices()[:n])
    B, S, H, Hkv, D, W = 1, 8 * n, 4, 2, 16, 11
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True, window=W)
    out = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                         zigzag_shard(v, n), mesh, axis="sp",
                         causal=True, use_flash=True,
                         schedule="zigzag", window=W)
    np.testing.assert_allclose(np.asarray(zigzag_unshard(out, n)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)
    # Windowed zigzag gradients for ALL inputs (sum-of-squares is
    # permutation-invariant so the reference grad applies directly):
    # dK/dV specifically exercise the pruned plan's accumulator-homing
    # jump in the zigzag backward.
    g = jax.grad(lambda q_, k_, v_: jnp.sum(ring_attention(
        zigzag_shard(q_, n), zigzag_shard(k_, n), zigzag_shard(v_, n),
        mesh, axis="sp", causal=True, use_flash=True,
        schedule="zigzag", window=W) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(attention_reference(
        q_, k_, v_, causal=True, window=W) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=nm)


def test_hop_plan_shapes_and_coverage():
    """The static hop plan must (a) shrink to O(window/chunk) hops,
    (b) cover every mask-visible (q-chunk, k-chunk) device pair —
    checked exhaustively over a grid of (n, chunk, window)."""
    from nbdistributed_tpu.parallel.ring import hop_plan

    # No window -> every step.
    assert hop_plan(8, 16, None) == tuple(range(8))
    # Plain: prefix of 1 + ceil((w-1)/C) steps.
    assert hop_plan(8, 16, 16) == (0, 1)
    assert hop_plan(8, 16, 1) == (0,)
    assert hop_plan(8, 16, 17) == (0, 1)
    assert hop_plan(8, 16, 18) == (0, 1, 2)
    # Zigzag: short prefix + suffix (window neighbors of the high
    # half-chunk arrive at ring distance n-1, n-2, ...).
    zz = hop_plan(8, 16, 8, "zigzag")
    assert 0 in zz and len(zz) < 8 and max(zz) == 7

    # Exhaustive sufficiency: every visible pair is planned.  Plain
    # covers cross-length (Ck != Cq) plans too; zigzag requires equal.
    for n in (2, 4, 8):
        for C in (4, 8):
            for w in (1, 3, C, C + 1, 2 * C, 3 * C + 1):
                for schedule, Ck in (("plain", C // 2), ("plain", C),
                                     ("plain", 2 * C), ("zigzag", C)):
                    if schedule == "zigzag":
                        plan = set(hop_plan(n, 2 * C, w, schedule))
                    else:
                        plan = set(hop_plan(n, C, w, sk_local=Ck))
                    for my in range(n):
                        for s in range(n):
                            src = (my - s) % n
                            if schedule == "zigzag":
                                q_iv = [(my * C, (my + 1) * C),
                                        ((2 * n - 1 - my) * C,
                                         (2 * n - my) * C)]
                                k_iv = [(src * C, (src + 1) * C),
                                        ((2 * n - 1 - src) * C,
                                         (2 * n - src) * C)]
                            else:
                                q_iv = [(my * C, (my + 1) * C)]
                                k_iv = [(src * Ck, (src + 1) * Ck)]
                            # discrete ground truth for this pair
                            visible = any(
                                k0 <= qi and ki <= qi and ki > qi - w
                                for q0, q1 in q_iv
                                for k0, k1 in k_iv
                                for qi in range(q0, q1)
                                for ki in range(k0, k1))
                            if visible:
                                assert s in plan, (n, C, w, schedule,
                                                   my, s)


def test_windowed_ring_skips_hops():
    """The VERDICT item: SWA x SP must not pay all n hops.  Count
    ppermute equations in the traced program — windowed rings must
    issue strictly fewer collectives than the full causal ring, for
    forward and backward, einsum, flash, and zigzag paths."""
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import ring_attention

    n = 8
    mesh = mesh_mod.make_mesh({"sp": n})
    B, S, H, Hkv, D, W = 1, 64, 4, 2, 16, 8  # chunk 8, plan (0, 1)

    def _subjaxprs(v):
        vals = v if isinstance(v, (list, tuple)) else [v]
        for x in vals:
            if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr
            elif hasattr(x, "eqns"):
                yield x

    def _count(jaxpr, mult):
        total = 0
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "ppermute":
                total += mult
                continue
            sub = mult
            if name == "while":
                sub = mult * n   # the ring hop loop runs n trips
            elif name == "scan":
                sub = mult * eqn.params.get("length", n)
            for v in eqn.params.values():
                for sj in _subjaxprs(v):
                    total += _count(sj, sub)
        return total

    def executed_ppermutes(fn, *args):
        """ppermutes EXECUTED per call: walk the jaxpr, multiplying
        collectives inside while/scan bodies by the trip count (the
        full ring keeps its per-array ppermute inside the n-trip hop
        fori_loop; the windowed plan path is fully unrolled)."""
        return _count(jax.make_jaxpr(fn)(*args).jaxpr, 1)

    q = rand((B, S, H, D), 40)
    k = rand((B, S, Hkv, D), 41)
    v = rand((B, S, Hkv, D), 42)

    for use_flash in (False, True):
        def fwd(q, k, v, w=None, uf=use_flash):
            return ring_attention(q, k, v, mesh, axis="sp",
                                  causal=True, use_flash=uf, window=w)

        full = executed_ppermutes(fwd, q, k, v)
        win = executed_ppermutes(lambda q, k, v: fwd(q, k, v, W),
                                 q, k, v)
        # plan (0, 1): one k/v jump -> 2 collectives vs 2n in full.
        assert win == 2 and full == 2 * n, (use_flash, win, full)

        def loss(q, k, v, w):
            return jnp.sum(ring_attention(
                q, k, v, mesh, axis="sp", causal=True,
                use_flash=use_flash, window=w) ** 2)

        full_g = executed_ppermutes(
            jax.grad(lambda q, k, v: loss(q, k, v, None),
                     argnums=(0, 1, 2)), q, k, v)
        win_g = executed_ppermutes(
            jax.grad(lambda q, k, v: loss(q, k, v, W),
                     argnums=(0, 1, 2)), q, k, v)
        assert win_g < full_g, (use_flash, win_g, full_g)

    # Zigzag: windowed plan still beats the full ring on collectives.
    def zz(q, k, v, w):
        return ring_attention(q, k, v, mesh, axis="sp", causal=True,
                              use_flash=True, schedule="zigzag",
                              window=w)

    full_zz = executed_ppermutes(lambda q, k, v: zz(q, k, v, None),
                                 q, k, v)
    win_zz = executed_ppermutes(lambda q, k, v: zz(q, k, v, W),
                                q, k, v)
    assert win_zz < full_zz, (win_zz, full_zz)


def test_ring_window_validation():
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import ring_attention
    from nbdistributed_tpu.parallel.ulysses import ulysses_attention

    mesh = mesh_mod.make_mesh({"sp": 2}, devices=jax.devices()[:2])
    x = jnp.zeros((1, 8, 2, 8))
    with pytest.raises(ValueError, match="causal"):
        ring_attention(x, x, x, mesh, axis="sp", causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        ring_attention(x, x, x, mesh, axis="sp", window=0)
    with pytest.raises(ValueError, match="causal"):
        ulysses_attention(x, x, x, mesh, axis="sp", causal=False,
                          window=4)


class TestRingSegments:
    """Packed-document masking through the ring: the K-side segment
    chunk rides the ring; every hop masks in both kernel passes."""

    def _inputs(self, B=1, S=64, H=4, Hkv=2, D=16, seed=0):
        import jax
        import jax.numpy as jnp
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
        seg = jnp.sort(jax.random.randint(ks[3], (B, S), 0, 3), axis=1)
        return q, k, v, seg

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_matches_reference(self, use_flash):
        import jax
        import numpy as np

        from nbdistributed_tpu.ops import attention_reference
        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.ring import ring_attention
        q, k, v, seg = self._inputs()
        mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
        out = ring_attention(q, k, v, mesh, causal=True,
                             use_flash=use_flash, segment_ids=seg)
        ref = attention_reference(q, k, v, causal=True,
                                  segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_gradients_match_reference(self, use_flash):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.ops import attention_reference
        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.ring import ring_attention
        q, k, v, seg = self._inputs()
        mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])

        def loss_r(q_, k_, v_):
            return jnp.sum(ring_attention(
                q_, k_, v_, mesh, causal=True, use_flash=use_flash,
                segment_ids=seg) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(attention_reference(
                q_, k_, v_, causal=True, segment_ids=seg) ** 2)

        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gr, ge, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")

    def test_zigzag_segments_match_reference(self):
        """Zigzag + segments: the segment array rides the ring in
        zigzag order like K/V; exact vs the masked reference in fwd
        and q/k/v grads."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.ops import attention_reference
        from nbdistributed_tpu.parallel import mesh as mesh_mod
        from nbdistributed_tpu.parallel.ring import (ring_attention,
                                                     zigzag_shard,
                                                     zigzag_unshard)
        q, k, v, seg = self._inputs()
        n = 4
        mesh = mesh_mod.make_mesh({"sp": n}, devices=jax.devices()[:n])
        out_zz = ring_attention(
            zigzag_shard(q, n), zigzag_shard(k, n), zigzag_shard(v, n),
            mesh, causal=True, use_flash=True, schedule="zigzag",
            segment_ids=zigzag_shard(seg, n))
        ref = attention_reference(q, k, v, causal=True,
                                  segment_ids=seg)
        np.testing.assert_allclose(
            np.asarray(zigzag_unshard(out_zz, n)), np.asarray(ref),
            atol=1e-5, rtol=1e-5)

        def loss_zz(q_, k_, v_):
            o = ring_attention(
                zigzag_shard(q_, n), zigzag_shard(k_, n),
                zigzag_shard(v_, n), mesh, causal=True, use_flash=True,
                schedule="zigzag", segment_ids=zigzag_shard(seg, n))
            return jnp.sum(zigzag_unshard(o, n) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(attention_reference(
                q_, k_, v_, causal=True, segment_ids=seg) ** 2)

        gz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gz, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4,
                                       err_msg=f"d{name}")

    def test_model_sp_packed_matches_plain_packed(self):
        """Full train-loss parity: the sp-ring packed loss equals the
        single-device packed loss."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from nbdistributed_tpu.models import (SeqParallel, init_params,
                                              loss_fn, tiny_config)
        from nbdistributed_tpu.parallel import mesh as mesh_mod

        cfg = tiny_config(dtype=jnp.float32, use_flash=False)
        params = init_params(jax.random.PRNGKey(0), cfg)
        mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
        S = 32
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0,
                                 cfg.vocab_size)
        seg = jnp.sort(jax.random.randint(jax.random.PRNGKey(2),
                                          (2, S), 0, 3), axis=1)
        batch = {"tokens": tok, "segments": seg}
        ref = float(loss_fn(params, batch, cfg))
        sp = SeqParallel(mesh=mesh, axis="sp", method="ring",
                         use_flash=False)
        got = float(loss_fn(params, batch, cfg, sp=sp))
        np.testing.assert_allclose(got, ref, rtol=1e-5)
