"""Ring attention vs full attention on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.parallel.ring import ring_attention


def rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_mod.make_mesh({"sp": 8})


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full_attention(sp_mesh, causal):
    B, S, H, D = 2, 64, 2, 16  # S shards into 8 chunks of 8
    q, k, v = (rand((B, S, H, D), i) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_output_stays_sequence_sharded(sp_mesh):
    B, S, H, D = 1, 64, 2, 16
    q, k, v = (rand((B, S, H, D), i + 3) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh)
    assert len(out.sharding.device_set) == 8


def test_ring_long_sequence(sp_mesh):
    """Longer-than-VMEM-friendly sequence: the point of the exercise."""
    B, S, H, D = 1, 512, 2, 32
    q, k, v = (rand((B, S, H, D), i + 7) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gqa_native(sp_mesh, causal, use_flash):
    """K/V circulate the ring at n_kv_heads (no pre-expansion) — exact
    vs the full-attention oracle, einsum and Pallas inner paths."""
    B, S, H, Hkv, D = 1, 64, 8, 2, 16
    q = rand((B, S, H, D), 20)
    k = rand((B, S, Hkv, D), 21)
    v = rand((B, S, Hkv, D), 22)
    out = ring_attention(q, k, v, sp_mesh, causal=causal,
                         use_flash=use_flash)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches(sp_mesh, causal):
    """MHA through the Pallas hop kernel (chunk-offset causal mask)."""
    B, S, H, D = 2, 64, 2, 16
    q, k, v = (rand((B, S, H, D), i + 30) for i in range(3))
    out = ring_attention(q, k, v, sp_mesh, causal=causal, use_flash=True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gradients_match_reference(sp_mesh, use_flash):
    """Both inner paths must differentiate exactly: einsum via plain
    autodiff, flash via the ring custom-VJP over the blockwise Pallas
    backward (dk/dv accumulators ride the ring home)."""
    B, S, H, Hkv, D = 1, 64, 4, 2, 16
    q = rand((B, S, H, D), 40)
    k = rand((B, S, Hkv, D), 41)
    v = rand((B, S, Hkv, D), 42)

    def loss_r(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, causal=True,
                                      use_flash=use_flash) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gr_ring = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gr_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr_ring, gr_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch "
                                           f"(use_flash={use_flash})")


def test_zigzag_order_is_permutation():
    from nbdistributed_tpu.parallel.ring import zigzag_order
    order = zigzag_order(64, 8)
    assert sorted(order.tolist()) == list(range(64))
    # device 0's shard = first 8 entries = chunks 0 and 15
    assert order[:8].tolist() == [0, 1, 2, 3, 60, 61, 62, 63]


def test_zigzag_shard_roundtrip():
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    x = jnp.arange(2 * 64 * 3).reshape(2, 64, 3)
    np.testing.assert_array_equal(
        np.asarray(zigzag_unshard(zigzag_shard(x, 8), 8)), np.asarray(x))


@pytest.mark.parametrize("H,Hkv", [(2, 2), (4, 2)])
def test_zigzag_matches_full_attention(sp_mesh, H, Hkv):
    """Zigzag-scheduled causal ring == full attention after undoing the
    zigzag ordering (the load-balanced schedule must stay exact)."""
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    B, S, D, n = 1, 64, 16, 8
    q = rand((B, S, H, D), 50)
    k = rand((B, S, Hkv, D), 51)
    v = rand((B, S, Hkv, D), 52)
    out_zz = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                            zigzag_shard(v, n), sp_mesh, causal=True,
                            use_flash=True, schedule="zigzag")
    out = zigzag_unshard(out_zz, n)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_zigzag_gradients_match_reference(sp_mesh):
    from nbdistributed_tpu.parallel.ring import (zigzag_shard,
                                                 zigzag_unshard)
    B, S, H, Hkv, D, n = 1, 64, 4, 2, 16, 8
    q = rand((B, S, H, D), 60)
    k = rand((B, S, Hkv, D), 61)
    v = rand((B, S, Hkv, D), 62)

    def loss_zz(q, k, v):
        out = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                             zigzag_shard(v, n), sp_mesh, causal=True,
                             use_flash=True, schedule="zigzag")
        return jnp.sum(zigzag_unshard(out, n) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_zigzag_rejects_bad_configs(sp_mesh):
    q = rand((1, 64, 2, 16), 0)
    with pytest.raises(ValueError, match="use_flash"):
        ring_attention(q, q, q, sp_mesh, causal=True, use_flash=False,
                       schedule="zigzag")
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, sp_mesh, causal=False, use_flash=True,
                       schedule="zigzag")
    q65 = rand((1, 40, 2, 16), 0)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q65, q65, q65, sp_mesh, causal=True,
                       use_flash=True, schedule="zigzag")


def test_ring_sliding_window_exact_and_grads():
    """Windowed ring attention (einsum and Pallas paths) vs the
    windowed reference, forward and gradients."""
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import ring_attention

    mesh = mesh_mod.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    B, S, H, Hkv, D, W = 1, 32, 4, 2, 16, 9
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True, window=W)
    for use_flash in (False, True):
        got = ring_attention(q, k, v, mesh, axis="sp", causal=True,
                             use_flash=use_flash, window=W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"flash={use_flash}")
    # Full-argnum grads: dK/dV exercise the windowed
    # _flash_backward_folded accumulation riding the ring.
    g = jax.grad(lambda q_, k_, v_: jnp.sum(ring_attention(
        q_, k_, v_, mesh, axis="sp", causal=True, use_flash=True,
        window=W) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(attention_reference(
        q_, k_, v_, causal=True, window=W) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=nm)


def test_ring_zigzag_sliding_window_exact():
    from nbdistributed_tpu.ops import attention_reference
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import (ring_attention,
                                                 zigzag_shard,
                                                 zigzag_unshard)

    n = 4
    mesh = mesh_mod.make_mesh({"sp": n}, devices=jax.devices()[:n])
    B, S, H, Hkv, D, W = 1, 8 * n, 4, 2, 16, 11
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    ref = attention_reference(q, k, v, causal=True, window=W)
    out = ring_attention(zigzag_shard(q, n), zigzag_shard(k, n),
                         zigzag_shard(v, n), mesh, axis="sp",
                         causal=True, use_flash=True,
                         schedule="zigzag", window=W)
    np.testing.assert_allclose(np.asarray(zigzag_unshard(out, n)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)
    # Windowed zigzag gradients (q grad; sum-of-squares is
    # permutation-invariant so the reference grad applies directly).
    g = jax.grad(lambda q_: jnp.sum(ring_attention(
        zigzag_shard(q_, n), zigzag_shard(k, n), zigzag_shard(v, n),
        mesh, axis="sp", causal=True, use_flash=True,
        schedule="zigzag", window=W) ** 2))(q)
    g_ref = jax.grad(lambda q_: jnp.sum(attention_reference(
        q_, k, v, causal=True, window=W) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_ring_window_validation():
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.parallel.ring import ring_attention
    from nbdistributed_tpu.parallel.ulysses import ulysses_attention

    mesh = mesh_mod.make_mesh({"sp": 2}, devices=jax.devices()[:2])
    x = jnp.zeros((1, 8, 2, 8))
    with pytest.raises(ValueError, match="causal"):
        ring_attention(x, x, x, mesh, axis="sp", causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        ring_attention(x, x, x, mesh, axis="sp", window=0)
    with pytest.raises(ValueError, match="causal"):
        ulysses_attention(x, x, x, mesh, axis="sp", causal=False,
                          window=4)
