"""The runnable self-test entry must pass end-to-end (it is itself an
integration artifact: SURVEY §4 notes the reference declared one but
never shipped it)."""

import re
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.integration]


def test_selftest_passes():
    proc = subprocess.run(
        [sys.executable, "-m", "nbdistributed_tpu.selftest"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # All checks must pass, however many the selftest carries today.
    m = re.search(r"(\d+)/(\d+) checks passed", proc.stdout)
    assert m, proc.stdout
    assert m.group(1) == m.group(2), proc.stdout
    assert int(m.group(2)) >= 10, proc.stdout
