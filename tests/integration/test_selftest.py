"""The runnable self-test entry must pass end-to-end (it is itself an
integration artifact: SURVEY §4 notes the reference declared one but
never shipped it)."""

import subprocess
import sys

import pytest

pytestmark = [pytest.mark.integration]


def test_selftest_passes():
    proc = subprocess.run(
        [sys.executable, "-m", "nbdistributed_tpu.selftest"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "10/10 checks passed" in proc.stdout
