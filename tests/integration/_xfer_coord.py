"""Sacrificial first coordinator for the bulk-transfer chaos test.

NOT a test module (no ``test_`` prefix).  Run as a subprocess:

    python tests/integration/_xfer_coord.py RUN_DIR WORLD NBYTES CHUNK

Brings up WORLD CPU workers with durable-session env (token, epoch 1),
writes the session manifest, then starts a chunked push of a
DETERMINISTIC NBYTES payload (seeded rng — the reattaching test
recomputes the identical value, hence the identical content-addressed
xid) and deliberately delivers only the FIRST HALF of the chunks,
never sending the commit.  It publishes the transfer identity to
``RUN_DIR/xcoord.json``, prints READY, and sleeps until the test
SIGKILLs it — the coordinator-crash-mid-%dist_push scenario the
resumable transfer plane exists for.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

PAYLOAD_SEED = 2020
PUSH_NAME = "big"


def make_value(nbytes: int):
    import numpy as np
    rng = np.random.default_rng(PAYLOAD_SEED)
    return {"w": rng.standard_normal(nbytes // 4, dtype=np.float32)}


def main() -> int:
    run_dir, world = sys.argv[1], int(sys.argv[2])
    nbytes, csize = int(sys.argv[3]), int(sys.argv[4])
    os.environ["NBD_RUN_DIR"] = run_dir
    os.environ["NBD_XFER_CHUNK_BYTES"] = str(csize)

    from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
    from nbdistributed_tpu.messaging import CommunicationManager
    from nbdistributed_tpu.messaging import xfer
    from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
    from nbdistributed_tpu.resilience import session

    token = session.mint_token()
    comm = CommunicationManager(num_workers=world, timeout=120,
                                session_token=token, session_epoch=1)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    pm.start_workers(world, comm.port, backend="cpu", extra_env={
        "NBD_SESSION_TOKEN": token,
        "NBD_SESSION_EPOCH": "1",
        "NBD_ORPHAN_TTL_S": "180",
        "NBD_XFER_CHUNK_BYTES": str(csize),
    })
    wait_until_ready(comm, pm, 180)
    session.write_manifest(run_dir, session.make_manifest(
        world_size=world, control_host="127.0.0.1",
        control_port=comm.port, token=token, epoch=1,
        pids={r: p.pid for r, p in pm.processes.items()},
        backend="cpu", dist_port=pm.dist_port,
        init_line=f"-n {world} --backend cpu"))

    # The interrupted push: same flatten/crc/xid computation the real
    # push engine performs, but the chunk loop stops at the halfway
    # mark and xfer_commit is NEVER sent.
    meta, bufs = flatten_pytree_wire(make_value(nbytes))
    src = xfer.ChunkSource(bufs)
    n = src.n_chunks(csize)
    crcs = src.crcs(csize)
    xid = xfer.transfer_id("var", PUSH_NAME, src.total, csize, crcs)
    ranks = list(range(world))
    begin = comm.send_to_ranks(
        ranks, "xfer_begin",
        {"xid": xid, "kind": "var", "name": PUSH_NAME, "dest": None,
         "total": src.total, "chunk_bytes": csize, "n_chunks": n,
         "meta": meta, "descs": src.descs}, timeout=120)
    assert all((m.data or {}).get("ok") for m in begin.values()), \
        {r: m.data for r, m in begin.items()}
    half = n // 2
    for seq in range(half):
        raw = src.read(seq, csize)
        replies = comm.submit(
            ranks, "xfer_chunk", None, bufs={"c": raw},
            xfer={"x": xid, "s": seq, "c": crcs[seq], "e": "stored",
                  "r": len(raw)}, timeout=120).wait()
        assert all((m.data or {}).get("ok") for m in replies.values()), \
            {r: m.data for r, m in replies.items()}

    # Atomic publish (tmp + rename): the test polls for existence then
    # json.loads — a plain write would expose an empty file.
    status_path = os.path.join(run_dir, "xcoord.json")
    with open(status_path + ".tmp", "w") as f:
        json.dump({"xid": xid, "n_chunks": n, "half": half,
                   "total": src.total, "pid": os.getpid(),
                   "port": comm.port}, f)
    os.replace(status_path + ".tmp", status_path)
    print("READY", flush=True)
    time.sleep(600)  # SIGKILLed here by the test, mid-transfer
    return 0


if __name__ == "__main__":
    sys.exit(main())
