"""The ssh-proxy multihost path, exercised for real.

``start_workers_multihost`` spawns remote workers as local ssh proxy
processes whose stdio/kill semantics must match a direct child's
(multihost.ssh_argv builds ``ssh host 'exec env ... python -m worker'``).
This suite drives that path end-to-end through a *fake ssh executable*
that executes the remote command locally — the argv construction, proxy
spawn, control-plane attach, streamed stdio, collectives, and teardown
are all the production code; only the network hop is simulated.  A
second test uses the genuine ``ssh`` client against localhost and skips
(never silently passes) where ssh/sshd is unavailable — as in this CI
image, which ships no ssh client at all.
"""

import shutil
import socket
import subprocess
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager

FAKE_SSH = """#!/bin/sh
# fake ssh: swallow -o opts and the host argument, run the remote
# command string locally.  `exec` both times, so this proxy process IS
# the worker — kill semantics are exactly what real ssh forwards.
while [ "$1" = "-o" ]; do shift 2; done
shift
exec sh -c "$1"
"""


def _nonloopback_addr() -> str | None:
    """An address of this box that isn't the literal loopback the plan
    validator rejects (remote workers must not dial their own lo).
    UDP connect() picks the outbound interface without sending any
    packet (TEST-NET-1 destination; works in zero-egress sandboxes)."""
    candidates = []
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("192.0.2.1", 9))
            candidates.append(s.getsockname()[0])
    except OSError:
        pass
    try:
        candidates.append(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for ip in candidates:
        if ip not in ("127.0.0.1", "localhost", "", "0.0.0.0"):
            return ip
    return None


def _drive_cluster(comm: CommunicationManager, pm: ProcessManager):
    """Attach, run a collective-bearing cell, assert per-rank replies
    and streamed stdout from the proxied rank."""
    streamed: list[tuple[int, str]] = []
    wait_until_ready(comm, pm, 120)
    comm.set_output_callback(
        lambda rank, data: streamed.append((rank, data.get("text", ""))))
    resp = comm.send_to_all(
        "execute",
        "print(f'hello-from-{rank}')\n"
        "total = float(all_reduce(jnp.array([rank + 1.0]))[0])\n"
        "total",
        timeout=180)
    for rank in (0, 1):
        data = resp[rank].data
        assert not data.get("error"), data
        assert data["output"].strip().endswith("3.0")  # 1 + 2 all-reduced
    assert any(r == 1 and "hello-from-1" in t for r, t in streamed), (
        f"no streamed stdout from the ssh-proxied rank: {streamed}")


def test_ssh_proxy_spawn_stdio_kill(tmp_path):
    """Mixed local + ssh-proxied plan through a fake ssh executable:
    rank 0 local (hosts jax.distributed), rank 1 through the proxy."""
    fake = tmp_path / "ssh"
    fake.write_text(FAKE_SSH)
    fake.chmod(0o755)
    addr = _nonloopback_addr()
    if addr is None:
        pytest.skip("no non-loopback address resolvable on this host")

    # Non-loopback bind => shared-secret handshake, exactly like
    # %dist_init --hosts generates.
    comm = CommunicationManager(num_workers=2, host="0.0.0.0", timeout=60,
                                auth_token="it-test-token")
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        pm.start_workers_multihost(
            "local,sshbox", comm.port, coordinator_host=addr,
            backend="cpu", ssh=str(fake), auth_token="it-test-token")
        procs = dict(pm.processes)
        assert set(procs) == {0, 1}
        _drive_cluster(comm, pm)
    finally:
        pm.shutdown()
        comm.shutdown()
    # Kill semantics: tearing down the proxy must take the worker with
    # it (here proxy == worker via exec; real ssh forwards teardown).
    deadline = time.time() + 10
    while time.time() < deadline and any(p.poll() is None
                                         for p in procs.values()):
        time.sleep(0.1)
    assert all(p.poll() is not None for p in procs.values()), (
        "ssh proxy process(es) survived shutdown")


def _localhost_ssh_works() -> bool:
    ssh = shutil.which("ssh")
    if ssh is None:
        return False
    try:
        rc = subprocess.run(
            [ssh, "-o", "BatchMode=yes", "-o", "ConnectTimeout=2",
             "localhost", "true"], capture_output=True, timeout=10
        ).returncode
    except Exception:
        return False
    return rc == 0


@pytest.mark.skipif(not _localhost_ssh_works(),
                    reason="ssh to localhost unavailable (no ssh client "
                           "or no sshd/keys) — fake-ssh variant covers "
                           "the proxy path")
def test_ssh_real_localhost(tmp_path):
    """The same plan through the genuine ssh client to localhost."""
    addr = _nonloopback_addr()
    if addr is None:
        pytest.skip("no non-loopback address resolvable on this host")
    comm = CommunicationManager(num_workers=2, host="0.0.0.0", timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        pm.start_workers_multihost(
            f"local,{socket.gethostname()}", comm.port,
            coordinator_host=addr, backend="cpu")
        _drive_cluster(comm, pm)
    finally:
        pm.shutdown()
        comm.shutdown()
