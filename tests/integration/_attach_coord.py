"""Sacrificial first coordinator for the reattach integration test.

NOT a test module (no ``test_`` prefix).  Run as a subprocess:

    python tests/integration/_attach_coord.py RUN_DIR WORLD

Brings up WORLD CPU workers with durable-session env (token, epoch 1,
short-ish orphan TTL), writes the session manifest, seeds the
namespace (``x = 42``, ``hits = 0``), then fires an in-flight cell
(bump ``hits``, sleep, yield ``hits``) WITHOUT waiting for the reply,
publishes the cell's msg_id + status to ``RUN_DIR/coord1.json``,
prints READY, and sleeps until the test SIGKILLs it mid-cell — the
coordinator-crash scenario the reattach path exists for.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))


def main() -> int:
    run_dir, world = sys.argv[1], int(sys.argv[2])
    os.environ["NBD_RUN_DIR"] = run_dir

    from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
    from nbdistributed_tpu.messaging import CommunicationManager
    from nbdistributed_tpu.resilience import session

    token = session.mint_token()
    comm = CommunicationManager(num_workers=world, timeout=120,
                                session_token=token, session_epoch=1)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    pm.start_workers(world, comm.port, backend="cpu", extra_env={
        "NBD_SESSION_TOKEN": token,
        "NBD_SESSION_EPOCH": "1",
        "NBD_ORPHAN_TTL_S": "120",
    })
    wait_until_ready(comm, pm, 180)
    session.write_manifest(run_dir, session.make_manifest(
        world_size=world, control_host="127.0.0.1",
        control_port=comm.port, token=token, epoch=1,
        pids={r: p.pid for r, p in pm.processes.items()},
        backend="cpu", dist_port=pm.dist_port,
        init_line=f"-n {world} --backend cpu"))
    comm.send_to_all("execute", "x = 42", timeout=120)
    comm.send_to_all("execute", "hits = 0", timeout=120)
    # The in-flight cell: mutates state (so double-execution would be
    # provable), sleeps past this process's death, and its final
    # expression is the result the mailbox must redeliver exactly once.
    fatal_mid = comm.post(
        list(range(world)), "execute",
        {"code": "hits += 1\nimport time\ntime.sleep(4.0)\nhits"})
    # Atomic publish: the test polls for this file's EXISTENCE and
    # then json.loads it — a plain open(..., "w") exposes an empty
    # file between create and dump (observed as a flaky
    # JSONDecodeError under load).
    status_path = os.path.join(run_dir, "coord1.json")
    with open(status_path + ".tmp", "w") as f:
        json.dump({"fatal_mid": fatal_mid, "pid": os.getpid(),
                   "port": comm.port, "token": token}, f)
    os.replace(status_path + ".tmp", status_path)
    print("READY", flush=True)
    time.sleep(600)  # SIGKILLed here by the test
    return 0


if __name__ == "__main__":
    sys.exit(main())
