"""Acceptance tests for the session gateway (ISSUE 8), end to end on
the CPU backend: two tenants sharing one 4-rank pool.

1. **Interleaved cells, isolated namespaces**: both tenants' cells
   complete; each tenant reads back its OWN ``x`` on every rank, and
   the ``shared`` dict is the one deliberate crossing.
2. **Tenant-crash isolation** (the scenario the tentpole exists for):
   a sacrificial tenant-kernel subprocess is SIGKILLed mid-cell by a
   seeded :class:`FaultPlan` while the other tenant's concurrently
   queued cells keep flowing — all of them complete with zero
   double-executions, the dead tenant's result parks in its own
   mailbox partition, a reattach under the same name + token bumps
   the tenant epoch and drains the parked result exactly once, and
   ``%dist_pool status``-shape payloads + per-tenant metrics reflect
   the whole episode.
3. **Tenant fencing over the wire**: after a reattach, the old
   connection's epoch-stamped frames get ``stale_epoch`` (raised
   client-side as :class:`TenantFenced`), and a wrong token can never
   hijack a tenant name.

Marked ``slow`` on purpose: pool spin-up is the timing-sensitive part
tier-1 must not absorb; the CI resilience job owns these (marker
``gateway``).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from nbdistributed_tpu.gateway.client import (CellSubmitError,
                                              TenantClient,
                                              TenantFenced)
from nbdistributed_tpu.gateway.daemon import GatewayDaemon
from nbdistributed_tpu.gateway.scheduler import SchedPolicy
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.observability import metrics as obs_metrics

pytestmark = [pytest.mark.integration, pytest.mark.gateway,
              pytest.mark.faults, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
KERNEL = os.path.join(REPO_ROOT, "tests", "integration",
                      "_tenant_kernel.py")
WORLD = 4


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    """One in-process gateway daemon owning a 4-rank CPU fleet,
    shared by every test in this module (tenants are cheap; pools are
    not).  Serial mesh + fair-share, bounded queue — the pool-shaped
    policy the knobs default to."""
    run_dir = str(tmp_path_factory.mktemp("pool"))
    old_env = os.environ.get("NBD_RUN_DIR")
    os.environ["NBD_RUN_DIR"] = run_dir
    flightrec.reset_for_tests()
    gw = GatewayDaemon(
        WORLD, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=1, tenant_inflight=8,
                           queue_depth=16),
        request_timeout=None, attach_timeout=240.0)
    try:
        yield gw
    finally:
        gw.close()
        if old_env is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = old_env


def attach(pool, name, **kw):
    return TenantClient(pool.tenant_host, pool.tenant_port, name,
                        pool_token=pool.pool_token, **kw)


def rank_outputs(data):
    return {r: (d or {}).get("output")
            for r, d in (data.get("results") or {}).items()}


# ----------------------------------------------------------------------


def test_interleaved_cells_isolated_namespaces(pool):
    t1 = attach(pool, "t1")
    t2 = attach(pool, "t2")
    try:
        assert t1.world_size == WORLD
        # Interleave writes under the SAME variable name.
        assert t1.execute("x = 'one'")["status"] == "ok"
        assert t2.execute("x = 'two'")["status"] == "ok"
        assert t1.execute("x += '!'")["status"] == "ok"
        out1 = rank_outputs(t1.execute("x"))
        out2 = rank_outputs(t2.execute("x"))
        assert len(out1) == WORLD and len(out2) == WORLD
        assert all(v == "'one!'" for v in out1.values()), out1
        assert all(v == "'two'" for v in out2.values()), out2
        # A tenant's del cannot reach the other namespace either.
        t2.execute("del x")
        data = t2.execute("'x' in dir()")
        assert all(v == "False"
                   for v in rank_outputs(data).values())
        assert all(v == "'one!'"
                   for v in rank_outputs(t1.execute("x")).values())
        # The ONE deliberate crossing: the shared segment.
        t1.execute("shared['weights'] = 123")
        out = rank_outputs(t2.execute("shared['weights']"))
        assert all(v == "123" for v in out.values())
        # Tenant identity is visible inside the namespace.
        out = rank_outputs(t1.execute("tenant"))
        assert all(v == "'t1'" for v in out.values())
    finally:
        t1.close(detach=True)
        t2.close(detach=True)


def test_sigkill_tenant_mid_cell_isolation_and_redelivery(pool):
    """The headline chaos scenario, deterministic via the seeded
    FaultPlan: SIGKILL tenant A's kernel mid-cell -> tenant B's queued
    cells all complete (zero double-executions), A's result parks and
    redelivers exactly once on reattach, and status/metrics attribute
    the episode to the right tenant."""
    reg = obs_metrics.registry()
    out_json = os.path.join(pool.run_dir, "tenant_a.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Seeded chaos: the kernel self-SIGKILLs at tick 5 (~0.5 s into
    # its 3 s in-flight cell) — mid-cell by construction.
    env["NBD_FAULT_PLAN"] = json.dumps(
        {"seed": 3, "kill_rank": 0, "kill_at": 5})
    proc = subprocess.Popen(
        [sys.executable, KERNEL, pool.run_dir, "A", out_json],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    b = attach(pool, "B")
    try:
        deadline = time.time() + 120
        while not os.path.exists(out_json):
            assert time.time() < deadline, \
                (proc.stdout.read() or b"").decode("utf-8", "replace")
            assert proc.poll() is None or os.path.exists(out_json)
            time.sleep(0.1)
        with open(out_json) as f:
            a_info = json.load(f)

        # B floods while A's 3 s cell holds the single mesh slot:
        # every one of B's cells queues (explicit position), then
        # completes after the crash — the pool never wedges.
        b.execute("b_hits = 0")
        positions, results, errors = [], [], []

        def run_b(i):
            try:
                results.append(b.execute(
                    "b_hits += 1\nb_hits",
                    on_queued=lambda n: positions.append(
                        n.get("position"))))
            except Exception as e:            # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=run_b, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()

        # While A's cell is in flight, the busy rank view attributes
        # the mesh to tenant A (the %dist_top tenant column).
        saw_busy_a = False
        deadline = time.time() + 20
        while time.time() < deadline and not saw_busy_a:
            st = pool.status()
            saw_busy_a = any(r.get("tenant") == "A"
                             for r in st["ranks"].values())
            time.sleep(0.1)
        assert saw_busy_a, "A's in-flight cell never showed up " \
                           "tenant-attributed in the rank view"

        # The seeded plan SIGKILLs A mid-cell.
        assert proc.wait(timeout=30) == -9

        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 3
        # Zero double-executions: the counter saw exactly 3 bumps on
        # every rank, and positions were explicit backpressure.
        out = rank_outputs(b.execute("b_hits"))
        assert all(v == "3" for v in out.values()), out
        assert positions, "B's cells never got a queued-position reply"

        # A's interrupted cell finishes on the mesh and PARKS in A's
        # partition (its kernel is gone).
        deadline = time.time() + 30
        parked = 0
        while time.time() < deadline and not parked:
            st = pool.status()
            parked = st["tenants"]["tenants"]["A"]["parked"]
            time.sleep(0.2)
        assert parked == 1, st["tenants"]["tenants"]["A"]
        assert reg.counter("nbd_tenant_parked_total",
                           labels={"tenant": "A"}).value >= 1
        assert reg.counter("nbd_tenant_detaches_total",
                           labels={"tenant": "A",
                                   "kind": "lost"}).value >= 1

        # Reattach as A under the same name + token: epoch bumps,
        # the parked result redelivers EXACTLY once.
        a2 = attach(pool, "A", token=a_info["token"])
        try:
            assert a2.attach_status == "reattached"
            assert a2.epoch == a_info["epoch"] + 1
            assert len(a2.parked) == 1
            drained = a2.drain()
            assert len(drained) == 1
            (res,) = drained.values()
            outs = rank_outputs(res)
            assert len(outs) == WORLD
            assert all(v == "1" for v in outs.values()), outs
            assert res.get("status") == "ok"
            assert a2.drain() == {}          # exactly once
            # The tripwire proves the crash caused no re-execution.
            out = rank_outputs(a2.execute("a_hits"))
            assert all(v == "1" for v in out.values()), out
            # The episode is visible in the tenant table.
            st = pool.status()
            row = st["tenants"]["tenants"]["A"]
            assert row["reattaches"] == 1
            assert row["parked"] == 0 and row["parked_total"] == 1
            assert st["tenants"]["tenants"]["B"]["cells_done"] >= 4
            sched = st["scheduler"]["tenants"]
            assert sched["A"]["completed"] >= 2
            assert sched["B"]["served"] >= 4
        finally:
            a2.close(detach=True)
    finally:
        if proc.poll() is None:
            proc.kill()
        b.close(detach=True)


def test_stale_tenant_connection_is_fenced(pool):
    c1 = attach(pool, "fenceme")
    token = c1.token
    assert c1.execute("y = 1")["status"] == "ok"
    # A second kernel resumes the tenant: epoch bumps gateway-side.
    c2 = attach(pool, "fenceme", token=token)
    try:
        assert c2.attach_status == "reattached"
        assert c2.epoch == c1.epoch + 1
        # The OLD connection's frames now carry a stale epoch and are
        # refused with an explicit fence, not executed.
        with pytest.raises(TenantFenced):
            c1.execute("y = 'hijacked'")
        out = rank_outputs(c2.execute("y"))
        assert all(v == "1" for v in out.values())
        # And a wrong token cannot hijack the name at hello time.
        with pytest.raises(RuntimeError, match="refused"):
            attach(pool, "fenceme", token="not-the-token")
    finally:
        c1.close()
        c2.close(detach=True)


def test_admission_rejects_beyond_max_tenants(pool):
    """Headcount admission on the REGISTRY bound (scoped: this pool
    admits 8; earlier tests used some slots, so push to the bound and
    assert the refusal is explicit)."""
    extra = []
    try:
        with pytest.raises(RuntimeError, match="max_tenants"):
            for i in range(pool.registry.max_tenants + 1):
                extra.append(attach(pool, f"filler-{i}"))
    finally:
        for c in extra:
            c.close()
