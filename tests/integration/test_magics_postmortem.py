"""Notebook surface of the flight-recorder stack (ISSUE 3): %dist_top
live telemetry dashboard on a 4-rank CPU cluster, %dist_postmortem
bundle capture/replay, and the %dist_status heartbeat-age column.
"""

import json
import os
import time

import pytest

pytestmark = [pytest.mark.integration, pytest.mark.obs,
              pytest.mark.postmortem]

WORLD = 4


@pytest.fixture(scope="module")
def ip(tmp_path_factory):
    from IPython.testing.globalipapp import get_ipython, start_ipython

    from nbdistributed_tpu.observability import flightrec

    # Fresh run dir for this module's rings and bundles; the workers
    # inherit it at spawn, and reset_for_tests forces the coordinator
    # ring to re-open there too.
    run_d = str(tmp_path_factory.mktemp("nbd_run"))
    old_run_dir = os.environ.get("NBD_RUN_DIR")
    os.environ["NBD_RUN_DIR"] = run_d
    flightrec.reset_for_tests()

    shell = start_ipython() or get_ipython()
    shell.run_line_magic("load_ext", "nbdistributed_tpu")
    shell.run_line_magic(
        "dist_init", f"-n {WORLD} --backend cpu --attach-timeout 240 "
                     f"-t 120")
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is not None, "cluster failed to start"
    yield shell
    shell.run_line_magic("dist_shutdown", "")
    if old_run_dir is None:
        os.environ.pop("NBD_RUN_DIR", None)
    else:
        os.environ["NBD_RUN_DIR"] = old_run_dir
    flightrec.reset_for_tests()


def _wait_for_telemetry(comm, ranks, timeout=60):
    """Block until every rank's heartbeat has piggybacked at least one
    telemetry snapshot (first ping ~2 s after attach)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(comm.last_telemetry(r) is not None for r in ranks):
            return
        time.sleep(0.2)
    raise AssertionError("telemetry snapshots never arrived")


def test_dist_top_renders_live_table(ip, capsys):
    from nbdistributed_tpu.magics.magic import DistributedMagics

    _wait_for_telemetry(DistributedMagics._comm, range(WORLD))
    capsys.readouterr()
    ip.run_line_magic("dist_top", "")
    out = capsys.readouterr().out
    assert f"cluster top · {WORLD} workers" in out
    assert "hb-age" in out and "HBM" in out and "bufs" in out
    import re
    lines = out.splitlines()
    for r in range(WORLD):
        row = next(ln for ln in lines if ln.startswith(f"{r} "))
        assert "alive" in row, row
        # heartbeat age rendered as a number, not the '-' placeholder
        assert re.search(r"\d+\.\d+s", row), row
        # push-based: the live-buffer count rode a heartbeat piggyback
        assert any(tok.isdigit() for tok in row.split()[3:]), row
    assert "run dir" in out


def test_dist_status_shows_heartbeat_age(ip, capsys):
    ip.run_line_magic("dist_status", "")
    out = capsys.readouterr().out
    # every rank line carries the hb column with a real age
    hb_lines = [ln for ln in out.splitlines() if "· hb " in ln]
    assert len(hb_lines) == WORLD, out
    assert not any("hb –" in ln for ln in hb_lines), out


def test_dist_postmortem_on_demand_and_last(ip, capsys):
    ip.run_cell("pm_probe = rank * 2\npm_probe")
    capsys.readouterr()
    ip.run_line_magic("dist_postmortem", "")
    out = capsys.readouterr().out
    assert "nbdistributed_tpu postmortem" in out
    assert "bundle →" in out
    bundle = out.split("bundle →")[1].split()[0]
    # every process's flight ring was recovered into the bundle
    trace = json.load(open(os.path.join(bundle, "trace.json")))
    flight = [e for e in trace["traceEvents"]
              if e.get("cat") == "flight"]
    assert {e["pid"] for e in flight} >= {-1, 0, 1, 2, 3}
    # the probe cell's dispatch + cell events are in a worker ring
    ring0 = json.load(open(os.path.join(bundle, "flight_rank0.json")))
    kinds = {e["t"] for e in ring0["events"]}
    assert "dispatch" in kinds and "cell_start" in kinds
    assert not ring0["torn_tail"]          # healthy worker, clean ring
    # --last re-prints the newest bundle without capturing a new one
    from nbdistributed_tpu.observability import postmortem as pm_mod
    n_before = len(pm_mod.list_bundles())
    ip.run_line_magic("dist_postmortem", "--last")
    out = capsys.readouterr().out
    assert "nbdistributed_tpu postmortem" in out
    assert len(pm_mod.list_bundles()) == n_before


def test_dist_postmortem_save_dir(ip, capsys, tmp_path):
    target = str(tmp_path / "pm_bundle")
    ip.run_line_magic("dist_postmortem", f"--save {target}")
    out = capsys.readouterr().out
    assert "bundle →" in out
    assert os.path.exists(os.path.join(target, "report.txt"))
    assert os.path.exists(os.path.join(target, "manifest.json"))
