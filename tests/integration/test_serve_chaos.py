"""Chaos-hardened serving through the gateway (ISSUE 11), end to end
on the CPU backend: a 4-rank pool serving staggered generation
requests under a seeded FaultPlan.

The headline scenario the tentpole exists for:

1. **Rank SIGKILL mid-decode, then control-plane drops.**  Twelve
   staggered requests; the decode rank is SIGKILLed by a seeded
   ``kill_at`` plan while most of them are mid-stream, then the
   surviving ranks drop 8% of control-plane frames.  Every accepted
   request must complete with its EXACT solo-``generate`` greedy
   tokens (journal-replay re-admission is bit-identical), with zero
   duplicated emissions (``dup_dropped`` pinned to 0 — the offset
   dedup never had to repair a double-emit), explicit failover/replay
   counters, and zero hang verdicts.
2. **Overload degrades with explicit verdicts**: the per-tenant
   in-flight cap rejects, the bounded queue sheds the lowest-priority
   pending request — and an accepted-then-shed request's verdict is
   DELIVERED, not silent.
3. **Serving-tenant mode refuses cells** with a message naming
   ``%dist_serve`` instead of queueing a cell behind the decode loop.
4. **Reattach mid-generation**: a submitter that dies mid-decode
   finds its terminal result parked in its mailbox partition, drained
   exactly once on reattach; ``serve_stream`` resumes from any acked
   offset.

Marked ``slow`` on purpose (pool spin-up); the CI resilience job owns
these (marker ``serve``).
"""

import ast
import os
import time

import pytest

from nbdistributed_tpu.gateway.client import (CellSubmitError,
                                              TenantClient)
from nbdistributed_tpu.gateway.daemon import GatewayDaemon
from nbdistributed_tpu.gateway.scheduler import SchedPolicy
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience.faults import FaultPlan

pytestmark = [pytest.mark.integration, pytest.mark.serve,
              pytest.mark.gateway, pytest.mark.faults,
              pytest.mark.slow]

WORLD = 4

SPEC = (
    "import jax as _j, jax.numpy as _jn\n"
    "from nbdistributed_tpu.models import tiny_config, init_params\n"
    "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "params = init_params(_j.random.PRNGKey(0), cfg)\n")

PROMPTS = [[5, 9, 2], [7, 1], [3, 4, 8, 1], [11, 3], [2, 2, 2, 2],
           [6, 13], [1, 2, 3], [9, 9], [4, 10, 5], [12], [8, 3, 7],
           [10, 1, 1]]
MAX_NEW = 6

# Solo reference computed ON a pool rank (same process family as the
# decode loop) so the equality check cannot hinge on cross-process
# XLA flag differences.
REF_CELL = (
    "import jax as _j, jax.numpy as _jn, numpy as _np\n"
    "from nbdistributed_tpu.models import (tiny_config, init_params, "
    "generate)\n"
    "_cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "_p = init_params(_j.random.PRNGKey(0), _cfg)\n"
    f"_prompts = {PROMPTS!r}\n"
    f"[[int(t) for t in _np.asarray(generate(_p, _jn.asarray(pr, "
    f"_jn.int32)[None], _cfg, {MAX_NEW}))[0][len(pr):]] "
    "for pr in _prompts]")


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    run_dir = str(tmp_path_factory.mktemp("servepool"))
    old = {k: os.environ.get(k)
           for k in ("NBD_RUN_DIR", "NBD_RETRY_TIMEOUT_S",
                     "NBD_RETRY_ATTEMPTS")}
    os.environ["NBD_RUN_DIR"] = run_dir
    # Retry layer ON: the 8%-drop phase leans on same-msg-id
    # redelivery + the worker replay cache.
    os.environ["NBD_RETRY_TIMEOUT_S"] = "5"
    os.environ["NBD_RETRY_ATTEMPTS"] = "6"
    flightrec.reset_for_tests()
    gw = GatewayDaemon(
        WORLD, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=1, tenant_inflight=16,
                           queue_depth=32),
        request_timeout=None, attach_timeout=240.0)
    try:
        yield gw
    finally:
        gw.close()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def attach(pool, name, **kw):
    return TenantClient(pool.tenant_host, pool.tenant_port, name,
                        pool_token=pool.pool_token, **kw)


def solo_reference(client) -> list[list[int]]:
    # Rank 0 only: later tests in this module run on a pool whose
    # decode rank was deliberately killed, and an all-ranks cell
    # would fail fast on the dead rank.
    out = client.execute(REF_CELL, target_ranks=[0], timeout=300)
    results = out.get("results") or {}
    assert "0" in results, out
    return ast.literal_eval(results["0"].get("output"))


def wait_results(client, rids, timeout=300.0) -> dict:
    got: dict = {}
    deadline = time.time() + timeout
    while len(got) < len(rids) and time.time() < deadline:
        for rid in rids:
            if rid in got:
                continue
            r = client.serve_result(rid)
            if r.get("done"):
                got[rid] = r
        time.sleep(0.25)
    return got


# ----------------------------------------------------------------------


def test_sigkill_mid_decode_then_drops_exact_streams(pool):
    t1 = attach(pool, "t1")
    try:
        solo = solo_reference(t1)
        t1.serve_start(SPEC, max_batch=4, max_len=48, pad_to=4,
                       steps=2, queue_depth=32, inflight=32,
                       timeout=600)
        rids = []
        for pr in PROMPTS[:4]:
            rids.append(t1.serve_submit(pr, MAX_NEW)["rid"])
        # Seeded SIGKILL on the decode rank (the HIGHEST live rank —
        # rank 0 hosts the jax.distributed coordination service, whose
        # death is a whole-world loss, the supervisor's territory):
        # dies on its 3rd control message after arming — a serve_step
        # mid-decode.
        kill = WORLD - 1
        pool.comm.send_to_ranks([kill], "chaos", {
            "action": "set",
            "spec": {"seed": 5, "kill_rank": kill, "kill_at": 3}},
            timeout=60)
        for pr in PROMPTS[4:]:
            rids.append(t1.serve_submit(pr, MAX_NEW)["rid"])
            time.sleep(0.1)
        # The kill must actually land before we judge the episode.
        deadline = time.time() + 120
        while time.time() < deadline:
            if t1.serve_status().get("failovers", 0) >= 1:
                break
            time.sleep(0.5)
        assert t1.serve_status().get("failovers", 0) >= 1, \
            "seeded SIGKILL never triggered a failover"
        # Phase 2: 8% control-plane drops on the survivors, both
        # directions (worker plans shape worker->gateway; the
        # coordinator plan shapes gateway->worker).
        live = sorted(set(range(WORLD)) - pool.comm.dead_ranks())
        pool.comm.send_to_ranks(live, "chaos", {
            "action": "set", "spec": {"seed": 9, "drop": 0.08}},
            timeout=60)
        pool.comm.set_fault_plan(FaultPlan(seed=11, drop=0.08))
        try:
            got = wait_results(t1, rids, timeout=300)
        finally:
            pool.comm.set_fault_plan(None)
            live = sorted(set(range(WORLD)) - pool.comm.dead_ranks())
            pool.comm.send_to_ranks(live, "chaos",
                                    {"action": "clear"}, timeout=60)
        assert len(got) == len(rids), \
            (f"unfinished requests: "
             f"{sorted(set(rids) - set(got))}; "
             f"status={t1.serve_status()}")
        # Every accepted request: exact solo-generate greedy stream.
        for i, rid in enumerate(rids):
            assert got[rid]["status"] == "completed", got[rid]
            assert got[rid]["tokens"] == solo[i], \
                (f"request {rid} (prompt {PROMPTS[i]}): "
                 f"{got[rid]['tokens']} != solo {solo[i]}")
        st = t1.serve_status()
        # Exactly-once receipts: the offset dedup never had to drop a
        # double-emission, the journal replayed the killed rank's
        # in-flight requests, and nothing hung.
        assert st["dup_dropped"] == 0, st
        assert st["replayed"] >= 1, st
        assert st["accepted"] == len(rids), st
        assert st["completed"] == len(rids), st
        assert st["shed"] == 0 and st["rejected"] == 0, st
        status = pool.status()
        assert not status.get("hang_verdicts"), status["hang_verdicts"]
        # Serving telemetry reached the status plane (tokens/s + KV
        # occupancy piggyback from the decode rank).
        deadline = time.time() + 30
        seen_srv = False
        while time.time() < deadline and not seen_srv:
            seen_srv = any(v.get("srv")
                           for v in pool.status()["ranks"].values())
            if not seen_srv:
                time.sleep(1.0)
        assert seen_srv, "no srv heartbeat piggyback ever arrived"
        stopped = t1.serve_stop()
        assert stopped["status"] == "stopped"
    finally:
        try:
            t1.serve_stop()
        except Exception:
            pass
        t1.close(detach=True)


def test_overload_sheds_and_rejects_explicitly(pool):
    lo = attach(pool, "lo", priority=0)
    hi = attach(pool, "hi", priority=5)
    try:
        lo.serve_start(SPEC, max_batch=1, max_len=48, pad_to=4,
                       steps=1, queue_depth=2, inflight=2,
                       timeout=600)
        # Fill the low-priority tenant to its in-flight cap (long
        # budgets so the slot stays held through the burst below).
        v0 = lo.serve_submit(PROMPTS[0], 30)
        v1 = lo.serve_submit(PROMPTS[1], 30)
        assert v0["status"] == "accepted"
        assert v1["status"] == "accepted"
        with pytest.raises(CellSubmitError) as exc:
            lo.serve_submit(PROMPTS[2], 30)
        assert exc.value.verdict["status"] == "rejected"
        # A higher-priority burst overflows the bounded queue: the
        # lowest-priority pending request sheds WITH a delivered
        # verdict (v1 was accepted — silence would be a lie).
        hi_rids = [hi.serve_submit(pr, 8)["rid"]
                   for pr in PROMPTS[3:5]]
        deadline = time.time() + 60
        while time.time() < deadline:
            if lo.serve_result(v1["rid"]).get("status") == "shed":
                break
            time.sleep(0.25)
        shed = lo.serve_result(v1["rid"])
        assert shed["status"] == "shed", shed
        st = lo.serve_status()
        assert st["shed"] >= 1 and st["rejected"] >= 1, st
        got = wait_results(hi, hi_rids, timeout=240)
        assert len(got) == len(hi_rids)
        assert all(r["status"] == "completed" for r in got.values())
    finally:
        try:
            lo.serve_stop()
        except Exception:
            pass
        lo.close(detach=True)
        hi.close(detach=True)


def test_serving_tenant_mode_refuses_cells(pool):
    admin = attach(pool, "admin")
    srv_kernel = None
    try:
        admin.serve_start(SPEC, tenant="srvplane", max_batch=2,
                          max_len=48, pad_to=4, timeout=600)
        # A kernel attached UNDER the serving tenant's name cannot run
        # cells behind the decode loop — explicit refusal naming
        # %dist_serve (the PR 8 _require_cluster mirror).
        srv_kernel = attach(pool, "srvplane")
        with pytest.raises(CellSubmitError) as exc:
            srv_kernel.execute("x = 1")
        v = exc.value.verdict
        assert v["status"] == "rejected"
        assert v["reason"] == "serving-tenant"
        assert "%dist_serve" in v["error"]
        # Starting a second plane is refused too.
        with pytest.raises(RuntimeError, match="already running"):
            admin.serve_start(SPEC, timeout=60)
    finally:
        try:
            admin.serve_stop()
        except Exception:
            pass
        if srv_kernel is not None:
            srv_kernel.close(detach=True)
        admin.close(detach=True)


def test_reattach_mid_generation_parks_and_resumes(pool):
    crashy = attach(pool, "crashy")
    watcher = attach(pool, "watcher")
    resumed = None
    try:
        solo = solo_reference(watcher)
        crashy.serve_start(SPEC, max_batch=2, max_len=48, pad_to=4,
                           steps=1, timeout=600)
        rid = crashy.serve_submit(PROMPTS[0], MAX_NEW)["rid"]
        token = crashy.token
        # Kernel crash mid-generation: hard socket close, no detach.
        crashy._ch.close()
        got = wait_results(watcher, [rid], timeout=240)
        assert got[rid]["status"] == "completed"
        assert got[rid]["tokens"] == solo[0]
        # Reattach under the same name + token: the terminal result
        # parked in the tenant's mailbox partition and drains exactly
        # once.
        resumed = attach(pool, "crashy", token=token)
        drained = resumed.drain()
        key = f"serve:{rid}"
        assert key in drained, drained.keys()
        assert drained[key]["status"] == "completed"
        assert drained[key]["tokens"] == solo[0]
        assert resumed.drain() == {}  # exactly once
        # Stream resume from an acked offset: the suffix, bit-exact.
        s = resumed.serve_stream(rid, 3)
        assert s["tokens"] == solo[0][3:] and s["done"]
        assert resumed.serve_status()["resumed"] >= 1
    finally:
        try:
            (resumed or watcher).serve_stop()
        except Exception:
            pass
        watcher.close(detach=True)
        if resumed is not None:
            resumed.close(detach=True)
        try:
            crashy.close()
        except Exception:
            pass
